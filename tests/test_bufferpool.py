"""Buffer-pool lifecycle (ISSUE 12): bitwise parity pooled vs unpooled
on every model route, no cross-frame aliasing, mutate-after-release
oracle, steady-state zero-miss, hot reload / shutdown-drain hygiene,
and conservation under predictive-shed storms."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from odigos_tpu.features import FeaturizerConfig, featurize
from odigos_tpu.features.bufferpool import (
    BufferPool, MIN_BUCKET_BYTES, alloc, lease_scope, pools_enabled,
    set_pools_enabled)
from odigos_tpu.features.featurizer import assemble_sequences, pack_sequences
from odigos_tpu.pdata import synthesize_traces
from odigos_tpu.pipeline.service import Collector
from odigos_tpu.selftelemetry.flow import flow_ledger
from odigos_tpu.selftelemetry.latency import latency_ledger
from odigos_tpu.serving import EngineConfig, ScoringEngine
from odigos_tpu.serving.fastpath import FastPathSaturated, IngestFastPath
from odigos_tpu.utils.telemetry import meter


def wait_for(cond, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.005)
    return cond()


class Sink:
    def __init__(self):
        self.batches = []
        self.lock = threading.Lock()

    def consume(self, b):
        with self.lock:
            self.batches.append(b)

    def span_count(self):
        with self.lock:
            return sum(len(b) for b in self.batches)


# ------------------------------------------------------------ pool units

class TestPoolUnits:
    def test_bucketing_and_exact_shapes(self):
        pool = BufferPool("t/unit")
        lease = pool.lease()
        a = lease.take((7, 3), np.int32, 0)
        assert a.shape == (7, 3) and a.dtype == np.int32
        assert (a == 0).all()
        b = lease.take((5,), np.float32, -1.5)
        assert (b == -1.5).all()
        c = lease.take((4, 2), np.int64)  # fill=None: caller overwrites
        c[...] = 9
        lease.release()
        s = pool.stats()
        assert s["misses"] == 3 and s["hits"] == 0
        assert s["outstanding_leases"] == 0
        # everything came back: same shapes now hit
        lease2 = pool.lease()
        lease2.take((7, 3), np.int32, 0)
        lease2.take((5,), np.float32, 0.0)
        lease2.release()
        assert pool.stats()["misses"] == 3  # no fresh allocations

    def test_different_shapes_share_byte_buckets(self):
        pool = BufferPool("t/bucket")
        lease = pool.lease()
        lease.take((100,), np.int32)  # 400 B -> the 4096 B bucket
        lease.release()
        lease = pool.lease()
        arr = lease.take((10, 25), np.float32)  # 1000 B -> same bucket
        arr[...] = 1.0
        lease.release()
        s = pool.stats()
        assert s["misses"] == 1 and s["hits"] == 1

    def test_live_leases_never_share_backing(self):
        pool = BufferPool("t/alias")
        l1, l2 = pool.lease(), pool.lease()
        a = l1.take((64,), np.int32, 1)
        b = l2.take((64,), np.int32, 2)
        assert not np.shares_memory(a, b)
        assert (a == 1).all() and (b == 2).all()
        l1.release()
        l2.release()

    def test_refcount_release_only_at_zero(self):
        pool = BufferPool("t/ref")
        lease = pool.lease()
        lease.take((32,), np.int32, 0)
        lease.retain()
        lease.release()  # one of two holders
        assert pool.stats()["free_buffers"] == 0
        lease.release()  # last holder
        assert pool.stats()["free_buffers"] == 1
        assert pool.stats()["outstanding_leases"] == 0

    def test_mutate_after_release_oracle(self):
        """Holding a checked-out array past the lease's final release is
        the one contract violation; poison mode makes it deterministic:
        the stale reference reads poison, and a NEW frame's checkout is
        fully re-initialized regardless."""
        pool = BufferPool("t/poison", poison=True)
        lease = pool.lease()
        stale = lease.take((16,), np.uint8, 7)
        lease.release()
        assert (stale == 0xAB).all()  # recycled: the hold was a bug
        fresh = pool.lease()
        clean = fresh.take((16,), np.uint8, 0)
        assert (clean == 0).all()  # fills always overwrite poison
        fresh.release()

    def test_retention_cap_drops_over_budget(self):
        pool = BufferPool("t/cap", max_bytes=MIN_BUCKET_BYTES)
        lease = pool.lease()
        lease.take((8,), np.int32)
        lease.take((8,), np.float32)
        lease.release()
        s = pool.stats()
        assert s["bytes_held"] <= MIN_BUCKET_BYTES
        assert s["dropped"] == 1

    def test_alloc_falls_back_outside_scope_and_pools_inside(self):
        plain = alloc((4, 4), np.int32, 0)
        assert (plain == 0).all()
        pool = BufferPool("t/scope")
        with lease_scope(pool.lease()) as lease:
            pooled = alloc((4, 4), np.int32, 0)
            assert (pooled == 0).all()
            lease.release()
        assert pool.stats()["leases"] == 1
        assert pool.stats()["misses"] == 1

    def test_disable_switch(self):
        prev = pools_enabled()
        try:
            set_pools_enabled(False)
            assert not pools_enabled()
        finally:
            set_pools_enabled(prev)


# ---------------------------------------------------------- kernel parity

class TestKernelParity:
    """Pooled and unpooled featurize/assemble/pack are BITWISE equal —
    the acceptance contract (pooled arrays are exact-shape initialized
    views; nothing about the math may change)."""

    CFG = FeaturizerConfig(attr_slots=4)

    def _batches(self):
        out = []
        for s in range(3):
            out.append(synthesize_traces(24 + 8 * s, seed=s))
        return out

    def test_featurize_parity(self):
        pool = BufferPool("t/parity-feat")
        for b in self._batches():
            base = featurize(b, self.CFG)
            lease = pool.lease()
            with lease_scope(lease):
                pooled = featurize(b, self.CFG)
            assert np.array_equal(base.categorical, pooled.categorical)
            assert np.array_equal(base.continuous, pooled.continuous)
            assert base.categorical.dtype == pooled.categorical.dtype
            assert base.continuous.dtype == pooled.continuous.dtype
            lease.release()

    def test_pack_and_assemble_parity(self):
        pool = BufferPool("t/parity-pack")
        for b in self._batches():
            feats = featurize(b, self.CFG)
            base_p = pack_sequences(b, feats, max_len=16, pad_rows_to=8)
            base_a = assemble_sequences(b, feats, max_len=16,
                                        pad_traces_to=8)
            lease = pool.lease()
            with lease_scope(lease):
                pool_p = pack_sequences(b, feats, max_len=16,
                                        pad_rows_to=8)
                pool_a = assemble_sequences(b, feats, max_len=16,
                                            pad_traces_to=8)
            for name in ("categorical", "continuous", "segments",
                         "positions", "span_index"):
                assert np.array_equal(getattr(base_p, name),
                                      getattr(pool_p, name)), name
            for name in ("categorical", "continuous", "mask",
                         "span_index"):
                assert np.array_equal(getattr(base_a, name),
                                      getattr(pool_a, name)), name
            lease.release()

    def test_empty_batch_parity(self):
        b = synthesize_traces(2, seed=0).take(np.array([], np.int64))
        pool = BufferPool("t/parity-empty")
        base = featurize(b, self.CFG)
        lease = pool.lease()
        with lease_scope(lease):
            pooled = featurize(b, self.CFG)
        assert pooled.categorical.shape == base.categorical.shape
        assert pooled.continuous.shape == base.continuous.shape
        lease.release()

    def test_steady_state_zero_misses(self):
        """The headline claim: after one warm pass over the rotating
        inputs, repeated featurize+pack checks out ONLY recycled
        buffers — zero fresh allocations in the pooled category."""
        pool = BufferPool("t/steady")
        batches = self._batches()

        def one_pass():
            for b in batches:
                lease = pool.lease()
                with lease_scope(lease):
                    feats = featurize(b, self.CFG)
                    pack_sequences(b, feats, max_len=16, pad_rows_to=8)
                lease.release()

        one_pass()  # warm: populates the bucket ladder
        warm_misses = pool.stats()["misses"]
        for _ in range(5):
            one_pass()
        s = pool.stats()
        assert s["misses"] == warm_misses, (
            f"steady state allocated fresh buffers: {s}")
        assert s["hits"] > 0


# ------------------------------------------------------ model-route parity

class TestModelRouteParity:
    """Every scoring route returns bitwise-identical scores pooled vs
    unpooled — featurize pooling (fast-path submit lanes) and the
    engine's pack-stage lease must be invisible to the math."""

    def _scores(self, cfg: EngineConfig, batches, pooled: bool):
        prev = pools_enabled()
        set_pools_enabled(pooled)
        try:
            eng = ScoringEngine(cfg).start()
            try:
                out = []
                for b in batches:
                    s = eng.score_sync(b, timeout_s=60.0)
                    assert s is not None
                    out.append(np.asarray(s))
                return out
            finally:
                eng.shutdown()
        finally:
            set_pools_enabled(prev)

    @pytest.mark.parametrize("model", ["mock", "zscore"])
    def test_cpu_routes_bitwise(self, model):
        batches = [synthesize_traces(16 + 8 * s, seed=s)
                   for s in range(3)]
        base = self._scores(EngineConfig(model=model), batches, False)
        pooled = self._scores(EngineConfig(model=model), batches, True)
        for a, b in zip(base, pooled):
            assert np.array_equal(a, b)

    @pytest.mark.parametrize("model", ["transformer", "autoencoder"])
    def test_sequence_routes_bitwise(self, model):
        jax = pytest.importorskip("jax")
        jnp = jax.numpy
        from odigos_tpu.models import TransformerConfig
        from odigos_tpu.models.autoencoder import AutoencoderConfig

        mc = (TransformerConfig(d_model=32, n_heads=2, n_layers=1,
                                d_ff=64, max_len=16, dtype=jnp.float32)
              if model == "transformer" else
              AutoencoderConfig(d_model=32, d_latent=16, n_heads=2,
                                n_layers=1, d_ff=64, max_len=16,
                                dtype=jnp.float32))
        cfg = dict(model=model, model_config=mc, max_len=16,
                   trace_bucket=8, bucket_ladder=2, seed=3)
        batches = [synthesize_traces(12 + 4 * s, seed=s)
                   for s in range(2)]
        base = self._scores(EngineConfig(**cfg), batches, False)
        pooled = self._scores(EngineConfig(**cfg), batches, True)
        for a, b in zip(base, pooled):
            assert np.array_equal(a, b)


# --------------------------------------------------- fast-path lifecycle

class TestFastPathPoolLifecycle:
    def _fp(self, sink=None, **cfg):
        eng = ScoringEngine(EngineConfig(model="zscore",
                                         max_queue=256)).start()
        base = {"deadline_ms": 10_000.0, "predictive": False}
        base.update(cfg)
        fp = IngestFastPath("traces/pool", eng, 0.99, sink or Sink(),
                            base)
        fp.start()
        return fp, eng

    def test_leases_drain_to_zero_after_traffic(self):
        fp, eng = self._fp()
        try:
            total = 0
            for s in range(8):
                b = synthesize_traces(24, seed=s)
                fp.consume(b)
                total += len(b)
            assert fp.drain(30.0)
            stats = fp.pool_stats()
            assert stats is not None
            assert stats["leases"] == 8
            # frame + engine references both released on every path
            assert wait_for(
                lambda: fp.pool_stats()["outstanding_leases"] == 0)
            assert fp.downstream.span_count() == total
        finally:
            fp.shutdown()
            eng.shutdown()

    def test_steady_state_zero_misses_through_fastpath(self):
        # one submit lane = one pool, drain after EVERY frame: the
        # in-flight depth is pinned at 1, so the warm set is exactly
        # one frame's buffers and the zero-miss claim is deterministic
        # under any CI load (bench.py steady_state_allocs measures the
        # concurrent/amortized version of the same claim)
        fp, eng = self._fp(submit_lanes=1, lanes=2)
        try:
            batches = [synthesize_traces(24, seed=s) for s in range(4)]
            for b in batches:  # warm pass sizes the buckets
                fp.consume(b)
                assert fp.drain(30.0)
            warm = fp.pool_stats()["misses"]
            for _ in range(4):
                for b in batches:
                    fp.consume(b)
                    assert fp.drain(30.0)
            assert fp.pool_stats()["misses"] == warm, fp.pool_stats()
        finally:
            fp.shutdown()
            eng.shutdown()

    def test_scores_parity_through_fastpath(self):
        """End-to-end: the tagged output of the pooled fast path equals
        the unpooled one bitwise (same engine config, same frames).
        Drained frame-by-frame so both runs score at MATCHED request
        grouping — zscore's online state evolves per coalesced call, so
        load-dependent coalescing would diff the runs, not pooling."""
        def run(pooled: bool):
            sink = Sink()
            eng = ScoringEngine(EngineConfig(model="zscore",
                                             max_queue=256)).start()
            fp = IngestFastPath("traces/pp", eng, 0.2, sink,
                                {"deadline_ms": 10_000.0,
                                 "predictive": False,
                                 "ordered": True,
                                 "pooled": pooled})
            fp.start()
            try:
                for s in range(4):
                    fp.consume(synthesize_traces(16, seed=s))
                    assert fp.drain(30.0)
            finally:
                fp.shutdown()
                eng.shutdown()
            return sink.batches

        base = run(False)
        pooled = run(True)
        assert len(base) == len(pooled)
        for a, b in zip(base, pooled):
            assert list(a.span_attrs) == list(b.span_attrs)

    def test_shutdown_drain_releases_leases(self):
        """A wedged downstream forces the timed-out-drain shutdown path
        (named shutdown_drain sheds) — every claimed frame's lease must
        still return to its pool."""
        gate = threading.Event()

        class Wedge:
            def consume(self, b):
                gate.wait(20.0)

        fp, eng = self._fp(sink=Wedge(), drain_timeout_s=0.3)
        try:
            for s in range(4):
                fp.consume(synthesize_traces(8, seed=s))
            time.sleep(0.2)
        finally:
            fp.shutdown()
            gate.set()
            eng.shutdown()
        # lanes parked in the wedged consume release their frames (and
        # leases) once the gate opens; shutdown-claimed frames released
        # theirs inline — either way every lease returns
        assert wait_for(
            lambda: fp.pool_stats()["outstanding_leases"] == 0), \
            fp.pool_stats()

    def test_hot_reload_mid_stream_conserved(self):
        """Collector reload swaps in a fresh fast path (fresh pools);
        traffic across the swap stays conserved and the new route's
        pools work."""
        flow_ledger.reset()
        cfg = {
            "receivers": {"synthetic": {"traces_per_batch": 6,
                                        "n_batches": 4,
                                        "interval_s": 0.01}},
            "processors": {"memory_limiter": {"limit_mib": 512},
                           "batch": {"send_batch_size": 512,
                                     "timeout_s": 0.05},
                           "tpuanomaly": {"model": "zscore",
                                          "threshold": 0.99,
                                          "timeout_ms": 10_000.0,
                                          "shared_engine": False}},
            "exporters": {"tracedb": {}},
            "service": {"pipelines": {"traces/in": {
                "receivers": ["synthetic"],
                "processors": ["memory_limiter", "tpuanomaly", "batch"],
                "exporters": ["tracedb"],
                "fast_path": {"deadline_ms": 10_000.0,
                              "predictive": False}}}},
        }
        collector = Collector(cfg).start()
        try:
            import copy

            collector.drain_receivers(30.0)  # first wave through old fp
            new_cfg = copy.deepcopy(cfg)
            new_cfg["service"]["pipelines"]["traces/in"]["fast_path"][
                "lanes"] = 2
            collector.reload(new_cfg)
            # the new graph's synthetic receiver produces a second wave
            # through the NEW fast path (fresh pools)
            fp = collector.graph.fastpaths["traces/in"]
            collector.drain_receivers(30.0)
            assert fp.drain(30.0)
            assert fp.pool_stats()["leases"] >= 1
            bal = flow_ledger.conservation()["traces/in"]
            assert bal["leak"] == 0, bal
            assert wait_for(
                lambda: fp.pool_stats()["outstanding_leases"] == 0)
        finally:
            collector.shutdown()


# ------------------------------------------------- predictive-shed storm

class TestPredictiveShedConservation:
    def test_storm_is_named_and_conserved(self):
        """Force the predictor hot (huge priced cost) and storm the
        intake: every accepted frame forwards, every shed is a named
        queue_full drop with blame=predicted, and the ledger balances
        exactly — no silent loss under a predictive storm."""
        flow_ledger.reset()
        latency_ledger.reset()
        meter.reset()

        class GatedSink(Sink):
            def __init__(self):
                super().__init__()
                self.gate = threading.Event()
                self.gate.set()

            def consume(self, b):
                self.gate.wait(30.0)
                super().consume(b)

        sink = GatedSink()
        eng = ScoringEngine(EngineConfig(model="zscore",
                                         max_queue=256)).start()
        fp = IngestFastPath("traces/storm", eng, 0.99, sink,
                            {"deadline_ms": 5.0, "predictive": True,
                             "predictive_min_frames": 1})
        fp._flow_site = ("traces/storm", fp.name, "traces")
        fp.start()
        accepted = shed = 0
        accepted_spans = 0
        try:
            # prime the route so recorder means exist, then poison the
            # cached price so every prediction exceeds the 5 ms budget
            b0 = synthesize_traces(8, seed=0)
            fp.consume(b0)
            assert fp.drain(30.0)
            accepted += 1
            accepted_spans += len(b0)
            fp._stage_cost_ms = 10_000.0
            fp._stage_cost_next_ns = time.monotonic_ns() + int(60e9)
            # an IDLE route must admit (the anti-starvation guard): the
            # first poisoned-cost frame goes through so the estimator
            # could refresh; frames arriving while it is in flight
            # shed. The gated sink pins it in flight for the whole
            # storm (deterministic under any CI load).
            sink.gate.clear()
            b1 = synthesize_traces(8, seed=100)
            fp.consume(b1)
            accepted += 1
            accepted_spans += len(b1)
            shed_spans = 0
            for s in range(20):
                b = synthesize_traces(8, seed=s + 1)
                try:
                    fp.consume(b)
                    accepted += 1
                    accepted_spans += len(b)
                except FastPathSaturated:
                    shed += 1
                    shed_spans += len(b)
            sink.gate.set()
            assert fp.drain(30.0)
        finally:
            sink.gate.set()
            fp.shutdown()
            eng.shutdown()
        assert shed == 20 and accepted == 2
        assert sink.span_count() == accepted_spans
        # the ledger names every shed with the predicted blame
        snap = flow_ledger.snapshot()
        drops = {(d["pipeline"], r): n for d in snap["drops"]
                 for r, n in d["reasons"].items()}
        assert drops.get(("traces/storm", "queue_full"), 0) == shed_spans
        # blame dimension on the metric key
        keys = meter.snapshot()
        blamed = [k for k in keys
                  if k.startswith("odigos_flow_dropped_items_total")
                  and "blame=predicted" in k]
        assert blamed, sorted(
            k for k in keys if "dropped_items" in k)
        expired = [k for k in keys
                   if k.startswith(
                       "odigos_latency_deadline_expired_spans_total")
                   and "blame=predicted" in k]
        assert expired and int(keys[expired[0]]) == shed_spans
        # predictive watermark published for the pre-decode gate
        wm = flow_ledger.watermark_current("fastpath/traces/storm",
                                           "predicted_burn_ms")
        assert wm is not None and wm > 5.0

    def test_predictor_recovers_after_overload(self):
        """Anti-starvation regression: windowed means + the idle-admit
        guard mean a polluted price cannot latch the gate shut — an
        idle route admits, the admitted frame's (healthy) stage times
        refresh the recent-ring means, and the next re-price drops the
        cost back below the deadline."""
        latency_ledger.reset()
        sink = Sink()
        eng = ScoringEngine(EngineConfig(model="zscore",
                                         max_queue=256)).start()
        fp = IngestFastPath("traces/recover", eng, 0.99, sink,
                            {"deadline_ms": 10_000.0,
                             "predictive": True,
                             "predictive_min_frames": 1})
        fp.start()
        try:
            for s in range(3):  # healthy frames fill the recent ring
                fp.consume(synthesize_traces(8, seed=s))
            assert fp.drain(30.0)
            # simulate an overload's polluted price; idle route: admit
            fp._stage_cost_ms = 1e9
            fp._stage_cost_next_ns = 0  # next refresh re-prices
            fp.consume(synthesize_traces(8, seed=77))
            assert fp.drain(30.0)
            # the refresh ran from the (healthy) window: cost recovered
            assert fp._stage_cost_ms is not None
            assert fp._stage_cost_ms < 10_000.0, fp._stage_cost_ms
        finally:
            fp.shutdown()
            eng.shutdown()

    def test_cold_route_never_predicts(self):
        """Below predictive_min_frames the gate must not shed — a cold
        route has no means to price with."""
        sink = Sink()
        eng = ScoringEngine(EngineConfig(model="zscore",
                                         max_queue=256)).start()
        fp = IngestFastPath("traces/cold", eng, 0.99, sink,
                            {"deadline_ms": 1.0, "predictive": True})
        fp.start()
        try:
            fp.consume(synthesize_traces(8, seed=1))  # must not raise
            assert fp.drain(30.0)
        finally:
            fp.shutdown()
            eng.shutdown()
