"""Operator-style single-resource installer (VERDICT r2 item 7;
reference: operator/api/v1alpha1/odigos_types.go:26,105 +
internal/controller/odigos_controller.go): apply one Odigos resource →
full install; delete it → uninstall."""

import pytest

from odigos_tpu.api import ControllerManager, ObjectMeta, Store
from odigos_tpu.api.resources import ConditionStatus, Odigos
from odigos_tpu.controlplane import Autoscaler, Operator, Scheduler
from odigos_tpu.config.model import Configuration
from odigos_tpu.controlplane.autoscaler import GATEWAY_CONFIG_NAME
from odigos_tpu.controlplane.scheduler import (
    EFFECTIVE_CONFIG_NAME,
    GATEWAY_GROUP_NAME,
    ODIGOS_NAMESPACE,
)
from test_auth import make_token  # noqa: E402


def make_plane():
    store = Store()
    mgr = ControllerManager(store)
    Scheduler(store, mgr)
    Autoscaler(store, mgr, Configuration())
    Operator(store, mgr)
    return store, mgr


def test_apply_one_resource_installs_everything():
    store, mgr = make_plane()
    store.apply(Odigos(meta=ObjectMeta(name="odigos",
                                       namespace=ODIGOS_NAMESPACE),
                       telemetry_enabled=True,
                       ignored_namespaces=["kube-system"]))
    mgr.run_once()
    # the whole chain ran: effective config, collectors groups, gateway cfg
    eff = store.get("ConfigMap", ODIGOS_NAMESPACE, EFFECTIVE_CONFIG_NAME)
    assert eff is not None
    assert eff.data["config"]["telemetry_enabled"] is True
    assert eff.data["config"]["ignored_namespaces"] == ["kube-system"]
    assert store.get("CollectorsGroup", ODIGOS_NAMESPACE,
                     GATEWAY_GROUP_NAME) is not None
    assert store.get("ConfigMap", ODIGOS_NAMESPACE,
                     GATEWAY_CONFIG_NAME) is not None
    odigos = store.get("Odigos", ODIGOS_NAMESPACE, "odigos")
    cond = odigos.condition("Installed")
    assert cond.status == ConditionStatus.TRUE
    assert "community" in cond.message


def test_delete_resource_uninstalls():
    store, mgr = make_plane()
    store.apply(Odigos(meta=ObjectMeta(name="odigos",
                                       namespace=ODIGOS_NAMESPACE)))
    mgr.run_once()
    assert store.get("ConfigMap", ODIGOS_NAMESPACE, EFFECTIVE_CONFIG_NAME)
    store.delete("Odigos", ODIGOS_NAMESPACE, "odigos")
    mgr.run_once()
    assert store.get("ConfigMap", ODIGOS_NAMESPACE,
                     EFFECTIVE_CONFIG_NAME) is None
    assert store.get("ConfigMap", ODIGOS_NAMESPACE,
                     GATEWAY_CONFIG_NAME) is None
    assert store.get("CollectorsGroup", ODIGOS_NAMESPACE,
                     GATEWAY_GROUP_NAME) is None


def test_delete_one_of_two_keeps_survivor_installed():
    """Deleting one Odigos resource while another exists must not tear
    down the survivor's stack (advisor r3: reconcile ran the full
    uninstall whenever the event's key no longer resolved)."""
    store, mgr = make_plane()
    store.apply(Odigos(meta=ObjectMeta(name="primary",
                                       namespace=ODIGOS_NAMESPACE),
                       telemetry_enabled=True))
    store.apply(Odigos(meta=ObjectMeta(name="secondary",
                                       namespace=ODIGOS_NAMESPACE)))
    mgr.run_once()
    assert store.get("ConfigMap", ODIGOS_NAMESPACE, EFFECTIVE_CONFIG_NAME)
    store.delete("Odigos", ODIGOS_NAMESPACE, "secondary")
    mgr.run_once()
    # the survivor's install is intact (re-reconciled, not uninstalled)
    eff = store.get("ConfigMap", ODIGOS_NAMESPACE, EFFECTIVE_CONFIG_NAME)
    assert eff is not None
    assert store.get("CollectorsGroup", ODIGOS_NAMESPACE,
                     GATEWAY_GROUP_NAME) is not None
    # deleting the LAST one still uninstalls
    store.delete("Odigos", ODIGOS_NAMESPACE, "primary")
    mgr.run_once()
    assert store.get("ConfigMap", ODIGOS_NAMESPACE,
                     EFFECTIVE_CONFIG_NAME) is None


def test_valid_token_installs_onprem_tier():
    store, mgr = make_plane()
    store.apply(Odigos(meta=ObjectMeta(name="odigos",
                                       namespace=ODIGOS_NAMESPACE),
                       on_prem_token=make_token(),
                       profiles=["java-ebpf-instrumentations"]))
    mgr.run_once()
    odigos = store.get("Odigos", ODIGOS_NAMESPACE, "odigos")
    cond = odigos.condition("Installed")
    assert cond.status == ConditionStatus.TRUE and "onprem" in cond.message
    # the tier-gated profile resolved (would be a problem under community)
    eff = store.get("ConfigMap", ODIGOS_NAMESPACE, EFFECTIVE_CONFIG_NAME)
    assert "java-ebpf-instrumentations" in eff.data["applied_profiles"]


def test_invalid_token_blocks_install():
    store, mgr = make_plane()
    store.apply(Odigos(meta=ObjectMeta(name="odigos",
                                       namespace=ODIGOS_NAMESPACE),
                       on_prem_token="garbage"))
    mgr.run_once()
    odigos = store.get("Odigos", ODIGOS_NAMESPACE, "odigos")
    cond = odigos.condition("Installed")
    assert cond.status == ConditionStatus.FALSE
    assert cond.reason == "InvalidToken"
    assert store.get("ConfigMap", ODIGOS_NAMESPACE,
                     EFFECTIVE_CONFIG_NAME) is None


def test_spec_update_reconciles_config():
    store, mgr = make_plane()
    store.apply(Odigos(meta=ObjectMeta(name="odigos",
                                       namespace=ODIGOS_NAMESPACE)))
    mgr.run_once()
    odigos = store.get("Odigos", ODIGOS_NAMESPACE, "odigos")
    odigos.ignored_containers = ["istio-proxy"]
    store.apply(odigos)
    mgr.run_once()
    eff = store.get("ConfigMap", ODIGOS_NAMESPACE, EFFECTIVE_CONFIG_NAME)
    assert eff.data["config"]["ignored_containers"] == ["istio-proxy"]


def test_cloud_token_does_not_escalate_to_onprem():
    """The audience claim is the entitlement on the operator path too: a
    cloud token requesting an onprem-gated profile blocks the install,
    exactly as cmd_install would."""
    store, mgr = make_plane()
    store.apply(Odigos(meta=ObjectMeta(name="odigos",
                                       namespace=ODIGOS_NAMESPACE),
                       on_prem_token=make_token(aud="cloud"),
                       profiles=["java-ebpf-instrumentations"]))
    mgr.run_once()
    odigos = store.get("Odigos", ODIGOS_NAMESPACE, "odigos")
    cond = odigos.condition("Installed")
    assert cond.status == ConditionStatus.FALSE
    assert cond.reason == "InvalidProfiles"
    assert store.get("ConfigMap", ODIGOS_NAMESPACE,
                     EFFECTIVE_CONFIG_NAME) is None


def test_unknown_profile_blocks_install_with_condition():
    store, mgr = make_plane()
    store.apply(Odigos(meta=ObjectMeta(name="odigos",
                                       namespace=ODIGOS_NAMESPACE),
                       profiles=["no-such-profile"]))
    mgr.run_once()
    cond = store.get("Odigos", ODIGOS_NAMESPACE,
                     "odigos").condition("Installed")
    assert cond.status == ConditionStatus.FALSE
    assert cond.reason == "InvalidProfiles"
    assert "no-such-profile" in cond.message


def test_operator_tier_reaches_distro_provider():
    """An operator-validated onprem token enables tier-gated distros in a
    control plane booted at community tier (review finding: the tier
    previously reached only the scheduler)."""
    from odigos_tpu.api.resources import (
        InstrumentationRule, ObjectMeta as OM, RuleKind, RuntimeDetails,
        Source, WorkloadKind, WorkloadRef)
    from odigos_tpu.controlplane import Cluster, Container, Instrumentor
    from odigos_tpu.controlplane.instrumentor import ic_name

    store = Store()
    mgr = ControllerManager(store)
    cluster = Cluster(nodes=1)
    Scheduler(store, mgr)
    Autoscaler(store, mgr, Configuration())
    Instrumentor(store, mgr, cluster, Configuration())  # community boot
    Operator(store, mgr)
    store.apply(Odigos(meta=ObjectMeta(name="odigos",
                                       namespace=ODIGOS_NAMESPACE),
                       on_prem_token=make_token(aud="onprem")))
    w = cluster.add_workload("default", "japp", [
        Container(name="main", language="java", runtime_version="17")])
    store.apply(Source(meta=OM(name="src-japp", namespace="default"),
                       workload=w.ref))
    store.apply(InstrumentationRule(
        meta=OM(name="use-ebpf", namespace="default"),
        rule_kind=RuleKind.OTEL_SDK,
        details={"distro_names": ["java-ebpf"]}))
    mgr.run_once()
    ic = store.get("InstrumentationConfig", "default", ic_name(w.ref))
    ic.runtime_details = [RuntimeDetails(container_name="main",
                                         language="java",
                                         runtime_version="17")]
    store.update_status(ic)
    mgr.run_once()
    ic = store.get("InstrumentationConfig", "default", ic_name(w.ref))
    assert ic.containers[0].agent_enabled
    assert ic.containers[0].distro_name == "java-ebpf"


def test_uninstall_strips_agents_from_workloads():
    """Deleting the Odigos resource un-instruments running pods via the
    Source-deletion path (review finding: agents previously survived)."""
    from odigos_tpu.controlplane import Cluster, Container, Instrumentor
    from odigos_tpu.api.resources import RuntimeDetails, Source
    from odigos_tpu.api import ObjectMeta as OM
    from odigos_tpu.controlplane.instrumentor import ic_name
    from odigos_tpu.config.model import RolloutConfiguration

    store = Store()
    mgr = ControllerManager(store)
    cluster = Cluster(nodes=1)
    Scheduler(store, mgr)
    Autoscaler(store, mgr, Configuration())
    Instrumentor(store, mgr, cluster, Configuration(
        rollout=RolloutConfiguration(rollback_grace_time_s=0.0)))
    Operator(store, mgr)
    store.apply(Odigos(meta=ObjectMeta(name="odigos",
                                       namespace=ODIGOS_NAMESPACE)))
    w = cluster.add_workload("default", "app", [
        Container(name="main", language="python", runtime_version="3.11")])
    store.apply(Source(meta=OM(name="src-app", namespace="default"),
                       workload=w.ref))
    mgr.run_once()
    ic = store.get("InstrumentationConfig", "default", ic_name(w.ref))
    ic.runtime_details = [RuntimeDetails(container_name="main",
                                         language="python",
                                         runtime_version="3.11")]
    store.update_status(ic)
    mgr.run_once()
    assert any(p.injected_env for p in cluster.pods.values())

    store.delete("Odigos", ODIGOS_NAMESPACE, "odigos")
    mgr.run_once()
    assert store.get("InstrumentationConfig", "default",
                     ic_name(w.ref)) is None
    assert all(not p.injected_env for p in cluster.pods.values())


def test_invalid_spec_enum_surfaces_condition():
    store, mgr = make_plane()
    store.apply(Odigos(meta=ObjectMeta(name="odigos",
                                       namespace=ODIGOS_NAMESPACE),
                       ui_mode="dark"))
    mgr.run_once()
    cond = store.get("Odigos", ODIGOS_NAMESPACE,
                     "odigos").condition("Installed")
    assert cond.status == ConditionStatus.FALSE
    assert cond.reason == "InvalidSpec"


def test_uninstall_removes_destinations():
    from odigos_tpu.api.resources import DestinationResource

    store, mgr = make_plane()
    store.apply(Odigos(meta=ObjectMeta(name="odigos",
                                       namespace=ODIGOS_NAMESPACE)))
    store.apply(DestinationResource(
        meta=ObjectMeta(name="old-backend", namespace=ODIGOS_NAMESPACE),
        dest_type="tracedb", signals=["traces"]))
    mgr.run_once()
    store.delete("Odigos", ODIGOS_NAMESPACE, "odigos")
    mgr.run_once()
    assert store.list("DestinationResource") == []
