"""Install-time platform autodetection (cli/pkg/autodetect analog).

The reference senses its environment before rendering anything: cluster
kind from name/context heuristics (kindofcluster.go: kind-/k3s/eks/gke/
aks/openshift/minikube detectors, first match wins) and adapts images/
securityContexts accordingly.  Ours detects the same cluster-kind
signals plus the node-level traits that matter on a TPU host:

* ``kind``            — kind|k3s|eks|gke|aks|openshift|minikube|vanilla
                        from cluster name / kube context (env overrides
                        ODIGOS_CLUSTER_NAME / ODIGOS_KUBE_CONTEXT let
                        tests and odd setups pin it)
* ``cgroup_version``  — 2 when /sys/fs/cgroup/cgroup.controllers exists
                        (unified hierarchy), else 1; decides which
                        cgroup paths the odiglet manifest mounts
* ``systemd``         — /run/systemd/system present; decides the VM
                        distribution's service-install path
* ``tpu_present``     — accelerator device nodes (/dev/accel*, /dev/vfio)
                        or a JAX_PLATFORMS hint; decides whether the
                        deviceplugin ships and manifests request the
                        TPU resource

Detection is pure-read (stat/env only — never imports jax; install must
stay fast and side-effect-free) and returns a plain dict so it persists
in state.json/Configuration.platform verbatim.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Optional

# ordered like the reference's availableKindDetectors: first match wins
_KIND_SIGNALS = [
    ("kind", ("kind-",)),
    ("k3s", ("k3s", "k3d-")),
    ("eks", (".eks.amazonaws.com", "arn:aws:eks", "eks-")),
    ("gke", ("gke_",)),
    ("aks", ("aks-", "-aks")),
    ("openshift", ("openshift", "api.crc.testing")),
    ("minikube", ("minikube",)),
]


def detect_cluster_kind(cluster_name: str = "",
                        context: str = "") -> str:
    name = (cluster_name
            or os.environ.get("ODIGOS_CLUSTER_NAME", "")).lower()
    ctx = (context or os.environ.get("ODIGOS_KUBE_CONTEXT", "")).lower()
    for kind, needles in _KIND_SIGNALS:
        for n in needles:
            if n in name or n in ctx:
                return kind
    return "vanilla"


def detect_cgroup_version(root: str = "/sys/fs/cgroup") -> int:
    return 2 if os.path.exists(os.path.join(root,
                                            "cgroup.controllers")) else 1


def detect_systemd(run_dir: str = "/run/systemd/system") -> bool:
    return os.path.isdir(run_dir)


def detect_tpu(dev_glob: str = "/dev/accel*") -> bool:
    # /dev/accel* is the TPU driver's device-node pattern; generic vfio
    # nodes are deliberately NOT a signal (any IOMMU/GPU-passthrough
    # host has /dev/vfio/vfio, and a false positive renders manifests
    # requesting a TPU resource the cluster cannot schedule)
    if glob.glob(dev_glob):
        return True
    plat = os.environ.get("JAX_PLATFORMS", "")
    return "tpu" in plat.lower()


def detect_platform(cluster_name: str = "",
                    context: str = "",
                    sysroot: Optional[str] = None) -> dict[str, Any]:
    """One detection pass; ``sysroot`` redirects the filesystem probes
    (tests point it at a fixture tree)."""
    root = sysroot or "/"

    def p(*parts: str) -> str:
        return os.path.join(root, *parts)

    return {
        "kind": detect_cluster_kind(cluster_name, context),
        "cgroup_version": detect_cgroup_version(p("sys", "fs", "cgroup")),
        "systemd": detect_systemd(p("run", "systemd", "system")),
        "tpu_present": detect_tpu(p("dev", "accel*")),
    }
