"""Trace transformer classifier — the flagship model (BASELINE config #5).

DeepTraLog-style: a bidirectional transformer over the span sequence of one
trace, emitting a per-span anomaly logit and a per-trace logit (masked
mean-pool head). Trained supervised on injected-fault traces
(odigos_tpu.train.faults), served by the scoring engine at ≥1M spans/s/chip
in bfloat16, data-parallel across the mesh (odigos_tpu.parallel).

Default dims are MXU-shaped: d_model 256, d_ff 1024, heads 4 — all multiples
of the 128-lane tile.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from . import jitstats
from .layers import Encoder

# Shape-bucketing strategy per jitted scoring entry point (the package
# hygiene test asserts every jit path in models/ and parallel/ declares
# one — an undeclared path is an unbounded-recompile hazard at serving
# rates). Values are documentation; the mechanisms live where named.
SHAPE_BUCKETING = {
    "score_spans": "leading trace axis padded by the engine's BucketLadder "
                   "(serving.engine) or a fixed trace_bucket multiple; "
                   "L/C fixed by TransformerConfig",
    "score_packed": "packed row axis padded by BucketLadder.round_rows "
                    "(geometric ladder over trace_bucket, warmed at "
                    "engine start); L/C fixed by TransformerConfig",
}


def serving_donation(argnums: tuple[int, ...],
                     enabled: bool) -> tuple[int, ...]:
    """Donate per-call input buffers on TPU only, and only when the owner
    opted in (the serving engine does — its pack stage materializes fresh
    arrays every call, so donated buffers are never reused). Donation is a
    no-op-with-a-warning on CPU, and callers that re-time the same staged
    arrays (tools/quant_geometry.py, tools/layer_ablation.py, eval loops)
    must keep it off or the second call reads a deleted buffer."""
    if not enabled:
        return ()
    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — no device runtime: serve undonated
        return ()
    return argnums if backend == "tpu" else ()


@dataclass(frozen=True)
class TransformerConfig:
    service_vocab: int = 512
    name_vocab: int = 2048
    attr_vocab: int = 4096
    attr_slots: int = 0  # must match FeaturizerConfig.attr_slots
    d_model: int = 256
    n_heads: int = 4
    n_layers: int = 4
    d_ff: int = 1024
    max_len: int = 64
    dtype: Any = jnp.bfloat16


class _TraceTransformerModule(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, categorical, continuous, mask, deterministic=True,
                 positions=None, segments=None):
        c = self.cfg
        h = Encoder(c.service_vocab, c.name_vocab, c.attr_vocab, c.d_model,
                    c.n_heads, c.n_layers, c.d_ff, c.max_len, c.dtype,
                    name="encoder")(categorical, continuous, mask,
                                    deterministic, positions=positions,
                                    segments=segments)
        span_logit = nn.Dense(1, dtype=jnp.float32,
                              name="span_head")(h)[..., 0]
        denom = jnp.maximum(mask.sum(-1, keepdims=True), 1)
        pooled = (h * mask[..., None].astype(h.dtype)).sum(-2) / denom.astype(h.dtype)
        trace_logit = nn.Dense(1, dtype=jnp.float32,
                               name="trace_head")(pooled)[..., 0]
        return span_logit, trace_logit


class TraceTransformer:
    """Functional wrapper: init / apply / score / loss, all jit-friendly.

    The scoring entrypoint ``score_spans`` is what __graft_entry__.entry()
    exposes to the driver.
    """

    def __init__(self, config: TransformerConfig | None = None):
        self.cfg = config or TransformerConfig()
        self.module = _TraceTransformerModule(self.cfg)
        self._score_packed_jit = None  # built lazily: donation is opt-in
        self._donate_inputs = False

    def enable_input_donation(self) -> None:
        """Opt this instance into donating packed input buffers on TPU
        (serving engine only — every engine call passes freshly
        materialized arrays). Must be called before the first
        ``score_packed`` to take effect on the compiled function."""
        self._donate_inputs = True
        self._score_packed_jit = None

    def init(self, rng: jax.Array, sample_cat=None, sample_cont=None,
             sample_mask=None):
        c = self.cfg
        if sample_cat is None:
            from ..features.featurizer import CAT_FIELDS, CONT_FIELDS
            width = len(CAT_FIELDS) + c.attr_slots
            sample_cat = jnp.zeros((1, c.max_len, width), jnp.int32)
            sample_cont = jnp.zeros((1, c.max_len, len(CONT_FIELDS)),
                                    jnp.float32)
            sample_mask = jnp.ones((1, c.max_len), bool)
        return self.module.init(rng, sample_cat, sample_cont, sample_mask)

    def apply(self, variables, categorical, continuous, mask,
              deterministic=True):
        return self.module.apply(variables, categorical, continuous, mask,
                                 deterministic)

    @partial(jax.jit, static_argnums=0)
    def score_spans(self, variables, categorical, continuous, mask):
        """(T, L) per-span anomaly probability + (T,) per-trace probability."""
        span_logit, trace_logit = self.apply(
            variables, categorical, continuous, mask)
        return jax.nn.sigmoid(span_logit), jax.nn.sigmoid(trace_logit)

    def _score_packed_impl(self, variables, categorical, continuous,
                           segments, positions):
        mask = segments > 0
        span_logit, _ = self.module.apply(
            variables, categorical, continuous, mask,
            positions=positions, segments=segments)
        return jax.nn.sigmoid(span_logit)

    def score_packed(self, variables, categorical, continuous, segments,
                     positions):
        """Packed-rows scoring (features.pack_sequences): block-diagonal
        attention per trace chunk; returns (R, L) span probabilities. The
        per-row trace head is meaningless under packing and skipped.

        Jitted lazily so the packed input buffers (not the variables —
        those persist across calls) can be donated on TPU when the owner
        opted in via ``enable_input_donation``: the serving engine
        re-materializes inputs every call, so their HBM can host the
        output instead of churning allocations at north-star call rates.
        """
        if self._score_packed_jit is None:
            self._score_packed_jit = jitstats.track_jit(
                "transformer.score_packed", jax.jit(
                    self._score_packed_impl,
                    donate_argnums=serving_donation((1, 2, 3, 4),
                                                    self._donate_inputs)))
        return self._score_packed_jit(variables, categorical, continuous,
                                      segments, positions)

    def loss_fn(self, variables, categorical, continuous, mask,
                span_labels, trace_labels, rngs=None):
        """Masked BCE on spans + BCE on traces (equal weight)."""
        span_logit, trace_logit = self.module.apply(
            variables, categorical, continuous, mask, deterministic=rngs is None,
            rngs=rngs)
        span_bce = optax_sigmoid_bce(span_logit, span_labels)
        m = mask.astype(jnp.float32)
        span_loss = (span_bce * m).sum() / jnp.maximum(m.sum(), 1.0)
        # all-padding rows (dp padding, trace-count buckets) must not train
        # the trace head: weight by per-trace validity
        valid = mask.any(-1).astype(jnp.float32)
        trace_bce = optax_sigmoid_bce(trace_logit, trace_labels)
        trace_loss = (trace_bce * valid).sum() / jnp.maximum(valid.sum(), 1.0)
        return span_loss + trace_loss


# compile accounting for the class-level jitted scoring entry (shared by
# every instance; __dict__ access skips any descriptor binding)
jitstats.track_jit("transformer.score_spans",
                   TraceTransformer.__dict__["score_spans"])


def optax_sigmoid_bce(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Numerically-stable sigmoid binary cross-entropy."""
    labels = labels.astype(jnp.float32)
    return jnp.maximum(logits, 0) - logits * labels + jnp.log1p(
        jnp.exp(-jnp.abs(logits)))
