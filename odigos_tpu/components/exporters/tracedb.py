"""Queryable in-memory trace store exporter — the simple-trace-db analog.

The reference's e2e scenarios assert by deploying simple-trace-db as a
Destination and querying it (tests/common/apply/
simple-trace-db-deployment.yaml:9, tests/common/simple_trace_db_query_runner.sh,
queries in tests/common/queries/*.yaml: wait-for-trace, span/resource
attributes, context propagation). This exporter plays that role in-process:
scenarios route telemetry to it through the full generated pipeline, then
assert with the query API below.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from ...pdata.spans import SpanBatch, concat_batches
from ..api import ComponentKind, Exporter, Factory, Signal, register


class TraceDbExporter(Exporter):
    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self._batches: list[SpanBatch] = []
        self._lock = threading.Lock()
        self._arrival = threading.Condition(self._lock)

    # ------------------------------------------------------------ pipeline

    def export(self, batch: SpanBatch) -> None:
        with self._arrival:
            self._batches.append(batch)
            self._arrival.notify_all()

    # ------------------------------------------------------------- queries

    def all_spans(self) -> SpanBatch:
        with self._lock:
            batches = list(self._batches)
        return concat_batches(batches) if batches else SpanBatch.empty()

    @property
    def span_count(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._batches)

    def wait_for_spans(self, n: int = 1, timeout: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._arrival:
            while self.span_count_locked() < n:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._arrival.wait(remaining)
        return True

    def span_count_locked(self) -> int:
        return sum(len(b) for b in self._batches)

    def wait_for_trace(self, service: str, min_spans: int = 1,
                       timeout: float = 10.0) -> Optional[SpanBatch]:
        """Wait until some trace containing a span of ``service`` has at
        least ``min_spans`` spans stored; returns that trace's spans
        (the wait-for-trace query)."""
        deadline = time.monotonic() + timeout
        seen_batches = -1
        while True:
            with self._arrival:
                # rescan only when new batches arrived (no busy-poll)
                while len(self._batches) == seen_batches:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._arrival.wait(remaining)
                seen_batches = len(self._batches)
            spans = self.all_spans()
            if not len(spans):
                continue
            services = np.asarray(spans.col("service"))
            svc_idx = [i for i, s in enumerate(spans.strings)
                       if s == service]
            if not svc_idx:
                continue
            hit = np.isin(services, svc_idx)
            for t in np.unique(spans.col("trace_id_lo")[hit]):
                trace = spans.filter(spans.col("trace_id_lo") == t)
                if len(trace) >= min_spans:
                    return trace

    def query(self, predicate: Callable[[dict[str, Any]], bool]
              ) -> list[dict[str, Any]]:
        """Span-dict filter (the span/resource-attribute query style)."""
        spans = self.all_spans()
        return [s for s in spans.iter_spans() if predicate(s)]

    def clear(self) -> None:
        with self._lock:
            self._batches = []


register(Factory(
    type_name="tracedb", kind=ComponentKind.EXPORTER,
    create=TraceDbExporter, signals=(Signal.TRACES,)))
