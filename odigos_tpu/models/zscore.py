"""Per-group latency z-score detector (BASELINE config #3).

The univariate baseline model: maintains streaming mean/variance of
log-duration per (service, operation) group and scores each span by |z|.
Everything is a jitted kernel over fixed-size state tables:

* state: three (G,) arrays — count, mean, M2 (Chan/Welford parallel merge);
* ``update``: batch-parallel Welford merge via segment_sum — one XLA scatter,
  no Python per span;
* ``score``: gather + normalize — one XLA gather.

Group id = hash-mix of (service_id, name_id) mod G, computed inside the
kernel so the whole path stays on device. G defaults to 8192 (tiny: 96 KiB of
state in f32 — lives comfortably in VMEM).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..features.featurizer import SpanFeatures
from . import jitstats

# see models/transformer.py: every jitted scoring entry point declares its
# recompile-bounding strategy (asserted by the package hygiene test)
SHAPE_BUCKETING = {
    "update_kernel": "state tables fixed at (n_groups,); the span axis of "
                     "the stateful update()/score() path is padded to "
                     "geometric buckets (span_bucket, 2x, 4x, ...) with a "
                     "weight mask so the engine's adaptive coalescer — "
                     "which emits deadline-sized, variable span counts — "
                     "compiles O(log max_batch) kernels, not one per size. "
                     "The functional *_fn forms stay exact-shape (tests "
                     "and fixed-size callers)",
    "update_masked_kernel": "the weighted form behind the padded path "
                            "(weights zero out pad rows in every "
                            "segment_sum, so padding never perturbs "
                            "the streaming state)",
    "score_kernel": "same bucketing via update_kernel's pad-and-slice "
                    "(shared (G,) state geometry; pad rows score garbage "
                    "that is sliced off before returning)",
}


class ZScoreState(NamedTuple):
    count: jax.Array  # (G,) float32
    mean: jax.Array   # (G,) float32
    m2: jax.Array     # (G,) float32


def _group_ids(categorical: jax.Array, n_groups: int) -> jax.Array:
    """(service, name) -> group id. Knuth multiplicative mix, on device."""
    svc = categorical[:, 0].astype(jnp.uint32)
    name = categorical[:, 1].astype(jnp.uint32)
    h = svc * jnp.uint32(2654435761) ^ (name * jnp.uint32(40503))
    return (h % jnp.uint32(n_groups)).astype(jnp.int32)


@partial(jax.jit, static_argnames=("n_groups",))
def _update_kernel(state: ZScoreState, categorical: jax.Array,
                   log_dur: jax.Array, n_groups: int) -> ZScoreState:
    gid = _group_ids(categorical, n_groups)
    ones = jnp.ones_like(log_dur)
    b_count = jax.ops.segment_sum(ones, gid, num_segments=n_groups)
    b_sum = jax.ops.segment_sum(log_dur, gid, num_segments=n_groups)
    safe = jnp.maximum(b_count, 1.0)
    b_mean = b_sum / safe
    b_m2 = jax.ops.segment_sum((log_dur - b_mean[gid]) ** 2, gid,
                               num_segments=n_groups)
    # Chan parallel merge of (count, mean, M2) pairs; reduces to the prior
    # state when n_b == 0 (b_mean is 0 there, but delta is multiplied by 0)
    n_a, n_b = state.count, b_count
    n_ab = n_a + n_b
    safe_ab = jnp.maximum(n_ab, 1.0)
    delta = b_mean - state.mean
    mean_ab = state.mean + delta * (n_b / safe_ab)
    m2_ab = state.m2 + b_m2 + delta**2 * (n_a * n_b / safe_ab)
    return ZScoreState(count=n_ab, mean=mean_ab, m2=m2_ab)


@partial(jax.jit, static_argnames=("n_groups", "min_count"))
def _score_kernel(state: ZScoreState, categorical: jax.Array,
                  log_dur: jax.Array, n_groups: int,
                  min_count: int) -> jax.Array:
    gid = _group_ids(categorical, n_groups)
    count = state.count[gid]
    mean = state.mean[gid]
    var = state.m2[gid] / jnp.maximum(count - 1.0, 1.0)
    std = jnp.sqrt(jnp.maximum(var, 1e-8))
    z = jnp.abs(log_dur - mean) / std
    # cold groups (not enough history) score 0 — never page on unknowns
    return jnp.where(count >= min_count, z, 0.0)


@partial(jax.jit, static_argnames=("n_groups",))
def _update_masked_kernel(state: ZScoreState, categorical: jax.Array,
                          log_dur: jax.Array, weights: jax.Array,
                          n_groups: int) -> ZScoreState:
    """The weighted Welford merge behind span-axis bucketing: pad rows
    carry weight 0, so every segment_sum term they touch contributes
    exactly +0.0 — the merged state is identical to the unpadded
    kernel's on the real rows."""
    gid = _group_ids(categorical, n_groups)
    b_count = jax.ops.segment_sum(weights, gid, num_segments=n_groups)
    b_sum = jax.ops.segment_sum(weights * log_dur, gid,
                                num_segments=n_groups)
    safe = jnp.maximum(b_count, 1.0)
    b_mean = b_sum / safe
    b_m2 = jax.ops.segment_sum(weights * (log_dur - b_mean[gid]) ** 2,
                               gid, num_segments=n_groups)
    n_a, n_b = state.count, b_count
    n_ab = n_a + n_b
    safe_ab = jnp.maximum(n_ab, 1.0)
    delta = b_mean - state.mean
    mean_ab = state.mean + delta * (n_b / safe_ab)
    m2_ab = state.m2 + b_m2 + delta**2 * (n_a * n_b / safe_ab)
    return ZScoreState(count=n_ab, mean=mean_ab, m2=m2_ab)


# compile accounting for the module-level jitted kernels (ISSUE 3
# device-runtime telemetry: jit cache size per site)
jitstats.track_jit("zscore.update", _update_kernel)
jitstats.track_jit("zscore.update_masked", _update_masked_kernel)
jitstats.track_jit("zscore.score", _score_kernel)


@dataclass
class ZScoreDetector:
    """Streaming z-score anomaly model.

    >>> det = ZScoreDetector()
    >>> det.update(features)           # fit on presumed-normal traffic
    >>> z = det.score(features)        # (n,) |z| per span
    """

    n_groups: int = 8192
    min_count: int = 32
    # span-axis shape bucket for the stateful update()/score() path:
    # inputs pad up to span_bucket, 2x, 4x, ... (0 = exact shapes). The
    # serving engine's adaptive coalescer emits deadline-sized batches of
    # near-arbitrary span counts; without bucketing every novel count
    # pays an XLA compile on the hot path (measured ~1.2 s per 64k-span
    # shape on CPU — the soak-tail pathology this bound removes)
    span_bucket: int = 4096

    def __post_init__(self) -> None:
        self.state = self.init()

    def init(self) -> ZScoreState:
        z = jnp.zeros(self.n_groups, jnp.float32)
        return ZScoreState(count=z, mean=z, m2=z)

    # -- functional kernels (used directly by the serving engine / tests)
    def update_fn(self, state: ZScoreState, categorical: jax.Array,
                  log_dur: jax.Array) -> ZScoreState:
        return _update_kernel(state, categorical, log_dur, self.n_groups)

    def score_fn(self, state: ZScoreState, categorical: jax.Array,
                 log_dur: jax.Array) -> jax.Array:
        return _score_kernel(state, categorical, log_dur, self.n_groups,
                             self.min_count)

    def _bucket_rows(self, n: int) -> int:
        """Geometric span bucket ≥ n: O(log max_batch) distinct shapes."""
        b = self.span_bucket
        while b < n:
            b <<= 1
        return b

    def warm(self, max_spans: int, cat_width: int) -> None:
        """Compile every span bucket up to ``max_spans`` ahead of
        serving. The masked update runs with all-zero weights, so every
        merge term contributes exactly +0.0 — warming is a pure compile,
        bit-safe on live state (the engine's adaptive coalescer will hit
        these shapes mid-stream otherwise, each a worker-stalling XLA
        compile). Warms ONE bucket past ``max_spans``: the engine's
        coalescer checks its cap before appending a request, so a group
        can end up to one request over it — that overshoot must land on
        a warmed shape too. (A SINGLE request larger than ``max_spans``
        can still exceed the warmed set — but such a batch pays its
        compile on the componentwise path identically; the wire
        receiver's byte budget bounds frame size in practice.)"""
        if not self.span_bucket:
            return
        b = self.span_bucket
        past = False
        while True:
            cat = jnp.zeros((b, cat_width), jnp.int32)
            ld = jnp.zeros(b, jnp.float32)
            state = _update_masked_kernel(self.state, cat, ld,
                                          jnp.zeros(b, jnp.float32),
                                          self.n_groups)
            np.asarray(state.count)  # block: compile finished
            np.asarray(self.score_fn(self.state, cat, ld))
            if past:
                return
            past = b >= max_spans
            b <<= 1

    # -- stateful convenience over SpanFeatures
    def update(self, features: SpanFeatures) -> None:
        cat = features.categorical
        log_dur = features.continuous[:, 0]
        n = cat.shape[0]
        if not self.span_bucket or n == 0:
            # same input-ownership rule as the bucketed branch below:
            # this update is async and the contiguous categorical view
            # may be pool-backed — copy before the zero-copy device_put
            self.state = self.update_fn(
                self.state, jnp.asarray(cat.copy() if n else cat),
                jnp.asarray(log_dur))
            return
        b = self._bucket_rows(n)
        pad = b - n
        if pad:
            cat = np.concatenate(
                [cat, np.zeros((pad, cat.shape[1]), cat.dtype)])
            log_dur = np.concatenate(
                [log_dur, np.zeros(pad, log_dur.dtype)])
        else:
            # own the categorical input: this update is dispatched async
            # and never blocked on, and jax's CPU client zero-copies
            # contiguous host arrays — a pool-backed features matrix
            # (ISSUE 12) could recycle mid-kernel otherwise. Exact-bucket
            # frames are the rare case; padded ones copied above anyway.
            cat = cat.copy()
        weights = np.zeros(b, np.float32)
        weights[:n] = 1.0
        self.state = _update_masked_kernel(
            self.state, jnp.asarray(cat), jnp.asarray(log_dur),
            jnp.asarray(weights), self.n_groups)

    def score(self, features: SpanFeatures) -> np.ndarray:
        cat = features.categorical
        log_dur = features.continuous[:, 0]
        n = cat.shape[0]
        if self.span_bucket and n:
            pad = self._bucket_rows(n) - n
            if pad:
                # pad rows score garbage against group 0's state and are
                # sliced off — the state is never touched by score()
                cat = np.concatenate(
                    [cat, np.zeros((pad, cat.shape[1]), cat.dtype)])
                log_dur = np.concatenate(
                    [log_dur, np.zeros(pad, log_dur.dtype)])
        z = self.score_fn(self.state, jnp.asarray(cat),
                          jnp.asarray(log_dur))
        return np.asarray(z)[:n]
