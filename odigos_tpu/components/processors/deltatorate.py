"""``deltatorate`` processor — delta SUM points to per-second rates.

Upstream's deltatorateprocessor (collector/builder-config.yaml): behind a
``cumulativetodelta`` stage, converts delta counters into per-second rate
gauges for backends that chart rates directly. Per-series state keyed the
same way as cumulativetodelta (name, resource service, sorted attrs); the
rate divides the delta by the wall-time since the series' previous point
(the upstream timestamp-delta behavior). The first observation of a
series has no interval and passes through unchanged as a SUM; zero or
negative intervals (clock skew, duplicate timestamps) leave the point
untouched rather than emitting an infinite rate.
"""

from __future__ import annotations

import threading
from typing import Any

import numpy as np

from ...pdata.metrics import MetricBatch, MetricType
from ..api import Capabilities, ComponentKind, Factory, Processor, register


class DeltaToRateProcessor(Processor):
    """Config: include (optional list of metric-name prefixes; default:
    every SUM metric)."""

    capabilities = Capabilities(mutates_data=True)

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self._last_t: dict[tuple, int] = {}  # series -> last time_unix_nano
        self._lock = threading.Lock()

    def _series_key(self, batch: MetricBatch, i: int, mname: str) -> tuple:
        ri = int(batch.col("resource_index")[i])
        res = (batch.resources[ri].get("service.name", "")
               if 0 <= ri < len(batch.resources) else "")
        attrs = tuple(sorted(
            (str(k), str(v)) for k, v in batch.point_attrs[i].items()))
        return (mname, res, attrs)

    def process(self, batch: Any) -> Any:
        if not isinstance(batch, MetricBatch) or not len(batch):
            return batch
        include = self.config.get("include")
        types = batch.col("type").copy()
        values = batch.col("value").copy()
        times = batch.col("time_unix_nano")
        names = batch.metric_names()
        changed = False
        with self._lock:
            for i in range(len(batch)):
                if int(types[i]) != MetricType.SUM:
                    continue
                if include and not any(names[i].startswith(p)
                                       for p in include):
                    continue
                key = self._series_key(batch, i, names[i])
                t = int(times[i])
                last_t = self._last_t.get(key)
                self._last_t[key] = t
                if last_t is None or t <= last_t:
                    continue  # no interval yet / non-advancing clock
                values[i] = float(values[i]) / ((t - last_t) / 1e9)
                types[i] = MetricType.GAUGE  # a rate is not monotonic
                changed = True
        if not changed:
            return batch
        from dataclasses import replace

        cols = dict(batch.columns)
        cols["value"] = values.astype(np.float64)
        cols["type"] = types.astype(np.int8)
        return replace(batch, columns=cols)


register(Factory(
    type_name="deltatorate",
    kind=ComponentKind.PROCESSOR,
    create=DeltaToRateProcessor,
    default_config=dict,
))
