"""``redaction`` processor — attribute allow-lists and value masking.

Upstream's redactionprocessor (collector/builder-config.yaml:78): drop
attributes not on an allow-list, mask attribute VALUES matching blocked
patterns (credit cards, keys...), and summarize what was redacted.  The
piimasking Action compiles to conditionalattributes (its own path);
this is the user-created ``Processor`` CR of type ``redaction``.

Config (upstream names)::

    redaction:
      allow_all_keys: true        # false => only allowed_keys survive
      allowed_keys: [http.method]
      ignored_keys: [safe.attr]   # never masked even if value matches
      blocked_values:             # regexes masked out of string values
        - "4[0-9]{12}(?:[0-9]{3})?"
      summary: info               # info | debug | silent

Applies to span attributes, log record attributes, and metric point
attributes, plus each batch's resource attributes — dict side-lists,
off the device path by design.
"""

from __future__ import annotations

import re
from dataclasses import replace
from typing import Any

from ..api import Capabilities, ComponentKind, Factory, Processor, register

MASK = "****"

REDACTED_COUNT_KEY = "redaction.masked.count"
REDACTED_KEYS_KEY = "redaction.masked.keys"


class RedactionProcessor(Processor):
    """See module docstring."""

    capabilities = Capabilities(mutates_data=True)

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self.allow_all_keys = bool(config.get("allow_all_keys", True))
        self.allowed = {str(k) for k in (config.get("allowed_keys") or [])}
        self.ignored = {str(k) for k in (config.get("ignored_keys") or [])}
        self.blocked = [re.compile(p)
                        for p in (config.get("blocked_values") or [])]
        summary = str(config.get("summary", "silent"))
        if summary not in ("info", "debug", "silent"):
            raise ValueError(
                f"redaction summary must be info|debug|silent, "
                f"got {summary!r}")
        self.summary = summary

    def _redact(self, d: dict[str, Any]) -> dict[str, Any] | None:
        """Returns the redacted copy, or None when unchanged."""
        deleted = [k for k in d
                   if not self.allow_all_keys and k not in self.allowed
                   and k not in self.ignored]
        masked = []
        for k, v in d.items():
            if k in deleted or k in self.ignored:
                continue
            if isinstance(v, str) and any(rx.search(v)
                                          for rx in self.blocked):
                masked.append(k)
        if not deleted and not masked:
            return None
        out = {k: v for k, v in d.items() if k not in deleted}
        for k in masked:
            out[k] = MASK
        if self.summary in ("info", "debug") and masked:
            out[REDACTED_COUNT_KEY] = len(masked)
            if self.summary == "debug":
                out[REDACTED_KEYS_KEY] = ",".join(sorted(masked))
        return out

    def _redact_list(self, dicts) -> tuple | None:
        changed = False
        out = []
        for d in dicts:
            r = self._redact(d)
            if r is None:
                out.append(d)
            else:
                out.append(r)
                changed = True
        return tuple(out) if changed else None

    def process(self, batch: Any) -> Any:
        if not len(batch):
            return batch
        fields = {}
        for attr_field in ("span_attrs", "record_attrs", "point_attrs",
                           "resources"):
            dicts = getattr(batch, attr_field, None)
            if dicts is None:
                continue
            redacted = self._redact_list(dicts)
            if redacted is not None:
                fields[attr_field] = redacted
        return replace(batch, **fields) if fields else batch


register(Factory(
    type_name="redaction",
    kind=ComponentKind.PROCESSOR,
    create=RedactionProcessor,
    default_config=lambda: {"allow_all_keys": True},
))
