"""Operator API layer (frontend/): HTTP/JSON over the store, SSE push, and
the collector-metrics consumer fed by the gateway's otlp/ui stream over the
real wire (VERDICT r1 item 5; reference: frontend/main.go:155,217 +
services/collector_metrics).
"""

import json
import threading
import urllib.request

import pytest

from odigos_tpu.components.api import Signal
from odigos_tpu.destinations import Destination
from odigos_tpu.e2e.environment import E2EEnvironment
from odigos_tpu.frontend import CollectorMetricsConsumer, FrontendServer
from odigos_tpu.frontend.collector_metrics import parse_flat_name
from odigos_tpu.pdata import synthesize_traces


def get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def post_json(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, json.loads(r.read())


# ------------------------------------------------------------- unit level
def test_parse_flat_name():
    assert parse_flat_name("x_total") == ("x_total", {})
    assert parse_flat_name("x_total{service=cart}") == (
        "x_total", {"service": "cart"})
    assert parse_flat_name("x{pipeline=traces/in,extra=1}") == (
        "x", {"pipeline": "traces/in", "extra": "1"})


def test_consumer_rates_from_counter_deltas():
    from odigos_tpu.components.receivers.prometheus import snapshot_to_batch

    c = CollectorMetricsConsumer()
    b1 = snapshot_to_batch({"odigos_traffic_spans_total{service=cart}": 100})
    c.consume(b1)
    # 10s later, 400 more spans
    import numpy as np

    b2 = snapshot_to_batch({"odigos_traffic_spans_total{service=cart}": 500})
    cols = dict(b2.columns)
    cols["time_unix_nano"] = b1.col("time_unix_nano") + np.uint64(10_000_000_000)
    from dataclasses import replace

    c.consume(replace(b2, columns=cols))
    tp = c.throughput()
    svc = tp["services"]["cart"]["odigos_traffic_spans_total"]
    assert svc["total"] == 500
    assert svc["per_sec"] == pytest.approx(40.0, rel=0.01)


# ---------------------------------------------------------------- e2e
@pytest.fixture
def env_with_frontend():
    env = E2EEnvironment(nodes=1)
    fe = FrontendServer(env.store, cluster=env.cluster).start()
    env.config.ui_endpoint = f"127.0.0.1:{fe.metrics_port}"
    env.start()
    try:
        yield env, fe
    finally:
        env.shutdown()
        fe.shutdown()


def test_api_reflects_store_and_metrics_flow(env_with_frontend):
    env, fe = env_with_frontend
    from odigos_tpu.controlplane.cluster import Container

    env.cluster.add_workload("shop", "cart",
                             [Container("main", language="python")])
    env.instrument_workload("shop", "cart")
    env.add_destination(Destination(
        id="db", dest_type="tracedb", signals=[Signal.TRACES]))

    base = fe.url
    assert get_json(f"{base}/healthz")["status"] == "ok"

    sources = get_json(f"{base}/api/sources")
    assert len(sources) == 1 and sources[0]["meta"]["name"] == "src-cart"

    ics = get_json(f"{base}/api/instrumentation-configs")
    assert len(ics) == 1
    assert any(c["type"] == "AgentEnabled" for c in ics[0]["conditions"])

    dests = get_json(f"{base}/api/destinations")
    assert len(dests) == 1 and dests[0]["dest_type"] == "tracedb"

    topo = get_json(f"{base}/api/pipeline")
    assert topo["pipelines"], "gateway topology empty"
    assert any(n["type"] == "odigostrafficmetrics" for n in topo["nodes"])

    # traffic through the gateway, then its self-scrape ships the
    # own-metrics batch over the wire to the frontend consumer
    env.send_traces(synthesize_traces(50, seed=1))
    scraper = env.gateway_component("prometheus/self-metrics")
    scraper.scrape_once()
    ui_exporter = env.gateway_component("otlp/ui")
    assert ui_exporter.flush(timeout=10), "otlp/ui did not drain"

    deadline = threading.Event()
    for _ in range(100):
        tp = get_json(f"{base}/api/metrics")
        if tp["batches_received"] > 0:
            break
        deadline.wait(0.05)
    assert tp["batches_received"] > 0, "no metrics batch reached frontend"
    totals = tp["pipelines"]
    assert any("odigos_traffic_spans_total" in m for m in totals.values()), totals

    anomalies = get_json(f"{base}/api/anomalies")
    assert "flagged" in anomalies and "scored" in anomalies

    desc = get_json(f"{base}/api/describe/workload?namespace=shop"
                    "&kind=deployment&name=cart")
    assert "MarkedForInstrumentation" in desc["text"]


def test_sse_stream_pushes_store_events(env_with_frontend):
    env, fe = env_with_frontend
    events = []
    got_one = threading.Event()

    def listen():
        req = urllib.request.Request(f"{fe.url}/api/events")
        with urllib.request.urlopen(req, timeout=15) as r:
            for raw in r:
                line = raw.decode().strip()
                if line.startswith("data: "):
                    events.append(json.loads(line[6:]))
                    got_one.set()
                    return

    t = threading.Thread(target=listen, daemon=True)
    t.start()
    import time

    time.sleep(0.3)  # let the client subscribe
    from odigos_tpu.controlplane.cluster import Container

    env.cluster.add_workload("shop", "web",
                             [Container("main", language="python")])
    env.instrument_workload("shop", "web")
    assert got_one.wait(10), "no SSE event received"
    assert events and events[0]["kind"]


def test_mutating_endpoints(env_with_frontend):
    env, fe = env_with_frontend
    from odigos_tpu.controlplane.cluster import Container

    env.cluster.add_workload("shop", "pay",
                             [Container("main", language="python")])
    status, out = post_json(f"{fe.url}/api/sources",
                            {"namespace": "shop", "name": "pay"})
    assert status == 201 and out["applied"] == "src-pay"
    env.reconcile()
    assert env.store.get("InstrumentationConfig", "shop",
                         "deployment-pay") is not None

    req = urllib.request.Request(f"{fe.url}/api/sources/shop/src-pay",
                                 method="DELETE")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 200
    assert env.store.get("Source", "shop", "src-pay") is None


def test_dashboard_page_serves(env_with_frontend):
    """The webapp analog: the dashboard page serves at / and wires itself to
    the data endpoints the page's JS polls (VERDICT r2 item 2)."""
    env, fe = env_with_frontend
    with urllib.request.urlopen(fe.url + "/", timeout=10) as r:
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/html")
        page = r.read().decode()
    # every endpoint the page polls must exist and round-trip
    for endpoint in ("/api/pipeline", "/api/metrics", "/api/anomalies",
                     "/api/sources", "/api/destinations", "/api/events"):
        assert endpoint in page, f"dashboard does not reference {endpoint}"
        if endpoint != "/api/events":
            get_json(fe.url + endpoint)  # 200 + JSON body
    for element in ("pipeline", "throughput", "anomalies", "eventlog",
                    "tiles"):
        assert f'id="{element}"' in page
    # /dashboard is an alias
    with urllib.request.urlopen(fe.url + "/dashboard", timeout=10) as r:
        assert r.read().decode() == page


def test_sse_client_cap_sheds_excess(env_with_frontend):
    env, fe = env_with_frontend
    fe.max_sse_clients = 2
    import time

    held = []
    try:
        for _ in range(2):
            held.append(urllib.request.urlopen(
                f"{fe.url}/api/events", timeout=10))
        time.sleep(0.2)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{fe.url}/api/events", timeout=10)
        assert ei.value.code == 503
    finally:
        for h in held:
            h.close()


def test_sse_heartbeat_frees_dead_client(env_with_frontend):
    """A silently-disconnected SSE client is detected by the ping write and
    unsubscribed (round-2 advisor finding: handler threads leaked)."""
    env, fe = env_with_frontend
    fe.sse_heartbeat_s = 0.1
    import time

    conn = urllib.request.urlopen(f"{fe.url}/api/events", timeout=10)
    deadline = time.time() + 5
    while not fe._sse_clients and time.time() < deadline:
        time.sleep(0.02)
    assert len(fe._sse_clients) == 1
    conn.close()  # client vanishes without a byte
    deadline = time.time() + 5
    while fe._sse_clients and time.time() < deadline:
        time.sleep(0.05)
    assert not fe._sse_clients, "dead SSE client never unsubscribed"


def test_series_rate_resets_on_counter_reset():
    """Collector restart: the cumulative counter drops; the stale rate must
    not be reported forever (round-2 advisor finding)."""
    from odigos_tpu.frontend.collector_metrics import _Series

    s = _Series()
    s.observe(100.0, 10.0)
    s.observe(500.0, 20.0)
    assert s.rate == pytest.approx(40.0)
    s.observe(50.0, 30.0)  # restart: counter went backwards
    assert s.rate == 0.0
    s.observe(150.0, 40.0)  # rates resume from the new baseline
    assert s.rate == pytest.approx(10.0)


def test_dashboard_source_form_and_sparkline_wiring(env_with_frontend):
    """The dashboard carries the sources CRUD form (wired to the POST/
    DELETE endpoints) and the throughput sparkline."""
    env, fe = env_with_frontend
    with urllib.request.urlopen(fe.url + "/", timeout=10) as r:
        page = r.read().decode()
    for element in ('id="src-add"', 'id="src-ns"', 'id="src-name"',
                    "data-del-src", "sparkline", 'method: "POST"',
                    '{method: "DELETE"}'):
        assert element in page, f"dashboard missing {element}"


def test_delete_source_with_encoded_name(env_with_frontend):
    """Percent-encoded DELETE paths decode server-side: a workload name
    with a space is removable from the dashboard (review finding)."""
    env, fe = env_with_frontend
    status, _ = post_json(f"{fe.url}/api/sources",
                          {"namespace": "shop", "name": "my app"})
    assert status == 201
    req = urllib.request.Request(
        f"{fe.url}/api/sources/shop/src-my%20app", method="DELETE")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 200
    assert env.store.get("Source", "shop", "src-my app") is None


def test_destination_secret_env_lifecycle_over_socket(monkeypatch):
    """Env-secret delivery/revocation through the JSON API (round-4
    advisor, medium): env names are type-scoped, so deleting one of two
    same-type destinations must keep the survivor's credential; deleting
    the last one revokes exactly what the server delivered — never an
    ambient operator env var."""
    import os

    from odigos_tpu.api.store import Store

    monkeypatch.delenv("DATADOG_API_KEY", raising=False)
    fe = FrontendServer(Store(), metrics_port=None).start()
    base = fe.url
    try:
        def delete(path):
            req = urllib.request.Request(base + path, method="DELETE")
            with urllib.request.urlopen(req, timeout=10) as r:
                body = json.loads(r.read())
                # the response names the deleted DESTINATION (clients
                # confirm against it), never an env-var name
                assert body["deleted"] == path.rsplit("/", 1)[-1], body
                return r.status

        status, _ = post_json(f"{base}/api/destinations", {
            "name": "dd-a", "type": "datadog", "signals": ["traces"],
            "fields": {"DATADOG_SITE": "datadoghq.com",
                       "DATADOG_API_KEY": "delivered-key"}})
        assert status == 201
        assert os.environ["DATADOG_API_KEY"] == "delivered-key"
        # dd-b rides the already-delivered credential (no key supplied)
        status, _ = post_json(f"{base}/api/destinations", {
            "name": "dd-b", "type": "datadog", "signals": ["traces"],
            "fields": {"DATADOG_SITE": "datadoghq.eu"}})
        assert status == 201
        assert delete("/api/destinations/dd-a") == 200
        assert os.environ.get("DATADOG_API_KEY") == "delivered-key", \
            "survivor's shared credential revoked"
        assert delete("/api/destinations/dd-b") == 200
        assert "DATADOG_API_KEY" not in os.environ, \
            "delivered credential lingered after last same-type delete"

        # ambient env vars the server never delivered are never popped
        monkeypatch.setenv("DATADOG_API_KEY", "operator-ambient")
        status, _ = post_json(f"{base}/api/destinations", {
            "name": "dd-c", "type": "datadog", "signals": ["traces"],
            "fields": {"DATADOG_SITE": "datadoghq.com"}})
        assert status == 201
        assert delete("/api/destinations/dd-c") == 200
        assert os.environ.get("DATADOG_API_KEY") == "operator-ambient"
    finally:
        fe.shutdown()


class TestFrontendAuth:
    """Bearer/session middleware (VERDICT r4 item 6; reference OIDC
    middleware frontend/main.go:130): with auth configured, mutations
    and SSE require a token; reads stay open; open servers unchanged."""

    def _server(self, token="s3ss10n"):
        from odigos_tpu.api.store import Store

        return FrontendServer(Store(), metrics_port=None,
                              auth_token=token).start()

    def _post(self, url, body, token=None):
        headers = {"Content-Type": "application/json"}
        if token:
            headers["Authorization"] = f"Bearer {token}"
        req = urllib.request.Request(
            url, data=json.dumps(body).encode(), headers=headers,
            method="POST")
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code

    def test_unauthenticated_mutation_rejected_401(self):
        import urllib.error

        fe = self._server()
        try:
            body = {"namespace": "shop", "name": "cart"}
            assert self._post(f"{fe.url}/api/sources", body) == 401
            # wrong token also rejected
            assert self._post(f"{fe.url}/api/sources", body,
                              token="wrong") == 401
            # right token accepted
            assert self._post(f"{fe.url}/api/sources", body,
                              token="s3ss10n") == 201
            # DELETE gated too
            req = urllib.request.Request(
                f"{fe.url}/api/sources/shop/src-cart", method="DELETE")
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    status = r.status
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 401
        finally:
            fe.shutdown()

    def test_reads_stay_open_and_sse_requires_token(self):
        import urllib.error

        fe = self._server()
        try:
            assert get_json(f"{fe.url}/healthz")["status"] == "ok"
            assert get_json(f"{fe.url}/api/sources") == []
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{fe.url}/api/events", timeout=10)
            assert ei.value.code == 401
            # EventSource cannot set headers: query token accepted
            req = urllib.request.urlopen(
                f"{fe.url}/api/events?token=s3ss10n", timeout=10)
            assert req.status == 200
            req.close()
        finally:
            fe.shutdown()

    def test_forged_jwt_rejected(self):
        """utils/auth validates claims, not signatures (entitlement
        parser) — a well-formed JWT must NOT satisfy the auth gate, or
        anyone could forge one (round-5 review, security)."""
        from tests.test_auth import make_token

        fe = self._server(token="static-secret")
        try:
            jwt = make_token()  # valid claims, no verifiable signature
            assert self._post(f"{fe.url}/api/sources",
                              {"namespace": "n", "name": "w"},
                              token=jwt) == 401
        finally:
            fe.shutdown()

    def test_open_server_requires_nothing(self):
        from odigos_tpu.api.store import Store

        fe = FrontendServer(Store(), metrics_port=None).start()
        try:
            assert self._post(f"{fe.url}/api/sources",
                              {"namespace": "n", "name": "w"}) == 201
        finally:
            fe.shutdown()
