from . import synthetic  # noqa: F401  (registers factories on import)
