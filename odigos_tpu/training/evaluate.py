"""ROC-AUC evaluation on injected faults.

The simple-trace-db query-assertion analog (SURVEY.md §4 item 4): generate a
held-out faulty stream, score spans with a detector, and measure span-level
ROC-AUC against the injected ground truth. North-star acceptance is
AUC >= 0.95 (BASELINE.json).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ..features import assemble_sequences, featurize
from ..pdata import inject_faults, synthesize_traces
from ..pdata.spans import SpanBatch


def roc_auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Rank-statistic AUC (Mann-Whitney U), ties handled by midranks."""
    labels = np.asarray(labels, dtype=bool)
    scores = np.asarray(scores, dtype=np.float64)
    n_pos = int(labels.sum())
    n_neg = labels.size - n_pos
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty_like(scores)
    ranks[order] = np.arange(1, scores.size + 1, dtype=np.float64)
    # midranks for ties
    sorted_scores = scores[order]
    i = 0
    while i < scores.size:
        j = i
        while j + 1 < scores.size and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = (i + j + 2) / 2.0
        i = j + 1
    u = ranks[labels].sum() - n_pos * (n_pos + 1) / 2.0
    return float(u / (n_pos * n_neg))


# A scorer maps (batch, labels-aligned arrays) -> per-span anomaly scores.
Scorer = Callable[[SpanBatch], np.ndarray]


def transformer_scorer(model, variables, *, max_len: int = 32) -> Scorer:
    """Adapt a trained TraceTransformer to a per-span scorer."""
    import jax.numpy as jnp

    def score(batch: SpanBatch) -> np.ndarray:
        feats = featurize(batch)
        seqs = assemble_sequences(batch, feats, max_len=max_len)
        span_scores, _trace_scores = model.score_spans(
            variables, jnp.asarray(seqs.categorical),
            jnp.asarray(seqs.continuous), jnp.asarray(seqs.mask))
        out = np.zeros(len(batch), dtype=np.float32)
        idx = seqs.span_index
        valid = idx >= 0
        out[idx[valid]] = np.asarray(span_scores)[valid]
        return out

    return score


def quantized_transformer_scorer(model, variables, *, max_len: int = 32
                                 ) -> Scorer:
    """Adapt the int8 serving path (models/quantized.py) to a per-span
    scorer — lets the injected-fault AUC bar apply to quantized serving
    exactly as it does to the float path."""
    import jax.numpy as jnp

    from ..features import pack_sequences
    from ..models.quantized import QuantizedTraceScorer

    scorer = QuantizedTraceScorer(model, variables)

    def score(batch: SpanBatch) -> np.ndarray:
        feats = featurize(batch)
        p = pack_sequences(batch, feats, max_len=max_len)
        probs = np.asarray(scorer.score_packed(
            jnp.asarray(p.categorical), jnp.asarray(p.continuous),
            jnp.asarray(p.segments), jnp.asarray(p.positions)))
        out = np.zeros(len(batch), dtype=np.float32)
        idx = p.span_index
        valid = idx >= 0
        out[idx[valid]] = probs[valid]
        return out

    return score


def zscore_scorer(detector, *, warmup_batch: Optional[SpanBatch] = None
                  ) -> Scorer:
    if warmup_batch is not None:
        detector.update(featurize(warmup_batch))

    def score(batch: SpanBatch) -> np.ndarray:
        return np.abs(np.asarray(detector.score(featurize(batch))))

    return score


def evaluate_detector(scorer: Scorer, *, n_traces: int = 2000,
                      fault_fraction: float = 0.1, seed: int = 1000,
                      kinds: Optional[tuple[str, ...]] = None
                      ) -> dict[str, Any]:
    """Held-out evaluation; returns {"auc", "auc_by_kind", n_spans, n_pos}."""
    clean = synthesize_traces(n_traces, seed=seed)
    kwargs = {"kinds": kinds} if kinds else {}
    batch, labels, reports = inject_faults(
        clean, fault_fraction=fault_fraction, seed=seed + 1, **kwargs)
    scores = scorer(batch)
    result = {
        "auc": roc_auc(labels, scores),
        "n_spans": int(len(batch)),
        "n_pos": int(labels.sum()),
        "auc_by_kind": {},
    }
    trace_lo = batch.col("trace_id_lo")
    faulty_traces_by_kind: dict[str, set[int]] = {}
    all_faulty = set()
    for r in reports:
        faulty_traces_by_kind.setdefault(r.kind, set()).add(r.trace_id_lo)
        all_faulty.add(r.trace_id_lo)
    for kind, traces in sorted(faulty_traces_by_kind.items()):
        # kind AUC: spans of this kind's traces vs all clean spans
        keep = np.isin(trace_lo, list(traces)) | ~np.isin(
            trace_lo, list(all_faulty))
        result["auc_by_kind"][kind] = roc_auc(labels[keep], scores[keep])
    return result
