"""Package hygiene: every module in odigos_tpu is imported from somewhere
(no dead modules — VERDICT r2 item 9's CI check), and the feature-gate
system actually gates behavior."""

import ast
import os

import pytest

PKG_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "odigos_tpu")
REPO_ROOT = os.path.dirname(PKG_ROOT)

# modules that are entrypoints by design: imported by the interpreter
# (python -m) or the driver, not by other modules
ENTRYPOINTS = {"odigos_tpu.cli.__main__", "odigos_tpu.pipeline.__main__"}


def _module_name(path: str) -> str:
    rel = os.path.relpath(path, REPO_ROOT)
    mod = rel[:-3].replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _imports_of(path: str, mod: str) -> set:
    """Absolute module names this file imports (relative resolved)."""
    with open(path) as f:
        tree = ast.parse(f.read(), path)
    pkg_parts = mod.split(".")
    if not path.endswith("__init__.py"):
        pkg_parts = pkg_parts[:-1]
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out.add(a.name)
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                parent = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                base = ".".join(parent + ([node.module] if node.module
                                          else []))
            if base:
                out.add(base)
            for a in node.names:
                out.add(f"{base}.{a.name}" if base else a.name)
    return out


def test_every_module_is_imported_somewhere():
    files = {}
    for dirpath, _dirs, names in os.walk(PKG_ROOT):
        for n in names:
            if n.endswith(".py"):
                p = os.path.join(dirpath, n)
                files[_module_name(p)] = p
    # tests and the driver entry also count as importers
    extra = [os.path.join(REPO_ROOT, "bench.py"),
             os.path.join(REPO_ROOT, "__graft_entry__.py")]
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    extra += [os.path.join(tests_dir, n) for n in os.listdir(tests_dir)
              if n.endswith(".py")]

    imported: set = set()
    for mod, path in files.items():
        imported |= _imports_of(path, mod)
    for path in extra:
        imported |= _imports_of(path, _module_name(path))

    orphans = []
    for mod in files:
        if mod == "odigos_tpu" or mod in ENTRYPOINTS:
            continue
        if mod in imported:
            continue
        # a package is live if any of its submodules is imported (the
        # import necessarily executes the package __init__)
        if files[mod].endswith("__init__.py") and any(
                i.startswith(mod + ".") for i in imported):
            continue
        # `from pkg import submodule` arrives as pkg.submodule above, but
        # `import pkg` alone also loads __init__ re-exports — accept a
        # parent-package import only for modules the parent re-exports
        parent = mod.rsplit(".", 1)[0]
        leaf = mod.rsplit(".", 1)[1]
        init = files.get(parent)
        if init and parent in imported:
            if f".{leaf}" in open(init).read():
                continue
        orphans.append(mod)
    assert not orphans, f"modules nothing imports (dead weight): {orphans}"


class TestJitShapeBucketing:
    """Every jitted scoring/training entry point in ``models/`` and
    ``parallel/`` must declare its shape-bucketing strategy (ISSUE 2
    satellite): an undeclared ``jax.jit`` path is an unbounded-recompile
    hazard — each novel input shape silently pays an XLA compile on the
    serving hot path. The contract: a module that jits exports a
    module-level ``SHAPE_BUCKETING`` dict, and every jit site resolves to
    one of its keys (the decorated/wrapped function name, the enclosing
    factory, or the lazy ``self._<name>_jit`` attribute, underscores and
    the ``_jit``/``_impl``/``_kernel`` suffixes stripped)."""

    JIT_DIRS = ("models", "parallel")

    @staticmethod
    def _is_jit_call(node: ast.AST) -> bool:
        """jax.jit(...) or partial(jax.jit, ...) in decorator/call form."""
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "jit":
            return True
        if isinstance(f, ast.Name) and f.id == "partial" and node.args:
            a = node.args[0]
            return isinstance(a, ast.Attribute) and a.attr == "jit"
        return False

    @classmethod
    def _jit_sites(cls, tree: ast.Module) -> list[tuple[int, set]]:
        """(lineno, candidate names) per jit site: enclosing defs plus any
        assignment target of the jit(...) call."""
        parents: dict = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        sites = []
        for node in ast.walk(tree):
            is_site = False
            names: set = set()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(cls._is_jit_call(d) or
                       (isinstance(d, ast.Attribute) and d.attr == "jit")
                       for d in node.decorator_list):
                    is_site = True
            elif cls._is_jit_call(node):
                # every jit(...) call is a site — assigned, returned, or
                # passed straight through (the `return jax.jit(fn)` factory
                # idiom must not escape the declaration contract)
                is_site = True
                p = parents.get(node)
                if isinstance(p, ast.Assign):
                    for t in p.targets:
                        if isinstance(t, ast.Attribute):
                            names.add(t.attr)
                        elif isinstance(t, ast.Name):
                            names.add(t.id)
            if not is_site:
                continue
            cur = node
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    names.add(cur.name)
                cur = parents.get(cur)
            sites.append((node.lineno, names))
        return sites

    @staticmethod
    def _normalize(name: str) -> str:
        name = name.strip("_")
        for suffix in ("_jit", "_impl", "_kernel"):
            if name.endswith(suffix):
                name = name[: -len(suffix)]
        return name.strip("_")

    def test_every_jit_path_declares_bucketing_strategy(self):
        problems = []
        for sub in self.JIT_DIRS:
            root = os.path.join(PKG_ROOT, sub)
            for fn in sorted(os.listdir(root)):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(root, fn)
                with open(path) as f:
                    src = f.read()
                if "jax.jit" not in src:
                    continue
                tree = ast.parse(src, path)
                declared = None
                for node in tree.body:
                    if isinstance(node, ast.Assign) and any(
                            isinstance(t, ast.Name) and
                            t.id == "SHAPE_BUCKETING"
                            for t in node.targets):
                        declared = ast.literal_eval(node.value)
                if declared is None:
                    problems.append(
                        f"{sub}/{fn}: jits but exports no SHAPE_BUCKETING")
                    continue
                assert all(isinstance(v, str) and v
                           for v in declared.values()), \
                    f"{sub}/{fn}: SHAPE_BUCKETING values must be non-empty"
                keys = {self._normalize(k) for k in declared}
                for lineno, names in self._jit_sites(tree):
                    cands = {self._normalize(n) for n in names}
                    if not (cands & keys):
                        problems.append(
                            f"{sub}/{fn}:{lineno}: jit site "
                            f"{sorted(names)} has no SHAPE_BUCKETING entry")
        assert not problems, (
            "jit paths without a declared shape-bucketing strategy "
            "(unbounded-recompile hazard):\n  " + "\n  ".join(problems))


class TestFeatureGates:
    def test_gate_stages_by_version(self):
        from odigos_tpu.utils.feature import Features

        old = Features(k8s_version="1.28", jax_version="0.3")
        new = Features(k8s_version="1.34", jax_version="0.6")
        assert not old.enabled("shard-map-scoring")
        assert new.enabled("shard-map-scoring")
        assert old.stage("native-sidecar-containers") == "alpha"
        assert not old.enabled("native-sidecar-containers")  # alpha opt-in
        assert Features(k8s_version="1.28",
                        enable_alpha=True).enabled(
                            "native-sidecar-containers")
        assert new.stage("native-sidecar-containers") == "ga"

    def test_effective_config_clamps_dp_without_gate(self, monkeypatch):
        import odigos_tpu.config.effective as eff_mod
        from odigos_tpu.config.effective import calculate_effective_config
        from odigos_tpu.config.model import Configuration

        monkeypatch.setattr(eff_mod, "_jax_version", lambda: "0.3")
        cfg = Configuration()
        cfg.anomaly.enabled = True
        cfg.anomaly.devices = 8
        eff = calculate_effective_config(cfg)
        assert eff.config.anomaly.devices == 1
        assert any("shard-map-scoring" in p for p in eff.problems)
        assert eff.features["shard-map-scoring"]["enabled"] is False

    def test_effective_config_keeps_dp_with_gate(self):
        from odigos_tpu.config.effective import calculate_effective_config
        from odigos_tpu.config.model import Configuration

        cfg = Configuration()
        cfg.anomaly.enabled = True
        cfg.anomaly.devices = 8
        eff = calculate_effective_config(cfg)  # real jax is new enough
        assert eff.config.anomaly.devices == 8
        assert eff.features["shard-map-scoring"]["enabled"] is True

    def test_snapshot_lands_in_effective_configmap(self):
        from odigos_tpu.api import ControllerManager, Store
        from odigos_tpu.config.model import Configuration
        from odigos_tpu.controlplane import Scheduler
        from odigos_tpu.controlplane.scheduler import (
            EFFECTIVE_CONFIG_NAME, ODIGOS_NAMESPACE)

        store = Store()
        mgr = ControllerManager(store)
        sched = Scheduler(store, mgr)
        sched.apply_authored(Configuration())
        mgr.run_once()
        cm = store.get("ConfigMap", ODIGOS_NAMESPACE, EFFECTIVE_CONFIG_NAME)
        assert cm is not None and "features" in cm.data
        assert "shard-map-scoring" in cm.data["features"]


class TestComponentObservability:
    """Every registered data-path component factory must record at least
    one own-telemetry metric or span (ISSUE 1 satellite): a component
    whose class hierarchy never touches ``meter`` or ``tracer`` ships
    invisible to the self-telemetry pipeline, /metrics, and the diagnose
    bundle. Static import-and-inspect — no runtime pipeline needed.

    Components inheriting the instrumented ``Processor.consume`` /
    ``Exporter.consume`` weave pass through their base class; components
    that OVERRIDE consume (stateful batching, memory limiting, routing)
    must record their own metric or span. Extensions are exempt: they sit
    outside the data path (health/zpages/pprof serve diagnostics, they do
    not carry batches)."""

    DATA_PATH_KINDS = ("receiver", "processor", "exporter", "connector")
    MARKERS = ("meter.", "tracer.")

    def test_every_component_factory_records_own_telemetry(self):
        import inspect

        import odigos_tpu.components  # noqa: F401  (registers factories)
        from odigos_tpu.components.api import registry

        unobservable = []
        for (kind, type_name), factory in sorted(
                registry._factories.items(),
                key=lambda kv: (kv[0][0].value, kv[0][1])):
            if kind.value not in self.DATA_PATH_KINDS:
                continue
            create = factory.create
            classes = getattr(create, "__mro__", None) or [create]
            blob = []
            for cls in classes:
                if getattr(cls, "__module__", "").startswith("odigos_tpu"):
                    try:
                        blob.append(inspect.getsource(cls))
                    except (OSError, TypeError):
                        pass
            source = "\n".join(blob)
            if not any(m in source for m in self.MARKERS):
                unobservable.append(f"{kind.value}/{type_name} "
                                    f"({create!r})")
        assert not unobservable, (
            "components with no own-telemetry metric or span — add a "
            "meter counter or tracer span before registering:\n  "
            + "\n  ".join(unobservable))
