"""Forward connector — 1:N pipeline bridge.

The reference composes destination pipelines with `forward/<dest>` connectors
(common/pipelinegen/config_builder.go:99-108). Ours passes batches through to
every configured output pipeline unchanged.
"""

from __future__ import annotations

from ...pdata.spans import SpanBatch
from ...utils.telemetry import labeled_key, meter
from ..api import ComponentKind, Connector, Factory, register


class ForwardConnector(Connector):
    def __init__(self, name, config):
        super().__init__(name, config)
        self._spans_metric = labeled_key(
            "odigos_connector_spans_total", connector=name)

    def consume(self, batch: SpanBatch) -> None:
        meter.add(self._spans_metric, len(batch))
        for consumer in self.outputs.values():
            consumer.consume(batch)


register(Factory(
    type_name="forward",
    kind=ComponentKind.CONNECTOR,
    create=ForwardConnector,
    default_config=dict,
))
