"""``hostmetrics`` receiver — node-level system metrics scraper.

Reference: the upstream hostmetrics receiver shipped in the collector
distro (collector/builder-config.yaml:94) configured by
autoscaler/controllers/nodecollector/collectorconfig/metrics.go:33-70 with
the scraper set {cpu, paging, disk, filesystem, load, memory, network,
processes}. This is the TPU-native analog: one psutil pass per interval
producing an otel-semconv MetricBatch (system.cpu.utilization,
system.memory.usage, ...), no cgo/hostfs mount — psutil reads /proc
directly, which on the DaemonSet node collector is the host's /proc.

Scrapers are pure functions ``(builder, resource_index, now) -> None`` so
each is unit-testable without a thread; the receiver composes the
configured subset and ships one batch per interval.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Optional

from ...pdata.metrics import MetricBatch, MetricBatchBuilder, MetricType
from ...utils.telemetry import label_value, meter
from ..api import ComponentKind, Factory, Receiver, Signal, register

ERRORS_METRIC = "odigos_hostmetrics_scrape_errors_total"

_Scraper = Callable[[MetricBatchBuilder, int, int], None]


def _psutil():
    # lazy: psutil is only a dependency of a node collector that enables
    # hostmetrics, not of everything that imports the component registry
    import psutil
    return psutil


def _scrape_cpu(b: MetricBatchBuilder, res: int, now: int) -> None:
    psutil = _psutil()
    # system.cpu.utilization (metrics.go:46-50) + cumulative system.cpu.time
    times = psutil.cpu_times()
    for state in ("user", "system", "idle", "iowait"):
        v = getattr(times, state, None)
        if v is not None:
            b.add_point(name="system.cpu.time", value=float(v),
                        metric_type=MetricType.SUM, time_unix_nano=now,
                        attrs={"state": state}, resource_index=res)
    util = psutil.cpu_percent(interval=None) / 100.0
    b.add_point(name="system.cpu.utilization", value=util,
                metric_type=MetricType.GAUGE, time_unix_nano=now,
                resource_index=res)


def _scrape_load(b: MetricBatchBuilder, res: int, now: int) -> None:
    psutil = _psutil()
    la1, la5, la15 = psutil.getloadavg()
    for name, v in (("1m", la1), ("5m", la5), ("15m", la15)):
        b.add_point(name=f"system.cpu.load_average.{name}", value=float(v),
                    metric_type=MetricType.GAUGE, time_unix_nano=now,
                    resource_index=res)


def _scrape_memory(b: MetricBatchBuilder, res: int, now: int) -> None:
    psutil = _psutil()
    vm = psutil.virtual_memory()
    used = vm.total - vm.available
    for state, v in (("used", used), ("free", vm.available)):
        b.add_point(name="system.memory.usage", value=float(v),
                    metric_type=MetricType.GAUGE, time_unix_nano=now,
                    attrs={"state": state}, resource_index=res)
    b.add_point(name="system.memory.utilization",
                value=used / vm.total if vm.total else 0.0,
                metric_type=MetricType.GAUGE, time_unix_nano=now,
                resource_index=res)


def _scrape_paging(b: MetricBatchBuilder, res: int, now: int) -> None:
    psutil = _psutil()
    sm = psutil.swap_memory()
    b.add_point(name="system.paging.utilization",
                value=sm.percent / 100.0,
                metric_type=MetricType.GAUGE, time_unix_nano=now,
                resource_index=res)
    b.add_point(name="system.paging.usage", value=float(sm.used),
                metric_type=MetricType.GAUGE, time_unix_nano=now,
                attrs={"state": "used"}, resource_index=res)


def _scrape_disk(b: MetricBatchBuilder, res: int, now: int) -> None:
    psutil = _psutil()
    io = psutil.disk_io_counters()
    if io is None:  # containers without block-device visibility
        return
    for direction, v in (("read", io.read_bytes), ("write", io.write_bytes)):
        b.add_point(name="system.disk.io", value=float(v),
                    metric_type=MetricType.SUM, time_unix_nano=now,
                    attrs={"direction": direction}, resource_index=res)
    for direction, v in (("read", io.read_count), ("write", io.write_count)):
        b.add_point(name="system.disk.operations", value=float(v),
                    metric_type=MetricType.SUM, time_unix_nano=now,
                    attrs={"direction": direction}, resource_index=res)


def _scrape_filesystem(b: MetricBatchBuilder, res: int, now: int) -> None:
    psutil = _psutil()
    # metrics.go:53-63: utilization enabled, kubelet mounts excluded —
    # here we keep real (device-backed) mounts only, same intent
    seen: set[str] = set()
    for part in psutil.disk_partitions(all=False):
        if part.mountpoint in seen:
            continue
        seen.add(part.mountpoint)
        try:
            du = psutil.disk_usage(part.mountpoint)
        except OSError:
            continue
        attrs = {"mountpoint": part.mountpoint, "device": part.device}
        b.add_point(name="system.filesystem.utilization",
                    value=du.percent / 100.0,
                    metric_type=MetricType.GAUGE, time_unix_nano=now,
                    attrs=attrs, resource_index=res)
        b.add_point(name="system.filesystem.usage", value=float(du.used),
                    metric_type=MetricType.GAUGE, time_unix_nano=now,
                    attrs={**attrs, "state": "used"}, resource_index=res)


def _scrape_network(b: MetricBatchBuilder, res: int, now: int) -> None:
    psutil = _psutil()
    io = psutil.net_io_counters()
    for direction, v in (("receive", io.bytes_recv),
                         ("transmit", io.bytes_sent)):
        b.add_point(name="system.network.io", value=float(v),
                    metric_type=MetricType.SUM, time_unix_nano=now,
                    attrs={"direction": direction}, resource_index=res)
    for direction, v in (("receive", io.packets_recv),
                         ("transmit", io.packets_sent)):
        b.add_point(name="system.network.packets", value=float(v),
                    metric_type=MetricType.SUM, time_unix_nano=now,
                    attrs={"direction": direction}, resource_index=res)


def _scrape_processes(b: MetricBatchBuilder, res: int, now: int) -> None:
    psutil = _psutil()
    counts: dict[str, int] = {}
    for p in psutil.process_iter(["status"]):
        try:
            st = p.info["status"] or "unknown"
        except psutil.Error:
            continue
        counts[st] = counts.get(st, 0) + 1
    for status, n in sorted(counts.items()):
        b.add_point(name="system.processes.count", value=float(n),
                    metric_type=MetricType.GAUGE, time_unix_nano=now,
                    attrs={"status": status}, resource_index=res)


SCRAPERS: dict[str, _Scraper] = {
    "cpu": _scrape_cpu,
    "load": _scrape_load,
    "memory": _scrape_memory,
    "paging": _scrape_paging,
    "disk": _scrape_disk,
    "filesystem": _scrape_filesystem,
    "network": _scrape_network,
    "processes": _scrape_processes,
}

# metrics.go scraper block — the full set the reference enables
DEFAULT_SCRAPERS = tuple(SCRAPERS)


class HostMetricsReceiver(Receiver):
    """Config:
    collection_interval_s: scrape period (default 10)
    scrapers:              subset of SCRAPERS keys (default: all; unknown
                           names are a start()-time error, not silence)
    node:                  k8s.node.name resource value (default hostname)
    """

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._scrapers: list[tuple[str, _Scraper]] = []

    def start(self) -> None:
        super().start()
        wanted = self.config.get("scrapers") or list(DEFAULT_SCRAPERS)
        unknown = [w for w in wanted if w not in SCRAPERS]
        if unknown:
            raise ValueError(
                f"{self.name}: unknown hostmetrics scrapers {unknown} "
                f"(known: {sorted(SCRAPERS)})")
        self._scrapers = [(w, SCRAPERS[w]) for w in wanted]
        # prime the utilization delta so the first real scrape is meaningful
        _psutil().cpu_percent(interval=None)
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"hostmetrics-{self.name}")
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        super().shutdown()

    def scrape_once(self) -> MetricBatch:
        b = MetricBatchBuilder()
        # generated configs carry node: "${NODE_NAME}" (the DaemonSet
        # downward-API env); resolve it, never stamp the literal
        node = str(self.config.get("node", ""))
        if node.startswith("${") and node.endswith("}"):
            node = os.environ.get(node[2:-1], "")
        node = node or _hostname()
        res = b.add_resource({"k8s.node.name": node,
                              "service.name": "hostmetrics"})
        now = time.time_ns()
        for sname, fn in self._scrapers or [
                (w, SCRAPERS[w]) for w in DEFAULT_SCRAPERS]:
            try:
                fn(b, res, now)
            except Exception:
                meter.add(f"{ERRORS_METRIC}{{scraper={label_value(sname)}}}")
        batch = b.build()
        if len(batch):
            self.next_consumer.consume(batch)
        return batch

    def _run(self) -> None:
        interval = float(self.config.get("collection_interval_s", 10))
        while not self._stop.wait(interval):
            try:
                self.scrape_once()
            except Exception:
                meter.add(f"{ERRORS_METRIC}{{scraper=_batch}}")


def _hostname() -> str:
    try:
        return os.uname().nodename
    except Exception:
        return "unknown"


register(Factory(
    type_name="hostmetrics",
    kind=ComponentKind.RECEIVER,
    create=HostMetricsReceiver,
    signals=(Signal.METRICS,),
    default_config=lambda: {"collection_interval_s": 10,
                            "scrapers": list(DEFAULT_SCRAPERS)},
))
