"""Model training, checkpointing, and evaluation.

The reference has no training stage (telemetry flows through); this package
exists for the TPU anomaly models the north star adds (BASELINE configs
#3-#5). Checkpoint/resume is orbax-backed — the one genuinely *new*
durability requirement relative to the reference (SURVEY.md §5.4).
"""

from .data import LabeledSequences, labeled_sequences, training_stream  # noqa: F401
from .trainer import TrainConfig, Trainer, TrainResult  # noqa: F401
from .evaluate import evaluate_detector, roc_auc  # noqa: F401
from .checkpoint import (  # noqa: F401
    ServingBundle, load_bundle, make_model_config, restore_variables,
    save_bundle)
