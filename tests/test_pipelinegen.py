"""pipelinegen golden tests — the generated-config assertion discipline of
the reference (tests/e2e/data-streams/expected-datastreams-config.yaml and
common/config golden tests)."""

import pytest

from odigos_tpu.components.api import Signal
from odigos_tpu.config.model import AnomalyStageConfiguration
from odigos_tpu.destinations import Destination
from odigos_tpu.pipelinegen import (
    SourceRef,
    DataStream,
    DataStreamDestination,
    GatewayOptions,
    NodeCollectorOptions,
    build_gateway_config,
    build_node_collector_config,
)
from odigos_tpu.pipeline.graph import validate_config

T, M, L = Signal.TRACES, Signal.METRICS, Signal.LOGS


def dd(id="dd1", signals=(T, M, L)):
    return Destination(id=id, dest_type="datadog", signals=list(signals),
                       config={"DATADOG_SITE": "datadoghq.com"})


def jaeger(id="j1"):
    return Destination(id=id, dest_type="jaeger", signals=[T],
                       config={"JAEGER_URL": "jaeger:4317"})


def mock(id="m1", signals=(T,)):
    return Destination(id=id, dest_type="mock", signals=list(signals),
                       config={"MOCK_REJECT_FRACTION": "0",
                               "MOCK_RESPONSE_DURATION": "0"})


class TestGatewayConfig:
    def test_single_destination_shape(self):
        cfg, status, signals = build_gateway_config([jaeger()])
        assert signals == [T]
        assert status.destination["j1"] is None
        pipes = cfg["service"]["pipelines"]
        # root pipeline: otlp -> [memory_limiter, version] -> router
        root = pipes["traces/in"]
        assert root["receivers"] == ["otlp"]
        assert root["processors"][:2] == ["memory_limiter",
                                          "resource/odigos-version"]
        assert "odigosrouter/traces" in root["exporters"]
        # destination pipeline: forward connector -> batch -> exporter
        destp = pipes["traces/jaeger-j1"]
        assert destp["receivers"] == ["forward/traces/jaeger-j1"]
        assert "batch" in destp["processors"]
        assert destp["exporters"] == ["otlp/jaeger-j1"]

    def test_no_destinations_no_root_pipelines(self):
        cfg, _, signals = build_gateway_config([])
        assert signals == []
        assert "traces/in" not in cfg["service"]["pipelines"]

    def test_signals_enabled_per_destination_support(self):
        _, _, signals = build_gateway_config([jaeger()])
        assert signals == [T]
        _, _, signals = build_gateway_config([dd()])
        assert signals == [T, M, L]

    def test_data_stream_pipelines(self):
        streams = [DataStream("prod", (DataStreamDestination("dd1"),)),
                   DataStream("dev", (DataStreamDestination("j1"),))]
        cfg, _, _ = build_gateway_config([dd(), jaeger()], data_streams=streams)
        pipes = cfg["service"]["pipelines"]
        # prod stream: all three datadog signals
        assert pipes["traces/prod"]["receivers"] == ["odigosrouter/traces"]
        assert pipes["traces/prod"]["exporters"] == ["forward/traces/datadog-dd1"]
        assert pipes["metrics/prod"]["exporters"] == ["forward/metrics/datadog-dd1"]
        # dev stream: jaeger is traces-only -> no metrics/dev pipeline
        assert pipes["traces/dev"]["exporters"] == ["forward/traces/jaeger-j1"]
        assert "metrics/dev" not in pipes

    def test_router_carries_datastream_details(self):
        streams = [DataStream("prod", (DataStreamDestination("j1"),),
                              (SourceRef("ns1", "deployment", "frontend"),))]
        cfg, _, _ = build_gateway_config([jaeger()], data_streams=streams)
        conn = cfg["connectors"]["odigosrouter/traces"]
        assert conn["data_streams"] == [{
            "name": "prod",
            "sources": [{"namespace": "ns1", "kind": "deployment",
                         "name": "frontend"}],
            "pipelines": ["traces/prod"]}]
        assert conn["default_pipelines"] == []

    def test_default_stream_synthesized(self):
        cfg, _, _ = build_gateway_config([jaeger()])
        conn = cfg["connectors"]["odigosrouter/traces"]
        assert conn["default_pipelines"] == ["traces/default"]
        assert cfg["service"]["pipelines"]["traces/default"]["exporters"] == \
            ["forward/traces/jaeger-j1"]

    def test_failed_destination_reported_not_fatal(self):
        bad = Destination(id="dd-bad", dest_type="datadog", signals=[T])  # no site
        cfg, status, signals = build_gateway_config([bad, jaeger()])
        assert status.destination["dd-bad"] is not None
        assert status.destination["j1"] is None
        assert signals == [T]
        assert "traces/datadog-dd-bad" not in cfg["service"]["pipelines"]

    def test_servicegraph_insertion(self):
        cfg, _, _ = build_gateway_config([jaeger()])
        assert "servicegraph" in cfg["connectors"]
        root = cfg["service"]["pipelines"]["traces/in"]
        assert "servicegraph" in root["exporters"]
        sg = cfg["service"]["pipelines"]["metrics/servicegraph"]
        assert sg["receivers"] == ["servicegraph"]

    def test_servicegraph_disabled(self):
        cfg, _, _ = build_gateway_config(
            [jaeger()], options=GatewayOptions(service_graph_disabled=True))
        assert "servicegraph" not in cfg["connectors"]
        assert "metrics/servicegraph" not in cfg["service"]["pipelines"]

    def test_self_telemetry_appended_everywhere(self):
        cfg, _, _ = build_gateway_config([jaeger()])
        for pname, pipe in cfg["service"]["pipelines"].items():
            if pname in ("metrics/servicegraph", "metrics/otelcol"):
                continue
            pid = f"odigostrafficmetrics/{pname}"
            assert pipe["processors"][-1] == pid, pname
            # per-pipeline instance carries its pipeline label; per-service
            # ingest counters only on root pipelines (a span traverses
            # root -> data-stream; counting per hop would double the
            # hero-tile totals)
            pconf = cfg["processors"][pid]
            assert pconf["pipeline"] == pname
            assert pconf["per_service"] == pname.startswith("traces/in")
        assert "metrics/otelcol" in cfg["service"]["pipelines"]

    def test_small_batches_profile(self):
        cfg, _, _ = build_gateway_config(
            [dd()], options=GatewayOptions(
                small_batches={"send_batch_size": 100, "timeout_ms": 100}))
        tp = cfg["service"]["pipelines"]["traces/datadog-dd1"]
        assert "batch/small-batches" in tp["processors"]
        # metrics pipelines unaffected (traces-only behavior)
        mp = cfg["service"]["pipelines"]["metrics/datadog-dd1"]
        assert "batch/small-batches" not in mp["processors"]

    def test_user_processors_in_root_chain(self):
        procs = [{"id": "odigossampling/tail", "type": "odigossampling",
                  "signals": ["traces"], "config": {"rules": []}}]
        cfg, status, _ = build_gateway_config([jaeger()], processors=procs)
        assert status.processor["odigossampling/tail"] is None
        root = cfg["service"]["pipelines"]["traces/in"]
        assert "odigossampling/tail" in root["processors"]
        assert "odigossampling/tail" in cfg["processors"]


class TestAnomalyStage:
    def anomaly_opts(self, **kw):
        a = AnomalyStageConfiguration(enabled=True, **kw)
        return GatewayOptions(anomaly=a)

    def test_anomaly_disabled_is_byte_identical(self):
        """North-star hard requirement: anomaly off == stage absent."""
        base, _, _ = build_gateway_config([jaeger()])
        off, _, _ = build_gateway_config(
            [jaeger()], options=GatewayOptions(
                anomaly=AnomalyStageConfiguration(enabled=False)))
        assert base == off

    def test_anomaly_fast_path_renders_on_root_traces_pipeline(self):
        """anomaly.fast_path=True marks the root traces pipeline for the
        ingest fast path (deadline = the scoring timeout); off by
        default, and the rendered config still builds a valid graph
        with the fast-path route installed."""
        cfg, _, _ = build_gateway_config(
            [jaeger()], options=self.anomaly_opts(fast_path=True,
                                                  timeout_ms=25.0))
        root = cfg["service"]["pipelines"]["traces/in"]
        # lanes/ordered (ISSUE 9) + predictive (ISSUE 12): the
        # retirement and predictive-shed knobs render alongside the
        # deadline
        assert root["fast_path"] == {"deadline_ms": 25.0, "lanes": 4,
                                     "ordered": False,
                                     "predictive": True}
        from odigos_tpu.pipeline.graph import build_graph

        g = build_graph(cfg)
        assert "traces/in" in g.fastpaths
        # default stays componentwise — no fast_path key at all
        off, _, _ = build_gateway_config([jaeger()],
                                         options=self.anomaly_opts())
        assert "fast_path" not in off["service"]["pipelines"]["traces/in"]

    def test_anomaly_enabled_inserts_processor_and_router(self):
        cfg, _, _ = build_gateway_config([jaeger()], options=self.anomaly_opts())
        root = cfg["service"]["pipelines"]["traces/in"]
        assert "tpuanomaly" in root["processors"]
        # processor runs before the router hands data off
        assert "anomalyrouter" in root["exporters"]
        assert cfg["processors"]["tpuanomaly"]["model"] == "zscore"
        # anomaly stream pipeline fed by the anomalyrouter, fanning out to
        # every traces destination
        ap = cfg["service"]["pipelines"]["traces/anomalies"]
        assert ap["receivers"] == ["anomalyrouter"]
        assert "forward/traces/jaeger-j1" in ap["exporters"]
        assert cfg["connectors"]["anomalyrouter"]["anomaly_pipelines"] == \
            ["traces/anomalies"]
        assert cfg["connectors"]["anomalyrouter"]["mode"] == "trace"

    def test_anomaly_respects_existing_stream(self):
        streams = [DataStream("anomalies", (DataStreamDestination("j1"),)),
                   DataStream("default", (DataStreamDestination("j1"),
                                          DataStreamDestination("m9")))]
        cfg, _, _ = build_gateway_config(
            [jaeger(), mock("m9")], data_streams=streams,
            options=self.anomaly_opts())
        ap = cfg["service"]["pipelines"]["traces/anomalies"]
        # operator scoped the stream to jaeger only; mock not added
        assert ap["exporters"] == ["forward/traces/jaeger-j1"]
        # the scoped pipeline gains the anomalyrouter as a second receiver
        assert "anomalyrouter" in ap["receivers"]


class TestGeneratedConfigBuildable:
    def test_mock_only_config_is_graph_valid(self):
        """A config whose components all exist in our registry must pass
        static graph validation (receivers resolved, DAG acyclic)."""
        cfg, _, _ = build_gateway_config(
            [mock()], options=GatewayOptions(self_telemetry=False,
                                             service_graph_disabled=True))
        # swap the external otlp receiver for the in-process synthetic one
        cfg["receivers"] = {"synthetic": {}}
        for pipe in cfg["service"]["pipelines"].values():
            pipe["receivers"] = ["synthetic" if r == "otlp" else r
                                 for r in pipe["receivers"]]
        problems = validate_config(cfg)
        assert problems == [], problems

    def test_anomaly_config_is_graph_valid(self):
        cfg, _, _ = build_gateway_config(
            [mock()], options=GatewayOptions(
                self_telemetry=False, service_graph_disabled=True,
                anomaly=AnomalyStageConfiguration(enabled=True)))
        cfg["receivers"] = {"synthetic": {}}
        for pipe in cfg["service"]["pipelines"].values():
            pipe["receivers"] = ["synthetic" if r == "otlp" else r
                                 for r in pipe["receivers"]]
        problems = validate_config(cfg)
        assert problems == [], problems


class TestNodeCollectorConfig:
    def test_traces_loadbalancing(self):
        cfg = build_node_collector_config(NodeCollectorOptions())
        lb = cfg["exporters"]["loadbalancing/traces"]
        assert lb["routing_key"] == "traceID"
        assert lb["resolver"]["k8s"]["service"] == \
            "odigos-gateway.odigos-system"
        assert cfg["service"]["pipelines"]["traces"]["exporters"] == \
            ["loadbalancing/traces"]

    def test_no_loadbalancing_uses_plain_otlp(self):
        cfg = build_node_collector_config(
            NodeCollectorOptions(load_balancing=False))
        assert "loadbalancing/traces" not in cfg["exporters"]
        assert cfg["service"]["pipelines"]["traces"]["exporters"] == \
            ["otlp/gateway"]

    def test_span_metrics_connector(self):
        cfg = build_node_collector_config(NodeCollectorOptions(
            span_metrics_enabled=True,
            enabled_signals=(T, M)))
        assert "spanmetrics" in cfg["connectors"]
        assert "spanmetrics" in cfg["service"]["pipelines"]["traces"]["exporters"]
        assert "spanmetrics" in cfg["service"]["pipelines"]["metrics"]["receivers"]

    def test_logs_pipeline_gated(self):
        cfg = build_node_collector_config(NodeCollectorOptions(
            enabled_signals=(T, L), log_collection_enabled=True))
        logs = cfg["service"]["pipelines"]["logs"]
        assert "odigoslogsresourceattrs" in logs["processors"]
        cfg2 = build_node_collector_config(NodeCollectorOptions(
            enabled_signals=(T,), log_collection_enabled=True))
        assert "logs" not in cfg2["service"]["pipelines"]

    def test_own_metrics_always_present(self):
        cfg = build_node_collector_config(NodeCollectorOptions())
        assert "metrics/otelcol" in cfg["service"]["pipelines"]


class TestReviewRegressions:
    def test_failed_configer_leaves_no_orphans(self):
        # tempo endpoint set but username missing: recipe fails mid-mutation
        bad = Destination(id="g9", dest_type="grafanacloudtempo", signals=[T],
                          config={"GRAFANA_CLOUD_TEMPO_ENDPOINT": "t:443"})
        cfg, status, _ = build_gateway_config([bad, jaeger()])
        assert status.destination["g9"] is not None
        assert not any("g9" in e for e in cfg["exporters"])
        assert not any("g9" in e for e in cfg.get("extensions", {}))

    def test_node_spanmetrics_requires_traces(self):
        cfg = build_node_collector_config(NodeCollectorOptions(
            enabled_signals=(M,), span_metrics_enabled=True,
            host_metrics_enabled=True))
        assert "spanmetrics" not in cfg["connectors"]
        assert "spanmetrics" not in \
            cfg["service"]["pipelines"]["metrics"]["receivers"]

    def test_tpuanomaly_config_keys_match_processor_contract(self):
        from odigos_tpu.components.api import registry, ComponentKind
        cfg, _, _ = build_gateway_config(
            [jaeger()], options=GatewayOptions(
                anomaly=AnomalyStageConfiguration(enabled=True)))
        # the emitted config must build a working processor instance
        factory = registry.get(ComponentKind.PROCESSOR, "tpuanomaly")
        proc = factory.build("tpuanomaly", cfg["processors"]["tpuanomaly"])
        assert proc.engine_cfg.max_batch_spans == 4096
        assert proc.threshold == 0.8
