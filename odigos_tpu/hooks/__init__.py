"""Manual-enrichment hooks — the hooks/go analog.

Reference: hooks/go/go_hooks.go — helpers an instrumented application calls
to read the current W3C trace context (GetW3CTraceContext/GetTraceID/
GetSpanID + zero-context predicates) and enrich auto-instrumented traces
with manual spans (the gin helper's role). Here the same surface is a
Python API: a context-var-backed ``ManualTracer`` whose spans land in the
same ``SpanBatch`` pdata the auto-instrumentation path produces, so they
flow through an ordinary exporter/ring into the collector unchanged.
"""

from .tracecontext import (  # noqa: F401
    ZERO_SPAN_ID,
    ZERO_TRACE_CONTEXT,
    ZERO_TRACE_ID,
    current_span_id,
    current_trace_context,
    current_trace_id,
    format_traceparent,
    is_zero_span_id,
    is_zero_trace_context,
    is_zero_trace_id,
    parse_traceparent,
)
from .tracer import (  # noqa: F401
    ManualTracer,
    flush,
    set_default_sink,
    span,
)
