"""Process exec/exit event source.

Equivalent of the eBPF runtime-detector the reference wraps behind a small
interface (instrumentation/detector/detector.go:31 NewDetector over
github.com/odigos-io/runtime-detector): the manager consumes a stream of
ProcessEvents and never cares how they were produced. Here the production
implementation is a poller diffing the proc source's pid set (no eBPF on
TPU hosts); tests drive events synchronously.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass
from typing import Callable, Optional, Protocol

from .proc import ProcessContext


class ProcessEventType(str, enum.Enum):
    EXEC = "exec"
    EXIT = "exit"


@dataclass(frozen=True)
class ProcessEvent:
    type: ProcessEventType
    pid: int
    context: Optional[ProcessContext] = None  # None for EXIT


EventSink = Callable[[ProcessEvent], None]


class Detector(Protocol):
    def start(self, sink: EventSink) -> None: ...
    def stop(self) -> None: ...


class PollingDetector:
    """Diffs the pid set every ``interval`` seconds. ``poll_once`` is public
    so tests and the odiglet sim can step it deterministically."""

    def __init__(self, source, interval: float = 1.0):
        self.source = source
        self.interval = interval
        self._known: set[int] = set()
        self._sink: Optional[EventSink] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self, sink: EventSink) -> None:
        self._sink = sink
        if self.interval > 0:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="process-detector")
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def poll_once(self) -> None:
        if self._sink is None:
            return
        current = set(self.source.pids())
        for pid in sorted(current - self._known):
            ctx = self.source.context(pid)
            if ctx is not None:
                self._sink(ProcessEvent(ProcessEventType.EXEC, pid, ctx))
        for pid in sorted(self._known - current):
            self._sink(ProcessEvent(ProcessEventType.EXIT, pid))
        self._known = current

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.poll_once()
