"""Own-telemetry: counters/gauges/histograms for the framework itself.

The reference injects a self-telemetry pipeline into every collector config
(autoscaler/controllers/clustercollector/configmap.go:42) and appends the
odigostrafficmetrics processor to every pipeline; the UI and the HPA custom
metric (odigos_gateway_memory_limiter_rejections_total) are fed from it.

We keep a process-local metrics registry with the same roles: pipeline
components record into it, the autoscaler's HPA math and the scoring engine's
latency accounting read from it, and `snapshot()` is the scrape endpoint.
"""

from __future__ import annotations

import random
import threading
from collections import defaultdict
from typing import Optional


class _Histogram:
    """Bounded uniform reservoir (Vitter's algorithm R) with exact
    ``count``/``total``. The old decimation scheme (``values[::2]`` on
    overflow) permanently halved resolution after one overflow and
    biased quantiles toward whatever survived the cut; random
    replacement keeps every sample equally likely to be resident, so
    quantile error stays bounded at any stream length."""

    __slots__ = ("values", "count", "total", "max_samples", "_dirty",
                 "_rng")

    def __init__(self, max_samples: int = 8192):
        self.values: list[float] = []  # reservoir; sorted lazily
        self.count = 0
        self.total = 0.0
        self.max_samples = max_samples
        self._dirty = False
        # deterministic per-instance stream: quantiles are reproducible
        # for a given record sequence (tests) without a global seed
        self._rng = random.Random(0x9E3779B97F4A7C15)

    def record(self, v: float) -> None:
        self.count += 1
        self.total += v
        if len(self.values) < self.max_samples:
            self.values.append(v)
            self._dirty = True
        else:
            j = self._rng.randrange(self.count)
            if j < self.max_samples:
                self.values[j] = v
                self._dirty = True

    def quantile(self, q: float) -> float:
        if not self.values:
            return 0.0
        if self._dirty:
            self.values.sort()
            self._dirty = False
        idx = min(int(q * len(self.values)), len(self.values) - 1)
        return self.values[idx]

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0


class Meter:
    """Thread-safe metrics registry. Labels are flattened into the name by the
    caller convention ``name{key=value}`` to keep the structure flat."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, _Histogram] = {}

    def add(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def record(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Histogram()
            h.record(value)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def gauge(self, name: str) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name)

    def quantile(self, name: str, q: float) -> float:
        with self._lock:
            h = self._hists.get(name)
            return h.quantile(q) if h else 0.0

    def snapshot(self) -> dict[str, float]:
        """Flat scrape of all instruments (histograms as _p50/_p99/_mean/_count)."""
        with self._lock:
            out: dict[str, float] = dict(self._counters)
            out.update(self._gauges)
            for name, h in self._hists.items():
                out[f"{name}_count"] = float(h.count)
                out[f"{name}_mean"] = h.mean
                out[f"{name}_p50"] = h.quantile(0.50)
                out[f"{name}_p99"] = h.quantile(0.99)
            return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


def label_value(v: str) -> str:
    """Sanitize a label VALUE for the flat ``name{key=value}`` encoding.

    The flat encoding is ambiguous if a value contains the structural
    characters — ``name{exporter=a,b}`` reads as two labels — so callers
    whose label values come from data (service names, exporter names from
    config) must route them through here at record time. Structural chars
    are replaced, not escaped: the flat string is the registry key and
    must round-trip through naive split."""
    return (v.replace(",", "_").replace("=", "_")
             .replace("{", "_").replace("}", "_"))


def labeled_key(metric: str, **labels: str) -> str:
    """Render a flat ``name{key=value}`` registry key, routing every
    label VALUE through ``label_value`` (see its contract). The flat
    encoding's one rule lives here; hot-path callers precompute the key
    once at construction."""
    inner = ",".join(f"{k}={label_value(str(v))}"
                     for k, v in labels.items())
    return f"{metric}{{{inner}}}"


def prometheus_text(snapshot: dict[str, float]) -> str:
    """Render a ``snapshot()`` as Prometheus text exposition (the
    own-observability scrape surface; reference: own-observability/
    prometheus ServiceMonitor scraping the collectors' self metrics).
    Flat ``name{label=value}`` names pass through with values quoted."""
    lines = []
    for name in sorted(snapshot):
        value = snapshot[name]
        if "{" in name:
            base, rest = name.split("{", 1)
            labels = []
            for part in rest.rstrip("}").split(","):
                if "=" in part:
                    k, v = part.split("=", 1)
                    v = v.strip().replace("\\", "\\\\").replace('"', '\\"')
                    labels.append(f'{k.strip()}="{v}"')
                elif labels:
                    # a ',' inside a legacy unsanitized value: splice the
                    # fragment back into the previous value (same escaping
                    # as the normal path) rather than emit a bare fragment
                    frag = (part.strip().replace("\\", "\\\\")
                            .replace('"', '\\"'))
                    labels[-1] = labels[-1][:-1] + "," + frag + '"'
            name = base + "{" + ",".join(labels) + "}"
        # full float precision: {:g} quantizes to 6 significant digits,
        # which freezes counters past 1e6 on the scrape surface
        lines.append(f"{name} {float(value)!r}")
    return "\n".join(lines) + "\n"


meter = Meter()
