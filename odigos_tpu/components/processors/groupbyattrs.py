"""``groupbyattrs`` processor — promote record attributes to resources.

Upstream's groupbyattrsprocessor (collector/builder-config.yaml:72):
regroup spans/log records/metric points under resources keyed by the
listed attribute values — the canonical "compact many per-span copies of
host.name into per-resource groups" tool.  With no keys it compacts
identical resources (upstream's documented no-keys behavior).

Config::

    groupbyattrs:
      keys: [host.name, k8s.pod.name]

For each row: the listed keys are read from the record's own attributes
(falling back to the current resource's), removed from the record
attrs, and the row is re-pointed at a resource extending the current
one with those values.  Columnar cost: one pass over the attr
side-lists plus a resource_index column rewrite.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any

import numpy as np

from ..api import Capabilities, ComponentKind, Factory, Processor, register

_ATTR_FIELD = {"span_attrs": "span_attrs", "record_attrs": "record_attrs",
               "point_attrs": "point_attrs"}


class GroupByAttrsProcessor(Processor):
    """See module docstring."""

    capabilities = Capabilities(mutates_data=True)

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self.keys = [str(k) for k in (config.get("keys") or [])]

    def process(self, batch: Any) -> Any:
        if not len(batch) or not hasattr(batch, "resources"):
            return batch
        attr_field = next((f for f in _ATTR_FIELD
                           if hasattr(batch, f)), None)
        if attr_field is None:
            return batch
        attrs = getattr(batch, attr_field)
        resources = batch.resources
        ridx = batch.col("resource_index")

        # cheap pre-pass: when no row carries a promotable key and the
        # resources are already distinct, the regroup loop below would
        # conclude "unchanged" after O(n) dict/tuple work per batch —
        # skip it (hot trace pipelines hit this case constantly)
        if not any(k in d for d in attrs for k in self.keys):
            idents = [tuple(sorted((k, str(v)) for k, v in r.items()))
                      for r in resources]
            if len(set(idents)) == len(idents):
                return batch

        new_resources: list[dict[str, Any]] = []
        intern: dict[tuple, int] = {}
        new_ridx = np.empty(len(batch), dtype=np.int32)
        new_attrs: list[dict[str, Any]] = []
        changed = False

        for i in range(len(batch)):
            base = resources[int(ridx[i])] if 0 <= int(ridx[i]) < len(
                resources) else {}
            d = attrs[i]
            promoted = {}
            for k in self.keys:
                v = d.get(k, base.get(k))
                if v is not None:
                    promoted[k] = v
            if promoted and any(k in d for k in promoted):
                d = {k: v for k, v in d.items() if k not in promoted}
                changed = True
            merged = dict(base)
            merged.update(promoted)
            key = tuple(sorted((k, str(v)) for k, v in merged.items()))
            j = intern.get(key)
            if j is None:
                j = len(new_resources)
                new_resources.append(merged)
                intern[key] = j
            if j != int(ridx[i]):
                changed = True
            new_ridx[i] = j
            new_attrs.append(d)

        if not changed and len(new_resources) == len(resources):
            return batch
        cols = dict(batch.columns)
        cols["resource_index"] = new_ridx
        return replace(batch, columns=cols,
                       resources=tuple(new_resources),
                       **{attr_field: tuple(new_attrs)})


register(Factory(
    type_name="groupbyattrs",
    kind=ComponentKind.PROCESSOR,
    create=GroupByAttrsProcessor,
    default_config=lambda: {"keys": []},
))
