"""Typed control-plane resources + watchable store.

Our equivalent of the reference's CRD layer (api/odigos/v1alpha1 +
api/actions/v1alpha1) and the slice of the k8s API machinery the
controllers rely on: a namespaced, versioned, watchable object store with
level-triggered reconcile dispatch (the controller-runtime pattern every
reference controller is built on — SURVEY.md §2.1).

The resource *types* keep the reference's semantics (same condition types,
reasons, roles) so operators can map concepts 1:1; the machinery is a small
in-process store rather than etcd — the framework's control plane is
embeddable and testable without a cluster, the same role KinD plays in the
reference's e2e suite.
"""

from .resources import (
    Action,
    AgentEnabledReason,
    CollectorsGroup,
    CollectorsGroupRole,
    Condition,
    ConditionStatus,
    DestinationResource,
    InstrumentationConfig,
    InstrumentationInstance,
    InstrumentationRule,
    MarkedForInstrumentationReason,
    ObjectMeta,
    Processor,
    RuntimeDetails,
    Source,
    WorkloadKind,
    WorkloadRef,
    condition_logical_order,
)
from .store import Event, EventType, Store, Reconciler, ControllerManager

__all__ = [
    "Action",
    "AgentEnabledReason",
    "CollectorsGroup",
    "CollectorsGroupRole",
    "Condition",
    "ConditionStatus",
    "DestinationResource",
    "InstrumentationConfig",
    "InstrumentationInstance",
    "InstrumentationRule",
    "MarkedForInstrumentationReason",
    "ObjectMeta",
    "Processor",
    "RuntimeDetails",
    "Source",
    "WorkloadKind",
    "WorkloadRef",
    "condition_logical_order",
    "Event",
    "EventType",
    "Store",
    "Reconciler",
    "ControllerManager",
]
