"""Self-tracing layer tests (ISSUE 1): tracer unit behavior, the
bounded-reservoir histogram regression, span propagation across the wire
hop, the e2e trace-coherence + overhead acceptance, the dogfood
receiver, control-plane and TPU-stage spans, the /metrics +
/api/selftrace surfaces, and the diagnose bundle (with redaction)."""

from __future__ import annotations

import json
import re
import tarfile
import time
import urllib.request

import numpy as np
import pytest
import yaml

from odigos_tpu.pdata import synthesize_traces
from odigos_tpu.pipeline import Collector
from odigos_tpu.selftelemetry import tracer
from odigos_tpu.utils.telemetry import _Histogram, meter


@pytest.fixture
def fresh():
    """Drained ring + tracing on; restores the enabled flag after."""
    was = tracer.enabled
    tracer.enabled = True
    tracer.ring.drain()
    yield tracer
    tracer.ring.drain()
    tracer.enabled = was


def wait_for(cond, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


# ------------------------------------------------------------------ tracer


class TestTracer:
    def test_parent_child_linkage(self, fresh):
        with tracer.span("test/parent") as parent:
            with tracer.span("test/child"):
                pass
        spans = {s.name: s for s in tracer.ring.snapshot()}
        child, par = spans["test/child"], spans["test/parent"]
        assert child.trace_id == par.trace_id
        assert child.parent_span_id == par.span_id
        assert par.parent_span_id == 0  # root
        assert parent.duration_ns >= child.duration_ns

    def test_error_sets_status_and_reraises(self, fresh):
        from odigos_tpu.pdata.spans import StatusCode

        with pytest.raises(ValueError):
            with tracer.span("test/boom"):
                raise ValueError("x")
        (span,) = tracer.ring.snapshot()
        assert span.status == StatusCode.ERROR

    def test_disabled_records_nothing(self, fresh):
        tracer.enabled = False
        with tracer.span("test/off") as sp:
            sp.set_attr("k", "v")  # null span absorbs attrs
        assert len(tracer.ring) == 0

    def test_suppressed_records_nothing(self, fresh):
        with tracer.suppressed():
            with tracer.span("test/suppressed"):
                pass
        assert len(tracer.ring) == 0

    def test_ring_bounded_with_drop_accounting(self, fresh):
        from odigos_tpu.selftelemetry import SpanRing

        ring = SpanRing(capacity=8)
        for i in range(20):
            with tracer.span(f"test/{i}"):
                pass
        # the global ring is big; exercise bounding on a private one
        for s in tracer.ring.drain():
            ring.append(s)
        assert len(ring) == 8
        assert ring.dropped == 12
        assert ring.total == 20

    def test_since_cursor_read_is_non_destructive(self, fresh):
        from odigos_tpu.selftelemetry import SpanRing

        ring = SpanRing(capacity=4)
        for i in range(3):
            with tracer.span(f"test/{i}"):
                pass
        for s in tracer.ring.drain():
            ring.append(s)
        spans, cursor, missed = ring.since(0)
        assert [s.name for s in spans] == ["test/0", "test/1", "test/2"]
        assert (cursor, missed) == (3, 0)
        assert len(ring) == 3  # the read did not consume the ring
        assert ring.since(cursor) == ([], 3, 0)
        # overflow between reads: evicted spans are counted, not silent
        for i in range(3, 9):
            with tracer.span(f"test/{i}"):
                pass
        for s in tracer.ring.drain():
            ring.append(s)
        spans, cursor, missed = ring.since(cursor)
        assert [s.name for s in spans] == [f"test/{i}" for i in range(5, 9)]
        assert (cursor, missed) == (9, 2)

    def test_drain_batch_is_own_pdata(self, fresh):
        with tracer.span("test/export") as sp:
            sp.set_attr("batch.spans", 7)
        batch = tracer.drain_batch()
        assert batch is not None and len(batch) == 1
        assert dict(batch.resources[0])["service.name"] == "odigos-tpu"
        assert dict(batch.resources[0])["odigos.selftelemetry"] is True
        assert tracer.drain_batch() is None  # drained

    def test_traces_grouping_most_recent_first(self, fresh):
        with tracer.span("test/t1"):
            with tracer.span("test/t1-child"):
                pass
        with tracer.span("test/t2"):
            pass
        traces = tracer.traces()
        assert [t["root"] for t in traces] == ["test/t2", "test/t1"]
        assert traces[1]["span_count"] == 2


# ------------------------------------------- histogram reservoir (satellite)


class TestHistogramReservoir:
    """The old decimation scheme (``values[::2]`` on overflow) permanently
    halved resolution after one overflow; the bounded uniform reservoir
    must keep quantile error bounded at 100k samples with exact
    count/total."""

    def test_p99_error_bound_at_100k_samples(self):
        h = _Histogram()
        vals = np.random.default_rng(42).permutation(100_000).astype(float)
        for v in vals:
            h.record(v)
        assert h.count == 100_000
        assert h.total == pytest.approx(float(vals.sum()))
        # reservoir of 8192 → quantile sd in value space ~110; 1.5% of the
        # range is ~13σ, deterministic here (per-instance seeded RNG)
        assert h.quantile(0.99) == pytest.approx(99_000, abs=1_500)
        assert h.quantile(0.50) == pytest.approx(50_000, abs=1_500)

    def test_sorted_stream_not_biased(self):
        # ascending input was the old scheme's worst case: every overflow
        # decimated the low half out, dragging quantiles upward
        h = _Histogram()
        for v in range(100_000):
            h.record(float(v))
        assert h.quantile(0.50) == pytest.approx(50_000, abs=1_500)
        assert h.quantile(0.99) == pytest.approx(99_000, abs=1_500)

    def test_resolution_never_degrades(self):
        # the decimation bug: one overflow halved the resident sample set
        # forever; the reservoir stays full at max_samples
        h = _Histogram(max_samples=64)
        for v in range(1_000):
            h.record(float(v))
        assert len(h.values) == 64
        assert h.count == 1_000


# ------------------------------------------ wire-hop propagation (satellite)


class TestWirePropagation:
    def test_codec_roundtrips_traceparent(self):
        from odigos_tpu.wire.codec import (
            decode_batch, decode_frame, encode_batch)

        batch = synthesize_traces(5, seed=1)
        tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        out, got_tp = decode_frame(encode_batch(batch, tp))
        assert got_tp == tp
        assert len(out) == len(batch)
        # frames without the key (pre-tp senders) decode with tp=None
        out2, got2 = decode_frame(encode_batch(batch))
        assert got2 is None and len(out2) == len(batch)
        # decode_batch stays a batch-only surface
        assert len(decode_batch(encode_batch(batch, tp))) == len(batch)

    def test_two_service_round_trip_shares_trace(self, fresh):
        from odigos_tpu.wire import WireExporter, WireReceiver

        got = []

        class _Sink:
            def consume(self, b):
                got.append(b)

        recv = WireReceiver("otlpwire/down", {"host": "127.0.0.1",
                                              "port": 0})
        recv.set_consumer(_Sink())
        recv.start()
        exp = WireExporter("otlpwire/up",
                           {"endpoint": f"127.0.0.1:{recv.port}"})
        exp.start()
        try:
            batch = synthesize_traces(8, seed=2)
            with tracer.span("pipeline/up"):
                exp.consume(batch)  # opens exporter span, stamps tp
            assert exp.flush(timeout=10)
            assert wait_for(lambda: got)
        finally:
            exp.shutdown()
            recv.shutdown()
        spans = {s.name: s for s in tracer.ring.snapshot()}
        up = spans["pipeline/up"]
        sender = spans["exporter/otlpwire/up"]
        downstream = spans["receiver/otlpwire/down"]
        # downstream trace id equals the upstream's
        assert downstream.trace_id == up.trace_id == sender.trace_id
        # parent/child ordering survived serde: the receive span hangs
        # under the exact exporter span the batch left through
        assert downstream.parent_span_id == sender.span_id
        assert sender.parent_span_id == up.span_id
        assert downstream.start_unix_nano >= sender.start_unix_nano
        assert downstream.attrs["batch.spans"] == len(batch)


# ------------------------------------------------- e2e acceptance criteria


class TestE2EAcceptance:
    def test_single_coherent_trace_across_wire_hop(self, fresh):
        """A batch through a 3-stage upstream pipeline, over one wire hop,
        into a downstream pipeline: one trace id, ≥4 spans, upstream stage
        latencies summing to within tolerance of the pipeline span."""
        down_cfg = {
            "receivers": {"otlpwire": {"host": "127.0.0.1", "port": 0}},
            "processors": {},
            "exporters": {"debug": {"keep": True}},
            "service": {"pipelines": {"traces/down": {
                "receivers": ["otlpwire"], "processors": [],
                "exporters": ["debug"]}}},
        }
        with Collector(down_cfg) as down:
            port = down.component("otlpwire").port
            up_cfg = {
                "receivers": {"synthetic": {"traces_per_batch": 40,
                                            "n_batches": 1, "seed": 5}},
                "processors": {"attributes": {"actions": []},
                               "resource": {"attributes": []}},
                "exporters": {"otlpwire":
                              {"endpoint": f"127.0.0.1:{port}"}},
                "service": {"pipelines": {"traces/up": {
                    "receivers": ["synthetic"],
                    "processors": ["attributes", "resource"],
                    "exporters": ["otlpwire"]}}},
            }
            with Collector(up_cfg) as up:
                up.drain_receivers()
                assert up.component("otlpwire").flush(timeout=10)
                dbg = down.component("debug")
                assert wait_for(lambda: dbg.span_count > 0)

        spans = tracer.ring.snapshot()
        pipe = next(s for s in spans if s.name == "pipeline/traces/up")
        group = [s for s in spans if s.trace_id == pipe.trace_id]
        names = {s.name for s in group}
        assert len(group) >= 4
        assert {"pipeline/traces/up", "processor/attributes",
                "processor/resource", "exporter/otlpwire",
                "receiver/otlpwire", "pipeline/traces/down",
                "exporter/debug"} <= names

        # flat stage spans under the pipeline span: their durations sum
        # to the pipeline's (the weave's bookkeeping is the remainder)
        stages = [s for s in group
                  if s.name in ("processor/attributes",
                                "processor/resource", "exporter/otlpwire")]
        assert len(stages) == 3
        stage_sum = sum(s.duration_ns for s in stages)
        assert stage_sum <= pipe.duration_ns
        assert stage_sum >= 0.5 * pipe.duration_ns

    def test_tracing_overhead_under_5_percent(self, fresh):
        """Enabled-vs-disabled wall time through the same pipeline: the
        weave must cost <5% (best-of interleaved runs — per-span
        bookkeeping is ~µs against ms-scale batch work). The stages do
        real batch work (attribute store rebuilds + redaction's pool
        scan), matching production pipelines; a no-op stage chain would
        make the <5% bar measure fixed span cost against nothing. Batches
        are sized so the denominator stays ms-scale now that the columnar
        attribute store took the per-span Python out of these stages —
        the weave's ~0.1 ms/batch must stay small against realistic
        work, not against an artificially slow attrs path."""
        cfg = {
            "receivers": {"synthetic": {"traces_per_batch": 2,
                                        "n_batches": 1}},
            "processors": {
                "attributes": {"actions": [
                    {"action": "upsert", "key": "bench.tag", "value": "x"},
                    {"action": "insert", "key": "bench.tier",
                     "value": "hot"}]},
                "redaction": {"blocked_values":
                              ["4[0-9]{12}(?:[0-9]{3})?"],
                              "summary": "info"},
                "resource": {"attributes": [
                    {"action": "upsert", "key": "odigos.version",
                     "value": "bench"}]}},
            "exporters": {"debug": {}},
            "service": {"pipelines": {"traces/bench": {
                "receivers": ["synthetic"],
                "processors": ["attributes", "redaction", "resource"],
                "exporters": ["debug"]}}},
        }
        with Collector(cfg) as col:
            col.drain_receivers()
            entry = col.graph.pipeline_entries["traces/bench"]
            batches = [synthesize_traces(4000, seed=100 + i)
                       for i in range(4)]

            def consume_timed(b):
                t0 = time.perf_counter()
                entry.consume(b)
                return time.perf_counter() - t0

            for enabled in (True, False):  # warm both paths + caches
                tracer.enabled = enabled
                for b in batches:
                    entry.consume(b)

            # Paired design: the same batch is consumed in both modes
            # back-to-back (within-pair order alternating), so the
            # multiplicative slowdown episodes of a shared CI box hit
            # both sides of each ratio near-equally; the median of the
            # paired ratios is then the overhead, not the noise. A noise
            # episode can still outlast one measurement window on a
            # loaded box, so the 5% bar gets up to three windows — the
            # claim is "the weave CAN run under 5%", which one clean
            # window proves and a preempted one cannot refute.
            def measure():
                ratios = []
                for i in range(10):
                    for j, b in enumerate(batches):
                        t = {}
                        modes = ((True, False) if (i + j) % 2
                                 else (False, True))
                        for enabled in modes:
                            tracer.enabled = enabled
                            t[enabled] = consume_timed(b)
                        ratios.append(t[True] / t[False])
                    tracer.ring.drain()
                ratios.sort()
                return ratios[len(ratios) // 2], ratios

            medians = []
            for _ in range(3):
                median, ratios = measure()
                medians.append(median)
                if median <= 1.05:
                    break
        assert min(medians) <= 1.05, (
            f"self-tracing overhead too high: median enabled/disabled "
            f"ratios across trials {[f'{m:.4f}' for m in medians]} "
            f"(last samples: {ratios[:3]} .. {ratios[-3:]})")


# ------------------------------------------------------ control-plane spans


class TestControlPlaneSpans:
    def test_reconcile_span_with_outcome(self, fresh):
        from odigos_tpu.api import ObjectMeta, Store
        from odigos_tpu.api.resources import ConfigMap
        from odigos_tpu.api.store import ControllerManager

        calls = []

        class _Rec:
            def reconcile(self, store, key):
                calls.append(key)
                if key[1] == "bad":
                    raise RuntimeError("injected")

        store = Store()
        mgr = ControllerManager(store)
        mgr.register("demo", _Rec(), {"ConfigMap": None})
        store.apply(ConfigMap(meta=ObjectMeta(name="ok", namespace="ns"),
                              data={}))
        store.apply(ConfigMap(meta=ObjectMeta(name="bad", namespace="ns"),
                              data={}))
        mgr.run_once()
        assert len(calls) >= 2
        spans = [s for s in tracer.ring.snapshot()
                 if s.name == "reconcile/demo"]
        outcomes = {s.attrs["name"]: s.attrs["outcome"] for s in spans}
        assert outcomes["ok"] == "ok"
        assert outcomes["bad"] == "error:RuntimeError"
        assert all(s.attrs["namespace"] == "ns" for s in spans)
        assert len(mgr.errors) == 1  # reconcile errors still recorded


# -------------------------------------------------------- TPU-stage spans


class TestTpuScoringSpans:
    def test_score_span_with_first_call_split(self, fresh):
        from odigos_tpu.features import featurize
        from odigos_tpu.serving import EngineConfig, ScoringEngine

        eng = ScoringEngine(EngineConfig(model="mock")).start()
        try:
            b = synthesize_traces(6, seed=3)
            f = featurize(b)
            eng.score_sync(b, f, timeout_s=10.0)
            eng.score_sync(b, f, timeout_s=10.0)
        finally:
            eng.shutdown()
        spans = [s for s in tracer.ring.snapshot() if s.name == "tpu/score"]
        assert len(spans) >= 2
        first, second = spans[0], spans[1]
        assert first.attrs["jit.first_call"] is True
        assert first.attrs["batch.spans"] == len(b)
        assert first.attrs["model"] == "mock"
        assert "device" in first.attrs
        assert first.attrs["queue_wait_ms"] >= 0
        assert "jit.compile_est_ms" in second.attrs
        assert meter.gauge("odigos_anomaly_jit_compile_est_ms") is not None


# --------------------------------------------------------- dogfood receiver


class TestDogfoodReceiver:
    def test_ring_re_enters_pipeline_without_recursion(self, fresh):
        cfg = {
            "receivers": {"selftelemetry": {"interval_s": 3600.0}},
            "processors": {},
            "exporters": {"debug": {"keep": True}},
            "service": {"pipelines": {"traces/self": {
                "receivers": ["selftelemetry"], "processors": [],
                "exporters": ["debug"]}}},
        }
        with Collector(cfg) as col:
            tracer.ring.drain()  # collector start-up spans are not ours
            with tracer.span("test/dogfood") as sp:
                sp.set_attr("k", "v")
            recv = col.component("selftelemetry")
            assert recv.emit() == 1
            dbg = col.component("debug")
            assert dbg.span_count == 1
            (batch,) = dbg.batches
            assert dict(batch.resources[0])["odigos.selftelemetry"] is True
            # the dogfood pipeline's own consumption ran suppressed: the
            # export of the ring did not trace itself back into the ring
            # — and the export is a cursor READ, not a drain, so the
            # /api/selftrace + diagnose surfaces keep their evidence
            assert len(tracer.ring) == 1
            assert recv.emit() == 0  # cursor advanced: nothing new

    def test_self_batches_suppressed_on_any_thread(self, fresh):
        """The contextvar-scoped suppressed() only covers the emit
        thread; a batch processor flushing the dogfood batch later does
        so on a Timer thread where the contextvar is unset. The resource
        marker on the batch itself must keep the weave silent there —
        otherwise every flush of exported self-spans mints new spans, a
        perpetual trickle with zero real traffic."""
        import threading

        cfg = {
            "receivers": {"selftelemetry": {"interval_s": 3600.0}},
            "processors": {"attributes": {"actions": []}},
            "exporters": {"debug": {"keep": True}},
            "service": {"pipelines": {"traces/self": {
                "receivers": ["selftelemetry"],
                "processors": ["attributes"],
                "exporters": ["debug"]}}},
        }
        with Collector(cfg) as col:
            tracer.ring.drain()
            with tracer.span("test/seed"):
                pass
            batch = tracer.to_batch(tracer.ring.snapshot())
            entry = col.graph.pipeline_entries["traces/self"]
            # simulate the batch-processor flush: consume the self-span
            # batch on a fresh thread with NO suppression contextvar set
            t = threading.Thread(target=entry.consume, args=(batch,))
            t.start()
            t.join()
            assert col.component("debug").span_count == 1
        names = [s.name for s in tracer.ring.snapshot()]
        assert names == ["test/seed"], (
            f"self-span batch minted spans about itself: {names}")


# ------------------------------------------------------- frontend surfaces

_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r' (-?\d+(\.\d+)?([eE][+-]?\d+)?|-?inf|nan)$')
# exemplar annotations (ISSUE 3): comment lines, ignored by plain
# Prometheus scrapers, linking a histogram to the self-trace that
# populated it — # EXEMPLAR <name>{...} {trace_id=..,span_id=..} v ts
_PROM_EXEMPLAR = re.compile(
    r'^# EXEMPLAR [a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? '
    r'\{trace_id="[0-9a-f]{32}",span_id="[0-9a-f]{16}"\} '
    r'-?\d+(\.\d+)?([eE][+-]?\d+)? \d+(\.\d+)?$')


class TestFrontendSurfaces:
    @pytest.fixture
    def frontend(self):
        from odigos_tpu.api import Store
        from odigos_tpu.frontend import FrontendServer

        fe = FrontendServer(Store(), metrics_port=None).start()
        yield fe
        fe.shutdown()

    def test_metrics_is_valid_prometheus_text(self, frontend, fresh):
        meter.add("odigos_selftrace_test_total{span=pipeline/traces}", 3)
        meter.record("odigos_selftrace_test_latency_ms", 1.5)
        with tracer.span("test/scrape"):
            pass
        req = urllib.request.urlopen(f"{frontend.url}/metrics", timeout=10)
        assert req.status == 200
        assert req.headers["Content-Type"].startswith("text/plain")
        body = req.read().decode()
        lines = [ln for ln in body.splitlines() if ln]
        assert lines, "empty exposition"
        bad = [ln for ln in lines
               if not (_PROM_EXEMPLAR.match(ln) if ln.startswith("#")
                       else _PROM_LINE.match(ln))]
        assert not bad, f"non-Prometheus lines: {bad[:5]}"
        names = {ln.split("{")[0].split(" ")[0] for ln in lines}
        assert "odigos_selftrace_spans_total" in names

    def test_metrics_matches_scrape_config(self, frontend):
        import os

        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "own-observability",
            "prometheus", "odigos-tpu-scrape.yaml")
        with open(path) as f:
            scrape = yaml.safe_load(f)
        jobs = scrape["scrape_configs"]
        assert jobs and all(j["metrics_path"] == "/metrics" for j in jobs)
        # the path the config scrapes is the path the server serves
        assert urllib.request.urlopen(
            f"{frontend.url}/metrics", timeout=10).status == 200

    def test_api_selftrace_recent_traces(self, frontend, fresh):
        with tracer.span("pipeline/demo") as sp:
            sp.set_attr("batch.spans", 12)
            with tracer.span("processor/demo"):
                pass
        out = json.loads(urllib.request.urlopen(
            f"{frontend.url}/api/selftrace?limit=5", timeout=10).read())
        assert out["enabled"] is True
        assert out["spans_total"] >= 2
        (trace,) = [t for t in out["traces"]
                    if t["root"] == "pipeline/demo"]
        assert trace["span_count"] == 2
        assert trace["duration_ms"] >= 0
        # the polled headline feed omits per-span detail; ?spans=1 opts in
        assert "spans" not in trace
        out = json.loads(urllib.request.urlopen(
            f"{frontend.url}/api/selftrace?limit=5&spans=1",
            timeout=10).read())
        (trace,) = [t for t in out["traces"]
                    if t["root"] == "pipeline/demo"]
        names = {s["name"] for s in trace["spans"]}
        assert names == {"pipeline/demo", "processor/demo"}
        ids = {s["trace_id"] for s in trace["spans"]}
        assert len(ids) == 1
        err = urllib.request.urlopen(
            f"{frontend.url}/api/selftrace?limit=1", timeout=10)
        assert len(json.loads(err.read())["traces"]) <= 1


# --------------------------------------------------------- diagnose bundle


@pytest.fixture
def cli_run(tmp_path, capsys):
    from odigos_tpu.cli.commands import main

    state_dir = str(tmp_path / "state")

    def _run(*argv, expect=0):
        rc = main(["--state-dir", state_dir, *argv])
        out = capsys.readouterr()
        assert rc == expect, f"{argv}: rc={rc}\n{out.out}\n{out.err}"
        return out.out

    return _run


class TestDiagnoseBundle:
    def test_bundle_contains_spans_and_metrics(self, cli_run, tmp_path,
                                               fresh):
        cli_run("install")
        with tracer.span("test/diagnose") as sp:
            sp.set_attr("batch.spans", 9)
        bundle = str(tmp_path / "bundle.tar.gz")
        cli_run("diagnose", "-o", bundle)
        with tarfile.open(bundle) as tar:
            names = tar.getnames()
            assert "selftrace.json" in names
            assert "metrics.json" in names
            st = json.load(tar.extractfile("selftrace.json"))
            mx = json.load(tar.extractfile("metrics.json"))
        assert any(s["name"] == "test/diagnose" for s in st["spans"])
        assert st["enabled"] is True
        assert any(k.startswith("odigos_selftrace_spans_total")
                   for k in mx)

    def test_redact_strips_destination_secrets(self, cli_run, tmp_path,
                                               fresh):
        secret = "dd-api-key-hunter2-0123456789"
        cli_run("install")
        cli_run("destinations", "add", "--name", "dd", "--type", "datadog",
                "--set", f"DATADOG_API_KEY={secret}",
                "--set", "DATADOG_SITE=datadoghq.com")
        with tracer.span("exporter/datadog") as sp:
            sp.set_attr("api_key", secret)

        clear = str(tmp_path / "clear.tar.gz")
        cli_run("diagnose", "-o", clear)
        with tarfile.open(clear) as tar:
            body = tar.extractfile("selftrace.json").read().decode()
        assert secret in body  # un-redacted bundle keeps it (opt-in flag)

        redacted = str(tmp_path / "redacted.tar.gz")
        cli_run("diagnose", "-o", redacted, "--redact")
        with tarfile.open(redacted) as tar:
            for name in tar.getnames():
                content = tar.extractfile(name).read().decode()
                assert secret not in content, f"secret leaked via {name}"
            body = tar.extractfile("selftrace.json").read().decode()
        assert "[REDACTED]" in body
