from . import blob, debug, filelog, mock, tracedb, vendor  # noqa: F401
