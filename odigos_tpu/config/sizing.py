"""Collector sizing presets and resource derivation.

Reference: k8sutils/pkg/sizing/sizing.go (size_s/m/l presets) and
scheduler/controllers/clustercollectorsgroup/resource_config.go:8-39 —
gateway defaults 500Mi/500m request, 1000m CPU limit, 1-10 replicas, memory
limit = 1.25x request, memory-limiter hard limit = limit - 50MiB, spike =
20% of hard limit, GOMEMLIMIT = 80% of hard limit.
"""

from __future__ import annotations

from dataclasses import dataclass

from .model import CollectorGatewayConfiguration, CollectorNodeConfiguration

# resource_config.go constants
DEFAULT_REQUEST_MEMORY_MIB = 500
DEFAULT_REQUEST_CPU_M = 500
DEFAULT_LIMIT_CPU_M = 1000
DEFAULT_MIN_REPLICAS = 1
DEFAULT_MAX_REPLICAS = 10
MEMORY_LIMITER_LIMIT_DIFF_MIB = 50
MEMORY_LIMITER_SPIKE_PERCENTAGE = 20.0
GOMEMLIMIT_PERCENTAGE = 80.0
MEMORY_LIMIT_ABOVE_REQUEST_FACTOR = 1.25


@dataclass(frozen=True)
class SizingPreset:
    name: str
    gateway_min_replicas: int
    gateway_max_replicas: int
    gateway_request_memory_mib: int
    gateway_request_cpu_m: int
    gateway_limit_cpu_m: int
    node_request_memory_mib: int
    node_limit_memory_mib: int
    node_request_cpu_m: int
    node_limit_cpu_m: int


# the sizing knobs the fleet recommender (selftelemetry/fleet.py) may
# name in an observe-only recommendation: knob -> the config path an
# operator (or, later, the ROADMAP auto-tuner) would turn. A closed
# table for the same reason DROP_REASONS is — the package-hygiene lint
# asserts every recommender rule's knob resolves here, so a
# recommendation can never point at a knob that does not exist.
TUNING_KNOBS: dict[str, str] = {
    "max_batch": "anomaly.max_batch (device batch budget per call)",
    "bucket_ladder": "anomaly trace_bucket / warm_ladder "
                     "(precompiled row-bucket geometry)",
    "replicas": "collector_gateway.min_replicas/max_replicas "
                "(gateway replica count; bounded by the sizing preset)",
    "submit_lanes": "anomaly fast_path.submit_lanes "
                    "(featurize/submit thread pool width)",
}

# k8sutils/pkg/sizing/sizing.go presets (small/medium/large clusters)
SIZING_PRESETS: dict[str, SizingPreset] = {
    "size_s": SizingPreset("size_s", 1, 5, 300, 150, 300, 150, 300, 150, 300),
    "size_m": SizingPreset("size_m", 2, 8, 500, 500, 1000, 250, 500, 250, 500),
    "size_l": SizingPreset("size_l", 3, 12, 750, 750, 1250, 500, 750, 500, 750),
}


@dataclass(frozen=True)
class ResolvedResources:
    min_replicas: int
    max_replicas: int
    request_memory_mib: int
    limit_memory_mib: int
    request_cpu_m: int
    limit_cpu_m: int
    memory_limiter_limit_mib: int
    memory_limiter_spike_limit_mib: int
    gomemlimit_mib: int


def _derive(request_mem: int, limit_mem: int | None,
            hard_override: int | None, spike_override: int | None,
            gomem_override: int | None) -> tuple[int, int, int, int]:
    limit = limit_mem if limit_mem is not None else int(
        request_mem * MEMORY_LIMIT_ABOVE_REQUEST_FACTOR)
    hard = hard_override if hard_override is not None else max(
        1, limit - MEMORY_LIMITER_LIMIT_DIFF_MIB)
    spike = spike_override if spike_override is not None else int(
        hard * MEMORY_LIMITER_SPIKE_PERCENTAGE / 100.0)
    gomem = gomem_override if gomem_override is not None else int(
        hard * GOMEMLIMIT_PERCENTAGE / 100.0)
    return limit, hard, spike, gomem


def gateway_resources(cfg: CollectorGatewayConfiguration,
                      preset: SizingPreset | None = None) -> ResolvedResources:
    """resource_config.go getGatewayResourceSettings: explicit config wins,
    then sizing preset, then hardcoded defaults; memory-limiter math derived."""
    p = preset
    req_mem = cfg.request_memory_mib or (p.gateway_request_memory_mib if p else DEFAULT_REQUEST_MEMORY_MIB)
    limit, hard, spike, gomem = _derive(
        req_mem, cfg.limit_memory_mib, cfg.memory_limiter_limit_mib,
        cfg.memory_limiter_spike_limit_mib, cfg.gomemlimit_mib)
    return ResolvedResources(
        min_replicas=cfg.min_replicas or (p.gateway_min_replicas if p else DEFAULT_MIN_REPLICAS),
        max_replicas=cfg.max_replicas or (p.gateway_max_replicas if p else DEFAULT_MAX_REPLICAS),
        request_memory_mib=req_mem,
        limit_memory_mib=limit,
        request_cpu_m=cfg.request_cpu_m or (p.gateway_request_cpu_m if p else DEFAULT_REQUEST_CPU_M),
        limit_cpu_m=cfg.limit_cpu_m or (p.gateway_limit_cpu_m if p else DEFAULT_LIMIT_CPU_M),
        memory_limiter_limit_mib=hard,
        memory_limiter_spike_limit_mib=spike,
        gomemlimit_mib=gomem,
    )


def node_resources(cfg: CollectorNodeConfiguration,
                   preset: SizingPreset | None = None) -> ResolvedResources:
    p = preset
    req_mem = cfg.request_memory_mib or (p.node_request_memory_mib if p else 250)
    limit_mem = cfg.limit_memory_mib or (p.node_limit_memory_mib if p else None)
    limit, hard, spike, gomem = _derive(
        req_mem, limit_mem, cfg.memory_limiter_limit_mib,
        cfg.memory_limiter_spike_limit_mib, cfg.gomemlimit_mib)
    return ResolvedResources(
        min_replicas=1, max_replicas=1,  # daemonset: one per node
        request_memory_mib=req_mem,
        limit_memory_mib=limit,
        request_cpu_m=cfg.request_cpu_m or (p.node_request_cpu_m if p else 250),
        limit_cpu_m=cfg.limit_cpu_m or (p.node_limit_cpu_m if p else 500),
        memory_limiter_limit_mib=hard,
        memory_limiter_spike_limit_mib=spike,
        gomemlimit_mib=gomem,
    )
