"""Attribute transform processor.

Covers the reference's attribute-manipulation action processors
(addclusterinfo / renameattribute / deleteattribute compiled by
autoscaler/controllers/actions/*.go into collector processors): insert,
rename, delete keys on span or resource attributes.
"""

from __future__ import annotations

from dataclasses import replace
from typing import Any

from ...pdata.spans import SpanBatch
from ..api import Capabilities, ComponentKind, Factory, Processor, register


class AttributesProcessor(Processor):
    """Config: actions: [{action: insert|update|upsert|delete|rename,
    key: ..., value: ..., new_key: ..., scope: span|resource}]"""

    capabilities = Capabilities(mutates_data=True)

    def process(self, batch: SpanBatch) -> SpanBatch:
        actions = self.config.get("actions", [])
        if not actions:
            return batch
        span_attrs = None
        resources = None
        for a in actions:
            scope = a.get("scope", "span")
            if scope == "resource":
                if resources is None:
                    resources = [dict(r) for r in batch.resources]
                _apply(resources, a)
            else:
                if span_attrs is None:
                    span_attrs = [dict(d) for d in batch.span_attrs]
                _apply(span_attrs, a)
        out = batch
        if span_attrs is not None:
            out = replace(out, span_attrs=tuple(span_attrs))
        if resources is not None:
            out = replace(out, resources=tuple(resources))
        return out


def _apply(dicts: list[dict[str, Any]], action: dict[str, Any]) -> None:
    kind = action.get("action", "upsert")
    key = action["key"]
    for d in dicts:
        if kind == "insert":
            d.setdefault(key, action.get("value"))
        elif kind == "update":
            if key in d:
                d[key] = action.get("value")
        elif kind == "upsert":
            d[key] = action.get("value")
        elif kind == "delete":
            d.pop(key, None)
        elif kind == "rename":
            if key in d:
                d[action["new_key"]] = d.pop(key)
        else:
            raise ValueError(f"unknown attributes action {kind!r}")


register(Factory(
    type_name="attributes",
    kind=ComponentKind.PROCESSOR,
    create=AttributesProcessor,
    default_config=lambda: {"actions": []},
))


class ResourceProcessor(AttributesProcessor):
    """``resource`` processor: same action set, always resource-scoped
    (the upstream collector's resourceprocessor; pipelinegen emits
    ``resource/odigos-version``, config_builder.go:186)."""

    def process(self, batch: SpanBatch) -> SpanBatch:
        # upstream resourceprocessor config key is "attributes"
        actions = self.config.get("attributes") or self.config.get("actions", [])
        if not actions:
            return batch
        resources = [dict(r) for r in batch.resources]
        for a in actions:
            _apply(resources, a)
        return replace(batch, resources=tuple(resources))


register(Factory(
    type_name="resource",
    kind=ComponentKind.PROCESSOR,
    create=ResourceProcessor,
    default_config=lambda: {"attributes": []},
))
