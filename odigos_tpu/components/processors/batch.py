"""Batch processor.

Every generated pipeline in the reference ends its processor chain with
`batch` (autoscaler/controllers/clustercollector/configmap.go base config;
SURVEY.md §3.3). Ours accumulates SpanBatches and flushes a single
concatenated batch when either `send_batch_size` spans are pending or
`timeout_s` elapses — the concat is the cheap columnar merge from pdata, so
downstream stages (featurizer!) always see large, TPU-friendly batches.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ...pdata import concat_any
from ...pdata.spans import SpanBatch
from ...selftelemetry.flow import FlowContext
from ..api import Capabilities, ComponentKind, Factory, Processor, register


class BatchProcessor(Processor):
    capabilities = Capabilities(mutates_data=False)

    # incremental hot reload (ISSUE 14): every sizing knob retunes live
    # — buffered spans are kept, the next consume/tick sees new bounds
    RECONFIGURABLE_KEYS = frozenset({
        "send_batch_size", "send_batch_max_size", "timeout_s"})

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self._lock = threading.Lock()
        self._pending: list[SpanBatch] = []
        self._pending_spans = 0
        self._timer: Optional[threading.Timer] = None
        self._apply_sizing(config)
        self._wm_name: str | None = None

    def _apply_sizing(self, config: dict[str, Any]) -> None:
        # ONE parse routine for __init__ and reconfigure — a default
        # changed in one place only would otherwise retune a reloaded
        # node differently from a freshly built one
        self.send_batch_size = int(config.get("send_batch_size", 8192))
        self.send_batch_max_size = int(config.get("send_batch_max_size",
                                                  0))
        self.timeout_s = float(config.get("timeout_s", 0.2))

    def _watermark_name(self) -> str:
        # resolved lazily: the graph stamps _flow_site after construction
        name = self._wm_name
        if name is None:
            name = self._wm_name = FlowContext.watermark_name(self)
        return name

    def reconfigure(self, config: dict[str, Any]) -> None:
        """Live retune (ISSUE 14): pending spans are NOT dropped — a
        shrunk send_batch_size flushes immediately if the buffer
        already crosses the new bound, and the flush timer is re-armed
        under the NEW timeout (an armed old-timeout timer — or no
        timer at all when timeout was 0 — would keep governing the
        current buffer)."""
        to_send: list[SpanBatch] = []
        with self._lock:
            self.config = config
            self._apply_sizing(config)
            if self._pending_spans >= self.send_batch_size:
                to_send = self._take_locked()
            else:
                if self._timer is not None:
                    self._timer.cancel()
                    self._timer = None
                if self._pending and self.timeout_s > 0:
                    self._timer = threading.Timer(self.timeout_s,
                                                  self._flush_timer)
                    self._timer.daemon = True
                    self._timer.start()
        if to_send:
            self._send(to_send)

    def consume(self, batch: SpanBatch) -> None:
        to_send: list[SpanBatch] = []
        with self._lock:
            self._pending.append(batch)
            self._pending_spans += len(batch)
            FlowContext.watermark(self._watermark_name(), "pending_spans",
                                  self._pending_spans)
            if self._pending_spans >= self.send_batch_size:
                to_send = self._take_locked()
            elif self._timer is None and self.timeout_s > 0:
                self._timer = threading.Timer(self.timeout_s, self._flush_timer)
                self._timer.daemon = True
                self._timer.start()
        if to_send:
            self._send(to_send)

    def _take_locked(self) -> list[SpanBatch]:
        taken = self._pending
        self._pending = []
        self._pending_spans = 0
        # reset the CURRENT watermark reading: admission gates watch it
        # live, and a stale pre-flush peak would keep shedding upstream
        FlowContext.watermark(self._watermark_name(), "pending_spans", 0)
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        return taken

    def _flush_timer(self) -> None:
        with self._lock:
            self._timer = None
            taken = self._take_locked()
        if taken:
            try:
                self._send(taken)
            except Exception:
                # downstream refusal on the timer thread: the caller that
                # could retry is long gone — count + drop, never kill the
                # timer path (retries belong to exporters' own queues)
                from ...utils.telemetry import meter
                meter.add("odigos_batch_dropped_on_flush_total"
                          f"{{processor={self.name}}}")

    def _send(self, batches: list[SpanBatch]) -> None:
        merged = concat_any(batches)
        if not merged:
            return
        max_size = self.send_batch_max_size
        if max_size and len(merged) > max_size:
            # contiguous chunks: slice() hands out column VIEWS (numpy
            # basic slicing + attr-store entry slices) — the old
            # take(arange(lo, hi)) copied every column per chunk
            for lo in range(0, len(merged), max_size):
                self.next_consumer.consume(
                    merged.slice(lo, min(lo + max_size, len(merged))))
        else:
            self.next_consumer.consume(merged)

    def flush(self) -> None:
        with self._lock:
            taken = self._take_locked()
        if taken:
            self._send(taken)

    def flow_pending(self) -> int:
        """Spans buffered here, not yet forwarded — the conservation
        checker's in-flight term (selftelemetry/flow.py). A downstream
        refusal on the timer path needs no extra ledger call: the
        out-edge already counted those spans as failed."""
        with self._lock:
            return self._pending_spans

    def shutdown(self) -> None:
        self.flush()
        super().shutdown()


register(Factory(
    type_name="batch",
    kind=ComponentKind.PROCESSOR,
    create=BatchProcessor,
    default_config=lambda: {
        "send_batch_size": 8192, "send_batch_max_size": 0, "timeout_s": 0.2},
))
