"""Full-stack e2e WITH node collectors: the autoscaler-generated
DaemonSet config actually boots, one collector per simulated node, and
data flows node -> (k8s-resolved loadbalancing) -> gateway -> destination
over real sockets (reference: the data-collection DaemonSet +
tests/e2e/trace-collection; the k8s resolver of traces.go:26)."""

from __future__ import annotations

import time

import pytest

from odigos_tpu.components.api import Signal
from odigos_tpu.config.model import Configuration, RolloutConfiguration
from odigos_tpu.controlplane.cluster import Container
from odigos_tpu.destinations import Destination
from odigos_tpu.e2e.environment import E2EEnvironment
from odigos_tpu.pdata import synthesize_traces
from odigos_tpu.wire.client import WireExporter


@pytest.fixture
def full_stack():
    config = Configuration(
        rollout=RolloutConfiguration(rollback_grace_time_s=0.0))
    config.metrics_sources.host_metrics = True
    config.metrics_sources.kubelet_stats = True
    env = E2EEnvironment(nodes=2, config=config, node_collectors=True)
    env.start()
    try:
        env.cluster.add_workload("shop", "cart",
                                 [Container("main", language="python")])
        env.instrument_workload("shop", "cart")
        env.add_destination(Destination(
            id="db", dest_type="tracedb", signals=[Signal.TRACES]))
        env.add_destination(Destination(
            id="m1", dest_type="mock",
            signals=[Signal.METRICS],
            config={"MOCK_REJECT_FRACTION": "0.0",
                    "MOCK_RESPONSE_DURATION": "0"}))
        yield env
    finally:
        env.shutdown()


def test_node_collectors_boot_from_generated_config(full_stack):
    env = full_stack
    assert set(env.node_collectors) == {"node-0", "node-1"}
    for node, collector in env.node_collectors.items():
        # generated receivers resolved and built (the contract this round
        # exists to protect)
        assert "spanring" in collector.graph.receivers
        assert "hostmetrics" in collector.graph.receivers
        assert "kubeletstats" in collector.graph.receivers
        # downward-API substitution happened per node
        assert collector.graph.receivers[
            "kubeletstats"].config["node"] == node


def test_spans_flow_node_to_gateway_destination(full_stack):
    """Wire in at a NODE collector -> loadbalancing (k8s service resolver)
    -> gateway -> tracedb destination."""
    env = full_stack
    port = env.node_otlp_port("node-0")
    exp = WireExporter("otlpwire/test", {"endpoint": f"127.0.0.1:{port}"})
    exp.start()
    try:
        batch = synthesize_traces(40, seed=11)
        exp.export(batch)
        assert exp.flush(timeout=15), "node collector did not accept"
    finally:
        exp.shutdown()
    db = env.gateway_component("tracedb/tracedb-db")
    assert db.wait_for_spans(len(batch), timeout=30), \
        f"gateway destination saw {db.span_count}/{len(batch)} spans"


def test_node_metrics_reach_gateway_destination(full_stack):
    """kubeletstats + hostmetrics scraped on each node arrive at the
    gateway's metrics destination, tagged with the scraping node."""
    env = full_stack
    for node, collector in env.node_collectors.items():
        collector.graph.receivers["kubeletstats"].scrape_once()
        collector.graph.receivers["hostmetrics"].scrape_once()
    mock = env.gateway_component("mockdestination/m1")
    deadline = time.time() + 30
    while time.time() < deadline and mock.accepted_spans == 0:
        time.sleep(0.1)
    assert mock.accepted_spans > 0, "no metrics reached the gateway"


def test_scaleout_routes_whole_traces_per_replica(full_stack):
    """Two gateway replicas: the node collector's consistent-hash
    loadbalancer must keep every trace intact on ONE replica (whole-trace
    operations — tail sampling, trace-tree models — depend on it;
    traces.go:26 routing_key traceID) while both replicas take traffic."""
    import numpy as np

    from odigos_tpu.pipeline.service import Collector
    from odigos_tpu.wire.hotreload import watch_configmap
    from odigos_tpu.wire.servicemap import register_service
    from odigos_tpu.controlplane.autoscaler import GATEWAY_CONFIG_NAME
    from odigos_tpu.controlplane.scheduler import ODIGOS_NAMESPACE

    env = full_stack
    # second replica from the same generated ConfigMap
    cm = env.store.get("ConfigMap", ODIGOS_NAMESPACE, GATEWAY_CONFIG_NAME)
    replica2 = Collector(cm.data["collector-conf"]).start()
    unsub = watch_configmap(env.store, ODIGOS_NAMESPACE,
                            GATEWAY_CONFIG_NAME, replica2,
                            extract=lambda d: d["collector-conf"])
    try:
        def port_of(collector):
            for rid, recv in collector.graph.receivers.items():
                if rid.split("/")[0] == "otlp" and hasattr(recv, "port"):
                    return recv.port
            raise AssertionError("no wire front door")

        register_service("odigos-gateway.odigos-system", [
            f"127.0.0.1:{env.gateway_otlp_port()}",
            f"127.0.0.1:{port_of(replica2)}"])

        port = env.node_otlp_port("node-0")
        exp = WireExporter("otlpwire/scale",
                           {"endpoint": f"127.0.0.1:{port}"})
        exp.start()
        try:
            batch = synthesize_traces(120, seed=21)
            exp.export(batch)
            assert exp.flush(timeout=15)
        finally:
            exp.shutdown()

        db1 = env.gateway_component("tracedb/tracedb-db")
        db2 = replica2.component("tracedb/tracedb-db")
        deadline = time.time() + 30
        while time.time() < deadline:
            if db1.span_count + db2.span_count >= len(batch):
                break
            time.sleep(0.1)
        assert db1.span_count + db2.span_count == len(batch), \
            f"{db1.span_count}+{db2.span_count} != {len(batch)}"
        assert db1.span_count and db2.span_count, \
            "one replica took all traffic — ring not spreading"
        # whole traces: no trace id appears on both replicas
        t1 = set(np.unique(db1.all_spans().col("trace_id_lo")).tolist())
        t2 = set(np.unique(db2.all_spans().col("trace_id_lo")).tolist())
        assert not (t1 & t2), f"split traces: {sorted(t1 & t2)[:5]}"
    finally:
        unsub()
        replica2.shutdown()
        # restore the single-replica registration for other tests
        env._refresh_gateway_service()


def test_gateway_restart_reresolves_service(full_stack):
    """The k8s-resolver seam: after a gateway hot-reload moves the wire
    listener, reconcile refreshes the service registration and node
    traffic keeps flowing (endpoints-watch behavior)."""
    env = full_stack
    old_port = env.gateway_otlp_port()
    # force a reload by toggling a config-affecting knob
    env.instrument_workload("shop", "cart2_missing")  # no-op workload ref
    env.cluster.add_workload("shop", "pay",
                             [Container("main", language="python")])
    env.instrument_workload("shop", "pay")
    env.reconcile()
    port = env.node_otlp_port("node-1")
    exp = WireExporter("otlpwire/test2", {"endpoint": f"127.0.0.1:{port}"})
    exp.start()
    try:
        batch = synthesize_traces(10, seed=12)
        exp.export(batch)
        assert exp.flush(timeout=15)
    finally:
        exp.shutdown()
    db = env.gateway_component("tracedb/tracedb-db")
    assert db.wait_for_spans(10, timeout=30)
    assert old_port  # referenced so the pre-reload port is demonstrably read