"""Shared utilities (the `common/` of the reference)."""
