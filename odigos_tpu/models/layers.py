"""Shared flax modules: span embedding trunk + transformer encoder blocks.

MXU discipline (see /opt/skills/guides/pallas_guide.md and SURVEY.md env
notes): feature dims multiples of 128, bfloat16 activations with float32
params, no data-dependent shapes — everything here jits to static-shape
einsums that XLA tiles onto the systolic array.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from ..features.featurizer import CAT_FIELDS


class SpanEmbedder(nn.Module):
    """Embeds the featurizer's categorical/continuous columns into d_model.

    Column layout follows features.featurizer.CAT_FIELDS:
      0 service, 1 name, 2 kind, 3 status, 4 parent_service, 5.. attr slots.
    parent_service shares the service table (same id space); attr slots share
    one attr table and are summed.
    """

    service_vocab: int
    name_vocab: int
    attr_vocab: int
    d_model: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, categorical: jnp.ndarray,
                 continuous: jnp.ndarray) -> jnp.ndarray:
        d = self.d_model
        svc_table = nn.Embed(self.service_vocab, d, dtype=self.dtype,
                             name="service_embed")
        x = svc_table(categorical[..., 0])
        x += nn.Embed(self.name_vocab, d, dtype=self.dtype,
                      name="name_embed")(categorical[..., 1])
        x += nn.Embed(8, d, dtype=self.dtype,
                      name="kind_embed")(categorical[..., 2])
        x += nn.Embed(4, d, dtype=self.dtype,
                      name="status_embed")(categorical[..., 3])
        x += svc_table(categorical[..., 4])  # parent edge, shared table
        n_attr = categorical.shape[-1] - len(CAT_FIELDS)
        if n_attr > 0:
            attr_table = nn.Embed(self.attr_vocab, d, dtype=self.dtype,
                                  name="attr_embed")
            x += attr_table(categorical[..., len(CAT_FIELDS):]).sum(axis=-2)
        x += nn.Dense(d, dtype=self.dtype, name="cont_proj")(
            continuous.astype(self.dtype))
        return x


class EncoderBlock(nn.Module):
    """Pre-LN bidirectional transformer block with padding mask."""

    d_model: int
    n_heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0

    @nn.compact
    def __call__(self, x: jnp.ndarray, attn_mask: jnp.ndarray,
                 deterministic: bool = True) -> jnp.ndarray:
        # attn_mask: (T, 1, L, L) bool, True where attention is allowed
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.MultiHeadDotProductAttention(
            num_heads=self.n_heads, dtype=self.dtype,
            dropout_rate=self.dropout, deterministic=deterministic,
        )(h, h, mask=attn_mask)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.d_ff, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.d_model, dtype=self.dtype)(h)
        return x + h


class Encoder(nn.Module):
    """Embedding trunk + positional embedding + N encoder blocks."""

    service_vocab: int
    name_vocab: int
    attr_vocab: int
    d_model: int
    n_heads: int
    n_layers: int
    d_ff: int
    max_len: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, categorical, continuous, mask,
                 deterministic: bool = True,
                 positions: jnp.ndarray | None = None,
                 segments: jnp.ndarray | None = None) -> jnp.ndarray:
        """``segments`` (row-local trace ids, 0 = padding) switches attention
        to block-diagonal — the packed-sequences path (features.pack_sequences)
        that keeps MXU density high regardless of trace length distribution.
        ``positions`` overrides the positional-embedding index (within-trace
        position for packed rows)."""
        x = SpanEmbedder(self.service_vocab, self.name_vocab, self.attr_vocab,
                         self.d_model, self.dtype, name="embed")(
            categorical, continuous)
        L = categorical.shape[-2]
        pos_ids = positions if positions is not None else jnp.arange(L)
        pos = nn.Embed(self.max_len, self.d_model, dtype=self.dtype,
                       name="pos_embed")(pos_ids)
        x = x + pos
        x = x * mask[..., None].astype(self.dtype)
        if segments is not None:
            attn_mask = ((segments[..., None] == segments[..., None, :])
                         & mask[..., None] & mask[..., None, :])[:, None]
        else:
            attn_mask = (mask[:, None, None, :] & mask[:, None, :, None])
        for i in range(self.n_layers):
            x = EncoderBlock(self.d_model, self.n_heads, self.d_ff,
                             self.dtype, name=f"block_{i}")(
                x, attn_mask, deterministic)
        return nn.LayerNorm(dtype=self.dtype, name="final_ln")(x)
