"""Per-jit-site compile accounting (ISSUE 3 device-runtime telemetry).

Every jitted scoring/training entry point already declares its shape-
bucketing strategy (``SHAPE_BUCKETING``, package-hygiene test); this
module adds the runtime half: which jit sites exist as live compiled
functions, how many cached executables each holds (one per traced input
shape — the cache growing past the declared bucket ladder is the
unbounded-recompile hazard showing up live), and how many cumulative
seconds each site has spent compiling (observed where code can see a
compile happen: the engine's first-call split, ladder warming).

Deliberately jax-free at import time: the DeviceRuntimeCollector reads
these tables from a telemetry thread that must never be the reason jax
(or a device runtime) gets initialized. Tracked functions are held by
weakref — accounting must not extend executable lifetimes.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Callable

_lock = threading.Lock()
# site -> weakref to the jitted callable (PjitFunction exposes
# _cache_size(); absent/changed API degrades to "size unknown")
_tracked: dict[str, Any] = {}
# site -> cumulative observed compile seconds
_compile_seconds: dict[str, float] = {}


def track_jit(site: str, fn: Callable) -> Callable:
    """Register a jitted callable under a stable site name and return it
    unchanged (wrap-at-assignment idiom: the jit site passes its freshly
    built compiled function through here)."""
    try:
        ref = weakref.ref(fn)
    except TypeError:  # some wrappers refuse weakrefs: drop tracking
        return fn
    with _lock:
        _tracked[site] = ref
    return fn


def record_compile_seconds(site: str, seconds: float) -> None:
    """Accumulate observed compile time for a site (engine first-call
    split, ladder warm passes)."""
    if seconds <= 0:
        return
    with _lock:
        _compile_seconds[site] = _compile_seconds.get(site, 0.0) + seconds


def cache_sizes() -> dict[str, int]:
    """Live jit-cache executable count per tracked site. Dead refs are
    pruned; callables without a readable cache size report -1 (tracked,
    size unknown) rather than vanishing."""
    out: dict[str, int] = {}
    with _lock:
        dead = []
        for site, ref in _tracked.items():
            fn = ref()
            if fn is None:
                dead.append(site)
                continue
            size = getattr(fn, "_cache_size", None)
            try:
                out[site] = int(size()) if callable(size) else -1
            except Exception:  # noqa: BLE001 — private API drifted
                out[site] = -1
        for site in dead:
            del _tracked[site]
    return out


def compile_seconds() -> dict[str, float]:
    with _lock:
        return dict(_compile_seconds)


def reset() -> None:
    """Test hook: drop all tracked sites and accumulated seconds."""
    with _lock:
        _tracked.clear()
        _compile_seconds.clear()
