"""Standalone collector entrypoint — the VM-distribution binary role.

Reference: collector/distribution/odigos-otelcol/ packages the same
collector binary for non-k8s VMs via systemd (``odigos-otelcol.service``
runs ``/usr/bin/odigos-otelcol $OTELCOL_OPTIONS``). The analog:

    python -m odigos_tpu.pipeline --config /etc/odigos-tpu/collector.json

Runs one Collector from a JSON config file, re-reads it on SIGHUP (the
odigosk8scmprovider hot-reload seam, file-flavored), drains on
SIGTERM/SIGINT, and exposes the self-metrics snapshot over a local HTTP
port for a node Prometheus (--metrics-port; own-observability role).
Packaging files live in ``distribution/odigos-tpu-collector/`` at the
repo root.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m odigos_tpu.pipeline",
        description="odigos-tpu standalone collector (VM distribution)")
    ap.add_argument("--config", required=True,
                    help="JSON collector config (receivers/processors/"
                         "exporters/service.pipelines)")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve the self-metrics snapshot on this port "
                         "(0 = disabled)")
    args = ap.parse_args(argv)

    from .service import Collector

    with open(args.config) as f:
        config = json.load(f)
    collector = Collector(config).start()
    print(f"collector up: {len(collector.graph.all_components())} "
          f"components", flush=True)

    metrics_server = None
    if args.metrics_port:
        metrics_server = _serve_metrics(args.metrics_port, collector)
        print(f"self-metrics on :{metrics_server.server_address[1]}"
              f"/metrics", flush=True)

    stop = threading.Event()

    def on_term(signum, frame):
        stop.set()

    def on_hup(signum, frame):
        # file-flavored hot reload (odigosk8scmprovider seam)
        try:
            with open(args.config) as f:
                new_config = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"reload skipped: {e}", file=sys.stderr, flush=True)
            return
        try:
            collector.reload(new_config)
        except Exception as e:  # noqa: BLE001 — bad config must not kill us
            # reload() resurrected the old graph; report and keep serving
            print(f"reload failed (old config still serving): {e}",
                  file=sys.stderr, flush=True)
            return
        print("config reloaded", flush=True)

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)
    signal.signal(signal.SIGHUP, on_hup)
    stop.wait()
    if metrics_server is not None:
        metrics_server.shutdown()
    collector.shutdown()
    print("collector drained", flush=True)
    return 0


def _serve_metrics(port: int, collector=None):
    """Prometheus-text self-metrics endpoint plus /healthz — the
    own-observability + healthcheckextension roles (the reference distro
    compiles healthcheckextension into the collector,
    builder-config.yaml; systemd/k8s probes poll it)."""
    import json as _json
    import socketserver
    from http.server import BaseHTTPRequestHandler

    from ..utils.telemetry import meter, prometheus_text

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *a):  # noqa: D102
            pass

        def do_GET(self):  # noqa: N802
            path = self.path.rstrip("/")
            if path == "/healthz":
                unhealthy = []
                if collector is not None:
                    unhealthy = sorted(
                        c.name for c in collector.graph.all_components()
                        if not c.healthy())
                body = _json.dumps(
                    {"status": "ok" if not unhealthy else "unhealthy",
                     "unhealthy_components": unhealthy}).encode()
                self.send_response(200 if not unhealthy else 503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if path not in ("", "/metrics"):
                self.send_response(404)
                self.end_headers()
                return
            # exemplar annotations ride the collector scrape too —
            # this process hosts the engine/pipeline histograms
            from ..selftelemetry.flow import flow_ledger

            flow_ledger.publish(meter)
            body = prometheus_text(meter.snapshot(),
                                   meter.exemplars()).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    server = Server(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="collector-metrics").start()
    return server


if __name__ == "__main__":
    sys.exit(main())
