"""Config system tests: profiles (tier gating, dependency expansion),
sizing derivation (memory-limiter math of resource_config.go:22-32),
effective-config computation."""

import pytest

from odigos_tpu.config import (
    ALL_PROFILES,
    Configuration,
    PROFILES_BY_NAME,
    Tier,
    available_profiles_for_tier,
    calculate_effective_config,
)
from odigos_tpu.config.model import EnvInjectionMethod, MountMethod
from odigos_tpu.config.profiles import resolve_profiles
from odigos_tpu.config.sizing import (
    SIZING_PRESETS,
    gateway_resources,
)
from odigos_tpu.config.model import CollectorGatewayConfiguration


class TestProfiles:
    def test_registry_size_and_categories(self):
        # parity with the reference's 22 profiles in 4 categories
        assert len(ALL_PROFILES) == 22
        cats = {p.category for p in ALL_PROFILES}
        assert cats == {"aggregators", "attributes", "instrumentation", "pipeline"}

    def test_tier_gating(self):
        community = available_profiles_for_tier(Tier.COMMUNITY)
        assert all(p.minimum_tier == Tier.COMMUNITY for p in community)
        assert len(available_profiles_for_tier(Tier.ONPREM)) == len(ALL_PROFILES)

    def test_aggregator_expands_dependencies(self):
        profiles, problems = resolve_profiles(["kratos"], Tier.ONPREM)
        names = [p.name for p in profiles]
        assert "kratos" in names
        assert "full-payload-collection" in names
        assert "allow_concurrent_agents" in names
        assert not problems

    def test_greatwall_is_kratos_plus_small_batches(self):
        profiles, _ = resolve_profiles(["greatwall"], Tier.ONPREM)
        names = {p.name for p in profiles}
        assert "kratos" in names and "small-batches" in names

    def test_tier_violation_reported(self):
        profiles, problems = resolve_profiles(["kratos"], Tier.COMMUNITY)
        assert profiles == []
        assert any("requires tier" in p for p in problems)

    def test_unknown_profile_reported(self):
        _, problems = resolve_profiles(["no-such-profile"], Tier.ONPREM)
        assert any("unknown profile" in p for p in problems)

    def test_duplicate_application_is_idempotent(self):
        profiles, _ = resolve_profiles(["kratos", "kratos"], Tier.ONPREM)
        names = [p.name for p in profiles]
        assert len(names) == len(set(names))


class TestSizing:
    def test_default_gateway_memory_limiter_math(self):
        # resource_config.go: 500Mi request -> 625Mi limit (1.25x) ->
        # hard 575 (limit-50), spike 115 (20%), gomem 460 (80%)
        r = gateway_resources(CollectorGatewayConfiguration())
        assert r.request_memory_mib == 500
        assert r.limit_memory_mib == 625
        assert r.memory_limiter_limit_mib == 575
        assert r.memory_limiter_spike_limit_mib == 115
        assert r.gomemlimit_mib == 460
        assert (r.min_replicas, r.max_replicas) == (1, 10)
        assert (r.request_cpu_m, r.limit_cpu_m) == (500, 1000)

    def test_explicit_overrides_win_over_preset(self):
        cfg = CollectorGatewayConfiguration(request_memory_mib=1000,
                                            min_replicas=4)
        r = gateway_resources(cfg, SIZING_PRESETS["size_s"])
        assert r.request_memory_mib == 1000
        assert r.min_replicas == 4
        # unset field falls back to the preset
        assert r.max_replicas == SIZING_PRESETS["size_s"].gateway_max_replicas

    def test_presets_monotonic(self):
        s, m, l = (SIZING_PRESETS[k] for k in ("size_s", "size_m", "size_l"))
        assert s.gateway_request_memory_mib < m.gateway_request_memory_mib \
            < l.gateway_request_memory_mib


class TestEffectiveConfig:
    def test_profiles_mutate_config(self):
        cfg = Configuration(profiles=["kratos", "mount-method-k8s-host-path"])
        eff = calculate_effective_config(cfg, Tier.ONPREM)
        assert eff.config.allow_concurrent_agents is True
        assert eff.config.mount_method == MountMethod.HOST_PATH
        assert eff.config.extra.get("payload_collection") == "full"
        assert not eff.problems

    def test_authored_config_not_mutated(self):
        cfg = Configuration(profiles=["allow_concurrent_agents"])
        calculate_effective_config(cfg, Tier.COMMUNITY)
        assert cfg.allow_concurrent_agents is None

    def test_small_batches_profile_surfaces_in_extra(self):
        cfg = Configuration(profiles=["greatwall"])
        eff = calculate_effective_config(cfg, Tier.ONPREM)
        assert eff.config.extra["small_batches"]["send_batch_size"] == 100

    def test_sizing_preset_applied(self):
        cfg = Configuration(resource_size_preset="size_l")
        eff = calculate_effective_config(cfg)
        assert eff.gateway.min_replicas == 3

    def test_unknown_preset_reported(self):
        cfg = Configuration(resource_size_preset="size_xxl")
        eff = calculate_effective_config(cfg)
        assert any("preset" in p for p in eff.problems)

    def test_roundtrip_dict(self):
        cfg = Configuration(profiles=["semconv"], cluster_name="c1")
        d = cfg.to_dict()
        back = Configuration.from_dict(d)
        assert back.cluster_name == "c1"
        assert back.profiles == ["semconv"]
        assert back.collector_gateway.min_replicas is None

    def test_env_injection_profile(self):
        cfg = Configuration(profiles=["pod-manifest-env-var-injection"])
        eff = calculate_effective_config(cfg)
        assert eff.config.agent_env_vars_injection_method == \
            EnvInjectionMethod.POD_MANIFEST


class TestReviewRegressions:
    def test_cloud_tier_excludes_onprem_profiles(self):
        from odigos_tpu.config.profiles import resolve_profiles
        profiles, problems = resolve_profiles(["kratos"], Tier.CLOUD)
        assert profiles == []
        assert any("requires tier" in p for p in problems)

    def test_optional_oidc_hydrated(self):
        from odigos_tpu.config.model import OidcConfiguration
        cfg = Configuration.from_dict(
            {"oidc": {"tenant_url": "https://t", "client_id": "c"}})
        assert isinstance(cfg.oidc, OidcConfiguration)
        assert cfg.oidc.tenant_url == "https://t"

    def test_anomaly_threshold_within_score_contract(self):
        from odigos_tpu.config.model import AnomalyStageConfiguration
        assert 0.0 <= AnomalyStageConfiguration().threshold <= 1.0

    def test_profile_cycle_reported(self):
        import odigos_tpu.config.profiles as profmod
        from odigos_tpu.config.profiles import Profile, resolve_profiles
        a = Profile("cycle-a", Tier.COMMUNITY, "", "attributes",
                    dependencies=("cycle-b",))
        b = Profile("cycle-b", Tier.COMMUNITY, "", "attributes",
                    dependencies=("cycle-a",))
        profmod.PROFILES_BY_NAME["cycle-a"] = a
        profmod.PROFILES_BY_NAME["cycle-b"] = b
        profmod.ALL_PROFILES.extend([a, b])
        try:
            _, problems = resolve_profiles(["cycle-a"], Tier.COMMUNITY)
            assert any("cycle" in p for p in problems), problems
        finally:
            profmod.ALL_PROFILES.remove(a)
            profmod.ALL_PROFILES.remove(b)
            del profmod.PROFILES_BY_NAME["cycle-a"]
            del profmod.PROFILES_BY_NAME["cycle-b"]
