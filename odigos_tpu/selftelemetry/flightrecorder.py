"""Incident flight recorder: the process-global black box.

The planes built so far *detect* trouble (alert rules, the failover
breaker, the conservation checker) and *react* to it (the actuator's
canary/rollback loop, patch-fallback reloads) — but the evidence ages
out: series leave the 240-slot ring, conditions round-trip back to
Healthy, and a rolled-back canary survives only as a counter. This
module is the black box that makes the last incident explainable after
the fact, the way a flight recorder outlives the flight.

Two layers:

* **event ring** — a bounded deque of structured events, recorded
  continuously by every plane that does something worth explaining:
  alert transitions, breaker trips/recoveries, actuator proposals/
  canaries/promotions/rollbacks/refusals, reload classifications and
  patch fallbacks, coalesced drop bursts (carrying the dropping frame's
  self-trace id), GC pauses over threshold, admission-watermark verdict
  transitions, chaos injections, and periodic compressed excerpts of
  the series alert rules reference. Recording is lock-light (one short
  critical section per event) and always on; ``ODIGOS_FLIGHT=0`` turns
  the whole recorder into a no-op.
* **incident store** — when a :data:`TRIGGERS` source fires, the
  recorder *freezes an incident*: the pre-trigger lookback of the event
  ring, a post-trigger tail (sealed after a bounded count/window), the
  triggering rule's series excerpt gathered at freeze time, the
  worst-frame self-trace exemplars from the stage-latency recorder,
  the active config hash + last reload classification, and the
  conditions snapshot. Incidents are retained in a bounded ring with
  evictions counted, and each (trigger, scope) pair is cooldown'd so a
  flapping source cannot flood the store.

The trigger registry is CLOSED — ``trigger()`` raises on an unknown
name, and package hygiene lints every call site against
:data:`TRIGGERS` (the DROP_REASONS / INJECTORS discipline).

Everything upstream of :mod:`utils.telemetry` is imported lazily at
freeze time: the recorder must be importable from any plane (fleet,
actuator, failover, flow, fastpath, wire) without creating a cycle.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from ..utils.telemetry import labeled_key, meter

# ------------------------------------------------------------- metrics

EVENTS_METRIC = "odigos_flightrecorder_events_total"
EVENTS_EVICTED_METRIC = "odigos_flightrecorder_events_evicted_total"
INCIDENTS_METRIC = "odigos_flightrecorder_incidents_total"
SUPPRESSED_METRIC = "odigos_flightrecorder_suppressed_total"
INCIDENTS_EVICTED_METRIC = \
    "odigos_flightrecorder_incidents_evicted_total"

# ------------------------------------------------------------- registry

# The closed trigger registry: every source that can freeze an incident
# must be named here, and every name here must have a live call site —
# TestFlightTriggerHygiene lints both directions (the stale-entry
# oracle). Values are the one-line operator description rendered on
# /debug/incidentz and in the docs trigger table.
TRIGGERS: dict[str, str] = {
    "alert_firing": "an alert rule transitioned to firing",
    "actuator_rollback": "a canary or promotion step rolled back",
    "breaker_trip": "the failover breaker opened on a scoring model",
    "conservation_leak": "flow conservation found a stable leak",
    "patch_fallback": "an incremental reload fell back to a rebuild",
    "chaos_injection": "a chaos injector faulted the system on purpose",
    "compile_storm": "unplanned XLA recompiles burst inside one window",
}

# ------------------------------------------------------------- sizing

EVENT_RING = 2048          # black-box timeline depth
LOOKBACK_EVENTS = 256      # pre-trigger slice copied into a bundle
TAIL_EVENTS = 64           # post-trigger events before the tail seals
TAIL_WINDOW_S = 15.0       # ... or this much wall time, whichever first
MAX_INCIDENTS = 32         # incident store cap (evictions counted)
TRIGGER_COOLDOWN_S = 30.0  # per (trigger, scope) refreeze suppression
EXCERPT_SERIES = 8         # series per excerpt (cardinality guard)
EXCERPT_POINTS = 32        # points per series after compression
EXCERPT_INTERVAL_S = 5.0   # periodic excerpt cadence per rule
WORST_FRAMES = 8           # trace exemplars joined into a bundle
DROP_COALESCE_S = 0.25     # drop-burst events merge inside this window


def _compress(pts: list[tuple[float, float]],
              cap: int = EXCERPT_POINTS) -> list[list[float]]:
    """Stride-downsample a point list to ``cap`` entries, always
    keeping the newest point (the one an operator reads first)."""
    if len(pts) > cap:
        stride = len(pts) / float(cap)
        pts = [pts[min(int(i * stride), len(pts) - 1)]
               for i in range(cap - 1)] + [pts[-1]]
    return [[round(float(t), 3), float(v)] for t, v in pts]


class FlightRecorder:
    """Process-global black box + incident store (singleton:
    :data:`flight_recorder`)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self.reset()

    # ------------------------------------------------------- lifecycle

    def reset(self) -> None:
        """Drop all state and re-sample the kill switch (the test
        seam every plane singleton exposes)."""
        with self._lock:
            self.enabled = os.environ.get("ODIGOS_FLIGHT", "1") != "0"
            self._events: deque[dict[str, Any]] = deque(
                maxlen=EVENT_RING)
            self._seq = 0
            self._events_total = 0
            self._events_evicted = 0
            self._incidents: deque[dict[str, Any]] = deque()
            self._incident_seq = 0
            self._incidents_evicted = 0
            self._open: list[dict[str, Any]] = []
            self._last_trigger: dict[tuple[str, str], float] = {}
            self._suppressed = 0
            self._excerpt_at: dict[str, float] = {}
            self._config: dict[str, Any] = {"hash": None,
                                            "last_reload": None}

    # ------------------------------------------------------ event ring

    def record(self, kind: str, **fields: Any) -> None:
        """Append one structured event to the black box. Lock-light:
        one short critical section, one labeled counter bump."""
        if not self.enabled:
            return
        evt: dict[str, Any] = {"kind": kind,
                               "unix_ts": time.time()}
        evt.update(fields)
        now = self._clock()
        with self._lock:
            self._seq += 1
            evt["seq"] = self._seq
            if len(self._events) == EVENT_RING:
                self._events_evicted += 1
            self._events.append(evt)
            self._events_total += 1
            self._feed_tails(evt, now)
        meter.add(labeled_key(EVENTS_METRIC, kind=kind))
        if len(self._events) == EVENT_RING:
            meter.set_gauge(EVENTS_EVICTED_METRIC,
                            float(self._events_evicted))

    def record_drop_burst(self, pipeline: str, component: str,
                          reason: str, n: int,
                          blame: Optional[str] = None,
                          trace_id: Optional[str] = None,
                          span_id: Optional[str] = None) -> None:
        """Drop-burst event with in-place coalescing: consecutive drops
        of the same (pipeline, component, reason) inside
        :data:`DROP_COALESCE_S` mutate the last event's count instead
        of minting a new one — a 10k-frame shed is one timeline line,
        not 10k. The trace fields carry the ACTIVE self-trace of the
        dropping frame (the flowz last-drop witness, unified on one
        field pair)."""
        if not self.enabled:
            return
        now_unix = time.time()
        with self._lock:
            last = self._events[-1] if self._events else None
            if (last is not None and last.get("kind") == "drop_burst"
                    and last.get("pipeline") == pipeline
                    and last.get("component") == component
                    and last.get("reason") == reason
                    and now_unix - last["unix_ts"] <= DROP_COALESCE_S):
                last["n"] += n
                if trace_id is not None:
                    last["trace_id"] = trace_id
                    last["span_id"] = span_id
                return
        fields: dict[str, Any] = {"pipeline": pipeline,
                                  "component": component,
                                  "reason": reason, "n": n}
        if blame is not None:
            fields["blame"] = blame
        if trace_id is not None:
            fields["trace_id"] = trace_id
            fields["span_id"] = span_id
        self.record("drop_burst", **fields)

    def excerpt_tick(self, rule: str, expr: str) -> None:
        """Periodic compressed excerpt of the series a rule references
        (rate-limited per rule) — the continuous-capture half of the
        tentpole: even before any trigger, the black box holds recent
        shape of every watched series."""
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            last = self._excerpt_at.get(rule)
            if last is not None and now - last < EXCERPT_INTERVAL_S:
                return
            self._excerpt_at[rule] = now
        ex = self._series_excerpt(expr)
        if ex is None:
            return
        stats = {key: {"last": s["last"], "min": s["min"],
                       "max": s["max"], "count": s["count"]}
                 for key, s in ex["series"].items()}
        self.record("series_excerpt", rule=rule,
                    metric=ex["metric"], series=stats)

    def note_config(self, config_hash: Optional[str],
                    collector: str = "") -> None:
        """Remember the active config hash (collector build time)."""
        if not self.enabled:
            return
        with self._lock:
            self._config["hash"] = config_hash
            if collector:
                self._config["collector"] = collector

    def note_reload(self, mode: str, config_hash: Optional[str] = None,
                    collector: str = "", detail: str = "") -> None:
        """Remember the last reload's diff classification + record the
        timeline event (``patch``/``partial``/``full``/
        ``patch_fallback`` — the PR 13 vocabulary)."""
        if not self.enabled:
            return
        with self._lock:
            self._config["last_reload"] = {
                "mode": mode, "collector": collector,
                "detail": detail, "unix_ts": time.time()}
            if config_hash is not None:
                self._config["hash"] = config_hash
            if collector:
                self._config["collector"] = collector
        self.record("reload", mode=mode, collector=collector,
                    detail=detail)

    # -------------------------------------------------------- triggers

    def trigger(self, name: str, detail: str = "",
                rule: Optional[str] = None,
                expr: Optional[str] = None,
                **fields: Any) -> Optional[str]:
        """Freeze an incident. ``name`` must be in :data:`TRIGGERS`
        (closed registry — unknown names raise, and the hygiene lint
        catches them statically). ``rule``/``expr`` select the series
        excerpt; extra ``fields`` ride into the bundle (``fault=`` for
        chaos injections). Returns the incident id, or None when the
        recorder is off or the (trigger, scope) pair is cooling down."""
        if name not in TRIGGERS:
            raise ValueError(f"unregistered flight trigger {name!r} "
                             f"(known: {sorted(TRIGGERS)})")
        if not self.enabled:
            return None
        now = self._clock()
        scope = str(fields.get("fault") or rule or "")
        with self._lock:
            last = self._last_trigger.get((name, scope))
            if last is not None and now - last < TRIGGER_COOLDOWN_S:
                self._suppressed += 1
                suppressed = True
            else:
                self._last_trigger[(name, scope)] = now
                suppressed = False
        if suppressed:
            meter.add(labeled_key(SUPPRESSED_METRIC, trigger=name))
            return None
        # bundle assembly happens OUTSIDE the lock: the excerpt /
        # worst-frame / conditions reads take other planes' locks, and
        # holding ours across them is the ABBA half of a deadlock
        if expr is None and rule is not None:
            expr = self._rule_expr(rule)
        incident: dict[str, Any] = {
            "trigger": name, "detail": detail, "rule": rule,
            "unix_ts": time.time(),
            "series_excerpt": self._series_excerpt(expr),
            "worst_frames": self._worst_frames(),
            "config": None,  # filled under the lock below
            "conditions": self._conditions(),
            "tail": [], "sealed": False,
        }
        incident.update(fields)
        with self._lock:
            self._incident_seq += 1
            incident["id"] = f"inc-{self._incident_seq:04d}"
            incident["events"] = [dict(e) for e in
                                  list(self._events)[-LOOKBACK_EVENTS:]]
            incident["config"] = dict(self._config)
            incident["_seal_at"] = now + TAIL_WINDOW_S
            self._incidents.append(incident)
            self._open.append(incident)
            while len(self._incidents) > MAX_INCIDENTS:
                evicted = self._incidents.popleft()
                if evicted in self._open:
                    self._open.remove(evicted)
                self._incidents_evicted += 1
        meter.add(labeled_key(INCIDENTS_METRIC, trigger=name))
        meter.set_gauge(INCIDENTS_EVICTED_METRIC,
                        float(self._incidents_evicted))
        self.record("incident_frozen", trigger=name,
                    incident=incident["id"], detail=detail)
        return incident["id"]

    def _feed_tails(self, evt: dict[str, Any], now: float) -> None:
        """Append a fresh event to every open incident's post-trigger
        tail; seal tails that hit their count or window bound. Caller
        holds the lock."""
        if not self._open:
            return
        still_open = []
        for inc in self._open:
            if now >= inc["_seal_at"]:
                inc["sealed"] = True
                continue
            inc["tail"].append(dict(evt))
            if len(inc["tail"]) >= TAIL_EVENTS:
                inc["sealed"] = True
            else:
                still_open.append(inc)
        self._open = still_open

    def _seal_expired(self) -> None:
        now = self._clock()
        with self._lock:
            still_open = []
            for inc in self._open:
                if now >= inc["_seal_at"]:
                    inc["sealed"] = True
                else:
                    still_open.append(inc)
            self._open = still_open

    # ------------------------------------------- bundle ingredient taps

    def _rule_expr(self, rule: str) -> Optional[str]:
        try:
            from .fleet import alert_engine
            with alert_engine._lock:
                r = alert_engine._rules.get(rule)
            return r.expr if r is not None else None
        except Exception:  # noqa: BLE001 — a broken tap must not
            return None    # lose the incident itself

    def _series_excerpt(self, expr: Optional[str]
                        ) -> Optional[dict[str, Any]]:
        """Compressed points of every series the triggering expression
        references, over twice its window (enough pre-breach shape to
        see the ramp, bounded enough to stay a bundle not a dump)."""
        if not expr:
            return None
        try:
            from .fleet import parse_expr
            from .seriesstate import series_store
            if not series_store.enabled:
                return None
            p = parse_expr(expr)
            out: dict[str, Any] = {"expr": expr, "metric": p["metric"],
                                   "window_s": p["window_s"],
                                   "series": {}}
            keys = sorted(series_store.select(
                p["metric"], p["labels"] or None))[:EXCERPT_SERIES]
            for key in keys:
                pts = series_store.points(key, p["window_s"] * 2.0)
                if not pts:
                    continue
                vals = [v for _, v in pts]
                out["series"][key] = {
                    "points": _compress(pts),
                    "count": len(pts),
                    "min": min(vals), "max": max(vals),
                    "last": vals[-1],
                }
            return out if out["series"] else out
        except Exception:  # noqa: BLE001
            return None

    def _worst_frames(self) -> list[dict[str, Any]]:
        try:
            from .latency import latency_ledger
            return latency_ledger.worst_frames()[:WORST_FRAMES]
        except Exception:  # noqa: BLE001
            return []

    def _conditions(self) -> list[dict[str, Any]]:
        """Best-effort snapshot of every registered rollup's CURRENT
        condition rows, without evaluating and without taking rollup
        locks: triggers fire from under plane locks (the breaker's
        ``_trip`` holds the breaker lock) that a concurrent
        ``HealthRollup.evaluate`` — holding the rollup lock — reads
        back through, so taking the rollup lock here is the ABBA half
        of a deadlock, and re-evaluating would recurse through the
        alert engine into this very trigger. A torn read loses one
        display row, never the incident."""
        try:
            from .flow import iter_rollups
            merged: dict[str, dict[str, Any]] = {}
            for rollup in iter_rollups():
                try:
                    conds = [dict(c) for c in
                             list(rollup._state.values())]
                except RuntimeError:  # resized mid-iteration
                    conds = []
                for cond in conds:
                    merged[cond["component"]] = cond
            return sorted(merged.values(),
                          key=lambda c: c["component"])
        except Exception:  # noqa: BLE001
            return []

    # -------------------------------------------------------- surfaces

    def incidents(self) -> list[dict[str, Any]]:
        """Full incident bundles, newest first (diagnose's
        incidents.json)."""
        self._seal_expired()
        with self._lock:
            out = []
            for inc in reversed(self._incidents):
                pub = {k: v for k, v in inc.items()
                       if not k.startswith("_")}
                pub["events"] = [dict(e) for e in pub["events"]]
                pub["tail"] = [dict(e) for e in pub["tail"]]
                out.append(pub)
            return out

    def incident(self, incident_id: str) -> Optional[dict[str, Any]]:
        for inc in self.incidents():
            if inc["id"] == incident_id:
                return inc
        return None

    def api_snapshot(self) -> dict[str, Any]:
        """The /api/incidents payload: store summaries + recorder
        health, full bundles by id via :meth:`incident`."""
        self._seal_expired()
        with self._lock:
            summaries = []
            for inc in reversed(self._incidents):
                summaries.append({
                    "id": inc["id"], "trigger": inc["trigger"],
                    "rule": inc["rule"], "detail": inc["detail"],
                    "unix_ts": inc["unix_ts"],
                    "sealed": inc["sealed"],
                    "events": len(inc["events"]),
                    "tail": len(inc["tail"]),
                    "worst_frames": len(inc["worst_frames"]),
                    "config_hash": (inc["config"] or {}).get("hash"),
                })
            return {
                "enabled": self.enabled,
                "events": len(self._events),
                "events_total": self._events_total,
                "events_evicted": self._events_evicted,
                "incidents": summaries,
                "incidents_evicted": self._incidents_evicted,
                "suppressed": self._suppressed,
                "triggers": sorted(TRIGGERS),
                "cooldown_s": TRIGGER_COOLDOWN_S,
            }

    def recent_events(self, n: int = 64) -> list[dict[str, Any]]:
        """Newest-first tail of the black box (/debug/incidentz)."""
        with self._lock:
            return [dict(e) for e in list(self._events)[-n:]][::-1]


flight_recorder = FlightRecorder()
