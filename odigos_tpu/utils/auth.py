"""On-prem/pro token validation — the odigosauth analog.

Reference: odigosauth/odigosauth.go:69 ValidateToken — decode the JWT
payload (no signature verification in the reference either; the token is
an entitlement record, not an authentication factor), then check exp /
iss / sub and extract the audience, which names the entitled tier.
Claim values keep reference parity so an existing odigos pro token is
accepted unchanged (migration compat).
"""

from __future__ import annotations

import base64
import binascii
import json
import time
from typing import Any

EXPECTED_ISSUER = "https://odigos.io"
EXPECTED_SUBJECT = "https://odigos.io/onprem"


class TokenError(ValueError):
    """Invalid/expired pro token."""


def extract_jwt_payload(token: str) -> dict[str, Any]:
    """odigosauth.go extractJWTPayload: split, base64url-decode the middle
    part, parse JSON."""
    parts = token.split(".")
    if len(parts) != 3:
        raise TokenError("invalid JWT token format")
    pad = "=" * (-len(parts[1]) % 4)
    try:
        # validate=True: non-alphabet bytes are an error, as in Go's
        # RawURLEncoding, not silently discarded
        raw = base64.b64decode(parts[1].replace("-", "+").replace("_", "/")
                               + pad, validate=True)
    except (binascii.Error, ValueError):
        raise TokenError("failed to decode JWT payload") from None
    try:
        payload = json.loads(raw)
    except json.JSONDecodeError as e:
        raise TokenError(f"failed to unmarshal JWT payload: {e}") from None
    if not isinstance(payload, dict):
        raise TokenError("JWT payload is not an object")
    return payload


def check_token_attributes(payload: dict[str, Any]) -> str:
    """odigosauth.go checkTokenAttributes: exp/iss/sub checks; returns the
    audience (string or first element of a list)."""
    exp = payload.get("exp")
    if exp is None:
        raise TokenError("missing exp claim")
    if isinstance(exp, bool) or not isinstance(exp, (int, float)):
        raise TokenError("invalid exp claim type")
    now = time.time()
    if now > float(exp):
        minutes = round((now - float(exp)) / 60)
        raise TokenError(f"token is expired for {minutes}m, contact "
                         f"support to issue a new one")
    if payload.get("iss") != EXPECTED_ISSUER:
        raise TokenError("invalid iss")
    if payload.get("sub") != EXPECTED_SUBJECT:
        raise TokenError("invalid sub")
    aud = payload.get("aud")
    if isinstance(aud, str) and aud:
        return aud
    if isinstance(aud, list) and aud and isinstance(aud[0], str) and aud[0]:
        return aud[0]
    raise TokenError("missing aud claim")


def validate_token(token: str) -> dict[str, Any]:
    """odigosauth.go:69 ValidateToken: full validation; returns the
    payload. Raises TokenError with an operator-actionable message."""
    payload, _aud = validate_token_audience(token)
    return payload


def validate_token_audience(token: str) -> tuple[dict[str, Any], str]:
    """Validate and return (payload, audience) in one pass; the audience
    names the entitled tier."""
    if not token:
        raise TokenError("missing pro token")
    payload = extract_jwt_payload(token.strip())
    aud = check_token_attributes(payload)
    return payload, aud


def entitled_tiers(aud: str) -> tuple[str, ...]:
    """Tiers an audience claim entitles: "onprem" also covers "cloud"."""
    return {"onprem": ("onprem", "cloud"), "cloud": ("cloud",)}.get(aud, ())


def validate_tier_claim(token: str, tier: str) -> dict[str, Any]:
    """Validate the token AND that its audience entitles ``tier`` — the
    enforcement point cmd_install/cmd_profile use."""
    payload, aud = validate_token_audience(token)
    if tier not in entitled_tiers(aud):
        raise TokenError(
            f"token audience {aud!r} does not entitle tier {tier!r}")
    return payload
