"""North-star acceptance: trained trace transformer reaches ROC-AUC >= 0.95
on held-out injected faults (BASELINE.json), at default model scale — and the
trained weights actually serve: exported as a bundle, loaded by a Collector's
tpuanomaly processor via ``checkpoint_path``, flagging injected-fault spans
into the anomaly-stream tracedb (the simple-trace-db assert pattern,
/root/reference tests/e2e/trace-collection).

Training runs once (module fixture, ~2 min single-core CPU; fast on TPU) and
feeds both tests.
"""

import numpy as np
import pytest

from odigos_tpu.components.processors.tpuanomaly import FLAG_ATTR
from odigos_tpu.pdata import inject_faults, synthesize_traces
from odigos_tpu.pipeline import Collector
from odigos_tpu.training import TrainConfig, Trainer, evaluate_detector, load_bundle
from odigos_tpu.training.evaluate import transformer_scorer


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    cfg = TrainConfig(steps=200, traces_per_step=64, max_len=32, seed=0)
    trainer = Trainer(cfg)
    res = trainer.train()
    bundle = trainer.export(
        str(tmp_path_factory.mktemp("bundle") / "transformer"), res.variables)
    return trainer, res, bundle


def test_northstar_auc(trained):
    trainer, res, _ = trained
    assert res.losses[-1] < res.losses[0] / 2
    scorer = transformer_scorer(trainer.model, res.variables, max_len=32)
    ev = evaluate_detector(scorer, n_traces=1000, seed=999)
    assert ev["auc"] >= 0.95, ev


def test_northstar_auc_quantized(trained):
    """The int8 serving path meets the same AUC bar on the same trained
    checkpoint (VERDICT r3 item 6: max-|dp| parity alone does not bound
    ranking quality; assert the detection metric directly)."""
    from odigos_tpu.training.evaluate import quantized_transformer_scorer

    trainer, res, _ = trained
    scorer = quantized_transformer_scorer(trainer.model, res.variables,
                                          max_len=32)
    ev = evaluate_detector(scorer, n_traces=1000, seed=999)
    assert ev["auc"] >= 0.95, ev


def test_train_serve_loop_flags_faults_into_tracedb(trained):
    """The VERDICT-r1 critical path: checkpoint → pipeline → anomaly stream."""
    _, _, bundle_path = trained

    # the bundle carries the trained geometry — serving needs only the path
    bundle = load_bundle(bundle_path)
    assert bundle.model == "transformer"
    assert bundle.model_config.max_len == 32

    cfg = {
        "receivers": {"synthetic": {"traces_per_batch": 2, "n_batches": 1}},
        "processors": {
            "batch": {"send_batch_size": 100000, "timeout_s": 0.05},
            "tpuanomaly": {
                "model": "transformer", "checkpoint_path": bundle_path,
                "threshold": 0.5, "timeout_ms": 30000,
                "trace_bucket": 512, "shared_engine": False},
        },
        "connectors": {"anomalyrouter": {
            "anomaly_pipelines": ["traces/anomaly"],
            "default_pipelines": ["traces/normal"],
            "mode": "trace"}},
        "exporters": {"tracedb/anomaly": {}, "tracedb/normal": {}},
        "service": {"pipelines": {
            "traces/in": {"receivers": ["synthetic"],
                          "processors": ["batch", "tpuanomaly"],
                          "exporters": ["anomalyrouter"]},
            "traces/anomaly": {"receivers": ["anomalyrouter"],
                               "exporters": ["tracedb/anomaly"]},
            "traces/normal": {"receivers": ["anomalyrouter"],
                              "exporters": ["tracedb/normal"]},
        }},
    }
    clean = synthesize_traces(400, seed=4242)
    faulty, labels, reports = inject_faults(clean, fault_fraction=0.15,
                                            seed=4243)
    assert labels.any() and reports

    with Collector(cfg) as c:
        proc = c.component("tpuanomaly")
        # the engine restored the trained variables, not a random init
        assert proc.engine.backend.max_len == 32
        c.drain_receivers()
        c.graph.pipeline_entries["traces/in"].consume(faulty)
        c.drain_receivers()

        anomaly = c.component("tracedb/anomaly")
        normal = c.component("tracedb/normal")
        assert anomaly.span_count > 0, "no traces reached the anomaly stream"
        assert normal.span_count > 0, "all traffic was flagged anomalous"

        spans = anomaly.all_spans()
        flagged = [d for d in spans.span_attrs if FLAG_ATTR in d]
        assert flagged, "anomaly stream contains no flagged spans"

    # flagged spans should be enriched in true culprits: compare the label
    # rate among flagged spans vs the base rate of the injected batch
    by_span = {}
    for i in range(len(faulty)):
        by_span[int(faulty.col("span_id")[i])] = bool(labels[i])
    flag_mask = np.fromiter((FLAG_ATTR in d for d in spans.span_attrs),
                            bool, len(spans))
    hit = [by_span.get(int(s), False)
           for s in spans.col("span_id")[flag_mask]]
    base_rate = labels.mean()
    assert np.mean(hit) > base_rate * 2, (np.mean(hit), base_rate)
