"""``routing`` connector — condition-routed pipelines over the OTTL
engine.

Upstream's routingconnector (collector/builder-config.yaml:107): a
routing table of OTTL conditions; telemetry matching a condition goes to
that entry's pipelines, everything else to ``default_pipelines``.  Ours
compiles each condition ONCE with the transform processor's expression
engine (components/processors/ottl.py) and evaluates it as a single
vectorized mask per batch — the batch is partitioned with numpy masks,
one sub-batch per destination, never a per-span interpreter loop.

Config (upstream shape)::

    routing:
      default_pipelines: [traces/default]
      table:
        - condition: attributes["X-Tenant"] == "acme"
          pipelines: [traces/acme]
        - condition: resource.attributes["env"] == "dev"
          pipelines: [traces/dev]

Matching is first-match-wins down the table (upstream match_once
default); rows matching no condition fall to the defaults.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ...pdata.logs import LogBatch
from ...pdata.metrics import MetricBatch
from ...pdata.spans import SpanBatch
from ...utils.telemetry import labeled_key, meter
from ..api import ComponentKind, Connector, Factory, register
from ..processors import ottl


class RoutingConnector(Connector):
    """See module docstring."""

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self.default_pipelines = list(config.get("default_pipelines", []))
        self._spans_metric = labeled_key(
            "odigos_connector_spans_total", connector=name)
        self.table = []
        for entry in config.get("table", []):
            cond_src = entry.get("condition") or ""
            if not cond_src:
                raise ottl.OttlError("routing table entry needs a "
                                     "condition")
            # parse as the where-clause of a no-op statement: same
            # grammar, build-time rejection of bad expressions
            st = ottl.parse_statement(
                f'set(attributes["_r"], true) where {cond_src}')
            self.table.append((st.where, list(entry.get("pipelines", []))))

    def _ctx_cls(self, batch):
        if isinstance(batch, MetricBatch):
            return ottl.MetricContext
        if isinstance(batch, LogBatch):
            return ottl.LogContext
        return ottl.SpanContext

    def consume(self, batch: Any) -> None:
        n = len(batch)
        if n == 0:
            return
        meter.add(self._spans_metric, n)
        if not self.table:
            self._emit(batch, self.default_pipelines)
            return
        ctx = self._ctx_cls(batch)(batch)
        unrouted = np.ones(n, dtype=bool)
        for cond, pipelines in self.table:
            try:
                mask = ottl._as_mask(ottl._eval(cond, ctx, n), n)
            except Exception:  # bad data for this batch: skip the rule
                continue
            mask = mask & unrouted  # first match wins
            if mask.any():
                self._emit(batch if mask.all() else batch.filter(mask),
                           pipelines)
                unrouted &= ~mask
            if not unrouted.any():
                return
        if unrouted.any():
            self._emit(batch if unrouted.all()
                       else batch.filter(unrouted),
                       self.default_pipelines)

    def _emit(self, batch: Any, pipelines: list[str]) -> None:
        delivered = False
        for pname in pipelines:
            out = self.outputs.get(pname)
            if out is not None:
                out.consume(batch)
                delivered = True
        if not delivered and len(batch):
            # no wired route (empty default_pipelines or dangling
            # pipeline name): the shed is named in the flow ledger
            from ...selftelemetry.flow import FlowContext

            FlowContext.drop(len(batch), "filtered", component=self)


register(Factory(
    type_name="routing",
    kind=ComponentKind.CONNECTOR,
    create=RoutingConnector,
    default_config=lambda: {"default_pipelines": [], "table": []},
))
