"""Language/runtime detection over ProcessContexts.

Equivalent of procdiscovery/pkg/inspectors (langdetect.go): one inspector per
runtime, each with a cheap *quick scan* (exe path / cmdline / env) and a
costlier *deep scan* (mapped libraries, exe contents). Detection runs all
quick scans first and falls back to deep scans; two different positives is a
conflict error (ErrLanguageDetectionConflict, langdetect.go:30). The same 13
runtimes are covered: go, java, python, dotnet, nodejs, php, ruby, rust,
cplusplus, nginx, mysql, postgres, redis.

Version detection and glibc/musl detection (procdiscovery/pkg/libc) ride on
the same context.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable, Optional

from ..distros.registry import AGENT_DIR as _AGENT_DIR
from .proc import GO_BUILDINFO_MAGIC, ProcessContext

ScanFn = Callable[[ProcessContext], bool]


class LanguageConflictError(Exception):
    def __init__(self, a: str, b: str):
        super().__init__(f"language detection conflict between {a} and {b}")
        self.languages = (a, b)


@dataclass(frozen=True)
class Inspector:
    language: str
    quick: ScanFn
    deep: ScanFn
    version: Callable[[ProcessContext], str] = lambda ctx: ""


def _base_in(*names: str) -> ScanFn:
    names_set = set(names)

    def scan(ctx: ProcessContext) -> bool:
        return ctx.exe_base in names_set
    return scan


def _base_matches(pattern: str) -> ScanFn:
    rx = re.compile(pattern)

    def scan(ctx: ProcessContext) -> bool:
        return bool(rx.match(ctx.exe_base))
    return scan


def _maps_contain(fragment: str) -> ScanFn:
    def scan(ctx: ProcessContext) -> bool:
        return any(fragment in m for m in ctx.mapped_files)
    return scan


def _never(_: ProcessContext) -> bool:
    return False


def _version_from_maps(pattern: str) -> Callable[[ProcessContext], str]:
    rx = re.compile(pattern)

    def version(ctx: ProcessContext) -> str:
        for m in ctx.mapped_files:
            hit = rx.search(m)
            if hit:
                return hit.group(1)
        return ""
    return version


def _python_version(ctx: ProcessContext) -> str:
    hit = re.match(r"python(\d+\.\d+)", ctx.exe_base)
    if hit:
        return hit.group(1)
    return _version_from_maps(r"libpython(\d+\.\d+)")(ctx)


def _go_version(ctx: ProcessContext) -> str:
    idx = ctx.exe_head.find(GO_BUILDINFO_MAGIC)
    if idx < 0:
        return ""
    tail = ctx.exe_head[idx + len(GO_BUILDINFO_MAGIC):idx + 64]
    hit = re.search(rb"go(\d+\.\d+)", tail)
    return hit.group(1).decode() if hit else ""


ALL_INSPECTORS: list[Inspector] = [
    Inspector("java", quick=_base_in("java", "javaw"),
              deep=_maps_contain("libjvm.so"),
              version=lambda ctx: ctx.environ.get("JAVA_VERSION", "")),
    Inspector("python", quick=_base_matches(r"python(\d+(\.\d+)?)?$"),
              deep=_maps_contain("libpython"),
              version=_python_version),
    Inspector("nodejs", quick=_base_in("node", "nodejs"),
              deep=_maps_contain("/node_modules/"),
              version=lambda ctx: ctx.environ.get("NODE_VERSION", "")),
    Inspector("dotnet", quick=_base_in("dotnet"),
              deep=_maps_contain("libcoreclr.so"),
              version=_version_from_maps(
                  r"Microsoft\.NETCore\.App/(\d+\.\d+)")),
    # Go has no reliable exe-name heuristic; detection is buildinfo-in-ELF
    # (the reference defers to its buildinfo reader in the golang inspector).
    Inspector("go", quick=_never,
              deep=lambda ctx: GO_BUILDINFO_MAGIC in ctx.exe_head,
              version=_go_version),
    Inspector("php", quick=_base_matches(r"php(-fpm|\d+(\.\d+)?)?$"),
              deep=_maps_contain("libphp")),
    Inspector("ruby", quick=_base_in("ruby", "irb", "puma"),
              deep=_maps_contain("libruby"),
              version=_version_from_maps(r"libruby\.so\.(\d+\.\d+)")),
    # Rust leaves no runtime lib; fingerprint is rustc paths / panic strings
    # in the binary. Must lose to Go when both look plausible (static ELF).
    Inspector("rust", quick=_never,
              deep=lambda ctx: b"/rustc/" in ctx.exe_head),
    Inspector("cplusplus", quick=_never,
              deep=lambda ctx: (any("libstdc++" in m
                                    for m in ctx.mapped_files)
                                and GO_BUILDINFO_MAGIC not in ctx.exe_head)),
    Inspector("nginx", quick=_base_in("nginx"), deep=_never),
    Inspector("mysql", quick=_base_in("mysqld"), deep=_never),
    Inspector("postgres", quick=_base_in("postgres"), deep=_never),
    Inspector("redis", quick=_base_in("redis-server"), deep=_never),
]

# Languages that are *markers inside any native binary* rather than distinct
# runtimes; a positive from them never conflicts with (always loses to) a
# positive from a real-runtime inspector in the same scan phase.
_WEAK = {"cplusplus", "rust"}


def detect_language(ctx: ProcessContext) -> Optional[str]:
    """Two-phase scan: quick then deep; conflict between two non-weak
    positives raises (langdetect.go behavior)."""
    for phase in ("quick", "deep"):
        found: Optional[str] = None
        weak_found: Optional[str] = None
        for insp in ALL_INSPECTORS:
            scan = insp.quick if phase == "quick" else insp.deep
            if not scan(ctx):
                continue
            if insp.language in _WEAK:
                weak_found = weak_found or insp.language
                continue
            if found is not None and found != insp.language:
                raise LanguageConflictError(found, insp.language)
            found = insp.language
        if found:
            return found
        if weak_found:
            return weak_found
    return None


def detect_version(ctx: ProcessContext, language: str) -> str:
    for insp in ALL_INSPECTORS:
        if insp.language == language:
            return insp.version(ctx)
    return ""


def detect_libc(ctx: ProcessContext) -> str:
    """glibc vs musl from the loader/libc mapping (procdiscovery/pkg/libc)."""
    for m in ctx.mapped_files:
        if "ld-musl" in m or "libc.musl" in m:
            return "musl"
    for m in ctx.mapped_files:
        if "libc.so.6" in m or "libc-2." in m:
            return "glibc"
    return ""


_KNOWN_AGENT_ENVS = {
    "NEW_RELIC_LICENSE_KEY": "newrelic",
    "DD_TRACE_ENABLED": "datadog",
    "DT_TENANT": "dynatrace",
    "ELASTIC_APM_SERVER_URL": "elastic-apm",
}


def detect_other_agent(ctx: ProcessContext) -> Optional[str]:
    """Pre-existing APM agent detection — the reference refuses to double-
    instrument (common/envOverwrite + RuntimeDetails.OtherAgent)."""
    for env_key, agent in _KNOWN_AGENT_ENVS.items():
        if env_key in ctx.environ:
            return agent
    java_opts = ctx.environ.get("JAVA_TOOL_OPTIONS", "")
    if "-javaagent:" in java_opts and _AGENT_DIR not in java_opts:
        # our own injected javaagent lives under the odigos agent dir; only
        # a *foreign* agent blocks instrumentation (otherwise re-creating a
        # Source over still-instrumented pods would permanently lock out)
        return "unknown-javaagent"
    return None


@dataclass
class InspectionResult:
    language: Optional[str]
    runtime_version: str = ""
    libc_type: str = ""
    exe_path: str = ""
    other_agent: Optional[str] = None
    secure_execution_mode: bool = False


def inspect_process(ctx: ProcessContext) -> InspectionResult:
    """Full inspection of one process (runtimeInspection's per-process body,
    odiglet/pkg/kube/runtime_details/inspection.go:98)."""
    try:
        lang = detect_language(ctx)
    except LanguageConflictError:
        lang = None
    res = InspectionResult(language=lang, exe_path=ctx.exe_path)
    if lang:
        res.runtime_version = detect_version(ctx, lang)
        res.libc_type = detect_libc(ctx)
    res.other_agent = detect_other_agent(ctx)
    # AT_SECURE processes (setuid etc.) must not get LD_PRELOAD-style
    # agents. RealProcSource parses it from /proc/<pid>/auxv (the kernel
    # never puts AT_SECURE in environ); the env spelling remains only for
    # fabricated simulator contexts.
    res.secure_execution_mode = (ctx.secure_execution
                                 or ctx.environ.get("AT_SECURE") == "1")
    return res
