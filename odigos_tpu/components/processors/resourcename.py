"""``odigosresourcename`` processor — on-node resource identity stamping.

Role analog of the reference node collector's resource-identity pair
(autoscaler/controllers/nodecollector/collectorconfig/common.go:25-29:
``resource/node-name`` upsert + ``resourcedetection`` env detector):
guarantee every batch leaving the node carries a usable service identity
and the node it came from, so the gateway never needs a per-span k8s
lookup.

Per resource:
* ``service.name`` — if absent, derived from the workload identity attrs
  the agents stamp (``odigos.workload.name`` / ``k8s.deployment.name`` /
  ``k8s.pod.name``), else ``unknown_service`` (otel SDK convention).
* ``k8s.node.name`` — upserted from config ``node`` or $NODE_NAME.

Works on any pdata batch type: spans, logs and metrics all carry a
``resources`` tuple of attr dicts (structure-of-arrays design, pdata/).
"""

from __future__ import annotations

import os
from dataclasses import replace
from typing import Any

from ..api import Capabilities, ComponentKind, Factory, Processor, register

_FALLBACK_KEYS = ("odigos.workload.name", "k8s.deployment.name",
                  "k8s.statefulset.name", "k8s.daemonset.name",
                  "k8s.pod.name")


class ResourceNameProcessor(Processor):
    """Config:
    node:         k8s.node.name value (default $NODE_NAME, else skipped)
    service_key:  attr to write the identity to (default service.name)
    """

    capabilities = Capabilities(mutates_data=True)

    def process(self, batch: Any) -> Any:
        node = str(self.config.get("node", "")
                   or os.environ.get("NODE_NAME", ""))
        service_key = str(self.config.get("service_key", "service.name"))
        resources = []
        changed = False
        for r in batch.resources:
            out = dict(r)
            if not out.get(service_key):
                out[service_key] = next(
                    (str(out[k]) for k in _FALLBACK_KEYS if out.get(k)),
                    "unknown_service")
            if node and out.get("k8s.node.name") != node:
                out["k8s.node.name"] = node
            changed = changed or out != r
            resources.append(out)
        if not changed:
            return batch
        return replace(batch, resources=tuple(resources))


register(Factory(
    type_name="odigosresourcename",
    kind=ComponentKind.PROCESSOR,
    create=ResourceNameProcessor,
    default_config=dict,
))
