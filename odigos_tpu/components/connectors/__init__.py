from . import (  # noqa: F401
    forward, router, anomalyrouter, spanmetrics, servicegraph, count,
    routing, exceptions)
