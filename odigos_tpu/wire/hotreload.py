"""ConfigMap-driven collector hot reload (odigosk8scmprovider role).

The reference's collectors load config through a confmap provider that
watches the generated ConfigMap and reloads the service on change
(collector/providers/odigosk8scmprovider/, SURVEY.md §3.4). Here the
autoscaler writes generated configs into the Store as ConfigMap resources;
``watch_configmap`` wires those events to ``Collector.reload``.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Callable, Optional

from ..api.store import Event, EventType, Store
from ..utils.canonical import content_hash as _content_hash

if TYPE_CHECKING:  # avoid import cycle: pipeline.service imports components
    from ..pipeline.service import Collector


def watch_configmap(store: Store, namespace: str, name: str,
                    collector: "Collector",
                    extract: Optional[Callable[[dict], dict]] = None
                    ) -> Callable[[], None]:
    """Subscribe the collector to the named ConfigMap; reload on content
    change (hash-diffed, so status-only rewrites are no-ops). ``extract``
    maps ConfigMap.data to the collector config dict (default: data as-is).
    Returns an unsubscribe function. If the ConfigMap already exists, the
    collector is reloaded from it immediately (level-triggered start)."""
    import threading

    state = {"hash": _content_hash(collector.config), "active": True}
    lock = threading.Lock()
    extract = extract or (lambda data: data)

    def apply_current() -> None:
        """Re-read the CURRENT ConfigMap and converge to it. Events are
        only triggers, never payloads: two racing events both land on the
        store's latest object, so a stale event can never clobber a newer
        config (level-triggered semantics). The lock serializes reloads."""
        with lock:
            cm = store.get("ConfigMap", namespace, name)
            if cm is None:
                return  # keep last good config, like a deleted CM in k8s
            cfg = extract(cm.data)
            h = _content_hash(cfg)
            if h == state["hash"]:
                return
            try:
                collector.reload(cfg)
            except Exception:
                # bad generated config must not kill the running
                # pipeline; keep serving the old graph (collector
                # reload semantics). The failure metric is counted by
                # Collector.reload itself — counting here too
                # double-booked every failure (ISSUE 14 satellite).
                # state["hash"] stays UNSET on purpose: the watch is
                # level-triggered, so the next event retries the
                # reload instead of skipping a hash it never applied.
                return
            state["hash"] = h  # Collector.reload counts reloads itself

    def on_event(event: Event) -> None:
        if not state["active"]:
            return
        if event.kind != "ConfigMap" or event.key != (namespace, name):
            return
        if event.type == EventType.DELETED:
            return
        apply_current()

    # watch-then-apply: a write between the two is caught either by its own
    # event or by the initial apply_current reading the latest state
    store.watch(on_event, kind="ConfigMap")
    apply_current()

    def unsubscribe() -> None:
        state["active"] = False
        store.unwatch(on_event)

    return unsubscribe
