"""Rendered install manifests (the helm-chart/resourcemanager analog).

The reference renders its components as k8s objects (helm charts +
cli/pkg/resources managers): odiglet DaemonSet (privileged, hostPath
mounts), gateway/instrumentor/scheduler/autoscaler Deployments with the
resource defaults BASELINE.md records (500m/128Mi control-plane pods,
gateway from sizing), frontend Service.  Ours renders the same shapes as
plain dicts so (a) the gatekeeper policy suite
(controlplane/gatekeeper.py) has real objects to validate, and (b)
`odigos manifests` gives operators the reviewable artifact the
reference's `--dry-run` renders.

Platform adaptation (cli/autodetect.py detect_platform output):

* openshift        — odiglet gets the SCC annotation + SELinux type the
                     reference's openshift images carry
* cgroup_version 1 — odiglet mounts the v1 hierarchy paths instead of
                     the unified mount
* tpu_present      — the deviceplugin container ships and the gateway
                     requests the TPU resource for its anomaly replicas
"""

from __future__ import annotations

from typing import Any

from ..config.model import Configuration
from ..config.sizing import gateway_resources, node_resources

NAMESPACE = "odigos-system"

# BASELINE.md / docs/benchmarks.mdx:30-34: control-plane pod defaults
CONTROL_PLANE_RESOURCES = {
    "requests": {"cpu": "10m", "memory": "64Mi"},
    "limits": {"cpu": "500m", "memory": "128Mi"},
}

TPU_RESOURCE = "odigos.io/tpu"


def _deployment(name: str, containers: list[dict[str, Any]],
                replicas: int = 1,
                annotations: dict[str, str] | None = None) -> dict:
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": NAMESPACE,
                     "annotations": dict(annotations or {})},
        "spec": {
            "replicas": replicas,
            "template": {"spec": {
                "hostNetwork": False,
                "hostPID": False,
                "hostIPC": False,
                "containers": containers,
                "volumes": [],
            }},
        },
    }


def _mib(v: int) -> str:
    return f"{v}Mi"


def render_manifests(config: Configuration,
                     platform: dict[str, Any] | None = None,
                     tier: str = "community") -> list[dict]:
    """Render every component manifest for the given effective config."""
    platform = dict(platform or {})
    openshift = platform.get("kind") == "openshift"
    cgroup_v = int(platform.get("cgroup_version", 2))
    tpu = bool(platform.get("tpu_present", False))

    out: list[dict] = []

    # ---- control plane (instrumentor / scheduler / autoscaler)
    for name in ("instrumentor", "scheduler", "autoscaler"):
        out.append(_deployment(f"odigos-{name}", [{
            "name": name,
            "image": f"{config.image_prefix or 'odigos-tpu'}/{name}",
            "resources": CONTROL_PLANE_RESOURCES,
            "securityContext": {"privileged": False,
                                "allowPrivilegeEscalation": False,
                                "readOnlyRootFilesystem": True},
        }]))

    # ---- gateway (cluster collector) from sizing
    gw = gateway_resources(config.collector_gateway,
                           config.resource_size_preset or None)
    gw_container: dict[str, Any] = {
        "name": "gateway",
        "image": f"{config.image_prefix or 'odigos-tpu'}/collector",
        "resources": {
            "requests": {"cpu": f"{gw.request_cpu_m}m",
                         "memory": _mib(gw.request_memory_mib)},
            "limits": {"cpu": f"{gw.limit_cpu_m}m",
                       "memory": _mib(gw.limit_memory_mib)},
        },
        "securityContext": {"privileged": False,
                            "allowPrivilegeEscalation": False,
                            "readOnlyRootFilesystem": True},
        "env": [{"name": "GOMEMLIMIT",
                 "value": f"{gw.gomemlimit_mib}MiB"}],
    }
    if tpu:
        n = (config.collector_gateway.tpu_replicas or 1)
        gw_container["resources"]["limits"][TPU_RESOURCE] = str(n)
    gateway = _deployment("odigos-gateway", [gw_container],
                          replicas=gw.min_replicas)
    out.append(gateway)

    # ---- odiglet (node agent): the ONE privileged component — it owns
    # the shm span rings, /proc inspection, and device plugin sockets
    nd = node_resources(config.collector_node,
                        config.resource_size_preset or None)
    cgroup_mounts = (
        [{"name": "cgroup", "hostPath": {"path": "/sys/fs/cgroup"}}]
        if cgroup_v == 2 else
        [{"name": "cgroup-cpu",
          "hostPath": {"path": "/sys/fs/cgroup/cpu"}},
         {"name": "cgroup-mem",
          "hostPath": {"path": "/sys/fs/cgroup/memory"}}])
    odiglet_containers = [{
        "name": "odiglet",
        "image": f"{config.image_prefix or 'odigos-tpu'}/odiglet",
        "securityContext": {
            "privileged": True,
            "allowPrivilegeEscalation": True,
            **({"seLinuxOptions": {"type": "spc_t"}} if openshift else {}),
        },
        "resources": {
            "requests": {"cpu": f"{nd.request_cpu_m}m",
                         "memory": _mib(nd.request_memory_mib)},
            "limits": {"cpu": f"{nd.limit_cpu_m}m",
                       "memory": _mib(nd.limit_memory_mib)},
        },
    }]
    if tpu:
        odiglet_containers.append({
            "name": "deviceplugin",
            "image": f"{config.image_prefix or 'odigos-tpu'}/deviceplugin",
            "securityContext": {"privileged": False,
                                "allowPrivilegeEscalation": False},
            "resources": CONTROL_PLANE_RESOURCES,
        })
    odiglet = {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {
            "name": "odiglet", "namespace": NAMESPACE,
            "annotations": (
                {"openshift.io/required-scc": "privileged"}
                if openshift else {}),
        },
        "spec": {"template": {"spec": {
            "hostNetwork": False,
            "hostPID": True,  # procdiscovery reads /proc of host pids
            "hostIPC": False,
            "containers": odiglet_containers,
            "volumes": [
                {"name": "odigos", "hostPath": {"path": "/var/odigos"}},
                {"name": "proc", "hostPath": {"path": "/proc"}},
                {"name": "pod-resources",
                 "hostPath": {"path":
                              "/var/lib/kubelet/pod-resources"}},
                *cgroup_mounts,
            ],
        }}},
    }
    out.append(odiglet)

    # ---- frontend/UI
    out.append(_deployment("odigos-ui", [{
        "name": "ui",
        "image": f"{config.image_prefix or 'odigos-tpu'}/ui",
        "resources": CONTROL_PLANE_RESOURCES,
        "securityContext": {"privileged": False,
                            "allowPrivilegeEscalation": False,
                            "readOnlyRootFilesystem": True},
    }]))
    if tier != "community":
        out.append(_deployment("odigos-pro", [{
            "name": "pro",
            "image": f"{config.image_prefix or 'odigos-tpu'}/pro",
            "resources": CONTROL_PLANE_RESOURCES,
            "securityContext": {"privileged": False,
                                "allowPrivilegeEscalation": False,
                                "readOnlyRootFilesystem": True},
        }]))
    return out
