"""Distribution manifests + runtime resolution.

Each ``Distro`` is the declarative analog of one distros/yamls/*.yaml:
which language it instruments, how the agent attaches (env vars, loader,
eBPF, virtual device), runtime-version constraints, and the env the webhook
must inject. ``DistroProvider`` resolves the distro for a detected runtime
the way distros/distro Provider does, honoring profile overrides
(java-native vs java-ebpf, legacy-dotnet).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

VIRTUAL_DEVICE_GENERIC = "instrumentation.odigos.io/generic"


@dataclass(frozen=True)
class Distro:
    name: str
    language: str
    tier: str = "community"
    # attachment mechanism: env | loader | ebpf | device
    mechanism: str = "env"
    # virtual device requested on the container (device-plugin mount path)
    device: Optional[str] = None
    # env vars the webhook injects (values may reference {agent_dir})
    environment: dict[str, str] = field(default_factory=dict)
    # minimum runtime version supported, as a (major, minor) tuple
    min_runtime_version: Optional[tuple[int, int]] = None
    # libc constraint: None = any, else "glibc"/"musl"
    libc: Optional[str] = None


AGENT_DIR = "/var/odigos"

ALL_DISTROS: list[Distro] = [
    # golang-community.yaml: eBPF uprobes; agent attaches from outside the
    # process via the generic virtual device for node affinity (:15-18)
    Distro("golang-community", "go", mechanism="ebpf",
           device=VIRTUAL_DEVICE_GENERIC),
    Distro("java-community", "java", mechanism="env",
           environment={"JAVA_TOOL_OPTIONS":
                        f"-javaagent:{AGENT_DIR}/java/javaagent.jar"},
           min_runtime_version=(8, 0)),
    Distro("java-ebpf", "java", tier="onprem", mechanism="ebpf",
           device=VIRTUAL_DEVICE_GENERIC),
    Distro("python-community", "python", mechanism="env",
           environment={"PYTHONPATH": f"{AGENT_DIR}/python",
                        "OTEL_PYTHON_CONFIGURATOR": "odigos"},
           min_runtime_version=(3, 8)),
    Distro("nodejs-community", "nodejs", mechanism="env",
           environment={"NODE_OPTIONS":
                        f"--require {AGENT_DIR}/nodejs/autoinstrumentation.js"},
           min_runtime_version=(14, 0)),
    Distro("dotnet-community", "dotnet", mechanism="loader",
           environment={"CORECLR_ENABLE_PROFILING": "1",
                        "CORECLR_PROFILER_PATH":
                        f"{AGENT_DIR}/dotnet/linux-glibc-x64/OpenTelemetry.AutoInstrumentation.Native.so"},
           libc="glibc"),
    Distro("dotnet-community-musl", "dotnet", mechanism="loader",
           environment={"CORECLR_ENABLE_PROFILING": "1",
                        "CORECLR_PROFILER_PATH":
                        f"{AGENT_DIR}/dotnet/linux-musl-x64/OpenTelemetry.AutoInstrumentation.Native.so"},
           libc="musl"),
    Distro("dotnet-legacy", "dotnet", mechanism="loader",
           environment={"CORECLR_ENABLE_PROFILING": "1"}),
    Distro("php-community", "php", mechanism="env",
           environment={"PHP_INI_SCAN_DIR": f":{AGENT_DIR}/php/ini"}),
    Distro("ruby-community", "ruby", mechanism="env",
           environment={"RUBYOPT": f"-r{AGENT_DIR}/ruby/autoinstrument"}),
]

DISTROS_BY_NAME: dict[str, Distro] = {d.name: d for d in ALL_DISTROS}


def _parse_version(v: str) -> Optional[tuple[int, int]]:
    parts = v.lstrip("v").split(".")
    try:
        return (int(parts[0]), int(parts[1]) if len(parts) > 1 else 0)
    except (ValueError, IndexError):
        return None


class DistroProvider:
    """Resolve a distro for a detected runtime.

    ``overrides`` come from the effective config (profiles): e.g.
    {"java_distro": "ebpf"} picks java-ebpf, {"dotnet_distro": "legacy"}
    picks dotnet-legacy (profiles/instrumentation/*.go behavior).
    """

    def __init__(self, tier: str = "community",
                 overrides: Optional[dict[str, str]] = None):
        self.tier = tier
        self.overrides = overrides or {}

    def default_distro_name(self, language: str, libc: str = "") -> Optional[str]:
        if language == "java" and self.overrides.get("java_distro") == "ebpf":
            return "java-ebpf"
        if language == "dotnet":
            if self.overrides.get("dotnet_distro") == "legacy":
                return "dotnet-legacy"
            return "dotnet-community-musl" if libc == "musl" else "dotnet-community"
        for d in ALL_DISTROS:
            if d.language == language and d.tier == "community":
                return d.name
        return None

    def resolve(self, language: str, runtime_version: str = "",
                libc: str = "", override_name: Optional[str] = None
                ) -> tuple[Optional[Distro], str]:
        """Returns (distro, problem). problem is "" on success, else an
        AgentEnabledReason-compatible string. ``override_name`` (from an
        otel-sdk InstrumentationRule) takes priority over default
        resolution but still passes tier/version checks."""
        if override_name is not None:
            if (override_name not in DISTROS_BY_NAME
                    or DISTROS_BY_NAME[override_name].language != language):
                return None, "NoAvailableAgent"
            name: Optional[str] = override_name
        else:
            name = self.default_distro_name(language, libc)
        if name is None:
            return None, "UnsupportedProgrammingLanguage"
        distro = DISTROS_BY_NAME[name]
        if distro.tier != "community" and self.tier == "community":
            return None, "NoAvailableAgent"
        if distro.min_runtime_version and runtime_version:
            parsed = _parse_version(runtime_version)
            if parsed is not None and parsed < distro.min_runtime_version:
                return None, "UnsupportedRuntimeVersion"
        return distro, ""
