from . import filelog, prometheus, synthetic  # noqa: F401  (registers factories on import)
