import os
import sys

from .commands import main

try:
    rc = main()
except BrokenPipeError:
    # stdout reader went away (odigos ... | head/grep -q): exit quietly
    # like any well-behaved CLI instead of tracebacking; devnull stops
    # the interpreter's flush-at-exit from raising again
    os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    rc = 0
sys.exit(rc)
