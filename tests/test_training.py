"""Training/eval tests: fault injection ground truth, ROC-AUC math, short
transformer + autoencoder training convergence, orbax checkpoint/resume."""

import numpy as np
import pytest

from odigos_tpu.pdata import FAULT_KINDS, inject_faults, synthesize_traces
from odigos_tpu.training import (
    TrainConfig,
    Trainer,
    evaluate_detector,
    labeled_sequences,
    roc_auc,
    training_stream,
)
from odigos_tpu.training.evaluate import transformer_scorer, zscore_scorer

TINY = dict(d_model=32, d_ff=64, n_layers=2, n_heads=2)


# --------------------------------------------------------- fault injection


class TestInjectFaults:
    def test_deterministic(self):
        b = synthesize_traces(100, seed=0)
        b1, l1, r1 = inject_faults(b, seed=3)
        b2, l2, r2 = inject_faults(b, seed=3)
        assert (l1 == l2).all() and len(b1) == len(b2)
        assert [(r.kind, r.trace_id_lo) for r in r1] == \
               [(r.kind, r.trace_id_lo) for r in r2]

    def test_all_kinds_produced(self):
        b = synthesize_traces(400, seed=1)
        _, _, reports = inject_faults(b, fault_fraction=0.3, seed=2)
        assert {r.kind for r in reports} == set(FAULT_KINDS)

    def test_labels_only_in_faulty_traces(self):
        b = synthesize_traces(200, seed=2)
        fb, labels, reports = inject_faults(b, fault_fraction=0.15, seed=5)
        faulty = {r.trace_id_lo for r in reports}
        labeled_traces = set(fb.col("trace_id_lo")[labels].tolist())
        assert labeled_traces <= faulty
        # clean traces untouched relative to original
        assert labels.sum() > 0

    def test_latency_spike_stretches_ancestors(self):
        b = synthesize_traces(150, seed=3)
        fb, labels, reports = inject_faults(
            b, fault_fraction=0.2, seed=7, kinds=("latency_spike",))
        spikes = [r for r in reports if r.kind == "latency_spike"]
        assert spikes
        # every labeled span got significantly longer than typical
        durs = fb.duration_ns
        assert durs[labels].mean() > 4 * durs[~labels].mean()

    def test_missing_subtree_removes_spans(self):
        b = synthesize_traces(150, seed=4)
        fb, labels, reports = inject_faults(
            b, fault_fraction=0.3, seed=9, kinds=("missing_subtree",))
        assert len(fb) < len(b)
        assert labels.sum() == sum(
            1 for r in reports if r.kind == "missing_subtree")


# ------------------------------------------------------------------- auc


class TestRocAuc:
    def test_perfect_separation(self):
        labels = np.array([0, 0, 0, 1, 1], dtype=bool)
        assert roc_auc(labels, np.array([.1, .2, .3, .8, .9])) == 1.0

    def test_random_is_half(self):
        rng = np.random.default_rng(0)
        labels = rng.random(5000) < 0.1
        scores = rng.random(5000)
        assert abs(roc_auc(labels, scores) - 0.5) < 0.05

    def test_inverted_is_zero(self):
        labels = np.array([1, 1, 0, 0], dtype=bool)
        assert roc_auc(labels, np.array([.1, .2, .8, .9])) == 0.0

    def test_ties_midrank(self):
        labels = np.array([0, 1], dtype=bool)
        assert roc_auc(labels, np.array([.5, .5])) == 0.5

    def test_degenerate_nan(self):
        assert np.isnan(roc_auc(np.zeros(3, bool), np.zeros(3)))


# ------------------------------------------------------------------ data


class TestData:
    def test_labeled_sequences_shapes(self):
        d = labeled_sequences(32, max_len=16, seed=0, pad_traces_to=32)
        assert d.categorical.shape[0] == 32
        assert d.mask.shape == d.span_labels.shape
        assert d.trace_labels.shape == (32,)
        assert (d.span_labels[~d.mask] == 0).all()

    def test_stream_resume_identical(self):
        s1 = training_stream(8, seed=5)
        for _ in range(3):
            step, d3 = next(s1)
        s2 = training_stream(8, seed=5, start_step=2)
        step2, d3b = next(s2)
        assert step == step2 == 2
        assert (d3.categorical == d3b.categorical).all()
        assert (d3.span_labels == d3b.span_labels).all()


# -------------------------------------------------------------- training


class TestTraining:
    def test_transformer_loss_decreases(self):
        cfg = TrainConfig(steps=12, traces_per_step=16, max_len=16,
                          model_kwargs=TINY, learning_rate=3e-3,
                          warmup_steps=2, seed=0)
        res = Trainer(cfg).train()
        assert len(res.losses) == 12
        assert res.losses[-1] < res.losses[0]

    def test_autoencoder_trains_unsupervised(self):
        cfg = TrainConfig(model="autoencoder", steps=6, traces_per_step=16,
                          max_len=16, model_kwargs=dict(
                              d_model=32, d_ff=64, d_latent=16,
                              n_layers=1, n_heads=2),
                          warmup_steps=2, seed=0)
        res = Trainer(cfg).train()
        assert res.losses[-1] < res.losses[0]

    def test_checkpoint_resume(self, tmp_path):
        common = dict(traces_per_step=8, max_len=16, model_kwargs=TINY,
                      warmup_steps=2, seed=3, schedule_steps=8,
                      checkpoint_dir=str(tmp_path / "ckpt"),
                      checkpoint_every=4)
        res_a = Trainer(TrainConfig(steps=4, **common)).train()
        # resume: second trainer picks up at step 4 and finishes to 8
        res_b = Trainer(TrainConfig(steps=8, **common)).train()
        assert res_b.start_step == 4
        assert len(res_b.losses) == 4  # only the remaining steps ran
        # uninterrupted reference run matches the resumed losses exactly
        common2 = dict(common)
        common2["checkpoint_dir"] = str(tmp_path / "ckpt2")
        res_full = Trainer(TrainConfig(steps=8, **common2)).train()
        np.testing.assert_allclose(
            res_full.losses[4:], res_b.losses, rtol=1e-4)

    def test_restore_latest_for_inference(self, tmp_path):
        cfg = TrainConfig(steps=4, traces_per_step=8, max_len=16,
                          model_kwargs=TINY, warmup_steps=2, seed=1,
                          checkpoint_dir=str(tmp_path / "ckpt"),
                          checkpoint_every=4)
        trainer = Trainer(cfg)
        res = trainer.train()
        step, state = Trainer(cfg).restore_latest()
        assert step == 4
        import jax
        leaves_a = jax.tree.leaves(res.variables)
        leaves_b = jax.tree.leaves(state["variables"])
        for a, b in zip(leaves_a, leaves_b):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6)


# ------------------------------------------------------------------ eval


class TestEvaluate:
    def test_zscore_detects_latency_spikes(self):
        """The untrained path (BASELINE config #3): z-score on durations
        separates latency faults without any training."""
        from odigos_tpu.models import ZScoreDetector
        warmup = synthesize_traces(800, seed=50)
        scorer = zscore_scorer(ZScoreDetector(), warmup_batch=warmup)
        ev = evaluate_detector(scorer, n_traces=600, seed=60,
                               kinds=("latency_spike", "slow_dependency"))
        assert ev["auc"] > 0.95, ev

    def test_trained_transformer_beats_chance_quickly(self):
        """Sanity: a tiny model learns signal in 30 steps. The full-scale
        AUC>=0.95 north-star check lives in test_northstar_auc.py."""
        cfg = TrainConfig(steps=30, traces_per_step=32, max_len=32,
                          model_kwargs=TINY, learning_rate=5e-3,
                          warmup_steps=5, seed=7)
        trainer = Trainer(cfg)
        res = trainer.train()
        scorer = transformer_scorer(trainer.model, res.variables,
                                    max_len=32)
        ev = evaluate_detector(scorer, n_traces=300, seed=70)
        assert ev["auc"] > 0.6, ev
