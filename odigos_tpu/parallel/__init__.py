from .mesh import make_mesh, mesh_axes
from .sharding import (
    transformer_param_spec,
    shard_variables,
    batch_spec,
    make_sharded_score_fn,
    make_sharded_packed_score_fn,
    make_sharded_train_step,
)
from .ring_attention import ring_attention

__all__ = [
    "make_mesh",
    "mesh_axes",
    "transformer_param_spec",
    "shard_variables",
    "batch_spec",
    "make_sharded_score_fn",
    "make_sharded_packed_score_fn",
    "make_sharded_train_step",
    "ring_attention",
]
