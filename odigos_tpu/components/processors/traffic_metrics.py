"""Traffic metrics processor.

Equivalent of odigostrafficmetrics (collector/processors/odigostrafficmetrics/
processor.go:31,71): appended as the last processor of every generated
pipeline, it measures span count and estimated bytes per source (service) and
feeds the own-telemetry meter that the UI/autoscaler read.
"""

from __future__ import annotations

from collections import Counter
from typing import Any

from ...pdata.spans import SpanBatch
from ...utils.telemetry import label_value, meter
from ..api import ComponentKind, Factory, Processor, register
from .memory_limiter import batch_nbytes


class TrafficMetricsProcessor(Processor):
    def process(self, batch: SpanBatch) -> SpanBatch:
        # pipeline names come from config — sanitize like any
        # other data-derived label value (metric-name lint)
        pipeline = label_value(
            str(self.config.get("pipeline", self.name)))
        nbytes = batch_nbytes(batch)
        meter.add(f"odigos_traffic_spans_total{{pipeline={pipeline}}}", len(batch))
        meter.add(f"odigos_traffic_bytes_total{{pipeline={pipeline}}}", nbytes)
        if self.config.get("per_service", True) and "service" in batch.columns:
            counts = Counter(batch.col("service").tolist())
            for sid, n in counts.items():
                # service names are span data — sanitize before flattening
                # into the metric name (',' would corrupt the label block)
                svc = label_value(batch.string_at(int(sid)))
                meter.add(f"odigos_traffic_spans_total{{service={svc}}}", n)
                # per-source byte share prorated by span count (the
                # reference estimates marshaled size per resource,
                # processor.go:71; columnar batches make an exact split
                # meaningless — spans share column buffers)
                meter.add(f"odigos_traffic_bytes_total{{service={svc}}}",
                          int(nbytes * n / len(batch)))
        return batch


register(Factory(
    type_name="odigostrafficmetrics",
    kind=ComponentKind.PROCESSOR,
    create=TrafficMetricsProcessor,
    default_config=lambda: {"per_service": True},
))
