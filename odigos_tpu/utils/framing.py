"""Length-prefixed socket framing shared by the unix-socket protocols
(scoring sidecar, OpAMP transport): ``magic | u32 payload_len | payload``,
little-endian. One implementation so a framing fix (length cap, recv
semantics) can never silently diverge between protocols.
"""

from __future__ import annotations

import socket
import struct
from typing import Optional

_LEN = struct.Struct("<I")
HEADER_SIZE = 8  # 4-byte magic + u32 length


_RECV_CHUNK = 1 << 20  # cap per-recv request: CPython allocates the full
# requested size per call, so asking for a 64 MiB remainder on every
# iteration of a segment-at-a-time stream churns GBs of transient buffers


def recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on clean EOF mid-read."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), _RECV_CHUNK))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, magic: bytes, payload: bytes) -> None:
    sock.sendall(magic + _LEN.pack(len(payload)) + payload)


def recv_frame(sock: socket.socket, magic: bytes,
               max_len: int) -> Optional[bytes]:
    """Read one frame's payload; None on EOF. Raises ValueError on a magic
    mismatch or a length beyond ``max_len`` (stream corruption — callers
    should drop the connection, not try to resync)."""
    hdr = recv_exact(sock, HEADER_SIZE)
    if hdr is None:
        return None
    if hdr[:4] != magic:
        raise ValueError(f"bad frame magic {hdr[:4]!r} (want {magic!r})")
    (n,) = _LEN.unpack_from(hdr, 4)
    if n > max_len:
        raise ValueError(f"frame length {n} exceeds cap {max_len}")
    return recv_exact(sock, n)


def shutdown_close(sock: socket.socket) -> None:
    """Half-close then close. The shutdown matters whenever ANY thread may
    be blocked in recv on this socket: close() alone defers the FIN until
    that recv returns, so the peer would never see EOF."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def connect_unix_retry(path: str, timeout_s: float) -> socket.socket:
    """Connect to a unix socket, retrying until the deadline (the server
    may still be binding). Raises ConnectionError at the deadline."""
    import time

    deadline = time.monotonic() + timeout_s
    last: Optional[Exception] = None
    while time.monotonic() < deadline:
        try:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.connect(path)
            return s
        except OSError as e:
            last = e
            time.sleep(0.05)
    raise ConnectionError(f"unix socket {path} not reachable: {last}")


class ConnRegistry:
    """Tracks accepted connections so a server shutdown can close them all
    (same-process peers blocked in recv otherwise never see a FIN)."""

    def __init__(self) -> None:
        import threading

        self._conns: set[socket.socket] = set()
        self._lock = threading.Lock()

    def add(self, conn: socket.socket) -> None:
        with self._lock:
            self._conns.add(conn)

    def discard(self, conn: socket.socket) -> None:
        with self._lock:
            self._conns.discard(conn)

    def close_all(self) -> None:
        with self._lock:
            conns, self._conns = set(self._conns), set()
        for conn in conns:
            shutdown_close(conn)
