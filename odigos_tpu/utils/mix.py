"""Shared vectorized splitmix64 finalizer.

One implementation for every consumer that needs arbitrary 64-bit keys
spread uniformly over the u64 ring space: the consistent-hash load
balancer (wire/client.py — raw trace ids are small/sequential and
hot-spot a ring; measured 100% pile-up on one replica before mixing)
and the probabilistic sampler (components/processors/
probabilisticsampler.py — the keep/drop verdict must be uniform in the
id, not in whatever id-allocation pattern the SDK has).
"""

from __future__ import annotations

import numpy as np


def splitmix64(x: np.ndarray) -> np.ndarray:
    """The splitmix64 finalizer, elementwise over a u64 array."""
    x = x.astype(np.uint64, copy=True)
    with np.errstate(over="ignore"):
        x ^= x >> np.uint64(30)
        x *= np.uint64(0xBF58476D1CE4E5B9)
        x ^= x >> np.uint64(27)
        x *= np.uint64(0x94D049BB133111EB)
        x ^= x >> np.uint64(31)
    return x
