"""Diagnose — support-bundle collection (odigos diagnose;
cli/cmd/diagnose.go + k8sutils/pkg/diagnose/ in the reference): dump the
full installation state, effective config, self-telemetry metrics snapshot,
and environment info into one tar.gz an operator can attach to a bug report.
"""

from __future__ import annotations

import io
import json
import os
import platform
import tarfile
import time
from typing import Optional

from ..controlplane.scheduler import (
    EFFECTIVE_CONFIG_NAME, ODIGOS_NAMESPACE)
from ..utils.serde import to_jsonable
from ..utils.telemetry import meter
from .describe import describe_install
from .state import CliState


def _add_file(tar: tarfile.TarFile, name: str, content: str) -> None:
    data = content.encode()
    info = tarfile.TarInfo(name)
    info.size = len(data)
    info.mtime = int(time.time())
    tar.addfile(info, io.BytesIO(data))


def collect_bundle(state: CliState, out_path: Optional[str] = None) -> str:
    """Write the support bundle; returns its path."""
    out_path = out_path or os.path.join(
        state.path, f"odigos-diagnose-{int(time.time())}.tar.gz")
    with tarfile.open(out_path, "w:gz") as tar:
        # resources, kind by kind (the kubectl-get-everything analog)
        for kind, objs in sorted(state.store._objects.items()):
            dump = json.dumps([to_jsonable(r) for r in objs.values()],
                              indent=1, sort_keys=True)
            _add_file(tar, f"resources/{kind}.json", dump)
        _add_file(tar, "cluster.json",
                  json.dumps(state.cluster.to_dict(), indent=1))
        _add_file(tar, "config/authored.json",
                  json.dumps(state.config.to_dict(), indent=1))
        eff = state.store.get("ConfigMap", ODIGOS_NAMESPACE,
                              EFFECTIVE_CONFIG_NAME)
        if eff is not None:
            _add_file(tar, "config/effective.json",
                      json.dumps(to_jsonable(eff.data), indent=1))
        # self-telemetry snapshot (the pprof/metrics piece of the bundle)
        _add_file(tar, "metrics.json",
                  json.dumps(meter.snapshot(), indent=1, sort_keys=True))
        _add_file(tar, "describe.txt", describe_install(state))
        _add_file(tar, "environment.json", json.dumps({
            "python": platform.python_version(),
            "platform": platform.platform(),
            "state_dir": state.path,
            "collected_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        }, indent=1))
    return out_path
