"""Latency attribution (ISSUE 8 tentpole): per-frame stage waterfall,
deadline-burn blame, and multi-window burn-rate SLOs.

The contracts pinned here:

* the stage clock TILES a frame's wall: every ``Stage`` member appears
  exactly once per scored frame and the stage durations sum to the
  measured end-to-end wall within tolerance (the acceptance criterion's
  >= 95 % attribution, overlap-corrected), under single frames, burst,
  and a mid-stream hot reload;
* every expired admission deadline carries a blamed stage (device when
  the request was dispatched, queue when it never left the engine
  queue) — and blame rides the drop taxonomy as a dimension, never a
  new reason;
* an injected latency fault flips the pipeline's ``SLOBurn`` condition
  within the fast window and clears within the slow window, through
  ``HealthRollup`` and visible on ``/api/slo`` and ``/debug/latencyz``;
* stage histograms carry exemplars resolving through the existing
  ``/api/selftrace`` loop (PR 3's acceptance discipline);
* ``ODIGOS_LATENCY=0`` (ledger disabled) records nothing.
"""

from __future__ import annotations

import threading
import time

import pytest

from odigos_tpu.pdata import synthesize_traces
from odigos_tpu.pipeline.service import Collector
from odigos_tpu.selftelemetry.flow import HealthRollup, flow_ledger
from odigos_tpu.selftelemetry.latency import (
    ENGINE_STAGES, STAGES, Stage, StageClock, latency_ledger)
from odigos_tpu.selftelemetry.tracer import tracer
from odigos_tpu.serving.engine import EngineConfig, ScoringEngine
from odigos_tpu.serving.fastpath import IngestFastPath
from odigos_tpu.utils.telemetry import labeled_key, meter
from odigos_tpu.wire.client import WireExporter

from tests.test_ingest_fastpath import soak_config, wait_for

E2E_KEY = labeled_key("odigos_latency_e2e_ms", pipeline="traces/in")


@pytest.fixture(autouse=True)
def _isolate_latency_ledger():
    """SLO trackers are process-global and keyed by pipeline name: one
    left behind for a common name (traces/in) would inject slo/ rows
    into every later test's rollup evaluation."""
    yield
    latency_ledger.reset()


def run_frames_attributed(cfg, batches):
    """Wire-feed each batch as one frame (delivery-synchronized), return
    (exporter batches, latency snapshot for traces/in)."""
    flow_ledger.reset()
    latency_ledger.reset()
    collector = Collector(cfg).start()
    try:
        port = collector.graph.receivers["otlpwire"].port
        exp = WireExporter("t", {"endpoint": f"127.0.0.1:{port}"})
        exp.start()
        sink = collector.graph.exporters["tracedb"]
        want = 0
        for b in batches:
            exp.export(b)
            want += len(b)
            assert wait_for(lambda: sink.span_count == want), \
                f"stuck at {sink.span_count}/{want}"
        exp.shutdown()
        collector.drain_receivers(20.0)
        return list(sink._batches), \
            latency_ledger.snapshot()["pipelines"]["traces/in"]
    finally:
        collector.shutdown()


def assert_frame_accounts(frame, tol_frac=0.05, tol_ms=0.5):
    """One recorded frame: every stage exactly once, in traversal order,
    and the stage sum covers the measured wall (>= 95 %, the acceptance
    criterion) without over-counting it."""
    got = [s["stage"] for s in frame["stages"]]
    assert got == list(STAGES), got
    ssum = sum(s["ms"] for s in frame["stages"])
    wall = frame["wall_ms"]
    tol = max(wall * tol_frac, tol_ms)
    assert abs(ssum - wall) <= tol, \
        f"stage sum {ssum:.3f} vs wall {wall:.3f} (tol {tol:.3f})"
    assert ssum >= 0.95 * wall


# ------------------------------------------------------------ the clock

class TestStageClock:
    def test_stamps_tile_the_wall(self):
        c = StageClock()
        c.stamp(Stage.ADMISSION)
        time.sleep(0.002)
        c.stamp(Stage.DECODE)
        assert [s for s, _ in c.stages] == ["admission", "decode"]
        assert abs(c.sum_ms() - c.wall_ms()) < 1e-6
        assert c.stages[1][1] >= 1.0  # the sleep landed in decode

    def test_merge_engine_clamps_monotone(self):
        c = StageClock()
        c.stamp(Stage.ADMISSION)
        now = time.monotonic_ns()
        # pack0 BEFORE the current mark (the worker raced submit): the
        # queue stage clamps to zero instead of going negative
        c.merge_engine({"pack0": now - 10_000_000, "dispatch": now + 1_000,
                       "harvest0": now + 2_000, "end": now + 3_000,
                       "overlap_ms": 1.25})
        stages = dict(c.stages)
        assert stages["queue"] == 0.0
        assert stages["pack"] >= 0.0 and stages["device"] >= 0.0
        assert c.overlap_ms == 1.25
        assert abs(c.sum_ms() - c.wall_ms()) < 1e-6

    def test_engine_stages_constant_matches_enum(self):
        assert [s.value for s in ENGINE_STAGES] == \
            ["queue", "pack", "device", "harvest"]


# ------------------------------------------------ end-to-end accounting

class TestStageAccounting:
    def test_wire_fed_frames_account_full_wall(self):
        batches = [synthesize_traces(24, seed=s) for s in range(4)]
        out, rec = run_frames_attributed(
            soak_config(fast_path=True, deadline_ms=5000), batches)
        assert rec["frames"] == 4 and rec["scored_frames"] == 4
        for frame in rec["recent"]:
            assert frame["scored"]
            assert_frame_accounts(frame)
        # the waterfall covers every stage with sane quantiles
        wf = rec["waterfall"]
        assert set(wf) == set(STAGES)
        for stage, row in wf.items():
            assert row["count"] == 4
            assert row["p99_ms"] >= row["p50_ms"] >= 0.0
        # burn table: budget registered from the fast path's deadline
        assert rec["burn"]["deadline_ms"] == 5000.0
        assert rec["burn"]["stages"]["device"]["frac_of_budget"] >= 0.0

    def test_burst_keeps_accounting(self):
        """A burst of unsynchronized frames (coalesced groups > 1
        request, depth-2 overlap active) still tiles every frame."""
        flow_ledger.reset()
        latency_ledger.reset()
        cfg = soak_config(fast_path=True, deadline_ms=10_000)
        collector = Collector(cfg).start()
        try:
            port = collector.graph.receivers["otlpwire"].port
            exp = WireExporter("t", {"endpoint": f"127.0.0.1:{port}",
                                     "queue_size": 64})
            exp.start()
            batches = [synthesize_traces(16, seed=s) for s in range(4)]
            want = 0
            for k in range(24):
                exp.export(batches[k % 4])
                want += len(batches[k % 4])
            assert exp.flush(30.0)
            exp.shutdown()
            collector.drain_receivers(30.0)
            sink = collector.graph.exporters["tracedb"]
            assert sink.span_count == want
            rec = latency_ledger.snapshot()["pipelines"]["traces/in"]
            assert rec["frames"] == 24 and rec["scored_frames"] == 24
            for frame in rec["recent"]:
                assert_frame_accounts(frame)
            bal = flow_ledger.conservation()["traces/in"]
            assert bal["leak"] == 0
        finally:
            collector.shutdown()

    def test_reload_mid_stream_keeps_attributing(self):
        flow_ledger.reset()
        latency_ledger.reset()
        cfg = soak_config(fast_path=True, deadline_ms=10_000)
        collector = Collector(cfg).start()
        stop = threading.Event()
        try:
            port = collector.graph.receivers["otlpwire"].port
            exp = WireExporter("t", {"endpoint": f"127.0.0.1:{port}",
                                     "max_elapsed_s": 30.0})
            exp.start()
            batches = [synthesize_traces(16, seed=s) for s in range(4)]

            def sender():
                k = 0
                while not stop.is_set():
                    exp.export(batches[k % 4])
                    k += 1
                    while exp.queued > 8 and not stop.is_set():
                        time.sleep(0.001)
                    time.sleep(0.002)

            t = threading.Thread(target=sender, daemon=True)
            t.start()
            time.sleep(0.2)
            before = latency_ledger.snapshot()[
                "pipelines"]["traces/in"]["frames"]
            assert before > 0
            new_cfg = soak_config(fast_path=True, deadline_ms=10_000,
                                  threshold=0.9)
            new_cfg["receivers"]["otlpwire"] = {"port": port}
            collector.reload(new_cfg)
            time.sleep(0.2)
            stop.set()
            t.join(timeout=10)
            assert exp.flush(30.0)
            exp.shutdown()
            collector.drain_receivers(30.0)
            rec = latency_ledger.snapshot()["pipelines"]["traces/in"]
            # the recorder survives the swap (same key, like flow edges)
            assert rec["frames"] > before
            for frame in rec["recent"]:
                assert_frame_accounts(frame)
        finally:
            stop.set()
            collector.shutdown()


# -------------------------------------------------- deadline-burn blame

class _SlowBackend:
    """Mock-shaped backend whose score blocks: forces deadline expiry."""

    def __init__(self, sleep_s: float):
        self.sleep_s = sleep_s
        self.release = threading.Event()

    def score(self, batch, features):
        import numpy as np

        time.sleep(self.sleep_s)
        return np.zeros(len(batch), np.float32)


class TestDeadlineBlame:
    def test_every_expiry_carries_a_blamed_stage(self):
        latency_ledger.reset()
        meter.reset()
        engine = ScoringEngine(EngineConfig(model="mock", max_queue=64))
        engine.backend = _SlowBackend(0.15)
        engine._depth = 1
        engine.start()
        seen = []
        fp = IngestFastPath(
            "traces/blame", engine, threshold=0.9,
            downstream=type("S", (), {
                "consume": lambda self, b: seen.append(b)})(),
            config={"deadline_ms": 20.0})
        fp.start()
        try:
            fp.consume(synthesize_traces(8, seed=1))
            assert fp.drain(20.0)
            rec = latency_ledger.snapshot()["pipelines"]["traces/blame"]
            blames = rec["burn"]["expired_spans_by_blame"]
            n = sum(len(b) for b in seen)
            assert n > 0, "frame never forwarded"
            # every expired span is blamed, and on a real stage
            assert sum(blames.values()) == n, blames
            assert set(blames) <= {"queue", "device"}, blames
            # the expiry counter carries the same blame dimension
            total = sum(
                v for k, v in meter.snapshot().items()
                if k.startswith(
                    "odigos_latency_deadline_expired_spans_total{"))
            assert total == n
            # expired frames forward unscored but still record e2e + SLO
            assert rec["frames"] == 1 and rec["scored_frames"] == 0
        finally:
            fp.shutdown()
            engine.shutdown()

    def test_downstream_failure_still_observes_and_blames(self):
        """A downstream outage is exactly when the SLO tracker must
        keep seeing frames: consume() raising must not skip the e2e
        observation or the expiry blame (regression: both sat after
        consume inside the try, so a broken exporter made the SLO
        layer read burn 0.0 during the incident)."""
        latency_ledger.reset()
        meter.reset()
        engine = ScoringEngine(EngineConfig(model="mock", max_queue=64))
        engine.backend = _SlowBackend(0.15)
        engine._depth = 1
        engine.start()
        tracker = latency_ledger.configure_slo(
            "traces/outage", {"latency_p99_ms": 1000.0})

        class _Boom:
            def consume(self, b):
                raise RuntimeError("downstream outage")

        fp = IngestFastPath(
            "traces/outage", engine, threshold=0.9, downstream=_Boom(),
            config={"deadline_ms": 20.0})
        fp.start()
        try:
            batch = synthesize_traces(8, seed=3)
            fp.consume(batch)
            assert fp.drain(20.0)
            rec = latency_ledger.snapshot()["pipelines"]["traces/outage"]
            assert rec["frames"] == 1, "frame lost to the consume error"
            blames = rec["burn"]["expired_spans_by_blame"]
            assert sum(blames.values()) == len(batch), blames
            assert tracker.status()["slow"]["spans"] == len(batch)
        finally:
            fp.shutdown()
            engine.shutdown()

    def test_engine_queue_full_drop_carries_queue_blame(self):
        flow_ledger.reset()
        engine = ScoringEngine(EngineConfig(model="mock", max_queue=1))
        # never started: the queue fills and stays full
        b = synthesize_traces(4, seed=0)
        deadline = time.monotonic_ns() + int(1e9)
        assert engine.submit(b, None, deadline_ns=deadline) is not None
        assert engine.submit(b, None, deadline_ns=deadline) is None
        witness = flow_ledger.snapshot()["drops"]
        drop = next(d for d in witness if d["component"] == "engine/mock")
        assert drop["reasons"]["queue_full"] == len(b)
        assert drop["last"]["queue_full"]["blame"] == "queue"
        engine.shutdown()


# --------------------------------------------------- SLO burn-rate math

class TestSloBurn:
    def _tracker(self, **cfg):
        latency_ledger.reset()
        fake = [0.0]
        base = {"latency_p99_ms": 100.0, "scored_fraction": 0.9,
                "fast_window_s": 10.0, "slow_window_s": 60.0}
        base.update(cfg)
        tracker = latency_ledger.configure_slo(
            "traces/slo-test", base, clock=lambda: fake[0])
        return tracker, fake

    def test_flips_within_fast_window_and_clears(self):
        tracker, fake = self._tracker()
        for _ in range(100):
            tracker.observe(5.0, True, 10)
        assert not tracker.status()["burning"]
        # hard latency fault at t=5: every frame violates the target.
        # Detection latency is bounded by the FAST window: at t=5 the
        # fast window holds 50% bad -> burn 50x >= 14.4, and the slow
        # window confirms budget consumption (>= 1x)
        fake[0] = 5.0
        for _ in range(100):
            tracker.observe(500.0, True, 10)
        st = tracker.status()
        assert st["burning"]
        assert st["worst_objective"] == "latency_p99_ms"
        assert st["fast"]["burn"] >= 14.4 and st["slow"]["burn"] >= 1.0
        # fault ends; good traffic resumes. Once the fast window drains
        # past the fault (t=16 > 5+10), the condition clears — within
        # the fast window of recovery, hence within the slow window
        fake[0] = 8.0
        for _ in range(100):
            tracker.observe(5.0, True, 10)
        fake[0] = 16.0
        for _ in range(50):
            tracker.observe(5.0, True, 10)
        assert not tracker.status()["burning"]

    def test_scored_fraction_objective_burns(self):
        tracker, fake = self._tracker(latency_p99_ms=None)
        # 40% unscored against a 0.9 target: burn = 0.4/0.1 = 4x on
        # both windows -> fast 4 < 14.4 keeps it quiet (one tail blip
        # must not page)...
        for i in range(100):
            tracker.observe(5.0, i % 5 != 0 and i % 2 == 0, 10)
        st = tracker.status()
        assert st["fast"]["burn"] >= 1.0
        # ...but a total scoring outage (100% unscored, burn 10x)
        # still needs the fast threshold; with threshold 2 it pages
        tracker.fast_burn_threshold = 2.0
        for _ in range(100):
            tracker.observe(5.0, False, 10)
        assert tracker.status()["burning"]

    def test_reconfigure_reuses_identical_recreates_changed(self):
        latency_ledger.reset()
        cfg = {"latency_p99_ms": 100.0, "fast_window_s": 60.0}
        t1 = latency_ledger.configure_slo("traces/x", cfg)
        # identical reload: same tracker, burn history survives
        assert latency_ledger.configure_slo("traces/x", dict(cfg)) is t1
        # ANY changed setting (not just objectives) rebuilds: a reload
        # that shrinks the fast window mid-incident must take effect
        t2 = latency_ledger.configure_slo(
            "traces/x", {"latency_p99_ms": 100.0, "fast_window_s": 10.0})
        assert t2 is not t1 and t2.fast_window_s == 10.0

    def test_reload_dropping_slo_stanza_retires_tracker(self):
        """Deleting the slo: stanza on hot reload must retire the
        tracker (regression: build_graph only had a create path, so the
        stale objectives kept evaluating — and paging — forever)."""
        flow_ledger.reset()
        latency_ledger.reset()
        cfg = soak_config(fast_path=True, deadline_ms=10_000)
        cfg["service"]["pipelines"]["traces/in"]["slo"] = {
            "latency_p99_ms": 1000.0}
        collector = Collector(cfg).start()
        try:
            port = collector.graph.receivers["otlpwire"].port
            assert "traces/in" in latency_ledger.slo_status()
            new_cfg = soak_config(fast_path=True, deadline_ms=10_000)
            new_cfg["receivers"]["otlpwire"] = {"port": port}
            collector.reload(new_cfg)
            assert "traces/in" not in latency_ledger.slo_status()
            assert all(c["component"] != "slo/traces/in"
                       for c in collector.graph.flow_health.evaluate())
        finally:
            collector.shutdown()

    def test_rollup_surfaces_slo_condition(self):
        tracker, fake = self._tracker(fast_burn_threshold=2.0)
        rollup = HealthRollup(None)
        for _ in range(50):
            tracker.observe(500.0, True, 10)
        conds = {c["component"]: c for c in rollup.evaluate()}
        cond = conds["slo/traces/slo-test"]
        assert cond["status"] == "Degraded"
        assert cond["reason"] == "SLOBurn"
        assert "latency_p99_ms" in cond["message"]
        # recovery: the fast window drains -> Healthy(WithinBudget)
        fake[0] = 20.0
        for _ in range(50):
            tracker.observe(5.0, True, 10)
        conds = {c["component"]: c for c in rollup.evaluate()}
        assert conds["slo/traces/slo-test"]["status"] == "Healthy"
        assert conds["slo/traces/slo-test"]["reason"] == "WithinBudget"


# -------------------------------------------- fault -> surfaces, live

class TestInjectedFaultEndToEnd:
    def test_slowed_device_flips_slo_and_surfaces_show_it(self):
        """Acceptance: an injected latency fault (slowed device step)
        flips SLOBurn within the fast window, clears within the slow
        window, and both /debug/latencyz and /api/slo show it."""
        import json
        import urllib.request

        from odigos_tpu.api.store import Store
        from odigos_tpu.components.extensions.zpages import (
            ZPagesExtension)
        from odigos_tpu.frontend import FrontendServer

        flow_ledger.reset()
        latency_ledger.reset()
        cfg = soak_config(fast_path=True, deadline_ms=10_000)
        cfg["service"]["pipelines"]["traces/in"]["slo"] = {
            "latency_p99_ms": 40.0, "scored_fraction": 0.5,
            "fast_window_s": 1.0, "slow_window_s": 4.0,
            "fast_burn_threshold": 14.4}
        collector = Collector(cfg).start()
        fe = FrontendServer(Store(), metrics_port=None).start()
        try:
            fp = collector.graph.fastpaths["traces/in"]
            engine = fp.engine
            orig_score = engine.backend.score

            def slowed(batch, features):
                time.sleep(0.08)  # the injected device-step fault
                return orig_score(batch, features)

            port = collector.graph.receivers["otlpwire"].port
            exp = WireExporter("t", {"endpoint": f"127.0.0.1:{port}"})
            exp.start()
            sink = collector.graph.exporters["tracedb"]
            batches = [synthesize_traces(8, seed=s) for s in range(4)]

            def pump(n):
                want = sink.span_count
                for k in range(n):
                    exp.export(batches[k % 4])
                    want += len(batches[k % 4])
                    assert wait_for(
                        lambda: sink.span_count == want), "stalled"

            pump(4)  # healthy baseline
            engine.backend.score = slowed
            pump(10)  # every frame now walls ~80ms > 40ms target
            rollup = collector.graph.flow_health
            assert wait_for(lambda: any(
                c["component"] == "slo/traces/in"
                and c["reason"] == "SLOBurn"
                for c in rollup.evaluate()), timeout=5.0), \
                "SLOBurn never raised inside the fast window"
            # visible on /debug/latencyz ...
            zp = ZPagesExtension("zpages", {})
            zp.set_graph(collector.graph)
            status, doc = zp._latencyz({})
            assert status == 200
            assert doc["slo"]["traces/in"]["burning"]
            assert doc["pipelines"]["traces/in"]["waterfall"]
            assert any(c["reason"] == "SLOBurn"
                       for c in doc["conditions"])
            # ... and on /api/slo
            with urllib.request.urlopen(f"{fe.url}/api/slo",
                                        timeout=10) as r:
                api = json.loads(r.read())
            assert api["pipelines"]["traces/in"]["burning"]
            assert "device" in api["waterfall"]["traces/in"]
            assert any(c["component"] == "slo/traces/in"
                       and c["reason"] == "SLOBurn"
                       for c in api["conditions"])
            # fault lifted: good frames refill the fast window and the
            # condition clears well inside the slow window
            engine.backend.score = orig_score
            t_clear0 = time.monotonic()
            pump(6)
            assert wait_for(lambda: (pump(1) or True) and all(
                c["reason"] != "SLOBurn"
                for c in rollup.evaluate()
                if c["component"] == "slo/traces/in"), timeout=4.0), \
                "SLOBurn never cleared inside the slow window"
            assert time.monotonic() - t_clear0 <= 4.0
            exp.shutdown()
        finally:
            fe.shutdown()
            collector.shutdown()


# ------------------------------------------------- exemplars + switch

class TestExemplarLoop:
    def test_stage_histogram_exemplar_resolves_via_selftrace(self):
        """PR 3's acceptance loop for the new histograms: a stage
        sample's exemplar trace id resolves to a ring-resident
        self-trace (the pipeline span that carried the frame)."""
        meter.reset()
        batches = [synthesize_traces(16, seed=s) for s in range(2)]
        run_frames_attributed(
            soak_config(fast_path=True, deadline_ms=5000), batches)
        exs = meter.exemplars(E2E_KEY)
        assert exs, "no exemplar on the e2e latency histogram"
        stage_key = labeled_key("odigos_latency_stage_ms",
                                pipeline="traces/in", stage="device")
        stage_exs = meter.exemplars(stage_key)
        assert stage_exs, "no exemplar on the device stage histogram"
        for witness in (exs[E2E_KEY][0], stage_exs[stage_key][0]):
            resolved = tracer.trace(witness["trace_id"])
            assert resolved["found"], witness
            names = {s["name"] for s in resolved["spans"]}
            assert "pipeline/traces/in" in names, names

    def test_kill_switch_records_nothing(self, monkeypatch):
        latency_ledger.reset()
        monkeypatch.setattr(latency_ledger, "enabled", False)
        batches = [synthesize_traces(8, seed=0)]
        out, _ = None, None
        flow_ledger.reset()
        collector = Collector(
            soak_config(fast_path=True, deadline_ms=5000)).start()
        try:
            port = collector.graph.receivers["otlpwire"].port
            exp = WireExporter("t", {"endpoint": f"127.0.0.1:{port}"})
            exp.start()
            sink = collector.graph.exporters["tracedb"]
            exp.export(batches[0])
            assert wait_for(lambda: sink.span_count == len(batches[0]))
            exp.shutdown()
            collector.drain_receivers(20.0)
            snap = latency_ledger.snapshot()
            assert not snap["enabled"]
            assert snap["pipelines"].get("traces/in", {}).get(
                "frames", 0) == 0
        finally:
            collector.shutdown()


# -------------------------------------------------- config contracts

class TestSloConfigContract:
    def test_invalid_slo_rejected_at_validation(self):
        from odigos_tpu.pipeline.graph import validate_config

        def cfg_with(slo):
            cfg = soak_config(fast_path=False)
            cfg["service"]["pipelines"]["traces/in"]["slo"] = slo
            return cfg

        assert any("no objective" in p for p in
                   validate_config(cfg_with({})))
        assert any("scored_fraction" in p for p in
                   validate_config(cfg_with({"scored_fraction": 1.0})))
        assert any("latency_p99_ms" in p for p in
                   validate_config(cfg_with({"latency_p99_ms": -5})))
        assert any("unknown slo keys" in p for p in
                   validate_config(cfg_with({"latency_p99_ms": 10,
                                             "nope": 1})))
        # a non-numeric objective is a NAMED problem, not a crash that
        # masks the rest of the aggregated list
        probs = validate_config(cfg_with({"latency_p99_ms": "abc",
                                          "fast_window_s": [1]}))
        assert any("latency_p99_ms must be a number" in p for p in probs)
        assert any("fast_window_s must be a number" in p for p in probs)
        # zero/negative windows or thresholds would silently evaluate
        # to "never burning" — refused at validation
        assert any("fast_window_s must be positive" in p for p in
                   validate_config(cfg_with({"latency_p99_ms": 10,
                                             "fast_window_s": 0})))
        assert any("slow_burn_threshold must be positive" in p for p in
                   validate_config(cfg_with({"latency_p99_ms": 10,
                                             "slow_burn_threshold": -1})))
        assert validate_config(
            cfg_with({"latency_p99_ms": 10.0,
                      "scored_fraction": 0.95})) == []

    def test_pipelinegen_renders_slo_stanza_byte_stable_when_unset(self):
        from odigos_tpu.config.model import (
            AnomalyStageConfiguration, SloConfiguration)
        from odigos_tpu.destinations import Destination
        from odigos_tpu.pipelinegen import (
            GatewayOptions, build_gateway_config)
        from odigos_tpu.components.api import Signal

        dests = [Destination(id="d1", dest_type="mock",
                             signals=[Signal.TRACES], config={})]
        base, _, _ = build_gateway_config(
            dests, options=GatewayOptions(
                anomaly=AnomalyStageConfiguration(enabled=True)))
        # empty SloConfiguration renders byte-identically to None
        empty, _, _ = build_gateway_config(
            dests, options=GatewayOptions(
                anomaly=AnomalyStageConfiguration(
                    enabled=True, slo=SloConfiguration())))
        assert empty == base
        with_slo, _, _ = build_gateway_config(
            dests, options=GatewayOptions(
                anomaly=AnomalyStageConfiguration(
                    enabled=True, slo=SloConfiguration(
                        latency_p99_ms=25.0, scored_fraction=0.99))))
        stanza = with_slo["service"]["pipelines"]["traces/in"]["slo"]
        assert stanza == {"latency_p99_ms": 25.0,
                          "scored_fraction": 0.99,
                          "fast_window_s": 60.0, "slow_window_s": 300.0}
        # and the rendered stanza passes graph validation
        from odigos_tpu.pipeline.graph import validate_config
        assert not [p for p in validate_config(with_slo)
                    if "slo" in p]

    def test_slo_config_round_trips_configuration(self):
        from odigos_tpu.config.model import Configuration

        conf = Configuration.from_dict({
            "anomaly": {"enabled": True,
                        "slo": {"latency_p99_ms": 12.5,
                                "scored_fraction": 0.97}}})
        assert conf.anomaly.slo.latency_p99_ms == 12.5
        assert conf.anomaly.slo.fast_window_s == 60.0
        again = Configuration.from_dict(conf.to_dict())
        assert again.anomaly.slo == conf.anomaly.slo
