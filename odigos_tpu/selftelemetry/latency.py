"""Latency attribution: per-frame stage waterfall, deadline-burn blame,
and multi-window burn-rate SLOs.

The flow ledger (PR 5) proves *what* flows — conservation per edge,
named drops — but not *where time goes*: SOAK.json records a 360 ms p99
with zero attribution across
wire→admission→decode→featurize→queue→pack→device→harvest→tag→forward.
This module is that attribution layer, the signal the ROADMAP's
auto-tuner item ("closes the loop from profiler/gauges back into batch
sizes, ladder rungs, replica counts") is blocked on:

* a :class:`StageClock` rides each wire frame through the ingest fast
  path and the scoring engine — the wire receiver stamps the admission
  verdict and decode, the fast path stamps submit/featurize/enqueue/
  wait/tag/forward (``wait`` is the completion-driven gap between the
  scores landing and a retirement lane picking the frame up — ISSUE 9
  redefined it from the old single-forwarder head-of-line wait), and
  the engine's per-call ``pack_ms``/``harvest_ms``/``overlap_ms``
  accounting (PR 2) is merged in as the queue/pack/device/harvest
  stages. Within ONE frame the stages tile
  its wall end to end (queue→pack→device→harvest is that frame's own
  serial critical path even under the depth-2 pipelined window; the
  cross-call host/device overlap rides along as ``overlap_ms``), so
  ``Σ stages ≈ wall`` per frame — the accounting
  ``tests/test_latency.py`` pins within tolerance.
* stage durations aggregate into
  ``odigos_latency_stage_ms{pipeline=,stage=}`` histograms with
  exemplars linking each tail sample to the self-trace that carried the
  frame (resolve via ``/api/selftrace?trace_id=``), plus a per-pipeline
  ``odigos_latency_e2e_ms`` end-to-end histogram.
* deadline-carrying frames get **burn accounting**: the burn table
  reports which stage consumed what fraction of the admission budget,
  and every expired deadline names a **blamed stage** — ``device`` when
  the request had been dispatched (the device call outran the budget),
  ``queue`` when it never left the engine queue. Blame is a new
  *dimension* on the existing drop taxonomy (``FlowContext.drop(...,
  blame=)`` and ``odigos_latency_deadline_expired_spans_total
  {pipeline=,blame=}``), never a new drop reason.
* declarative SLOs (``slo: {latency_p99_ms, scored_fraction}`` per
  pipeline, rendered by pipelinegen from ``anomaly.slo``) evaluate with
  Google-SRE-style fast/slow-window burn rates: burn = observed
  bad-fraction ÷ error budget (a p99 target affords a 1 % budget; a
  scored-fraction target Y affords 1−Y). ``SLOBurn`` raises while the
  fast window burns ≥ ``fast_burn_threshold`` (default 14.4, the SRE
  page threshold) AND the slow window confirms budget is actually being
  consumed (burn ≥ ``slow_burn_threshold``, default 1.0) — so a fault
  flips the condition within the fast window and a recovery clears it
  as soon as the fast window drains. Conditions surface through PR 5's
  ``HealthRollup`` as ``slo/<pipeline>`` rows, on ``GET /api/slo``,
  ``/debug/latencyz``, the dashboard, describe, and the diagnose
  bundle's ``latency.json``.

``ODIGOS_LATENCY=0`` disables the layer (clocks become no-ops, nothing
records) — the same opt-out contract as ``ODIGOS_FLOW`` /
``ODIGOS_SELFTRACE``. bench.py ``latency_attribution_overhead`` holds
the enabled cost under 2 % on the fast-path soak route.
"""

from __future__ import annotations

import contextvars
import enum
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from ..utils.telemetry import labeled_key, meter

STAGE_METRIC = "odigos_latency_stage_ms"
E2E_METRIC = "odigos_latency_e2e_ms"
EXPIRED_METRIC = "odigos_latency_deadline_expired_spans_total"

# SRE multi-window defaults: 14.4 is the classic page-threshold burn
# rate (2 % of a 30-day budget in one hour); the slow window confirms
# at >= 1.0 ("budget is actually being consumed"), so detection latency
# is bounded by the FAST window while one tail blip cannot page alone.
DEFAULT_FAST_WINDOW_S = 60.0
DEFAULT_SLOW_WINDOW_S = 300.0
DEFAULT_FAST_BURN = 14.4
DEFAULT_SLOW_BURN = 1.0


class Stage(enum.Enum):
    """The closed stage taxonomy one frame traverses on the fast path.

    Closed for the same reason DROP_REASONS is: free-form stage names
    would rot into unaggregatable cardinality. The package-hygiene lint
    (``TestLatencyStageHygiene``) asserts every member has exactly one
    stamp site across the fast path — a stage stamped twice would
    double-count its wall, a stage never stamped would silently vanish
    from the waterfall.
    """

    ADMISSION = "admission"   # frame header read -> admission verdict
    DECODE = "decode"         # verdict -> zero-copy decoded SpanBatch
    SUBMIT = "submit"         # decode -> submit-lane pickup (intake handoff)
    FEATURIZE = "featurize"   # decode -> device-ready feature matrices
    ENQUEUE = "enqueue"       # featurized -> engine queue accepted
    QUEUE = "queue"           # engine queue wait (submit -> pack start)
    PACK = "pack"             # host coalesce/pack (pack start -> dispatch)
    FUSED = "fused"           # fused route: column assembly -> device enqueue
    DEVICE = "device"         # device execution (dispatch -> harvest start)
    HARVEST = "harvest"       # result fetch + scatter (harvest -> scores)
    WAIT = "wait"             # scores landed -> retirement-lane pickup
    TAG = "tag"               # anomaly attribute tagging
    FORWARD = "forward"       # downstream consume (router/exporter edge)


# the four stages the ENGINE accounts per coalesced call (PR 2's
# pack/device/harvest split + per-request queue wait), merged into the
# frame clock by ``StageClock.merge_engine`` — the lint counts this
# tuple as those stages' single stamp site
ENGINE_STAGES = (Stage.QUEUE, Stage.PACK, Stage.DEVICE, Stage.HARVEST)

# the fused-route variant (ISSUE 19): host featurize+pack collapse into a
# single FUSED stage (column assembly + device-call enqueue) so the burn
# table prices the route it actually runs. Selected by ``merge_engine``
# when the engine flags the group as fused; together with ENGINE_STAGES
# these tuples are the single stamp site for their member stages.
ENGINE_STAGES_FUSED = (Stage.QUEUE, Stage.FUSED, Stage.DEVICE, Stage.HARVEST)

# the full stage vocabulary in traversal order — metric keys, waterfalls
# and burn tables iterate this (a fused frame's stages must aggregate
# like any other). STAGES keeps its pre-fused meaning: the HOST-route
# traversal, exactly the stages one non-fused frame stamps, once each,
# in order (the tiling tests pin frame["stages"] == STAGES); a fused
# frame swaps featurize+pack for the single `fused` stamp instead.
ALL_STAGES = tuple(s.value for s in Stage)
STAGES = tuple(s.value for s in Stage if s is not Stage.FUSED)

# blame value for PREDICTIVE admission sheds (ISSUE 12): a frame the
# fast path rejected because the priced burn table said it would expire
# before scoring. Not a Stage — no wall was ever spent — but it rides
# the same blame dimension (odigos_latency_deadline_expired_spans_total
# {blame=predicted} + the drop taxonomy's blame label) so every
# deadline-driven loss, realized or predicted, is countable in one place.
PREDICTED_BLAME = "predicted"

# bounded ring of recent frame clocks per recorder: the latencyz
# waterfall witnesses AND the window the predictive gate's stage means
# are computed over (consumers clamping thresholds key off this)
RECENT_WINDOW = 64


class StageClock:
    """Per-frame stage timeline: consecutive ``stamp()`` calls turn one
    monotonic clock read each into the duration since the previous mark,
    so the stages tile the frame's wall exactly (no gaps, no overlaps
    within one frame). Threads hand the clock off FIFO with the frame
    (receiver thread -> forwarder thread); the window queue is the
    synchronization, the clock itself is never shared concurrently."""

    __slots__ = ("t0", "_mark", "stages", "ctx", "overlap_ms",
                 "device_attrib", "fused_bucket")

    def __init__(self, ctx: Optional[tuple[int, int]] = None):
        self.t0 = self._mark = time.monotonic_ns()
        # (stage label, duration_ms) in traversal order
        self.stages: list[tuple[str, float]] = []
        self.ctx = ctx  # (trace_id, span_id) exemplar link
        self.overlap_ms = 0.0
        # ISSUE 20 device-plane payloads, merged from the engine call:
        # the sampled intra-fused waterfall (None on unsampled frames)
        # and the fused shape bucket ("r{rows}x{len}") the frame ran in
        self.device_attrib: Optional[dict] = None
        self.fused_bucket: Optional[str] = None

    def stamp(self, stage: Stage) -> None:
        now = time.monotonic_ns()
        self.stages.append((stage.value, (now - self._mark) / 1e6))
        self._mark = now

    def bind_trace(self, ctx: Optional[tuple]) -> None:
        """Attach the self-trace context carrying this frame (the
        pipeline/<name> span): every histogram sample this clock records
        becomes an exemplar resolvable via /api/selftrace."""
        if ctx is not None:
            self.ctx = (ctx[0], ctx[1])

    def merge_engine(self, info: dict[str, Any]) -> None:
        """Fold one engine call's stage boundaries (monotonic ns, same
        clock domain — ``ScoreRequest.stage_ns``) into the timeline as
        the QUEUE/PACK/DEVICE/HARVEST stages. Boundaries are clamped
        monotone non-decreasing from the current mark: the engine worker
        can start packing BEFORE the intake thread stamps ENQUEUE (the
        depth-2 window races submit), and a negative stage would corrupt
        the tiling by more than the microseconds it saves."""
        mark = self._mark
        stages = ENGINE_STAGES_FUSED if info.get("fused") else ENGINE_STAGES
        for stage, end in zip(stages,
                              (info["pack0"], info["dispatch"],
                               info["harvest0"], info["end"])):
            end = max(int(end), mark)
            self.stages.append((stage.value, (end - mark) / 1e6))
            mark = end
        self._mark = mark
        self.overlap_ms = float(info.get("overlap_ms") or 0.0)
        self.device_attrib = info.get("device_attrib")
        self.fused_bucket = info.get("fused_bucket")

    def wall_ms(self) -> float:
        return (self._mark - self.t0) / 1e6

    def sum_ms(self) -> float:
        return sum(d for _, d in self.stages)

    def to_dict(self) -> dict[str, Any]:
        return {"stages": [{"stage": s, "ms": round(d, 4)}
                           for s, d in self.stages],
                "wall_ms": round(self.wall_ms(), 4),
                "overlap_ms": round(self.overlap_ms, 4)}


class _NullClock:
    """Shared no-op clock when the layer is disabled (ODIGOS_LATENCY=0):
    every stamp site pays one attribute load and a no-op call."""

    __slots__ = ()
    ctx = None
    overlap_ms = 0.0
    stages: list = []
    device_attrib = None
    fused_bucket = None

    def stamp(self, stage: Stage) -> None:
        pass

    def bind_trace(self, ctx) -> None:
        pass

    def merge_engine(self, info) -> None:
        pass

    def wall_ms(self) -> float:
        return 0.0

    def sum_ms(self) -> float:
        return 0.0

    def to_dict(self) -> dict[str, Any]:
        return {"stages": [], "wall_ms": 0.0, "overlap_ms": 0.0}


NULL_CLOCK = _NullClock()

# hands the receiver-started clock to the fast path across the consume
# seam (same thread, synchronous call chain — the receiver cannot pass
# a parameter through the Consumer interface without breaking every
# other consumer)
_active_clock: contextvars.ContextVar[Optional[StageClock]] = \
    contextvars.ContextVar("odigos_latency_clock", default=None)


def start_clock() -> StageClock:
    """A fresh frame clock, or the shared no-op when the layer is off."""
    if not latency_ledger.enabled:
        return NULL_CLOCK  # type: ignore[return-value]
    return StageClock()


def publish_clock(clock) -> contextvars.Token:
    return _active_clock.set(clock if clock is not NULL_CLOCK else None)


def unpublish_clock(token: contextvars.Token) -> None:
    _active_clock.reset(token)


def claim_clock():
    """Take the receiver-published clock (one claimant per frame); a
    directly-fed fast path (no wire hop) starts its own, so the
    waterfall simply lacks the admission/decode stages."""
    clock = _active_clock.get()
    if clock is not None:
        _active_clock.set(None)
        return clock
    return start_clock()


def latency_enabled() -> bool:
    return latency_ledger.enabled


class _Recorder:
    """Per-pipeline aggregation: stage/e2e histograms (meter-resident,
    exemplar-carrying), per-stage running totals for the burn table, an
    expiry-blame table, and a bounded ring of recent clocks (the
    ``/debug/latencyz`` waterfall witnesses and the accounting tests'
    evidence)."""

    __slots__ = ("pipeline", "deadline_ms", "frames", "scored_frames",
                 "overlap_ms_total", "_stage_keys", "_e2e_key", "_totals",
                 "_expired", "recent", "_worst_blame", "_lock",
                 "_device_stages", "_device_sampled",
                 "_device_fused_ms_total", "_device_recent",
                 "_worst_fused")

    def __init__(self, pipeline: str):
        self.pipeline = pipeline
        self.deadline_ms: Optional[float] = None
        self.frames = 0
        self.scored_frames = 0
        self.overlap_ms_total = 0.0
        self._stage_keys = {
            s: labeled_key(STAGE_METRIC, pipeline=pipeline, stage=s)
            for s in ALL_STAGES}
        self._e2e_key = labeled_key(E2E_METRIC, pipeline=pipeline)
        self._totals: dict[str, list[float]] = {}  # stage -> [sum, count]
        self._expired: dict[str, int] = {}         # blame -> spans
        self.recent: deque[dict[str, Any]] = deque(maxlen=RECENT_WINDOW)
        # blame -> (wall_ms, trace_id, span_id, unix_ts): the worst
        # EXPIRED frame per blame dimension that carried a self-trace
        # (incident bundles join these — a p99 spike names one frame)
        self._worst_blame: dict[str, tuple] = {}
        # ISSUE 20 device burn table, nested under the FUSED stage:
        # sub-stage -> [sum_ms, count] over sampled attribution frames,
        # plus the fused stamps those samples decomposed and a short
        # ring of raw waterfalls for /debug/latencyz
        self._device_stages: dict[str, list[float]] = {}
        self._device_sampled = 0
        self._device_fused_ms_total = 0.0
        self._device_recent: deque[dict] = deque(maxlen=8)
        # (fused_stage_ms, trace_id, span_id, bucket, unix_ts): the
        # worst fused-stage frame that carried a self-trace — the
        # exemplar join's anchor (its bucket keys the compile-event
        # ring and the cost ledger)
        self._worst_fused: Optional[tuple] = None
        self._lock = threading.Lock()

    def observe(self, clock: StageClock, scored: bool) -> None:
        wall = clock.wall_ms()
        ex = clock.ctx
        if scored:
            # stage histograms carry scored frames only: an expired
            # frame's engine stages are unknowable (the request never
            # harvested), and recording its truncated partials would
            # bias exactly the tails the waterfall exists to explain.
            # One record_many = one meter lock hold for the whole
            # waterfall; the exemplar reservoir stays populated from
            # every 8th frame (algorithm-R does not need every sample
            # to carry a witness — allocating 13 exemplars per frame
            # would be the layer's own overhead bound violation)
            keys = self._stage_keys
            samples = [(keys[stage], d) for stage, d in clock.stages]
            samples.append((self._e2e_key, wall))
            stage_ex = ex if (self.frames & 7) == 0 else None
            meter.record_many(samples, exemplar=stage_ex)
        else:
            meter.record(self._e2e_key, wall, exemplar=ex)
        attrib = clock.device_attrib
        bucket = clock.fused_bucket
        with self._lock:
            self.frames += 1
            if scored:
                self.scored_frames += 1
                self.overlap_ms_total += clock.overlap_ms
                totals = self._totals
                fused_ms = None
                for stage, d in clock.stages:
                    tot = totals.get(stage)
                    if tot is None:
                        tot = totals[stage] = [0.0, 0]
                    tot[0] += d
                    tot[1] += 1
                    if stage == Stage.FUSED.value:
                        fused_ms = d
                if attrib is not None:
                    # sampled intra-fused waterfall: fold the sub-stage
                    # stamps into the device burn table nested under
                    # FUSED (ISSUE 20)
                    self._device_sampled += 1
                    self._device_fused_ms_total += float(
                        attrib.get("fused_device_ms") or 0.0)
                    dstages = self._device_stages
                    for sub, d in (attrib.get("stages") or {}).items():
                        tot = dstages.get(sub)
                        if tot is None:
                            tot = dstages[sub] = [0.0, 0]
                        tot[0] += d
                        tot[1] += 1
                    self._device_recent.append(attrib)
                if (bucket is not None and fused_ms is not None
                        and ex is not None):
                    worst = self._worst_fused
                    if worst is None or fused_ms > worst[0]:
                        self._worst_fused = (fused_ms, ex[0], ex[1],
                                             bucket, time.time())
            # raw refs only — the clock is dead after retire, and
            # rendering dicts per frame costs more than the rest of
            # this method (snapshot() renders on demand). The ctx ref
            # rides along so worst_frames() can name the slowest
            # frame's self-trace without a per-frame allocation.
            self.recent.append(
                (clock.stages, wall, clock.overlap_ms, scored, ex))

    def record_expiry(self, blame: str, n_spans: int,
                      clock=None) -> None:
        with self._lock:
            self._expired[blame] = self._expired.get(blame, 0) + n_spans
            if clock is not None and clock.ctx is not None:
                wall = clock.wall_ms()
                prev = self._worst_blame.get(blame)
                if prev is None or wall > prev[0]:
                    self._worst_blame[blame] = (
                        wall, clock.ctx[0], clock.ctx[1], time.time())

    def worst_frames(self) -> list[dict[str, Any]]:
        """Worst-frame trace exemplars: the slowest traced frame over
        the recent window, plus the worst expired frame per ``blame=``
        dimension — each a concrete self-trace id an operator (or an
        incident bundle) can pull the full timeline for."""
        out: list[dict[str, Any]] = []
        with self._lock:
            worst = None
            for stages, wall, _ov, scored, ex in self.recent:
                if ex is None:
                    continue
                if worst is None or wall > worst[0]:
                    worst = (wall, ex, scored)
            blames = dict(self._worst_blame)
        if worst is not None:
            out.append({
                "pipeline": self.pipeline, "scope": "window",
                "wall_ms": round(worst[0], 4),
                "trace_id": f"{worst[1][0]:032x}",
                "span_id": f"{worst[1][1]:016x}",
                "scored": worst[2],
            })
        for blame, (wall, tid, sid, ts) in sorted(blames.items()):
            out.append({
                "pipeline": self.pipeline, "scope": f"blame:{blame}",
                "wall_ms": round(wall, 4),
                "trace_id": f"{tid:032x}", "span_id": f"{sid:016x}",
                "unix_ts": ts,
            })
        with self._lock:
            worst_fused = self._worst_fused
        if worst_fused is not None:
            fused_ms, tid, sid, bucket, ts = worst_fused
            entry = {
                "pipeline": self.pipeline, "scope": "fused",
                # the fused stamp doubles as wall_ms: the ledger-level
                # worst_frames() sorts every scope on that key
                "wall_ms": round(fused_ms, 4),
                "fused_ms": round(fused_ms, 4),
                "trace_id": f"{tid:032x}", "span_id": f"{sid:016x}",
                "bucket": bucket, "unix_ts": ts,
            }
            # exemplar join (ISSUE 20): the worst fused-stage frame
            # links to its bucket's most recent compile event and its
            # cost-ledger row — a tail spike names the shape, whether
            # it recompiled, and what XLA expected it to cost
            try:
                from ..models import jitstats
                from ..models.costmodel import cost_ledger
                compiles = jitstats.recent_compiles(shape=bucket)
                if compiles:
                    entry["last_compile"] = compiles[0]
                row = None
                for r in cost_ledger.snapshot()["rows"]:
                    if r["bucket"] == bucket:
                        row = r
                        break
                if row is not None:
                    entry["cost"] = row
            except Exception:  # noqa: BLE001 — the join is best-effort
                pass
            out.append(entry)
        return out

    def device_burn(self) -> Optional[dict[str, Any]]:
        """The sampled intra-fused device burn table (ISSUE 20), nested
        under the FUSED stage: per-sub-stage mean device ms over the
        sampled attribution frames, the mean fused stamp those samples
        decomposed, and the reconcile ratio (Σ sub-stage means ÷ mean
        fused stamp — ≈1.0 means the decomposition accounts for the
        opaque stamp; the residue is lost cross-stage XLA fusion plus
        per-stage dispatch). None until a frame was sampled, so existing
        payload shapes are untouched when attribution is off."""
        with self._lock:
            if not self._device_sampled:
                return None
            sampled = self._device_sampled
            fused_total = self._device_fused_ms_total
            dstages = {s: (t[0], t[1])
                       for s, t in self._device_stages.items()}
            recent = list(self._device_recent)
        by_stage = {}
        sub_sum = 0.0
        for s, (tot, n) in dstages.items():
            mean = tot / n
            sub_sum += mean
            by_stage[s] = {"mean_ms": round(mean, 4), "count": n}
        fused_mean = fused_total / sampled if sampled else 0.0
        return {
            "sampled_frames": sampled,
            "fused_mean_ms": round(fused_mean, 4),
            "substage_sum_ms": round(sub_sum, 4),
            "reconcile_ratio": round(sub_sum / fused_mean, 4)
            if fused_mean > 0 else None,
            "stages": by_stage,
            "recent": recent,
        }

    def stage_means(self) -> tuple[int, dict[str, float]]:
        """(scored frames in window, per-stage mean ms over the RECENT
        ring) — the predictive admission gate's burn pricing input
        (ISSUE 12). Windowed on purpose: the lifetime ``_totals`` means
        never decay, so an overload that pushed them past the deadline
        would keep pricing frames as doomed long after the incident —
        with the gate then shedding the very traffic that could refresh
        the estimate (a permanent full-shed latch). The bounded recent
        ring (last 64 scored frames) forgets the incident as fast as
        healthy frames flow again. One lock hold, ≤64×12 adds; the fast
        path calls this throttled (~10 Hz), never per frame."""
        with self._lock:
            sums: dict[str, float] = {}
            counts: dict[str, int] = {}
            n = 0
            for stages, _wall, _ov, scored, _ex in self.recent:
                if not scored:
                    continue
                n += 1
                for s, d in stages:
                    sums[s] = sums.get(s, 0.0) + d
                    counts[s] = counts.get(s, 0) + 1
            return n, {s: sums[s] / counts[s] for s in sums}

    def waterfall(self) -> dict[str, dict[str, float]]:
        """Per-stage p50/p95/p99/mean over the meter histograms, in
        traversal order (stages with no samples are omitted)."""
        out: dict[str, dict[str, float]] = {}
        with self._lock:
            totals = {s: (t[0], t[1]) for s, t in self._totals.items()}
        for s in ALL_STAGES:
            tot = totals.get(s)
            if not tot or not tot[1]:
                continue
            key = self._stage_keys[s]
            out[s] = {
                "p50_ms": round(meter.quantile(key, 0.50), 4),
                "p95_ms": round(meter.quantile(key, 0.95), 4),
                "p99_ms": round(meter.quantile(key, 0.99), 4),
                "mean_ms": round(tot[0] / tot[1], 4),
                "count": tot[1],
            }
        return out

    def burn(self) -> dict[str, Any]:
        """The deadline-burn table: which stage consumed what fraction
        of the admission budget (mean stage wall ÷ deadline), plus the
        expiry-blame tally. Fractions are per-frame means, so a stage
        holding steady at 0.6 of budget is the tuning target even while
        nothing expires yet."""
        with self._lock:
            totals = {s: (t[0], t[1]) for s, t in self._totals.items()}
            expired = dict(self._expired)
            deadline = self.deadline_ms
        by_stage = {}
        for s in ALL_STAGES:
            tot = totals.get(s)
            if not tot or not tot[1]:
                continue
            mean = tot[0] / tot[1]
            row = {"mean_ms": round(mean, 4)}
            if deadline:
                row["frac_of_budget"] = round(mean / deadline, 4)
            by_stage[s] = row
        out = {"deadline_ms": deadline, "stages": by_stage,
               "expired_spans_by_blame": expired}
        device = self.device_burn()
        if device is not None:
            # sampled sub-stage decomposition nested under the fused
            # stamp — present only when attribution sampled a frame
            out["device"] = device
        return out

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            recent = list(self.recent)[-8:]
            frames, scored = self.frames, self.scored_frames
            overlap = self.overlap_ms_total
        return {
            "frames": frames, "scored_frames": scored,
            "overlap_ms_total": round(overlap, 3),
            "waterfall": self.waterfall(), "burn": self.burn(),
            "recent": [
                {"stages": [{"stage": s, "ms": round(d, 4)}
                            for s, d in stages],
                 "wall_ms": round(wall, 4),
                 "overlap_ms": round(ov, 4), "scored": sc}
                for stages, wall, ov, sc, _ex in recent],
            "worst_frames": self.worst_frames(),
        }


class SloTracker:
    """Multi-window burn-rate evaluation of one pipeline's declarative
    SLO. Per-frame samples (timestamp, latency-violated, scored) live in
    a time-pruned deque; ``status()`` computes the fast/slow-window
    burns fresh on every call, so alternating pollers (healthcheck,
    zpages, dashboard, tests with an injected clock) always agree."""

    def __init__(self, pipeline: str, cfg: dict[str, Any],
                 clock: Callable[[], float] = time.monotonic):
        self.pipeline = pipeline
        self.latency_p99_ms = (float(cfg["latency_p99_ms"])
                               if cfg.get("latency_p99_ms") else None)
        self.scored_fraction = (float(cfg["scored_fraction"])
                                if cfg.get("scored_fraction") else None)
        self.fast_window_s = float(cfg.get("fast_window_s",
                                           DEFAULT_FAST_WINDOW_S))
        self.slow_window_s = float(cfg.get("slow_window_s",
                                           DEFAULT_SLOW_WINDOW_S))
        self.fast_burn_threshold = float(cfg.get("fast_burn_threshold",
                                                 DEFAULT_FAST_BURN))
        self.slow_burn_threshold = float(cfg.get("slow_burn_threshold",
                                                 DEFAULT_SLOW_BURN))
        self._clock = clock
        self._lock = threading.Lock()
        # (t, n_spans, latency_violated, unscored)
        self._samples: deque[tuple[float, int, bool, bool]] = deque()

    def observe(self, wall_ms: float, scored: bool, n_spans: int) -> None:
        now = self._clock()
        violated = (self.latency_p99_ms is not None
                    and wall_ms > self.latency_p99_ms)
        with self._lock:
            self._samples.append((now, n_spans, violated, not scored))
            horizon = now - self.slow_window_s
            while self._samples and self._samples[0][0] < horizon:
                self._samples.popleft()

    def _render(self, window_s: float,
                counts: tuple[int, int, int]) -> dict[str, Any]:
        total, lat_bad, unscored = counts
        burns = {}
        if self.latency_p99_ms is not None and total:
            burns["latency_p99_ms"] = (lat_bad / total) / 0.01
        if self.scored_fraction is not None and total:
            budget = max(1.0 - self.scored_fraction, 1e-9)
            burns["scored_fraction"] = (unscored / total) / budget
        worst = max(burns, key=burns.get) if burns else None
        return {"window_s": window_s, "spans": total,
                "latency_violations": lat_bad, "unscored": unscored,
                "burn": round(max(burns.values()), 4) if burns else 0.0,
                "burn_by_objective": {k: round(v, 4)
                                      for k, v in burns.items()},
                "worst_objective": worst}

    def status(self) -> dict[str, Any]:
        now = self._clock()
        fast_cut = now - self.fast_window_s
        with self._lock:
            horizon = now - self.slow_window_s
            while self._samples and self._samples[0][0] < horizon:
                self._samples.popleft()
            # ONE pass over the (already slow-window-pruned) deque: the
            # fast window is a subset of the slow one, and every poller
            # (healthcheck, zpages, /api/slo, dashboard) holds the same
            # lock the forwarder's observe() needs — two full scans per
            # poll would stall the fast path exactly under load
            f = [0, 0, 0]
            s = [0, 0, 0]
            for t, n, violated, not_scored in self._samples:
                s[0] += n
                if violated:
                    s[1] += n
                if not_scored:
                    s[2] += n
                if t >= fast_cut:
                    f[0] += n
                    if violated:
                        f[1] += n
                    if not_scored:
                        f[2] += n
        fast = self._render(self.fast_window_s, tuple(f))
        slow = self._render(self.slow_window_s, tuple(s))
        burning = (fast["burn"] >= self.fast_burn_threshold
                   and slow["burn"] >= self.slow_burn_threshold)
        objective = fast["worst_objective"] or slow["worst_objective"]
        return {
            "pipeline": self.pipeline,
            "objectives": {
                k: v for k, v in (
                    ("latency_p99_ms", self.latency_p99_ms),
                    ("scored_fraction", self.scored_fraction))
                if v is not None},
            "fast": fast, "slow": slow,
            "fast_burn_threshold": self.fast_burn_threshold,
            "slow_burn_threshold": self.slow_burn_threshold,
            "burning": burning,
            "worst_objective": objective,
        }


class LatencyLedger:
    """Process-global latency-attribution registry (the flow_ledger /
    meter / tracer sibling)."""

    def __init__(self) -> None:
        self.enabled = os.environ.get("ODIGOS_LATENCY", "1") != "0"
        self._lock = threading.Lock()
        self._recorders: dict[str, _Recorder] = {}
        self._slos: dict[str, SloTracker] = {}
        self._expired_keys: dict[tuple[str, str], str] = {}

    # -------------------------------------------------------- recorders

    def recorder(self, pipeline: str) -> _Recorder:
        with self._lock:
            rec = self._recorders.get(pipeline)
            if rec is None:
                rec = self._recorders[pipeline] = _Recorder(pipeline)
            return rec

    def set_deadline(self, pipeline: str, deadline_ms: float) -> None:
        self.recorder(pipeline).deadline_ms = float(deadline_ms)

    def configure_slo(self, pipeline: str, cfg: dict[str, Any],
                      clock: Callable[[], float] = time.monotonic
                      ) -> SloTracker:
        """Get-or-create the pipeline's SLO tracker. Stable across hot
        reloads (an identical config re-binds the same tracker, so burn
        history survives the swap — the flow-edge discipline); ANY
        changed setting re-creates it — windows and thresholds redefine
        the burn math, so silently keeping the old ones would make a
        reload mid-incident a no-op."""
        candidate = SloTracker(pipeline, cfg, clock)
        with self._lock:
            tracker = self._slos.get(pipeline)
            if tracker is not None and (
                    tracker.latency_p99_ms, tracker.scored_fraction,
                    tracker.fast_window_s, tracker.slow_window_s,
                    tracker.fast_burn_threshold,
                    tracker.slow_burn_threshold) == (
                    candidate.latency_p99_ms, candidate.scored_fraction,
                    candidate.fast_window_s, candidate.slow_window_s,
                    candidate.fast_burn_threshold,
                    candidate.slow_burn_threshold):
                return tracker
            self._slos[pipeline] = candidate
            return candidate

    def remove_slo(self, pipeline: str) -> None:
        """Drop the pipeline's tracker. Called by graph build when a
        (re)loaded config carries no ``slo:`` stanza for the pipeline —
        without this, deleting the stanza mid-incident would leave the
        old objectives evaluating (and paging) forever."""
        with self._lock:
            self._slos.pop(pipeline, None)

    # ------------------------------------------------------- hot path

    def observe(self, pipeline: str, clock, scored: bool,
                n_spans: int) -> None:
        """One frame retired by the fast path: aggregate its waterfall
        and feed the pipeline's SLO tracker (if one is configured)."""
        if not self.enabled or clock is NULL_CLOCK:
            return
        self.recorder(pipeline).observe(clock, scored)
        tracker = self._slos.get(pipeline)
        if tracker is not None:
            tracker.observe(clock.wall_ms(), scored, n_spans)

    def record_expiry(self, pipeline: str, blame,
                      n_spans: int, clock=None) -> None:
        """An expired admission deadline, blamed on the stage that
        consumed the budget (the burn dimension on the drop taxonomy).
        ``blame`` is a :class:`Stage` for realized expiries, or
        :data:`PREDICTED_BLAME` for frames the predictive gate shed
        before any budget was spent (ISSUE 12). ``clock`` (when the
        expiring frame's is at hand) lets the recorder retain the
        worst expired frame's self-trace id per blame dimension."""
        if not self.enabled:
            return
        bval = blame.value if isinstance(blame, Stage) else str(blame)
        with self._lock:
            key = self._expired_keys.get((pipeline, bval))
            if key is None:
                key = self._expired_keys[(pipeline, bval)] = \
                    labeled_key(EXPIRED_METRIC, pipeline=pipeline,
                                blame=bval)
        meter.add(key, n_spans)
        self.recorder(pipeline).record_expiry(bval, n_spans,
                                              clock=clock)

    def worst_frames(self) -> list[dict[str, Any]]:
        """Every pipeline's worst-frame trace exemplars, slowest first
        (the flight recorder joins these into incident bundles)."""
        with self._lock:
            recs = list(self._recorders.values())
        out: list[dict[str, Any]] = []
        for r in recs:
            out.extend(r.worst_frames())
        out.sort(key=lambda f: f["wall_ms"], reverse=True)
        return out

    # -------------------------------------------------------- surfaces

    def waterfall(self) -> dict[str, dict[str, dict[str, float]]]:
        with self._lock:
            recs = list(self._recorders.values())
        return {r.pipeline: r.waterfall() for r in recs}

    def burn(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            recs = list(self._recorders.values())
        return {r.pipeline: r.burn() for r in recs}

    def slo_status(self) -> dict[str, dict[str, Any]]:
        with self._lock:
            trackers = list(self._slos.values())
        return {t.pipeline: t.status() for t in trackers}

    def snapshot(self) -> dict[str, Any]:
        """JSON-able dump (``/debug/latencyz``, diagnose ``latency.json``)."""
        with self._lock:
            recs = list(self._recorders.values())
        return {
            "enabled": self.enabled,
            "stages": list(ALL_STAGES),
            "pipelines": {r.pipeline: r.snapshot() for r in recs},
            "slo": self.slo_status(),
        }

    def reset(self) -> None:
        """Test isolation: forget every recorder/tracker (live fast
        paths lazily re-create theirs — the flow_ledger.reset contract)."""
        with self._lock:
            self._recorders.clear()
            self._slos.clear()
            self._expired_keys.clear()


latency_ledger = LatencyLedger()
