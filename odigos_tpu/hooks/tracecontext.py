"""W3C trace-context access (hooks/go/go_hooks.go parity).

The zero-context constants, predicates, and traceparent format follow the
reference exactly so enriched services interoperate with W3C-propagating
neighbors.
"""

from __future__ import annotations

import contextvars
from typing import Optional

ZERO_TRACE_CONTEXT = "00-00000000000000000000000000000000-0000000000000000-00"
ZERO_TRACE_ID = "00000000000000000000000000000000"
ZERO_SPAN_ID = "0000000000000000"

# (trace_id, span_id, flags) of the active span, set by ManualTracer and by
# inbound-request middleware that parsed a traceparent header
_active: contextvars.ContextVar[Optional[tuple[int, int, int]]] = \
    contextvars.ContextVar("odigos_active_span", default=None)


def format_traceparent(trace_id: int, span_id: int,
                       flags: int = 1) -> str:
    return f"00-{trace_id:032x}-{span_id:016x}-{flags:02x}"


def parse_traceparent(header: str) -> Optional[tuple[int, int, int]]:
    """Returns (trace_id, span_id, flags) or None on a malformed header."""
    parts = header.strip().split("-")
    if len(parts) != 4 or parts[0] != "00":
        return None
    if len(parts[1]) != 32 or len(parts[2]) != 16 or len(parts[3]) != 2:
        return None
    try:
        trace_id = int(parts[1], 16)
        span_id = int(parts[2], 16)
        flags = int(parts[3], 16)
    except ValueError:
        return None
    if trace_id == 0 or span_id == 0:
        return None
    return trace_id, span_id, flags


def current_trace_context() -> str:
    """GetW3CTraceContext: full traceparent of the active span, or the
    zero context when nothing is active."""
    active = _active.get()
    if active is None:
        return ZERO_TRACE_CONTEXT
    return format_traceparent(*active)


def current_trace_id() -> str:
    active = _active.get()
    return f"{active[0]:032x}" if active else ZERO_TRACE_ID


def current_span_id() -> str:
    active = _active.get()
    return f"{active[1]:016x}" if active else ZERO_SPAN_ID


def is_zero_trace_context(ctx: str) -> bool:
    return ctx == ZERO_TRACE_CONTEXT


def is_zero_trace_id(trace_id: str) -> bool:
    return trace_id == ZERO_TRACE_ID


def is_zero_span_id(span_id: str) -> bool:
    return span_id == ZERO_SPAN_ID
