"""API store + controller-manager runtime tests."""

import pytest

from odigos_tpu.api import (
    ControllerManager,
    Event,
    EventType,
    ObjectMeta,
    Source,
    Store,
    WorkloadKind,
    WorkloadRef,
)
from odigos_tpu.api.resources import (
    Condition,
    ConditionStatus,
    InstrumentationConfig,
    MARKED_FOR_INSTRUMENTATION,
    RUNTIME_DETECTION,
    condition_logical_order,
)


def make_source(name="s1", ns="default", workload_name="app"):
    return Source(meta=ObjectMeta(name=name, namespace=ns),
                  workload=WorkloadRef(ns, WorkloadKind.DEPLOYMENT,
                                       workload_name))


class TestStore:
    def test_apply_and_get(self):
        store = Store()
        store.apply(make_source())
        got = store.get("Source", "default", "s1")
        assert got is not None and got.meta.generation == 1

    def test_update_bumps_generation_keeps_uid(self):
        store = Store()
        first = store.apply(make_source())
        uid = first.meta.uid
        second = store.apply(make_source())
        assert second.meta.generation == 2
        assert second.meta.uid == uid

    def test_update_status_does_not_bump_generation(self):
        store = Store()
        store.apply(make_source())
        src = store.get("Source", "default", "s1")
        store.update_status(src)
        assert store.get("Source", "default", "s1").meta.generation == 1

    def test_list_by_namespace_and_labels(self):
        store = Store()
        a = make_source("a", ns="ns1")
        a.meta.labels["team"] = "x"
        store.apply(a)
        store.apply(make_source("b", ns="ns2"))
        assert len(store.list("Source")) == 2
        assert len(store.list("Source", namespace="ns1")) == 1
        assert len(store.list("Source", labels={"team": "x"})) == 1
        assert len(store.list("Source", labels={"team": "y"})) == 0

    def test_watch_events(self):
        store = Store()
        events: list[Event] = []
        store.watch(events.append, kind="Source")
        store.apply(make_source())
        store.apply(make_source())
        store.delete("Source", "default", "s1")
        assert [e.type for e in events] == [
            EventType.ADDED, EventType.MODIFIED, EventType.DELETED]

    def test_delete_missing_returns_false(self):
        assert Store().delete("Source", "x", "y") is False


class TestConditions:
    def test_logical_order(self):
        ic = InstrumentationConfig(
            meta=ObjectMeta(name="ic", namespace="d"),
            workload=WorkloadRef("d", WorkloadKind.DEPLOYMENT, "app"))
        ic.set_condition(Condition(RUNTIME_DETECTION, ConditionStatus.TRUE))
        ic.set_condition(Condition(MARKED_FOR_INSTRUMENTATION,
                                   ConditionStatus.TRUE))
        assert [c.type for c in ic.conditions] == [
            MARKED_FOR_INSTRUMENTATION, RUNTIME_DETECTION]
        assert condition_logical_order("WorkloadRollout") == 4

    def test_set_condition_idempotent(self):
        ic = InstrumentationConfig(
            meta=ObjectMeta(name="ic", namespace="d"),
            workload=WorkloadRef("d", WorkloadKind.DEPLOYMENT, "app"))
        assert ic.set_condition(
            Condition(RUNTIME_DETECTION, ConditionStatus.TRUE, "R", "m"))
        t0 = ic.condition(RUNTIME_DETECTION).last_transition
        assert not ic.set_condition(
            Condition(RUNTIME_DETECTION, ConditionStatus.TRUE, "R", "m"))
        assert ic.condition(RUNTIME_DETECTION).last_transition == t0
        assert ic.set_condition(
            Condition(RUNTIME_DETECTION, ConditionStatus.FALSE, "R", "m"))


class _Recorder:
    def __init__(self):
        self.keys = []

    def reconcile(self, store, key):
        self.keys.append(key)


class TestControllerManager:
    def test_event_dispatch_and_dedupe(self):
        store = Store()
        mgr = ControllerManager(store)
        rec = _Recorder()
        mgr.register("r", rec, {"Source": None})
        store.apply(make_source())
        store.apply(make_source())  # second event dedupes while pending
        n = mgr.run_once()
        assert n == 1
        assert rec.keys == [("default", "s1")]

    def test_cross_kind_mapping(self):
        store = Store()
        mgr = ControllerManager(store)
        rec = _Recorder()
        mgr.register("r", rec,
                     {"Source": lambda e: [("odigos-system", "gateway")]})
        store.apply(make_source())
        mgr.run_once()
        assert rec.keys == [("odigos-system", "gateway")]

    def test_reconcile_errors_recorded_not_fatal(self):
        store = Store()
        mgr = ControllerManager(store)

        class Boom:
            def reconcile(self, store, key):
                raise RuntimeError("boom")

        mgr.register("boom", Boom(), {"Source": None})
        store.apply(make_source())
        mgr.run_once()
        assert len(mgr.errors) == 1
        assert mgr.errors[0][0] == "boom"

    def test_enqueue_all_resync(self):
        store = Store()
        mgr = ControllerManager(store)
        store.apply(make_source("a"))
        store.apply(make_source("b"))
        mgr.run_once()  # drain creation events (no controllers yet anyway)
        rec = _Recorder()
        mgr.register("r", rec, {"Source": None})
        mgr.enqueue_all("Source")
        mgr.run_once()
        assert sorted(rec.keys) == [("default", "a"), ("default", "b")]

    def test_nonquiescent_loop_detected(self):
        store = Store()
        mgr = ControllerManager(store)

        class Fighter:
            def reconcile(self, store, key):
                src = store.get("Source", *key)
                store.apply(src)  # always rewrites -> infinite loop

        mgr.register("fighter", Fighter(), {"Source": None})
        store.apply(make_source())
        with pytest.raises(RuntimeError, match="quiesce"):
            mgr.run_once(max_iterations=50)
