"""CLI commands — the cobra-command surface of the reference
(cli/cmd/root.go:17: install / uninstall / ui / describe / diagnose /
sources / profile ...), over a persisted local control plane (state.py).

Every mutating command is level-triggered: load state (controllers
re-register and resync), mutate resources, reconcile, save — a controller
restart per invocation, which is exactly how the reference CLI relates to
its cluster (SURVEY.md §3.1: the CLI applies resources; controllers do the
work).
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional

from .. import __version__
from ..api.resources import (
    DestinationResource, ObjectMeta, Source, WorkloadKind, WorkloadRef)
from ..controlplane.cluster import Container
from ..controlplane.scheduler import ODIGOS_NAMESPACE
from .state import (
    CliState, create_state, default_state_dir, delete_state, load_state,
    state_exists)


def _err(msg: str) -> int:
    print(f"error: {msg}", file=sys.stderr)
    return 1


def _load(args) -> CliState:
    return load_state(args.state_dir)


def _workload_ref(namespace: str, name: str, kind: str) -> WorkloadRef:
    return WorkloadRef(namespace, WorkloadKind.parse(kind), name)


# ---------------------------------------------------------------- commands


def cmd_install(args) -> int:
    if state_exists(args.state_dir):
        return _err(f"already installed at "
                    f"{args.state_dir or default_state_dir()} "
                    "(run uninstall first)")
    from ..config.model import Configuration, Tier
    from ..config.profiles import resolve_profiles

    config = Configuration(profiles=list(args.profile or []))
    tier = Tier(args.tier)
    if tier != Tier.COMMUNITY:
        # paid tiers require a validated entitlement token
        # (odigosauth/odigosauth.go:69 ValidateToken at install)
        from ..utils.auth import TokenError, validate_tier_claim

        try:
            validate_tier_claim(getattr(args, "onprem_token", None) or "",
                                tier.value)
        except TokenError as e:
            return _err(f"tier {tier.value!r} requires a valid pro token "
                        f"(--onprem-token): {e}")
    _, unknown = resolve_profiles(config.profiles, tier)
    if unknown:
        return _err(f"unknown or tier-gated profiles: {unknown}")
    # sense the environment before rendering anything (the reference's
    # cli/pkg/autodetect step) and adapt the install to it
    from .autodetect import detect_platform

    platform = detect_platform(cluster_name=config.cluster_name)
    config.extra["platform"] = platform
    if platform["kind"] == "openshift":
        config.extra["openshift_enabled"] = True
    print("platform: " + ", ".join(
        f"{k}={v}" for k, v in sorted(platform.items())))
    # policy-validate the rendered manifests (tests/gatekeeper role):
    # an install that violates its own constraint set must not proceed
    from ..controlplane.gatekeeper import policy_violations

    violations = policy_violations(config, platform, tier.value)
    if violations:
        return _err("install manifests violate policy:\n  "
                    + "\n  ".join(str(v) for v in violations))
    state = create_state(path=args.state_dir, nodes=args.nodes,
                         config=config, tier=tier.value)
    state.save()
    print(f"installed odigos-tpu (nodes={args.nodes}, tier={tier.value}, "
          f"profiles={config.profiles or 'none'}) "
          f"at {state.path}")
    return 0


def cmd_manifests(args) -> int:
    """Render the component manifests for review (the reference's
    helm-template/resourcemanager dry-run role)."""
    import json as _json

    state = _load(args)
    from ..controlplane.gatekeeper import policy_violations
    from ..controlplane.manifests import render_manifests

    platform = (state.config.extra or {}).get("platform") or {}
    print(_json.dumps(render_manifests(state.config, platform,
                                       state.tier), indent=1))
    violations = policy_violations(state.config, platform, state.tier)
    for v in violations:
        print(f"policy violation: {v}", file=sys.stderr)
    return 1 if violations else 0


def cmd_upgrade(args) -> int:
    """Upgrade an existing install in place (the reference's
    install-or-upgrade path, cli/cmd/helm-install.go:21): reload state
    under the current code, revalidate profiles against the installed
    tier, re-render everything (level-triggered controllers make the
    'controller restart' the upgrade), persist."""
    from ..config.model import Tier
    from ..config.profiles import resolve_profiles

    state = _load(args)
    _, unknown = resolve_profiles(state.config.profiles, Tier(state.tier))
    if unknown:
        return _err(f"installed profiles no longer resolve: {unknown}")
    state.scheduler.apply_authored(state.config)
    state.reconcile()
    state.save()
    print(f"upgraded to odigos-tpu {__version__} "
          f"(tier={state.tier}, profiles={state.config.profiles or 'none'})")
    return 0


def cmd_preflight(args) -> int:
    """Installation health checks (cli/pkg/preflight/checks.go: is
    installed, are components ready). Hard failures exit 1; the TPU
    probe is advisory (the pipeline runs without a chip)."""
    from ..controlplane.autoscaler import GATEWAY_CONFIG_NAME
    from ..controlplane.scheduler import (
        EFFECTIVE_CONFIG_NAME, GATEWAY_GROUP_NAME)

    failures = 0

    def check(desc, fn, hard=True):
        nonlocal failures
        try:
            detail = fn()
            print(f"  ok  {desc}" + (f" ({detail})" if detail else ""))
            return True
        except Exception as e:  # noqa: BLE001 — each check reports
            mark = "FAIL" if hard else "warn"
            print(f"{mark:>4}  {desc}: {e}")
            if hard:
                failures += 1
            return False

    def installed():
        if not state_exists(args.state_dir):
            raise RuntimeError("no installation (run `install` first)")

    print("preflight:")
    check("installation exists", installed)
    if failures:
        return 1
    # the load itself is a check: a corrupt/version-mismatched state file
    # must print FAIL, not a traceback
    box: dict = {}

    def load():
        box["state"] = _load(args)
        return (f"{len(box['state'].cluster.nodes)} nodes, "
                f"tier {box['state'].tier}")

    if not check("state loads and reconciles", load):
        return 1
    state = box["state"]
    check("effective config rendered", lambda: _must(
        state.store.get("ConfigMap", ODIGOS_NAMESPACE,
                        EFFECTIVE_CONFIG_NAME), "missing effective config"))
    check("gateway config rendered", lambda: _must(
        state.store.get("ConfigMap", ODIGOS_NAMESPACE,
                        GATEWAY_CONFIG_NAME), "missing gateway config"))
    check("collectors group present", lambda: _must(
        state.store.get("CollectorsGroup", ODIGOS_NAMESPACE,
                        GATEWAY_GROUP_NAME), "missing gateway group"))

    def ring():
        from ..transport import SpanRing

        r = SpanRing.create(1 << 14)
        r.close()
        return "native C++ ring"

    check("shared-memory span ring", ring)

    def policy():
        from ..controlplane.gatekeeper import policy_violations

        platform = (state.config.extra or {}).get("platform") or {}
        violations = policy_violations(state.config, platform, state.tier)
        if violations:
            raise RuntimeError("; ".join(str(v) for v in violations))
        return "manifests clean"

    check("manifests pass constraint policy", policy)

    def tpu():
        import subprocess
        import sys as _sys

        # platform must actually be an accelerator: a CPU-only jax would
        # otherwise produce a false 'ok'
        probe = ("import jax, numpy as np; dev = jax.devices()[0]; "
                 "assert dev.platform != 'cpu', dev.platform; "
                 "np.asarray(jax.jit(lambda x: x + 1)"
                 "(jax.numpy.ones((8, 8)))); print(dev)")
        r = subprocess.run([_sys.executable, "-c", probe], timeout=30,
                           capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError("no TPU backend (CPU-only jax, or device "
                               "unreachable)")
        return r.stdout.strip().splitlines()[-1]

    if not getattr(args, "skip_device_probe", False):
        check("TPU device reachable", tpu, hard=False)
    return 1 if failures else 0


def _must(value, msg):
    if value is None:
        raise RuntimeError(msg)
    return ""


def cmd_uninstall(args) -> int:
    if not args.yes:
        return _err("refusing to uninstall without --yes")
    if delete_state(args.state_dir):
        print("uninstalled")
        return 0
    return _err("nothing installed")


def cmd_status(args) -> int:
    from .describe import describe_install

    print(describe_install(_load(args)))
    return 0


def cmd_version(args) -> int:
    print(f"odigos-tpu {__version__}")
    return 0


# ------------------------------------------------------------------ sources


def cmd_sources(args) -> int:
    state = _load(args)
    if args.action == "list":
        srcs = state.store.list("Source", namespace=args.namespace or None)
        for s in srcs:
            kind = ("namespace" if s.is_namespace_source
                    else s.workload.kind.value)
            mode = "disable" if s.disable_instrumentation else "enable"
            print(f"{s.namespace}/{s.name}: {kind} {s.workload.name} "
                  f"[{mode}]"
                  + (f" streams={s.data_stream_names}"
                     if s.data_stream_names else ""))
        if not srcs:
            print("(no sources)")
        return 0
    if args.action == "add":
        ref = _workload_ref(args.namespace, args.name, args.kind)
        state.store.apply(Source(
            meta=ObjectMeta(name=f"src-{args.name}",
                            namespace=args.namespace),
            workload=ref,
            disable_instrumentation=args.disable,
            otel_service_name=args.service_name or "",
            data_stream_names=list(args.stream or [])))
        state.reconcile()
        state.save()
        print(f"source src-{args.name} applied for "
              f"{args.namespace}/{ref.kind.value}/{args.name}")
        return 0
    if args.action == "remove":
        if state.store.delete("Source", args.namespace, f"src-{args.name}"):
            state.reconcile()
            state.save()
            print("source removed (workload will be un-instrumented)")
            return 0
        return _err(f"no source src-{args.name} in {args.namespace}")
    return _err(f"unknown sources action {args.action}")


# -------------------------------------------------------------- workloads


def cmd_workloads(args) -> int:
    state = _load(args)
    if args.action == "list":
        for w in state.cluster.workloads.values():
            pods = state.cluster.pods_of(w.ref)
            phases = ", ".join(f"{p.name}[{p.phase.value}]" for p in pods)
            print(f"{w.ref.namespace}/{w.ref.kind.value}/{w.ref.name}: "
                  f"replicas={w.replicas} {phases}")
        if not state.cluster.workloads:
            print("(no workloads)")
        return 0
    if args.action == "add":
        state.cluster.add_workload(
            args.namespace, args.name,
            [Container("main", language=args.language,
                       runtime_version=args.runtime_version)],
            kind=WorkloadKind.parse(args.kind),
            replicas=args.replicas)
        state.reconcile()
        state.save()
        print(f"workload {args.namespace}/{args.name} added "
              f"({args.language}, replicas={args.replicas})")
        return 0
    if args.action == "remove":
        ref = _workload_ref(args.namespace, args.name, args.kind)
        state.cluster.remove_workload(ref)
        state.reconcile()
        state.save()
        print("workload removed")
        return 0
    return _err(f"unknown workloads action {args.action}")


# ----------------------------------------------------------- destinations


def cmd_destinations(args) -> int:
    from ..components.api import Signal
    from ..destinations import SPECS, get_spec, validate_destination

    if args.action == "types":
        for spec in sorted(SPECS.values(), key=lambda s: s.dest_type):
            sigs = ",".join(s.value for s in Signal if spec.supports(s))
            print(f"{spec.dest_type}: {spec.display_name} [{sigs}]")
        return 0

    state = _load(args)
    if args.action == "list":
        dests = state.store.list("DestinationResource")
        for d in dests:
            print(f"{d.name}: {d.dest_type} signals={d.signals}"
                  + (f" streams={d.data_stream_names}"
                     if d.data_stream_names else ""))
        if not dests:
            print("(no destinations)")
        return 0
    if args.action == "add":
        try:
            spec = get_spec(args.type)
        except KeyError:
            return _err(f"unknown destination type {args.type!r} "
                        "(see `destinations types`)")
        config = {}
        for kv in args.set or []:
            if "=" not in kv:
                return _err(f"--set expects key=value, got {kv!r}")
            k, v = kv.split("=", 1)
            config[k] = v
        from ..destinations import Destination

        dest = Destination(
            id=args.name, dest_type=args.type,
            signals=[Signal(s) for s in (args.signal or ["traces"])],
            config=config,
            data_stream_names=list(args.stream or []))
        problems = validate_destination(dest)
        if problems:
            return _err("; ".join(problems))
        # secret fields never enter state.json (it travels in diagnose
        # bundles); they land in the 0600 secrets file + collector env —
        # the Secret analog, matching the UI wizard path
        secret_names = [f.name for f in spec.fields
                        if f.secret and f.name in config]
        # secret env names are type-scoped: a second destination of the
        # same type shares them, so a differing value silently rebinds the
        # first destination's credentials — surface that
        for n in secret_names:
            old = state.secrets.get(n)
            if old is not None and old != config[n]:
                others = [d.meta.name for d in
                          state.store.list("DestinationResource")
                          if d.meta.name != args.name
                          and any(f.secret and f.name == n for f in
                                  (SPECS[d.dest_type].fields
                                   if d.dest_type in SPECS else ()))]
                if others:
                    print(f"warning: {n} is shared with destination(s) "
                          f"{', '.join(others)}; the new value replaces "
                          "theirs", file=sys.stderr)
        state.set_secrets({n: config.pop(n) for n in secret_names})
        state.store.apply(DestinationResource(
            meta=ObjectMeta(name=args.name, namespace=ODIGOS_NAMESPACE),
            dest_type=args.type,
            signals=[s.value for s in dest.signals],
            config=config,
            secret_ref=(f"odigos-{args.name}-secret"
                        if secret_names else ""),
            data_stream_names=list(dest.data_stream_names)))
        state.reconcile()
        state.save()
        print(f"destination {args.name} ({args.type}) applied")
        return 0
    if args.action == "remove":
        existing = state.store.get("DestinationResource", ODIGOS_NAMESPACE,
                                   args.name)
        if existing is not None and state.store.delete(
                "DestinationResource", ODIGOS_NAMESPACE, args.name):
            # revoke every stored secret no longer referenced by any
            # surviving destination (env names are type-scoped, so a
            # same-type survivor — even one added without re-supplying
            # the credential — keeps the var; round-4 advisor, medium)
            from ..destinations.registry import (
                referenced_secret_env_names)

            keep = referenced_secret_env_names(
                state.store.list("DestinationResource"))
            state.drop_secrets([n for n in list(state.secrets)
                                if n not in keep])
            state.reconcile()
            state.save()
            print("destination removed")
            return 0
        return _err(f"no destination {args.name}")
    return _err(f"unknown destinations action {args.action}")


def cmd_ui(args) -> int:
    """Serve the operator dashboard over the installed state (the
    reference's `odigos ui` port-forward/serve, cli/cmd/ui.go)."""
    import os

    state = _load(args)
    from ..frontend import FrontendServer

    auth = (getattr(args, "auth_token", None)
            or os.environ.get("ODIGOS_UI_TOKEN") or None)
    fe = FrontendServer(state.store, cluster=state.cluster,
                        host=args.address, port=args.port,
                        auth_token=auth).start()
    print(f"dashboard: {fe.url} (ctrl-c to stop)", flush=True)
    if getattr(args, "once", False):  # tests: bind, report, exit
        fe.shutdown()
        return 0
    import signal as _signal
    import threading

    stop = threading.Event()
    _signal.signal(_signal.SIGINT, lambda *a: stop.set())
    _signal.signal(_signal.SIGTERM, lambda *a: stop.set())
    stop.wait()
    fe.shutdown()
    return 0


def cmd_pro(args) -> int:
    """Update the entitlement token of an existing install (the
    reference's `odigos pro --onprem-token`, cli/cmd/pro.go
    UpdateOdigosToken)."""
    from ..config.model import Tier
    from ..utils.auth import TokenError, validate_token_audience

    state = _load(args)
    try:
        _, aud = validate_token_audience(args.onprem_token or "")
        tier = Tier(aud)
    except (TokenError, ValueError) as e:
        return _err(f"invalid pro token: {e}")
    state.tier = tier.value
    state.scheduler.tier = tier
    state.instrumentor.distro_provider.tier = tier.value
    state.scheduler.apply_authored(state.config)
    state.reconcile()
    state.save()
    print(f"tier updated to {tier.value}")
    return 0


# -------------------------------------------------------------- profiles


def cmd_profile(args) -> int:
    from ..config.model import Tier
    from ..config.profiles import available_profiles_for_tier

    if args.action == "list":
        state = _load(args) if state_exists(args.state_dir) else None
        active = set(state.config.profiles) if state else set()
        for p in available_profiles_for_tier(Tier(args.tier)):
            mark = "*" if p.name in active else " "
            print(f"{mark} {p.name}: {p.short_description}")
        return 0
    state = _load(args)
    if args.action == "add":
        if args.name in state.config.profiles:
            return _err(f"profile {args.name} already active")
        from ..config.profiles import resolve_profiles

        # the tier validated at install time gates profile-add — a flag on
        # this command is not an entitlement (odigosauth enforcement)
        _, unknown = resolve_profiles([args.name], Tier(state.tier))
        if unknown:
            return _err(f"unknown or tier-gated profile {args.name!r} "
                        f"(installed tier: {state.tier})")
        state.config.profiles.append(args.name)
        state.scheduler.apply_authored(state.config)
        state.reconcile()
        state.save()
        print(f"profile {args.name} added")
        return 0
    if args.action == "remove":
        if args.name not in state.config.profiles:
            return _err(f"profile {args.name} not active")
        state.config.profiles.remove(args.name)
        state.scheduler.apply_authored(state.config)
        state.reconcile()
        state.save()
        print(f"profile {args.name} removed")
        return 0
    return _err(f"unknown profile action {args.action}")


# ----------------------------------------------------- describe / diagnose


def cmd_describe(args) -> int:
    from .describe import describe_install, describe_workload

    state = _load(args)
    if args.target == "odigos":
        print(describe_install(state))
        return 0
    print(describe_workload(state, args.namespace, args.kind, args.name))
    return 0


def cmd_diagnose(args) -> int:
    from .diagnose import collect_bundle

    path = collect_bundle(_load(args), args.output, redact=args.redact)
    print(f"bundle written: {path}")
    return 0


def cmd_actions(args) -> int:
    """Telemetry-policy actions (api/actions/v1alpha1; compiled into
    collector processors by the autoscaler)."""
    import json as _json

    from ..api.resources import Action, ActionKind

    state = _load(args)
    if args.action == "list":
        actions = state.store.list("Action")
        for a in actions:
            flag = " (disabled)" if a.disabled else ""
            print(f"{a.meta.name}: {a.action_kind.value}"
                  f" signals={a.signals or 'all'}{flag}")
        if not actions:
            print("(no actions)")
        return 0
    if args.action == "add":
        try:
            kind = ActionKind(args.kind)
        except ValueError:
            return _err(f"unknown action kind {args.kind!r} "
                        f"(known: {[k.value for k in ActionKind]})")
        try:
            details = _json.loads(args.details or "{}")
        except ValueError as e:
            return _err(f"--details must be JSON: {e}")
        state.store.apply(Action(
            meta=ObjectMeta(name=args.name, namespace=ODIGOS_NAMESPACE),
            action_kind=kind, signals=list(args.signal or []),
            details=details))
        state.reconcile()
        state.save()
        print(f"action {args.name} ({kind.value}) applied")
        return 0
    if args.action == "remove":
        if state.store.delete("Action", ODIGOS_NAMESPACE, args.name):
            state.reconcile()
            state.save()
            print("action removed")
            return 0
        return _err(f"no action {args.name}")
    return _err(f"unknown actions action {args.action}")


def cmd_rules(args) -> int:
    """Instrumentation rules (instrumentationrule_type.go; scoped SDK
    behavior consumed by the instrumentor)."""
    import json as _json

    from ..api.resources import InstrumentationRule, RuleKind

    state = _load(args)
    if args.action == "list":
        rules = state.store.list("InstrumentationRule")
        for r in rules:
            flag = " (disabled)" if r.disabled else ""
            scope = (f" workloads={len(r.workloads)}" if r.workloads
                     else " all-workloads")
            print(f"{r.meta.name}: {r.rule_kind.value}{scope}"
                  f" languages={r.languages or 'all'}{flag}")
        if not rules:
            print("(no rules)")
        return 0
    if args.action == "add":
        try:
            kind = RuleKind(args.kind)
        except ValueError:
            return _err(f"unknown rule kind {args.kind!r} "
                        f"(known: {[k.value for k in RuleKind]})")
        try:
            details = _json.loads(args.details or "{}")
        except ValueError as e:
            return _err(f"--details must be JSON: {e}")
        state.store.apply(InstrumentationRule(
            meta=ObjectMeta(name=args.name, namespace=ODIGOS_NAMESPACE),
            rule_kind=kind, languages=list(args.language or []),
            details=details))
        state.reconcile()
        state.save()
        print(f"rule {args.name} ({kind.value}) applied")
        return 0
    if args.action == "remove":
        if state.store.delete("InstrumentationRule", ODIGOS_NAMESPACE,
                              args.name):
            state.reconcile()
            state.save()
            print("rule removed")
            return 0
        return _err(f"no rule {args.name}")
    return _err(f"unknown rules action {args.action}")


# ----------------------------------------------------------- central stack

CENTRAL_NAMESPACE = "central-odigos"
# the enterprise central stack (cli/cmd/resources/centralodigos/
# {centralbackend,centralproxy,centralui,keycloak,redis}.go): component
# name -> (container image role, replicas)
CENTRAL_COMPONENTS = (
    ("central-backend", 1),
    ("central-proxy", 1),
    ("central-ui", 1),
    ("keycloak", 1),
    ("redis", 1),
)


def cmd_central(args) -> int:
    """`central install|uninstall|status` — the enterprise central stack
    (reference: cli/cmd/pro-dep.go centralCmdDep + centralodigos resource
    managers). Installing requires an onprem entitlement; components are
    scheduled as workloads in the cluster so status/describe see them."""
    from ..controlplane.cluster import Container
    from ..api.resources import WorkloadRef, WorkloadKind

    state = _load(args)

    def refs():
        return [WorkloadRef(CENTRAL_NAMESPACE, WorkloadKind.DEPLOYMENT, n)
                for n, _ in CENTRAL_COMPONENTS]

    installed = [r for r in refs()
                 if state.cluster.get_workload(r) is not None]

    if args.action == "status":
        if not installed:
            print("central stack: not installed")
            return 0
        for ref in installed:
            pods = state.cluster.pods_of(ref)
            phases = ",".join(p.phase.value for p in pods) or "no pods"
            print(f"{ref.name}: {phases}")
        return 0

    if args.action == "uninstall":
        if not installed:
            return _err("central stack is not installed")
        for ref in refs():
            state.cluster.remove_workload(ref)
        state.save()
        print(f"central stack removed from {CENTRAL_NAMESPACE}")
        return 0

    # install: enterprise entitlement required (pro-dep.go onprem-token)
    from ..utils.auth import TokenError, validate_tier_claim

    try:
        validate_tier_claim(getattr(args, "onprem_token", None) or "",
                            "onprem")
    except TokenError as e:
        return _err(f"central stack requires a valid onprem token "
                    f"(--onprem-token): {e}")
    if installed:
        return _err("central stack already installed")
    for name, replicas in CENTRAL_COMPONENTS:
        state.cluster.add_workload(
            CENTRAL_NAMESPACE, name,
            [Container(name, language="central")], replicas=replicas)
    state.save()
    print(f"central stack installed in {CENTRAL_NAMESPACE} "
          f"({', '.join(n for n, _ in CENTRAL_COMPONENTS)})")
    return 0


# ---------------------------------------------------------------- parser


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="odigos-tpu",
        description="TPU-native distributed-tracing platform CLI")
    ap.add_argument("--state-dir", default=None,
                    help="state directory (default ~/.odigos-tpu or "
                         "$ODIGOS_TPU_STATE)")
    sub = ap.add_subparsers(dest="command", required=True)

    p = sub.add_parser("install", help="install the control plane")
    p.add_argument("--nodes", type=int, default=1)
    p.add_argument("--profile", action="append")
    p.add_argument("--tier", default="community",
                   choices=["community", "cloud", "onprem"])
    p.add_argument("--onprem-token", default=None,
                   help="pro entitlement token (required for paid tiers)")
    p.set_defaults(fn=cmd_install)

    p = sub.add_parser("upgrade", help="upgrade an existing installation")
    p.set_defaults(fn=cmd_upgrade)

    p = sub.add_parser("manifests",
                       help="render component manifests + policy check")
    p.set_defaults(fn=cmd_manifests)

    p = sub.add_parser("preflight", help="installation health checks")
    p.add_argument("--skip-device-probe", action="store_true",
                   help="skip the (advisory, up to 30s) TPU probe")
    p.set_defaults(fn=cmd_preflight)

    p = sub.add_parser("uninstall", help="delete the installation")
    p.add_argument("--yes", action="store_true")
    p.set_defaults(fn=cmd_uninstall)

    p = sub.add_parser("status", help="installation summary")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("actions", help="manage telemetry-policy actions")
    p.add_argument("action", choices=["list", "add", "remove"])
    p.add_argument("--name")
    p.add_argument("--kind")
    p.add_argument("--signal", action="append")
    p.add_argument("--details", help="JSON details object")
    p.set_defaults(fn=cmd_actions)

    p = sub.add_parser("rules", help="manage instrumentation rules")
    p.add_argument("action", choices=["list", "add", "remove"])
    p.add_argument("--name")
    p.add_argument("--kind")
    p.add_argument("--language", action="append")
    p.add_argument("--details", help="JSON details object")
    p.set_defaults(fn=cmd_rules)

    p = sub.add_parser("central",
                       help="manage the enterprise central stack")
    p.add_argument("action", choices=["install", "uninstall", "status"])
    p.add_argument("--onprem-token", default=None,
                   help="enterprise entitlement (required for install)")
    p.set_defaults(fn=cmd_central)

    p = sub.add_parser("version")
    p.set_defaults(fn=cmd_version)

    p = sub.add_parser("sources", help="manage instrumentation sources")
    p.add_argument("action", choices=["list", "add", "remove"])
    p.add_argument("--namespace", default="default")
    p.add_argument("--name")
    p.add_argument("--kind", default="deployment")
    p.add_argument("--service-name")
    p.add_argument("--stream", action="append")
    p.add_argument("--disable", action="store_true",
                   help="exclude instead of include")
    p.set_defaults(fn=cmd_sources)

    p = sub.add_parser("workloads", help="manage simulated workloads")
    p.add_argument("action", choices=["list", "add", "remove"])
    p.add_argument("--namespace", default="default")
    p.add_argument("--name")
    p.add_argument("--kind", default="deployment")
    p.add_argument("--language", default="python")
    p.add_argument("--runtime-version", default="")
    p.add_argument("--replicas", type=int, default=1)
    p.set_defaults(fn=cmd_workloads)

    p = sub.add_parser("destinations", help="manage export destinations")
    p.add_argument("action", choices=["list", "add", "remove", "types"])
    p.add_argument("--name")
    p.add_argument("--type")
    p.add_argument("--signal", action="append",
                   choices=["traces", "metrics", "logs"])
    p.add_argument("--set", action="append", metavar="KEY=VALUE")
    p.add_argument("--stream", action="append")
    p.set_defaults(fn=cmd_destinations)

    p = sub.add_parser("ui", help="serve the operator dashboard")
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=3000)
    p.add_argument("--auth-token", default=None,
                   help="require this bearer token (or a valid pro JWT) "
                        "for mutations and the event stream; default: "
                        "$ODIGOS_UI_TOKEN, open when unset")
    p.add_argument("--once", action="store_true",
                   help="bind, print the URL, exit (smoke test)")
    p.set_defaults(fn=cmd_ui)

    p = sub.add_parser("pro", help="update the entitlement token")
    p.add_argument("--onprem-token", required=True)
    p.set_defaults(fn=cmd_pro)

    p = sub.add_parser("profile", help="manage config profiles")
    p.add_argument("action", choices=["list", "add", "remove"])
    p.add_argument("--name")
    p.add_argument("--tier", default="community",
                   choices=["community", "cloud", "onprem"])
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("describe",
                       help="explain one workload's instrumentation chain")
    p.add_argument("target", choices=["odigos", "workload"])
    p.add_argument("--namespace", default="default")
    p.add_argument("--kind", default="deployment")
    p.add_argument("--name")
    p.set_defaults(fn=cmd_describe)

    p = sub.add_parser("diagnose", help="collect a support bundle")
    p.add_argument("-o", "--output", default=None)
    p.add_argument("--redact", action="store_true",
                   help="strip destination-secret values from every "
                        "archived file (span attributes, metric labels, "
                        "resource dumps)")
    p.set_defaults(fn=cmd_diagnose)

    return ap


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    needs_name = {
        (cmd_sources, "add"), (cmd_sources, "remove"),
        (cmd_workloads, "add"), (cmd_workloads, "remove"),
        (cmd_destinations, "add"), (cmd_destinations, "remove"),
        (cmd_profile, "add"), (cmd_profile, "remove"),
    }
    action = getattr(args, "action", None)
    if (args.fn, action) in needs_name and not args.name:
        return _err(f"--name is required for `{args.command} {action}`")
    if args.fn is cmd_destinations and action == "add" and not args.type:
        return _err("--type is required for `destinations add`")
    if (args.fn is cmd_describe and args.target == "workload"
            and not args.name):
        return _err("--name is required for `describe workload`")
    try:
        return args.fn(args)
    except (FileNotFoundError, ValueError, RuntimeError) as e:
        # RuntimeError covers state-version mismatch: an actionable
        # message, never a raw traceback
        return _err(str(e))


if __name__ == "__main__":
    sys.exit(main())
