"""Wire path tests: codec roundtrip, receiver/exporter over real TCP,
pre-decode admission rejection + retry, loadbalancing consistency, hot
reload from ConfigMap events."""

import time

import numpy as np
import pytest

from odigos_tpu.api import ObjectMeta, Store
from odigos_tpu.api.resources import ConfigMap
from odigos_tpu.pdata import synthesize_traces
from odigos_tpu.pipeline.service import Collector
from odigos_tpu.utils.telemetry import meter
from odigos_tpu.wire import (
    LoadBalancingExporter,
    WireExporter,
    WireReceiver,
    decode_batch,
    encode_batch,
    watch_configmap,
)
from odigos_tpu.wire.server import REJECTIONS_METRIC


class _Sink:
    def __init__(self):
        self.batches = []

    def consume(self, batch):
        self.batches.append(batch)


def wait_for(cond, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def assert_batches_equal(a, b):
    assert len(a) == len(b)
    for col in a.columns:
        assert (a.col(col) == b.col(col)).all(), col
    assert a.service_names() == b.service_names()
    assert list(a.span_attrs) == list(b.span_attrs)
    assert [dict(r) for r in a.resources] == [dict(r) for r in b.resources]


class TestCodec:
    def test_roundtrip_full_fidelity(self):
        batch = synthesize_traces(50, seed=5)
        batch = batch.with_span_attr(
            "http.status_code", [200] * len(batch))
        out = decode_batch(encode_batch(batch))
        assert_batches_equal(out, batch)

    def test_roundtrip_store_and_legacy_json_formats(self):
        """New attr-store frames AND legacy dict-of-dicts frames both
        round-trip to identical attrs (the decode-old-frames contract)."""
        batch = synthesize_traces(30, seed=11)
        mask = np.zeros(len(batch), dtype=bool)
        mask[::3] = True
        batch = batch.with_span_attrs(
            {"http.route": ["/r"] * int(mask.sum()),
             "retry": list(range(int(mask.sum())))}, mask)
        new = decode_batch(encode_batch(batch, attr_format="store"))
        legacy = decode_batch(encode_batch(batch, attr_format="json"))
        assert_batches_equal(new, batch)
        assert_batches_equal(legacy, batch)
        assert list(new.span_attrs) == list(legacy.span_attrs)

    def test_store_frame_attrs_never_ride_json_per_row(self):
        """The header carries only DEDUPED pools: 1000 spans sharing one
        attr dict must not serialize 1000 dicts."""
        import json as _json

        from odigos_tpu.pdata.spans import SpanBatchBuilder

        b = SpanBatchBuilder()
        for i in range(1000):
            b.add_span(trace_id=i + 1, span_id=i + 1, name="op",
                       service="svc", start_unix_nano=1, end_unix_nano=2,
                       attrs={"env": "prod", "zone": "a"})
        payload = encode_batch(b.build())
        hdr_len = int.from_bytes(payload[:4], "little")
        hdr = _json.loads(payload[4:4 + hdr_len])
        assert hdr["astore"]["keys"] == ["env", "zone"]
        assert hdr["astore"]["vals"] == ["prod", "a"]
        assert hdr["astore"]["nnz"] == 2000  # int32 raw arrays, not JSON

    def test_decoded_store_is_zero_copy_and_cow(self):
        """Entry arrays are read-only views into the frame; mutating ops
        copy-on-write instead of corrupting the wire buffer."""
        batch = synthesize_traces(20, seed=7)
        batch = batch.with_span_attr("k", list(range(len(batch))))
        payload = encode_batch(batch)
        out = decode_batch(payload)
        store = out.attrs()
        assert not store.key_idx.flags.writeable
        assert np.shares_memory(store.key_idx,
                                np.frombuffer(payload, dtype=np.uint8))
        with pytest.raises(ValueError):
            store.key_idx[0] = 1
        tagged = out.with_span_attr("t", ["x"] * len(out))
        assert tagged.span_attrs[0]["t"] == "x"
        assert "t" not in out.span_attrs[0]  # original untouched

    def test_logs_roundtrip(self):
        from odigos_tpu.pdata.logs import LogBatch, LogBatchBuilder

        b = LogBatchBuilder()
        ri = b.add_resource({"service.name": "websvc"})
        for i in range(12):
            b.add_record(body=f"log line {i}", time_unix_nano=100 + i,
                         trace_id=i + 1, resource_index=ri,
                         attrs={"log.file.path": "/var/log/x"} if i % 3 == 0
                         else None)
        batch = b.build()
        out = decode_batch(encode_batch(batch))
        assert isinstance(out, LogBatch)
        assert out.bodies == batch.bodies
        assert list(out.record_attrs) == list(batch.record_attrs)
        assert [dict(r) for r in out.resources] == \
            [dict(r) for r in batch.resources]
        for col in batch.columns:
            assert (out.col(col) == batch.col(col)).all(), col

    def test_logs_over_tcp(self):
        """The node logs pipeline ships filelog output to the gateway via
        the otlp wire exporter (pipelinegen/nodecollector.py logs pipeline)
        — LogBatch must survive the real socket path end to end."""
        from odigos_tpu.pdata.logs import LogBatch, LogBatchBuilder

        recv, sink = start_receiver()
        exp = WireExporter("otlpwire", {
            "endpoint": f"127.0.0.1:{recv.port}"})
        exp.start()
        try:
            b = LogBatchBuilder()
            ri = b.add_resource({"k8s.pod.name": "web-1"})
            b.add_record(body="hello", time_unix_nano=7, resource_index=ri)
            exp.export(b.build())
            assert wait_for(lambda: sink.batches)
            out = sink.batches[0]
            assert isinstance(out, LogBatch) and out.bodies == ("hello",)
        finally:
            exp.shutdown()
            recv.shutdown()

    def test_decode_is_zero_copy_and_readonly(self):
        """ISSUE 2 satellite: decoded numeric columns are read-only views
        into the received payload (no per-column memcpy), copied only on
        misalignment — and downstream mutation still behaves, because
        every mutating path in the stack copies before writing."""
        batch = synthesize_traces(20, seed=11)
        payload = encode_batch(batch)
        out = decode_batch(payload)
        zero_copy = [n for n, c in out.columns.items()
                     if c.base is not None and not c.flags.writeable]
        # the padded header 8-aligns the first column; u64/i64/u8 span
        # columns keep alignment except after odd-length narrow columns,
        # so the bulk of the frame must decode without a copy
        assert len(zero_copy) >= len(out.columns) // 2, \
            f"only {zero_copy} decoded zero-copy"
        col = out.col("start_unix_nano")
        # in-place writes raise instead of silently corrupting the frame
        with pytest.raises(ValueError):
            col[0] = 123
        # the copy-before-write discipline downstream still mutates fine:
        # with_span_attr (processor tagging) and the dataclasses.replace +
        # copy pattern (spike injection, transform processors) both work
        tagged = out.with_span_attr("k", [1], np.arange(len(out)) == 0)
        assert tagged.span_attrs[0]["k"] == 1
        from dataclasses import replace
        cols = dict(out.columns)
        end = cols["end_unix_nano"].copy()
        end[0] += 1_000_000
        cols["end_unix_nano"] = end
        bumped = replace(out, columns=cols)
        assert bumped.duration_ns[0] != out.duration_ns[0]
        # and the original zero-copy view still matches the wire bytes
        assert (out.col("end_unix_nano") == batch.col("end_unix_nano")).all()
        # decoded batches feed scoring unchanged (read-only is fine there)
        from odigos_tpu.features import featurize
        assert len(featurize(out)) == len(out)

    def test_decode_misaligned_frame_still_copies_correctly(self):
        """Frames from a pre-padding encoder (unpadded JSON header) must
        still decode exactly — via the per-column copy fallback."""
        import json as _json
        import struct as _struct

        batch = synthesize_traces(5, seed=3)
        payload = encode_batch(batch)
        hdr_len = int.from_bytes(payload[:4], "little")
        hdr = _json.loads(payload[4:4 + hdr_len])
        raw = payload[4 + hdr_len:]
        unpadded = _json.dumps(hdr, separators=(",", ":")).encode()
        while (4 + len(unpadded)) % 8 == 0:  # force misalignment
            unpadded += b" "
        legacy = _struct.pack("<I", len(unpadded)) + unpadded + raw
        out = decode_batch(legacy)
        assert_batches_equal(out, batch)
        # misaligned columns came back as copies: writable after .copy()
        # upstream, but still correct values — fidelity is the contract
        for col in batch.columns:
            assert (out.col(col) == batch.col(col)).all(), col

    def test_empty_attrs_stay_sparse(self):
        from odigos_tpu.pdata.spans import SpanBatchBuilder
        b = SpanBatchBuilder()
        for i in range(10):
            b.add_span(trace_id=i + 1, span_id=i + 1, name="op",
                       service="svc", start_unix_nano=1, end_unix_nano=2)
        batch = b.build()
        payload = encode_batch(batch)
        import json as _json
        # no per-span attr dicts serialized for attr-less spans: the
        # store header carries empty pools and zero entries
        hdr_len = int.from_bytes(payload[:4], "little")
        hdr = _json.loads(payload[4:4 + hdr_len])
        assert "attrs" not in hdr
        assert hdr["astore"] == {"keys": [], "vals": [], "nnz": 0}
        out = decode_batch(payload)
        assert all(a == {} for a in out.span_attrs)
        # and the legacy escape hatch still emits the sparse dict shape
        legacy = encode_batch(batch, attr_format="json")
        hdr_len = int.from_bytes(legacy[:4], "little")
        assert _json.loads(legacy[4:4 + hdr_len])["attrs"] == {}
        assert all(a == {} for a in decode_batch(legacy).span_attrs)


def start_receiver(**cfg):
    recv = WireReceiver("otlpwire", {"port": 0, **cfg})
    sink = _Sink()
    recv.set_consumer(sink)
    recv.start()
    return recv, sink


class TestWireTransfer:
    def test_exporter_to_receiver(self):
        recv, sink = start_receiver()
        exp = WireExporter("otlpwire", {
            "endpoint": f"127.0.0.1:{recv.port}"})
        exp.start()
        try:
            batch = synthesize_traces(20, seed=2)
            exp.export(batch)
            assert wait_for(lambda: sink.batches)
            assert_batches_equal(sink.batches[0], batch)
        finally:
            exp.shutdown()
            recv.shutdown()

    def test_multiple_frames_one_connection(self):
        recv, sink = start_receiver()
        exp = WireExporter("otlpwire", {
            "endpoint": f"127.0.0.1:{recv.port}"})
        exp.start()
        try:
            for i in range(5):
                exp.export(synthesize_traces(5, seed=i))
            assert wait_for(lambda: len(sink.batches) == 5)
        finally:
            exp.shutdown()
            recv.shutdown()

    def test_predecode_rejection_and_retry(self):
        """Admission control rejects before decode; the exporter backs off
        and delivers once pressure clears."""
        recv, sink = start_receiver(max_inflight_bytes=1)  # reject all
        before = meter.counter(REJECTIONS_METRIC)
        exp = WireExporter("otlpwire", {
            "endpoint": f"127.0.0.1:{recv.port}",
            "retry_initial_s": 0.01, "max_elapsed_s": 30.0})
        exp.start()
        try:
            batch = synthesize_traces(10, seed=1)
            exp.export(batch)
            assert wait_for(
                lambda: meter.counter(REJECTIONS_METRIC) > before)
            assert sink.batches == []
            # pressure clears
            recv.admission.max_inflight_bytes = 64 << 20
            assert wait_for(lambda: sink.batches)
            assert_batches_equal(sink.batches[0], batch)
        finally:
            exp.shutdown()
            recv.shutdown()

    def test_exporter_survives_receiver_restart(self):
        recv, sink = start_receiver()
        port = recv.port
        exp = WireExporter("otlpwire", {
            "endpoint": f"127.0.0.1:{port}", "retry_initial_s": 0.01})
        exp.start()
        try:
            exp.export(synthesize_traces(3, seed=0))
            assert wait_for(lambda: sink.batches)
            recv.shutdown()
            exp.export(synthesize_traces(3, seed=1))  # queued, retried
            recv2 = WireReceiver("otlpwire", {"port": port})
            sink2 = _Sink()
            recv2.set_consumer(sink2)
            recv2.start()
            try:
                assert wait_for(lambda: sink2.batches)
            finally:
                recv2.shutdown()
        finally:
            exp.shutdown()


class TestLoadBalancing:
    def test_consistent_trace_routing(self):
        receivers = []
        sinks = []
        for _ in range(3):
            r, s = start_receiver()
            receivers.append(r)
            sinks.append(s)
        endpoints = [f"127.0.0.1:{r.port}" for r in receivers]
        lb = LoadBalancingExporter("loadbalancing", {
            "endpoints": endpoints, "child": {}})
        lb.start()
        try:
            batch = synthesize_traces(100, seed=7)
            lb.export(batch)
            assert lb.flush()
            assert wait_for(
                lambda: sum(len(b) for s in sinks
                            for b in s.batches) == len(batch))
            # every trace's spans landed on exactly one replica
            trace_to_replica = {}
            for i, sink in enumerate(sinks):
                for b in sink.batches:
                    for t in np.unique(b.col("trace_id_lo")):
                        assert trace_to_replica.setdefault(int(t), i) == i
            assert len(trace_to_replica) == 100
            # ...and spread across replicas even with small sequential
            # trace ids (the hot-spotting bug: raw ids on an md5 ring all
            # landed below the first vnode -> one replica took 100%)
            per_replica = np.bincount(
                np.asarray(list(trace_to_replica.values())),
                minlength=len(sinks))
            assert (per_replica > 0).all(), \
                f"replica(s) starved: {per_replica.tolist()}"
            # routing is deterministic: a second export lands identically
            sent_before = [sum(len(b) for b in s.batches) for s in sinks]
            lb.export(batch)
            lb.flush()
            assert wait_for(
                lambda: sum(len(b) for s in sinks
                            for b in s.batches) == 2 * len(batch))
            for i, sink in enumerate(sinks):
                assert sum(len(b) for b in sink.batches) == 2 * sent_before[i]
        finally:
            lb.shutdown()
            for r in receivers:
                r.shutdown()

    def test_resolver_rebalances(self):
        r1, s1 = start_receiver()
        r2, s2 = start_receiver()
        current = [f"127.0.0.1:{r1.port}"]
        lb = LoadBalancingExporter("loadbalancing", {
            "resolver": lambda: list(current),
            "resolve_interval_s": 0.0})
        lb.start()
        try:
            lb.export(synthesize_traces(10, seed=0))
            lb.flush()
            assert wait_for(lambda: s1.batches)
            current[:] = [f"127.0.0.1:{r2.port}"]  # replica set changes
            lb.export(synthesize_traces(10, seed=1))
            lb.flush()
            assert wait_for(lambda: s2.batches)
        finally:
            lb.shutdown()
            r1.shutdown()
            r2.shutdown()


class TestHotReload:
    def _config(self, seed):
        return {
            "receivers": {"synthetic": {"n_batches": 0, "interval_s": 60,
                                        "seed": seed}},
            "exporters": {"debug": {}},
            "service": {"pipelines": {
                "traces": {"receivers": ["synthetic"],
                           "processors": [], "exporters": ["debug"]}}},
        }

    def test_reload_on_configmap_change(self):
        store = Store()
        collector = Collector(self._config(0)).start()
        before = meter.counter("odigos_collector_reloads_total")
        unsub = watch_configmap(store, "odigos-system", "gw-config",
                                collector)
        try:
            store.apply(ConfigMap(
                meta=ObjectMeta(name="gw-config",
                                namespace="odigos-system"),
                data=self._config(42)))
            assert meter.counter("odigos_collector_reloads_total") == before + 1
            assert collector.config["receivers"]["synthetic"]["seed"] == 42
            # identical content: no reload
            store.apply(ConfigMap(
                meta=ObjectMeta(name="gw-config",
                                namespace="odigos-system"),
                data=self._config(42)))
            assert meter.counter("odigos_collector_reloads_total") == before + 1
        finally:
            unsub()
            collector.shutdown()

    def test_bad_config_keeps_old_graph(self):
        store = Store()
        collector = Collector(self._config(0)).start()
        failures = meter.counter("odigos_collector_reload_failures_total")
        unsub = watch_configmap(store, "odigos-system", "gw-config",
                                collector)
        try:
            store.apply(ConfigMap(
                meta=ObjectMeta(name="gw-config",
                                namespace="odigos-system"),
                data={"service": {"pipelines": {"traces": {
                    "receivers": ["nope"], "exporters": []}}}}))
            assert meter.counter(
                "odigos_collector_reload_failures_total") == failures + 1
            assert collector.config["receivers"]["synthetic"]["seed"] == 0
        finally:
            unsub()
            collector.shutdown()

    def test_start_failure_counted_once_via_watcher(self):
        """ISSUE 14 satellite: a reload that fails at component START
        (build succeeds, the new receiver can't bind) used to be
        counted twice — once by Collector.reload's resurrect path and
        again by watch_configmap's catch. Exactly once now, and the
        old graph keeps serving."""
        import socket

        blocker = socket.socket()
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        port = blocker.getsockname()[1]
        store = Store()
        collector = Collector(self._config(0)).start()
        failures = meter.counter("odigos_collector_reload_failures_total")
        unsub = watch_configmap(store, "odigos-system", "gw-config",
                                collector)
        try:
            bad = self._config(0)
            # topology change (receiver added) -> full-rebuild path;
            # the otlpwire receiver then fails to bind the taken port
            bad["receivers"]["otlpwire"] = {"port": port}
            bad["service"]["pipelines"]["traces"]["receivers"] = [
                "synthetic", "otlpwire"]
            store.apply(ConfigMap(
                meta=ObjectMeta(name="gw-config",
                                namespace="odigos-system"),
                data=bad))
            assert meter.counter(
                "odigos_collector_reload_failures_total") \
                == failures + 1, "failure must be counted exactly once"
            assert collector.config == self._config(0)
        finally:
            unsub()
            collector.shutdown()
            blocker.close()

    def test_failed_reload_retries_on_next_event(self):
        """Level-triggered contract: a failed reload leaves the
        watcher's hash UNSET, so the next event retries the same
        content instead of skipping a hash it never applied."""
        store = Store()
        collector = Collector(self._config(0)).start()
        failures = meter.counter("odigos_collector_reload_failures_total")
        unsub = watch_configmap(store, "odigos-system", "gw-config",
                                collector)
        try:
            bad = {"service": {"pipelines": {"traces": {
                "receivers": ["nope"], "exporters": []}}}}
            cm = ConfigMap(meta=ObjectMeta(name="gw-config",
                                           namespace="odigos-system"),
                           data=bad)
            store.apply(cm)
            assert meter.counter(
                "odigos_collector_reload_failures_total") == failures + 1
            # the SAME bad content on the next event must be retried,
            # not swallowed by a prematurely-recorded hash
            store.apply(cm)
            assert meter.counter(
                "odigos_collector_reload_failures_total") == failures + 2
        finally:
            unsub()
            collector.shutdown()

    def test_reverted_configmap_converges_without_spurious_reload(self):
        """A bad push followed by a revert to the RUNNING config must
        converge silently: the hash still matches the applied config,
        so no reload fires and nothing is counted."""
        store = Store()
        collector = Collector(self._config(0)).start()
        unsub = watch_configmap(store, "odigos-system", "gw-config",
                                collector)
        reloads = meter.counter("odigos_collector_reloads_total")
        failures = meter.counter("odigos_collector_reload_failures_total")
        try:
            store.apply(ConfigMap(
                meta=ObjectMeta(name="gw-config",
                                namespace="odigos-system"),
                data={"service": {"pipelines": {"traces": {
                    "receivers": ["nope"], "exporters": []}}}}))
            assert meter.counter(
                "odigos_collector_reload_failures_total") == failures + 1
            # operator reverts the ConfigMap to what is running
            store.apply(ConfigMap(
                meta=ObjectMeta(name="gw-config",
                                namespace="odigos-system"),
                data=self._config(0)))
            assert collector.config == self._config(0)
            assert meter.counter(
                "odigos_collector_reloads_total") == reloads, \
                "revert to the running config must not reload"
            assert meter.counter(
                "odigos_collector_reload_failures_total") == failures + 1
        finally:
            unsub()
            collector.shutdown()

    def test_existing_configmap_applied_at_subscribe(self):
        store = Store()
        store.apply(ConfigMap(
            meta=ObjectMeta(name="gw-config", namespace="odigos-system"),
            data=self._config(9)))
        collector = Collector(self._config(0)).start()
        unsub = watch_configmap(store, "odigos-system", "gw-config",
                                collector)
        try:
            assert collector.config["receivers"]["synthetic"]["seed"] == 9
        finally:
            unsub()
            collector.shutdown()


class TestInflightFrame:
    def test_inflight_frame_survives_queue_overflow(self):
        """Pop-before-send: the frame being retried is held out of the
        bounded deque, so producer overflow can neither displace it nor
        make the sender skip/double-send (round-2 advisor finding)."""
        import socket as socketlib
        s = socketlib.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        exp = WireExporter("otlpwire", {
            "endpoint": f"127.0.0.1:{port}", "queue_size": 2,
            "retry_initial_s": 0.02, "retry_max_s": 0.05})
        exp.start()
        try:
            first = synthesize_traces(3, seed=42)
            exp.export(first)  # no listener yet: goes in-flight, retries
            assert wait_for(lambda: exp._inflight is not None)
            for i in range(6):  # overflow the deque while head is in-flight
                exp.export(synthesize_traces(1, seed=100 + i))
            assert exp.queued == 3  # 2 queued + 1 in-flight
            recv = WireReceiver("otlpwire", {"port": port})
            sink = _Sink()
            recv.set_consumer(sink)
            recv.start()
            try:
                assert wait_for(lambda: sink.batches)
                assert_batches_equal(sink.batches[0], first)
            finally:
                recv.shutdown()
        finally:
            exp.shutdown()
