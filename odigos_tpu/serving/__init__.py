from .engine import ScoringEngine, EngineConfig, ScoreRequest
from .sidecar import RemoteBackend, SidecarClient, SidecarServer

__all__ = ["ScoringEngine", "EngineConfig", "ScoreRequest",
           "RemoteBackend", "SidecarClient", "SidecarServer"]
