"""Collector service: lifecycle over a built pipeline graph.

The odigosotelcol entrypoint equivalent (collector/odigosotelcol/main.go:17):
takes a config, builds the graph from registered factories, starts components
exporters-first / shuts down receivers-first, and supports hot config reload
(the odigosk8scmprovider role — collector/providers/odigosk8scmprovider/): on
``reload(new_config)`` a new graph is built, started, and atomically swapped
while the old one drains.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

import odigos_tpu.components  # noqa: F401  (registers builtin factories)

from ..selftelemetry.flow import register_rollup, unregister_rollup
from ..selftelemetry.profiler import start_from_config, stop_started
from ..serving.gcisolation import gc_plane
from ..utils.telemetry import meter
from .graph import Graph, build_graph


class Collector:
    def __init__(self, config: dict[str, Any], registry=None):
        self._registry = registry
        self._lock = threading.Lock()
        self.config = config
        self.graph: Graph = build_graph(config, registry)
        self._running = False
        # which process-global telemetry subsystems (continuous profiler,
        # device-runtime collector) THIS collector's config started — only
        # those are stopped on shutdown (another owner's stay running)
        self._telemetry_started: list[str] = []
        self._gc_started = False

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "Collector":
        with self._lock:
            if self._running:
                return self
            for comp in self.graph.all_components():
                comp.start()
            self._running = True
            # surface the graph's condition rollup to graph-less readers
            # (frontend /api/flow, diagnose) while this collector runs
            register_rollup(self.graph.flow_health)
            self._telemetry_started = start_from_config(
                self.config.get("service", {}).get("telemetry"))
            # GC isolation (ISSUE 12), AFTER components started: engine
            # warmup / ladder compiles have happened, so a configured
            # freeze pins the built object graph out of every future
            # collection's scan set. The janitor itself always runs
            # while a collector does (refcounted) — memory_limiter's
            # soft-pressure hints need a thread to land on.
            gc_plane.start(self.config.get("service", {}).get("gc"))
            self._gc_started = True
        meter.add("odigos_collector_starts_total")
        return self

    def shutdown(self) -> None:
        with self._lock:
            if not self._running:
                return
            self._stop_graph(self.graph)
            unregister_rollup(self.graph.flow_health)
            if self.graph.alert_rule_names:
                # the engine is process-global: a dead collector's rules
                # must not keep evaluating (and firing) against the
                # store forever — same lifetime as the rollup above
                from ..selftelemetry.fleet import alert_engine

                for name in self.graph.alert_rule_names:
                    alert_engine.remove(name)
            stop_started(self._telemetry_started)
            self._telemetry_started = []
            if self._gc_started:
                gc_plane.stop()
                self._gc_started = False
            self._running = False

    def __enter__(self) -> "Collector":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ------------------------------------------------------------- helpers
    def component(self, component_id: str):
        return self.graph.component(component_id)

    def health_conditions(self) -> list[dict]:
        """Per-component condition list (flow-ledger rollup) — the
        replacement for polling ``healthy()`` booleans one by one."""
        return self.graph.flow_health.evaluate()

    def drain_receivers(self, timeout: float = 30.0) -> None:
        """Wait for finite receivers (n_batches set) to finish, then flush
        processors upstream-first so pending data cascades to exporters."""
        for recv in self.graph.receivers.values():
            drain = getattr(recv, "drain", None)
            if drain is not None:
                drain(timeout)
        # fast-path windows drain after intake stops: everything
        # submitted must forward downstream before processors flush
        for fp in self.graph.fastpaths.values():
            fp.drain(timeout)
        for proc in self.graph.processors_topological():
            flush = getattr(proc, "flush", None)
            if flush is not None:
                flush()

    @staticmethod
    def _stop_graph(graph: Graph) -> None:
        """Stop intake, then flush/stop processors upstream-first (a downstream
        batch processor must shut down after upstream flushes reach it), then
        connectors and exporters."""
        for recv in graph.receivers.values():
            recv.shutdown()
        # fast paths next: their shutdown drains the pending window into
        # the (still running) downstream chain losslessly
        for fp in graph.fastpaths.values():
            fp.shutdown()
        for proc in graph.processors_topological():
            proc.shutdown()
        for conn in graph.connectors.values():
            conn.shutdown()
        for exp in graph.exporters.values():
            exp.shutdown()
        for ext in graph.extensions.values():
            ext.shutdown()  # last: health answers until the end

    # ------------------------------------------------------------ hot swap
    def reload(self, new_config: dict[str, Any]) -> None:
        """Swap in a rebuilt graph: drain + stop the old one first, then
        start the new (otelcol reload semantics). Stop-before-start is
        required for fixed-port receivers (the VM distribution's otlp
        port): the old graph still holds the bind until it stops, and
        allow_reuse_address makes the same-port rebind immediate."""
        if new_config == self.config:
            return  # a no-op reload must not bounce intake
        old_config = self.config
        new_graph = build_graph(new_config, self._registry)
        with self._lock:
            old_graph, old_running = self.graph, self._running
            if old_running:
                self._stop_graph(old_graph)
                started = []
                try:
                    for comp in new_graph.all_components():
                        comp.start()
                        started.append(comp)
                except Exception:
                    # bad new config must not leave the collector dead:
                    # unwind the partial start and resurrect the old graph
                    for comp in reversed(started):
                        try:
                            comp.shutdown()
                        except Exception:  # noqa: BLE001
                            pass
                    for comp in old_graph.all_components():
                        comp.start()
                    meter.add("odigos_collector_reload_failures_total")
                    raise
            # a reload that edited/deleted alert rules must retire the
            # ones no longer declared (the remove_slo discipline): the
            # new build upserted its own rules already, so the diff of
            # graph-stamped names is exactly the deleted set
            if old_graph.alert_rule_names - new_graph.alert_rule_names:
                from ..selftelemetry.fleet import alert_engine

                for name in (old_graph.alert_rule_names
                             - new_graph.alert_rule_names):
                    alert_engine.remove(name)
            # condition continuity across the swap: same-named components
            # keep their last-transition history (k8s lastTransitionTime
            # semantics survive a hot reload)
            new_graph.flow_health.adopt(old_graph.flow_health)
            if old_running:
                unregister_rollup(old_graph.flow_health)
                register_rollup(new_graph.flow_health)
            self.graph, self.config = new_graph, new_config
            if old_running:
                # re-anchor the telemetry subsystems on the new stanza
                stop_started(self._telemetry_started)
                self._telemetry_started = start_from_config(
                    new_config.get("service", {}).get("telemetry"))
                # same for the GC plane — but only when the stanza
                # actually changed: a bounce costs unfreeze + a full
                # stop-the-world collect + refreeze (tens of ms of
                # GIL hold landing in live lane frames), which an
                # unrelated-config reload must not pay
                old_gc = old_config.get("service", {}).get("gc")
                new_gc = new_config.get("service", {}).get("gc")
                if old_gc != new_gc or not self._gc_started:
                    if self._gc_started:
                        gc_plane.stop()
                    gc_plane.start(new_gc)
                    self._gc_started = True
        meter.add("odigos_collector_reloads_total")
