"""Conservation property tests (ISSUE 5 acceptance): for every pipeline
in every chaos scenario — exporter failures, queue pressure, reload
mid-stream — the flow-ledger balance holds:

    items_in == items_out + Σ dropped(reason) + Σ failed(error_class)
                + pending

and every imbalance is a *named* drop reason or error class, never a
silent leak."""

import time

import pytest

from odigos_tpu.components.api import Signal
from odigos_tpu.components.processors.memory_limiter import (
    MemoryLimiterError)
from odigos_tpu.controlplane import Container
from odigos_tpu.destinations import Destination
from odigos_tpu.e2e import E2EEnvironment, inject_exporter_chaos
from odigos_tpu.pdata import synthesize_traces
from odigos_tpu.pipeline.service import Collector
from odigos_tpu.selftelemetry.flow import DROP_REASONS, flow_ledger

T = Signal.TRACES


@pytest.fixture(autouse=True)
def fresh_ledger():
    flow_ledger.reset()
    flow_ledger.enabled = True
    yield
    flow_ledger.reset()


def assert_balanced(timeout: float = 8.0) -> dict:
    """Every registered pipeline balances to leak == 0 (polling through
    timer-thread flushes in flight), and every loss is NAMED: drop
    reasons from the closed taxonomy, failure classes non-empty."""
    deadline = time.monotonic() + timeout
    balances = {}
    while True:
        balances = flow_ledger.conservation()
        if all(b["leak"] == 0 for b in balances.values()) \
                or time.monotonic() > deadline:
            break
        time.sleep(0.05)
    for pname, b in balances.items():
        assert b["leak"] == 0, (
            f"pipeline {pname} leaks {b['leak']} items: {b}")
        for reason in b["dropped"]:
            assert reason in DROP_REASONS, \
                f"{pname}: unnamed drop reason {reason!r}"
        for cls in b["failed"]:
            assert cls and isinstance(cls, str), \
                f"{pname}: unnamed failure class {cls!r}"
    # drops recorded anywhere (incl. connectors/engine) are named too
    for d in flow_ledger.snapshot()["drops"]:
        for reason in d["reasons"]:
            assert reason in DROP_REASONS, d
    return balances


def tracedb_dest(id="db1", streams=()):
    return Destination(id=id, dest_type="tracedb", signals=[T],
                       data_stream_names=list(streams))


class TestExporterFailureChaos:
    """Destination rejects everything mid-stream: the lost spans must
    surface as failed{MockDestinationError} on the bad destination's
    pipeline, the good destination keeps flowing, and every pipeline
    still balances after the chaos clears."""

    def test_rejecting_exporter_accounted_not_leaked(self):
        with E2EEnvironment(nodes=1) as env:
            env.add_destination(tracedb_dest("good"))
            env.add_destination(Destination(
                id="bad", dest_type="mock", signals=[T],
                config={"MOCK_REJECT_FRACTION": "0",
                        "MOCK_RESPONSE_DURATION": "0"}))
            assert env.send_traces_wire(synthesize_traces(10, seed=0))
            env.gateway.drain_receivers()
            assert_balanced()

            inject_exporter_chaos(env, "mockdestination/bad",
                                  reject_fraction=1.0)
            assert env.send_traces_wire(synthesize_traces(10, seed=1))
            mock = env.gateway_component("mockdestination/bad")
            deadline = time.monotonic() + 5
            while mock.rejected_batches == 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert mock.rejected_batches > 0
            balances = assert_balanced()
            failed = {cls: n for b in balances.values()
                      for cls, n in b["failed"].items()}
            assert failed.get("MockDestinationError", 0) > 0, balances

            # chaos lifted: traffic flows and the books still balance
            inject_exporter_chaos(env, "mockdestination/bad",
                                  reject_fraction=0.0)
            assert env.send_traces_wire(synthesize_traces(10, seed=2))
            env.gateway.drain_receivers()
            assert_balanced()


class TestQueuePressure:
    """Memory-limiter rejection and engine queue saturation: both shed
    under pressure, both must land as named drops."""

    def test_memory_limiter_pressure_is_named_drop(self):
        cfg = {
            "receivers": {"synthetic": {"traces_per_batch": 1,
                                        "n_batches": 1, "interval_s": 0}},
            "processors": {
                "memory_limiter": {"limit_mib": 0},
                "batch": {"timeout_s": 0.01}},
            "exporters": {"debug": {}},
            "service": {"pipelines": {"traces/pressure": {
                "receivers": ["synthetic"],
                "processors": ["memory_limiter", "batch"],
                "exporters": ["debug"]}}},
        }
        with Collector(cfg) as col:
            col.drain_receivers()
            entry = col.graph.pipeline_entries["traces/pressure"]
            base = flow_ledger.conservation()["traces/pressure"][
                "dropped"].get("memory_limited", 0)
            b = synthesize_traces(20, seed=3)
            for _ in range(3):  # repeated backpressure, same named drop
                with pytest.raises(MemoryLimiterError):
                    entry.consume(b)
            balances = assert_balanced()
            dropped = balances["traces/pressure"]["dropped"]
            assert dropped.get("memory_limited", 0) - base == 3 * len(b)

    def test_engine_queue_full_named_and_spans_conserved(self):
        cfg = {
            "receivers": {"synthetic": {"traces_per_batch": 1,
                                        "n_batches": 1, "interval_s": 0}},
            "processors": {"tpuanomaly": {
                "model": "mock", "timeout_ms": 1.0, "max_queue": 1,
                "shared_engine": False, "pipeline_depth": 1}},
            "exporters": {"debug": {}},
            "service": {"pipelines": {"traces/scored": {
                "receivers": ["synthetic"],
                "processors": ["tpuanomaly"],
                "exporters": ["debug"]}}},
        }
        with Collector(cfg) as col:
            col.drain_receivers()
            entry = col.graph.pipeline_entries["traces/scored"]
            for i in range(20):
                entry.consume(synthesize_traces(5, seed=10 + i))
            balances = assert_balanced()
            # queue-full shed REQUESTS, never spans: the pipeline
            # balances because the batch passes through unscored
            assert balances["traces/scored"]["leak"] == 0
        drops = flow_ledger.snapshot()["drops"]
        engine_drops = [d for d in drops if d["pipeline"] == "(engine)"]
        if engine_drops:  # scheduling-dependent; when shed, it is named
            assert all(set(d["reasons"]) <=
                       {"queue_full", "shutdown_drain"}
                       for d in engine_drops)


class TestReloadMidStream:
    """Hot reload between batches: edges persist across the graph swap
    (same keys re-bound), the old graph drains losslessly, and the
    cumulative books still balance."""

    def test_reload_keeps_books_balanced(self):
        with E2EEnvironment(nodes=1) as env:
            env.add_destination(tracedb_dest("db1"))
            env.cluster.add_workload("default", "checkout", [
                Container(name="main", language="python",
                          runtime_version="3.11")])
            env.instrument_workload("default", "checkout")
            assert env.send_traces_wire(synthesize_traces(10, seed=4))
            # mid-stream config change: second destination => regenerated
            # gateway config, hot reload, new graph on the same ledger
            env.add_destination(tracedb_dest("db2"))
            assert env.send_traces_wire(synthesize_traces(10, seed=5))
            env.gateway.drain_receivers()
            balances = assert_balanced()
            assert any(b["items_in"] > 0 for b in balances.values())
            # the control-plane store consumed the rollup: the gateway
            # CollectorsGroup carries the CollectorHealth condition
            group = next(
                g for g in env.store.list("CollectorsGroup")
                if g.role.value == "CLUSTER_GATEWAY")
            cond = group.condition("CollectorHealth")
            assert cond is not None


class TestSamplingDropsNamed:
    """Intentional shedding (head sampling) is a named 'sampled' drop
    that keeps the balance exact."""

    def test_probabilistic_sampler_balance(self):
        cfg = {
            "receivers": {"synthetic": {"traces_per_batch": 1,
                                        "n_batches": 1, "interval_s": 0}},
            "processors": {"probabilisticsampler": {
                "sampling_percentage": 25.0}},
            "exporters": {"debug": {}},
            "service": {"pipelines": {"traces/sampled": {
                "receivers": ["synthetic"],
                "processors": ["probabilisticsampler"],
                "exporters": ["debug"]}}},
        }
        with Collector(cfg) as col:
            col.drain_receivers()
            entry = col.graph.pipeline_entries["traces/sampled"]
            for i in range(4):
                entry.consume(synthesize_traces(50, seed=20 + i))
            balances = assert_balanced()
            b = balances["traces/sampled"]
            assert b["dropped"].get("sampled", 0) > 0
            assert b["items_in"] == b["items_out"] \
                + sum(b["dropped"].values())
