"""Full-stack e2e scenarios (the chainsaw suite analog, SURVEY.md §4 item 2):
trace-collection, data-streams, instrumentation-rollback, chaos/backpressure
against the in-process KinD-analog environment."""

import time

import numpy as np
import pytest

from odigos_tpu.components.api import Signal
from odigos_tpu.controlplane import Container, PodPhase
from odigos_tpu.controlplane.instrumentor import ic_name
from odigos_tpu.destinations import Destination
from odigos_tpu.e2e import (
    E2EEnvironment,
    Scenario,
    Step,
    inject_exporter_chaos,
    inject_memory_pressure,
)
from odigos_tpu.pdata import synthesize_traces

T = Signal.TRACES


def tracedb_dest(id="db1", streams=()):
    return Destination(id=id, dest_type="tracedb", signals=[T],
                       data_stream_names=list(streams))


class TestTraceCollection:
    """tests/e2e/trace-collection: deploy db -> app -> instrument ->
    traffic -> query spans."""

    def test_spans_flow_to_destination(self):
        with E2EEnvironment(nodes=2) as env:
            scenario = Scenario("trace-collection", [
                Step("add tracedb destination",
                     apply=lambda e: e.add_destination(tracedb_dest())),
                Step("deploy + instrument app",
                     apply=lambda e: (
                         e.cluster.add_workload("default", "checkout", [
                             Container(name="main", language="python",
                                       runtime_version="3.11")]),
                         e.instrument_workload("default", "checkout"))),
                Step("agent enabled",
                     assert_fn=lambda e: any(
                         c.agent_enabled for ic in e.store.list(
                             "InstrumentationConfig")
                         for c in ic.containers)),
                Step("traffic over the wire",
                     script=lambda e: e.send_traces_wire(
                         synthesize_traces(50, seed=1))),
                Step("spans stored",
                     assert_fn=lambda e: _db(e).span_count > 0),
                Step("whole trace present",
                     assert_fn=lambda e: _db(e).wait_for_trace(
                         "frontend", min_spans=5, timeout=1) is not None),
            ])
            results = scenario.run(env)
            assert all(r.ok for r in results)


def _db(env, id="db1"):
    return env.gateway_component(f"tracedb/tracedb-{id}")


class TestDataStreams:
    """tests/e2e/data-streams: two destinations on different streams; spans
    route by source stream membership (golden assertion on the generated
    config + live routing)."""

    def test_streams_route_separately(self):
        with E2EEnvironment(nodes=1) as env:
            env.add_destination(tracedb_dest("dbA", streams=["stream-a"]))
            env.add_destination(tracedb_dest("dbB", streams=["stream-b"]))
            env.cluster.add_workload("default", "svc-a", [
                Container(name="main", language="python",
                          runtime_version="3.11")])
            env.instrument_workload("default", "svc-a",
                                    data_streams=["stream-a"])
            # golden config shape: router + one pipeline per stream
            cm = env.store.get("ConfigMap", "odigos-system",
                               "odigos-gateway-config")
            cfg = cm.data["collector-conf"]
            pipes = cfg["service"]["pipelines"]
            assert any("stream-a" in p for p in pipes), pipes.keys()
            assert any("stream-b" in p for p in pipes), pipes.keys()
            # live routing: traffic from svc-a's workload lands in dbA only
            batch = synthesize_traces(30, seed=3)
            from dataclasses import replace
            routed = replace(
                batch,
                resources=tuple({**dict(r),
                                 "k8s.deployment.name": "svc-a",
                                 "k8s.namespace.name": "default"}
                                for r in batch.resources))
            assert env.send_traces_wire(routed)
            assert _db(env, "dbA").wait_for_spans(1, timeout=5)
            assert _db(env, "dbB").span_count == 0


class TestInstrumentationRollback:
    """tests/e2e/instrumentation-rollback: instrumented pods crash-looping
    -> automatic rollback with reason."""

    def test_crashloop_triggers_rollback(self):
        with E2EEnvironment(nodes=1) as env:
            w = env.cluster.add_workload("default", "flaky", [
                Container(name="main", language="python",
                          runtime_version="3.11")])
            # next rollout of this workload enters CrashLoopBackOff
            env.cluster.fail_next_rollout(w.ref)
            env.instrument_workload("default", "flaky")
            env.reconcile(rounds=6)
            ic = env.store.get("InstrumentationConfig", "default",
                               ic_name_for("flaky"))
            assert ic is not None
            cond = ic.condition("AgentEnabled")
            assert cond is not None and cond.reason == "CrashLoopBackOff", \
                (cond.reason if cond else None)
            # rolled back: no agents, pods healthy again
            assert all(not c.agent_enabled for c in ic.containers)
            assert all(p.phase == PodPhase.RUNNING
                       for p in env.cluster.pods.values())


def ic_name_for(name, ns="default"):
    from odigos_tpu.api.resources import WorkloadKind, WorkloadRef
    return ic_name(WorkloadRef(ns, WorkloadKind.DEPLOYMENT, name))


class TestChaos:
    """Chaos: destination latency + rejection; pipeline keeps flowing and
    rejection metrics surface (backpressure-exporter.yaml analog)."""

    def test_rejecting_destination_does_not_stall_others(self):
        with E2EEnvironment(nodes=1) as env:
            env.add_destination(tracedb_dest("good"))
            env.add_destination(Destination(
                id="bad", dest_type="mock", signals=[T],
                config={"MOCK_REJECT_FRACTION": "0", "MOCK_RESPONSE_DURATION": "0"}))
            assert env.send_traces_wire(synthesize_traces(10, seed=0))
            assert _db(env, "good").wait_for_spans(1, timeout=5)
            before = _db(env, "good").span_count
            # chaos: the mock destination starts rejecting everything
            inject_exporter_chaos(env, "mockdestination/bad",
                                  reject_fraction=1.0)
            assert env.send_traces_wire(synthesize_traces(10, seed=1))
            assert _db(env, "good").wait_for_spans(before + 1, timeout=5)
            mock = env.gateway_component("mockdestination/bad")
            # bad's batch processor flushes on its own clock — the good
            # destination landing first says nothing about bad's tick yet
            deadline = time.monotonic() + 5
            while mock.rejected_batches == 0 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert mock.rejected_batches > 0

    def test_backpressure_rejection_drives_scale_up(self):
        """The full backpressure loop over the real wire (VERDICT r2 item 4;
        reference: configgrpc fork -> odigos_gateway_memory_limiter_
        rejections_total -> hpa.go custom metric): chaos memory pressure ->
        pre-decode REJECTED at the otlp front door -> rejection metric ->
        HpaDecider scales the gateway up -> pressure lifted -> the held
        frame is retried and delivered."""
        from odigos_tpu.utils.telemetry import meter
        from odigos_tpu.wire.server import REJECTIONS_METRIC

        with E2EEnvironment(nodes=1) as env:
            env.add_destination(tracedb_dest())
            assert env.send_traces_wire(synthesize_traces(10, seed=0))
            assert _db(env).wait_for_spans(1, timeout=5)
            stored = _db(env).span_count

            rejects0 = meter.counter(REJECTIONS_METRIC)
            inject_memory_pressure(env, on=True)
            # the frame is rejected pre-decode: not delivered, kept queued
            assert not env.send_traces_wire(synthesize_traces(10, seed=1),
                                            timeout=1.0)
            rejections = meter.counter(REJECTIONS_METRIC) - rejects0
            assert rejections > 0, "no pre-decode rejection recorded"
            assert _db(env).span_count == stored

            # the rejection metric is the HPA's scale-up signal
            assert env.autoscaler.gateway_replicas == 1
            n = env.autoscaler.observe_metrics(
                10.0, 10.0, rejections_per_pod=rejections, now=1000.0)
            assert n == 3, "rejections must trigger aggressive +2 scale-up"

            # pressure lifts; the exporter's retry delivers the held frame
            inject_memory_pressure(env, on=False)
            assert env._wire_tap.flush(timeout=10)
            assert _db(env).wait_for_spans(stored + 1, timeout=10)

    def test_config_change_hot_reloads_gateway(self):
        with E2EEnvironment(nodes=1) as env:
            env.add_destination(tracedb_dest("db1"))
            assert env.send_traces_wire(synthesize_traces(5, seed=0))
            assert _db(env, "db1").wait_for_spans(1, timeout=5)
            # adding a second destination regenerates the config; the
            # gateway hot-reloads and serves both
            env.add_destination(tracedb_dest("db2"))
            assert env.send_traces_wire(synthesize_traces(5, seed=1))
            assert _db(env, "db2").wait_for_spans(1, timeout=5)
