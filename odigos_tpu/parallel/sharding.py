"""Sharding rules + sharded score/train step factories.

Megatron-style layout for the trace transformer (odigos_tpu.models), expressed
as PartitionSpecs over the mesh from parallel.mesh:

* attention q/k/v kernels (d_model, n_heads, head_dim): heads on "model"
* attention out kernel (n_heads, head_dim, d_model): heads on "model"
* mlp up kernel (d_model, d_ff): d_ff on "model"; down kernel transposed
* embedding tables + layernorms + heads: replicated
* batch (trace) axis of inputs: "data"

XLA inserts the all-reduces (psum over "model" after attention-out and
mlp-down) — we only annotate placements, per the scaling-book recipe cited in
the build brief.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# see models/transformer.py: every jitted scoring/training entry point
# declares its recompile-bounding strategy (package hygiene test)
SHAPE_BUCKETING = {
    "make_sharded_score_fn": "delegates to model.score_spans — leading axis "
                             "padded to a data-axis multiple by "
                             "_shard_inputs on top of the engine bucketing",
    "make_sharded_packed_score_fn": "delegates to model.score_packed — row "
                                    "axis bucketed by the engine's ladder "
                                    "(multiples of data_parallel enforced)",
    "make_sharded_train_step": "training loop feeds fixed (batch, L) "
                               "shapes from data.py batching; one compile "
                               "per run",
}


def transformer_param_spec(path: tuple, leaf: Any) -> P:
    """Map a flax param path (tuple of str keys) to a PartitionSpec."""
    names = [str(p) for p in path]
    joined = "/".join(names)
    ndim = getattr(leaf, "ndim", 0)
    if "attention" in joined or any(n in ("query", "key", "value", "out")
                                    for n in names):
        if any(n in ("query", "key", "value") for n in names) and ndim == 3:
            return P(None, "model", None)  # (d_model, heads, head_dim)
        if "out" in names and ndim == 3:
            return P("model", None, None)  # (heads, head_dim, d_model)
    # transformer mlp: first Dense grows to d_ff (shard cols), second
    # shrinks. Size gate keeps tiny matmuls (span/trace heads, embedder
    # projections) replicated — sharding them only buys per-call collectives.
    if ndim == 2 and names[-1] == "kernel":
        in_dim, out_dim = leaf.shape
        if min(in_dim, out_dim) >= 64:
            if out_dim > in_dim:
                return P(None, "model")
            if in_dim > out_dim:
                return P("model", None)
    return P()  # replicate embeddings, norms, biases, heads


def shard_variables(variables: Any, mesh: Mesh,
                    spec_fn: Callable[[tuple, Any], P] = transformer_param_spec,
                    ) -> Any:
    """Place a variable pytree onto the mesh per spec_fn."""
    def place(path, leaf):
        spec = spec_fn(tuple(k.key for k in path), leaf)
        # axes must exist in this mesh and divide the dim; fall back to
        # replication when they don't (a pure-"data" DP mesh replicates
        # every "model"-sharded param)
        for axis_name, dim in zip(spec, getattr(leaf, "shape", ())):
            if axis_name is None:
                continue
            if (axis_name not in mesh.shape
                    or dim % mesh.shape[axis_name] != 0):
                spec = P()
                break
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(place, variables)


def batch_spec(mesh: Mesh) -> P:
    return P("data")


def _shard_inputs(mesh: Mesh, arrays: tuple) -> tuple:
    """Place batch-leading arrays on the data axis, padding the leading dim
    up to a multiple of the data-axis size (mask rows stay False)."""
    dp = mesh.shape["data"]
    sharded = []
    for a in arrays:
        n = a.shape[0]
        pad = (-n) % dp
        if pad:
            widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            a = np.pad(np.asarray(a), widths)
        sharded.append(jax.device_put(
            a, NamedSharding(mesh, P("data", *([None] * (a.ndim - 1))))))
    return tuple(sharded)


def make_sharded_score_fn(model, mesh: Mesh):
    """Data/tensor-parallel scoring: variables pre-sharded per the rules,
    inputs split on "data". Returns fn(variables, cat, cont, mask) ->
    (span_scores, trace_scores) gathered to host-replicated arrays."""

    def score(variables, cat, cont, mask):
        n = np.asarray(mask).shape[0]
        cat, cont, mask = _shard_inputs(mesh, (cat, cont, mask))
        # model.score_spans is jitted; XLA propagates the dp/tp shardings
        # from argument placements and inserts the collectives
        span_p, trace_p = model.score_spans(variables, cat, cont, mask)
        return np.asarray(span_p)[:n], np.asarray(trace_p)[:n]

    return score


def make_sharded_train_step(model, tx, mesh: Mesh):
    """Full sharded train step (used by __graft_entry__.dryrun_multichip and
    train.loop): grads computed under dp(batch) x tp(params) sharding; optax
    update applied in the same placement; loss replicated.
    """

    @jax.jit
    def step(variables, opt_state, cat, cont, mask, span_labels, trace_labels):
        loss, grads = jax.value_and_grad(model.loss_fn)(
            variables, cat, cont, mask, span_labels, trace_labels)
        updates, opt_state = tx.update(grads, opt_state, params=variables)
        import optax

        variables = optax.apply_updates(variables, updates)
        return variables, opt_state, loss

    def run(variables, opt_state, cat, cont, mask, span_labels, trace_labels):
        cat, cont, mask, span_labels, trace_labels = _shard_inputs(
            mesh, (cat, cont, mask, span_labels, trace_labels))
        return step(variables, opt_state, cat, cont, mask, span_labels,
                    trace_labels)

    return run


def make_sharded_packed_score_fn(model, mesh: Mesh, block: bool = True):
    """Data-parallel **packed** scoring (BASELINE config #5: DP across
    v5e-8) — the serving path's flagship shape. Packed rows shard on
    "data"; variables placed per the transformer rules (pure-DP meshes
    replicate them; a "model" axis shards heads/ffn too). XLA inserts the
    collectives from the placements.

    ``block=False`` returns the (R, L) device array without the host
    fetch: the pipelined engine harvests it against the *next* in-flight
    call so the transfer overlaps device execution. R is unpadded (the
    divisibility check guarantees it), so no trailing-slice is needed.
    """
    dp = mesh.shape["data"]
    # cache the sharded placement of the last-seen pytree. Keyed by id()
    # ALONE this is unsound — a GC'd pytree's address can be reused and
    # serve stale weights — so the cache holds a strong ref to the source
    # pytree and revalidates by identity against it.
    cache: dict[str, Any] = {"source": None, "sharded": None}

    def score(variables, cat, cont, segments, positions) -> np.ndarray:
        if cache["source"] is not variables:
            cache["source"] = variables
            cache["sharded"] = shard_variables(variables, mesh)
        v = cache["sharded"]
        R = np.asarray(segments).shape[0]
        if R % dp:
            raise ValueError(
                f"packed rows {R} not divisible by data axis {dp}; "
                f"choose trace_bucket as a multiple of data_parallel")
        cat, cont, segments, positions = _shard_inputs(
            mesh, (cat, cont, segments, positions))
        span_p = model.score_packed(v, cat, cont, segments, positions)
        if not block:
            return span_p
        return np.asarray(span_p)[:R]

    return score
