#!/bin/sh
# reference: collector/distribution/odigos-otelcol/preinstall.sh
getent passwd odigos >/dev/null || useradd --system --user-group --no-create-home --shell /sbin/nologin odigos
