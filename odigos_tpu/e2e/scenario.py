"""Chainsaw-style scenario runner.

A scenario is an ordered list of steps, each an apply / assert / script
(tests/e2e/trace-collection/chainsaw-test.yaml:1-40 shape). ``assert``
steps poll a predicate with a timeout — the level-triggered analog of
chainsaw's assert resources.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from .environment import E2EEnvironment

ApplyFn = Callable[[E2EEnvironment], None]
AssertFn = Callable[[E2EEnvironment], bool]


@dataclass
class Step:
    name: str
    apply: Optional[ApplyFn] = None
    assert_fn: Optional[AssertFn] = None
    script: Optional[ApplyFn] = None
    timeout_s: float = 10.0


@dataclass
class StepResult:
    step: str
    ok: bool
    elapsed_s: float
    error: str = ""


@dataclass
class Scenario:
    name: str
    steps: list[Step] = field(default_factory=list)

    def run(self, env: E2EEnvironment) -> list[StepResult]:
        """Run all steps; stops at the first failure (chainsaw semantics).
        Raises AssertionError with the failing step's name."""
        results: list[StepResult] = []
        for step in self.steps:
            t0 = time.monotonic()
            error = ""
            ok = True
            try:
                if step.apply is not None:
                    step.apply(env)
                    env.reconcile()
                if step.script is not None:
                    step.script(env)
                if step.assert_fn is not None:
                    ok = self._poll(env, step)
                    if not ok:
                        error = "assert timed out"
            except Exception as e:  # surfaced with step context below
                ok, error = False, f"{type(e).__name__}: {e}"
            results.append(StepResult(step.name, ok,
                                      time.monotonic() - t0, error))
            if not ok:
                raise AssertionError(
                    f"scenario {self.name!r} failed at step {step.name!r}: "
                    f"{error}\ncompleted: {[r.step for r in results if r.ok]}")
        return results

    @staticmethod
    def _poll(env: E2EEnvironment, step: Step) -> bool:
        deadline = time.monotonic() + step.timeout_s
        while time.monotonic() < deadline:
            env.reconcile(rounds=1)
            if step.assert_fn(env):
                return True
            time.sleep(0.02)
        return False
