"""Out-of-process TPU scoring sidecar over a unix domain socket.

The process boundary the north star requires (SURVEY.md §3.3: "processor →
gRPC/local → JAX sidecar on TPU"): the collector keeps its latency budget
and pass-through discipline while the JAX/TPU runtime lives in a separate
process — the same discipline as the reference's odiglet↔collector unix
socket (common/unixfd/server.go:26), minus FD passing because feature
tensors, not eBPF maps, cross the boundary.

Wire protocol (little-endian), framed like wire/codec.py:

    frame   := magic "OTS1" | u32 payload_len | payload
    payload := u32 req_id | u8 op | body
    ops     : SCORE  (body = wire.codec.encode_batch)   → scores response
              WARMUP (body = wire.codec.encode_batch)   → empty response
              PING   (empty body)                       → empty response
    reply   := u32 req_id | u8 status (0 ok / 1 error) | body
               SCORE body = raw float32[n] scores; error body = utf-8 message

Client side: ``RemoteBackend`` plugs into the ScoringEngine as the
``"remote"`` model, so the engine's queue admission, coalescing, and
score_sync timeout all still apply — the sidecar round-trip happens on the
engine worker thread, and a missed deadline passes spans through unscored
exactly as with a local backend. Server side: ``SidecarServer`` wraps a real
ScoringEngine (zscore/transformer/autoencoder/mock) so cross-connection
coalescing feeds the MXU big batches.

Run standalone:  python -m odigos_tpu.serving.sidecar --socket /tmp/score.sock \
                     --model transformer --checkpoint <bundle>
"""

from __future__ import annotations

import os
import socket
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from ..pdata.spans import SpanBatch
from ..utils.framing import (
    ConnRegistry, connect_unix_retry, recv_frame, send_frame, shutdown_close)
from ..utils.telemetry import meter
from ..wire.codec import decode_batch, encode_batch

MAGIC = b"OTS1"
MAX_FRAME = 256 << 20  # span batches are big; beyond this is corruption
_REQ = struct.Struct("<IB")  # req_id, op/status

OP_SCORE = 0
OP_WARMUP = 1
OP_PING = 2

ST_OK = 0
ST_ERROR = 1

REMOTE_ERRORS_METRIC = "odigos_sidecar_client_errors_total"
SERVED_METRIC = "odigos_sidecar_served_requests_total"
OVERLOAD_METRIC = "odigos_sidecar_overload_rejections_total"


# ----------------------------------------------------------------- framing

def _send_frame(sock: socket.socket, req_id: int, op: int,
                body: bytes = b"") -> None:
    send_frame(sock, MAGIC, _REQ.pack(req_id, op) + body)


def _recv_frame(sock: socket.socket) -> Optional[tuple[int, int, bytes]]:
    payload = recv_frame(sock, MAGIC, MAX_FRAME)
    if payload is None:
        return None
    if len(payload) < _REQ.size:
        # struct.error would escape the readers' (OSError, ValueError) nets
        # and kill the thread without its cleanup path
        raise ValueError(f"sidecar frame too short: {len(payload)}")
    req_id, op = _REQ.unpack_from(payload, 0)
    return req_id, op, payload[_REQ.size:]


# ------------------------------------------------------------------ server

class SidecarServer:
    """Serves Score() for one ScoringEngine over a unix socket.

    One accept loop, one reader thread per connection, one handler thread
    per in-flight request (requests block on the shared engine, which
    coalesces them into large device calls).
    """

    def __init__(self, engine, socket_path: str,
                 score_timeout_s: float = 5.0, max_inflight: int = 64):
        self.engine = engine
        self.socket_path = socket_path
        self.score_timeout_s = score_timeout_s
        # admission control at the accept boundary: without a cap, a slow
        # engine at north-star rates turns thread-per-request into a thread
        # bomb (same posture as the engine's bounded queue)
        self._inflight = threading.Semaphore(max_inflight)
        self._sock: Optional[socket.socket] = None
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._conns = ConnRegistry()

    def start(self) -> "SidecarServer":
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.socket_path)
        self._sock.listen(16)
        self.engine.start()
        t = threading.Thread(target=self._accept_loop, name="sidecar-accept",
                             daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def serve_forever(self) -> None:
        self.start()
        try:
            self._stop.wait()
        finally:
            self.shutdown()

    def shutdown(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        # close accepted connections too, or same-process clients blocked in
        # recv never see EOF (their FIN only comes at process exit)
        self._conns.close_all()
        if os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
        self.engine.shutdown()

    # ------------------------------------------------------------ internals

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="sidecar-conn", daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        wlock = threading.Lock()  # replies from handler threads interleave
        self._conns.add(conn)
        try:
            while not self._stop.is_set():
                got = _recv_frame(conn)
                if got is None:
                    return
                req_id, op, body = got
                if not self._inflight.acquire(blocking=False):
                    meter.add(OVERLOAD_METRIC)
                    try:
                        with wlock:
                            _send_frame(conn, req_id, ST_ERROR,
                                        b"sidecar overloaded")
                    except OSError:
                        return
                    continue
                threading.Thread(
                    target=self._handle, name="sidecar-req", daemon=True,
                    args=(conn, wlock, req_id, op, body)).start()
        except (OSError, ValueError):
            return
        finally:
            self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, conn, wlock, req_id: int, op: int, body: bytes) -> None:
        try:
            self._handle_inner(conn, wlock, req_id, op, body)
        finally:
            self._inflight.release()

    def _handle_inner(self, conn, wlock, req_id: int, op: int,
                      body: bytes) -> None:
        try:
            if op == OP_PING:
                reply = (ST_OK, b"")
            elif op == OP_WARMUP:
                self.engine.warmup(decode_batch(body))
                reply = (ST_OK, b"")
            elif op == OP_SCORE:
                batch = decode_batch(body)
                scores = self.engine.score_sync(
                    batch, timeout_s=self.score_timeout_s)
                if scores is None:
                    reply = (ST_ERROR, b"scoring timed out in sidecar")
                else:
                    reply = (ST_OK,
                             np.ascontiguousarray(scores, np.float32)
                             .tobytes())
            else:
                reply = (ST_ERROR, f"unknown op {op}".encode())
            meter.add(SERVED_METRIC)
        except Exception as e:  # noqa: BLE001 — report, don't kill the conn
            reply = (ST_ERROR, str(e).encode())
        status, rbody = reply
        try:
            with wlock:
                _send_frame(conn, req_id, status, rbody)
        except OSError:
            pass


# ------------------------------------------------------------------ client

class SidecarClient:
    """Thread-safe request/response client with a reader thread."""

    def __init__(self, socket_path: str, connect_timeout_s: float = 5.0):
        self.socket_path = socket_path
        self.connect_timeout_s = connect_timeout_s
        self._sock: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        self._clock = threading.Lock()  # serializes lazy connect()
        self._pending: dict[int, dict[str, Any]] = {}
        self._plock = threading.Lock()
        self._next_id = 0
        self._reader: Optional[threading.Thread] = None

    # one waiter record per in-flight request
    def _new_waiter(self) -> tuple[int, dict[str, Any]]:
        with self._plock:
            self._next_id = (self._next_id + 1) & 0xFFFFFFFF
            rec = {"event": threading.Event(), "status": None, "body": None}
            self._pending[self._next_id] = rec
            return self._next_id, rec

    def connect(self) -> None:
        with self._clock:  # concurrent first requests connect exactly once
            if self._sock is not None:
                return
            s = connect_unix_retry(self.socket_path, self.connect_timeout_s)
            self._sock = s
            self._reader = threading.Thread(
                target=self._read_loop, args=(s,),
                name="sidecar-client-reader", daemon=True)
            self._reader.start()

    def close(self) -> None:
        with self._clock:
            sock, self._sock = self._sock, None
        if sock is not None:
            shutdown_close(sock)  # reader blocks in recv; see framing.py

    def _read_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                got = _recv_frame(sock)
                if got is None:
                    break
                req_id, status, body = got
                with self._plock:
                    rec = self._pending.pop(req_id, None)
                if rec is not None:
                    rec["status"], rec["body"] = status, body
                    rec["event"].set()
        except (OSError, ValueError):
            pass
        # connection died: drop the dead socket first so the next request()
        # reconnects immediately instead of sending into it and burning the
        # full timeout, then fail everything in flight
        with self._clock:
            if self._sock is sock:
                self._sock = None
        try:
            sock.close()
        except OSError:
            pass
        with self._plock:
            pending, self._pending = self._pending, {}
        for rec in pending.values():
            rec["status"], rec["body"] = ST_ERROR, b"connection lost"
            rec["event"].set()

    def request(self, op: int, body: bytes = b"",
                timeout_s: float = 30.0) -> bytes:
        if self._sock is None:
            self.connect()
        # snapshot under the connect lock: the reader thread clears
        # self._sock asynchronously on connection loss, and sending into a
        # None must surface as ConnectionError, not AttributeError
        with self._clock:
            sock = self._sock
        if sock is None:
            raise ConnectionError("sidecar connection lost")
        req_id, rec = self._new_waiter()
        try:
            with self._wlock:
                _send_frame(sock, req_id, op, body)
        except OSError as e:
            with self._plock:
                self._pending.pop(req_id, None)
            self.close()
            raise ConnectionError(f"sidecar send failed: {e}") from e
        if not rec["event"].wait(timeout_s):
            with self._plock:
                self._pending.pop(req_id, None)
            raise TimeoutError("sidecar response timed out")
        if rec["status"] != ST_OK:
            raise RuntimeError(
                f"sidecar error: {rec['body'].decode(errors='replace')}")
        return rec["body"]

    def ping(self, timeout_s: float = 5.0) -> None:
        self.request(OP_PING, timeout_s=timeout_s)

    def score(self, batch: SpanBatch, timeout_s: float = 30.0) -> np.ndarray:
        body = self.request(OP_SCORE, encode_batch(batch), timeout_s)
        return np.frombuffer(body, np.float32).copy()

    def warmup(self, batch: SpanBatch, timeout_s: float = 120.0) -> None:
        self.request(OP_WARMUP, encode_batch(batch), timeout_s)


class RemoteBackend:
    """ScoringEngine backend that scores via a sidecar process.

    Registered as model ``"remote"``: the engine keeps its local queue
    admission + coalescing + deadline; only the device call crosses the
    process boundary. Errors surface as engine errors → pass-through.
    """

    # the sidecar featurizes server-side; the client engine must not
    # featurize too (double host cost on the latency budget)
    needs_features = False
    # no async dispatch: the socket round trip carries its own deadline
    # (remote_timeout_s) and overlapping calls here would reorder the
    # sidecar's cross-connection coalescing — the client engine runs this
    # backend at pipeline depth 1 and the SERVER engine (which owns the
    # device) does the double buffering where it pays off

    def __init__(self, cfg):
        if not cfg.socket_path:
            raise ValueError("model 'remote' requires socket_path")
        self.cfg = cfg
        self.client = SidecarClient(cfg.socket_path)

    def score(self, batch: SpanBatch, features) -> np.ndarray:
        try:
            # the config deadline bounds how long a stalled (not dead)
            # sidecar can pin the engine worker thread
            scores = self.client.score(
                batch, timeout_s=self.cfg.remote_timeout_s)
        except (ConnectionError, TimeoutError, RuntimeError):
            meter.add(REMOTE_ERRORS_METRIC)
            raise
        if len(scores) != len(batch):
            raise RuntimeError(
                f"sidecar returned {len(scores)} scores for "
                f"{len(batch)} spans")
        return scores

    def warmup(self, batch: SpanBatch) -> None:
        self.client.warmup(batch)


# -------------------------------------------------------------- standalone

def main(argv: Optional[list[str]] = None) -> None:
    import argparse

    from .engine import EngineConfig, ScoringEngine

    ap = argparse.ArgumentParser(
        description="odigos-tpu scoring sidecar (unix-socket Score server)")
    ap.add_argument("--socket", required=True)
    ap.add_argument("--model", default="zscore",
                    choices=["zscore", "transformer", "autoencoder", "mock"])
    ap.add_argument("--checkpoint", default=None,
                    help="serving bundle from Trainer.export()")
    ap.add_argument("--max-len", type=int, default=64)
    ap.add_argument("--trace-bucket", type=int, default=256)
    ap.add_argument("--timeout-ms", type=float, default=5000.0,
                    help="server-side scoring deadline")
    ap.add_argument("--pipeline-depth", type=int, default=2,
                    help="in-flight device calls (sequence models; "
                         "1 = serial)")
    ap.add_argument("--bucket-ladder", type=int, default=4,
                    help="geometric row-shape buckets above --trace-bucket")
    ap.add_argument("--warm-ladder", action="store_true",
                    help="compile every ladder bucket before serving "
                         "(slower start, zero steady-state recompiles)")
    args = ap.parse_args(argv)

    engine = ScoringEngine(EngineConfig(
        model=args.model, checkpoint_path=args.checkpoint,
        max_len=args.max_len, trace_bucket=args.trace_bucket,
        pipeline_depth=args.pipeline_depth,
        bucket_ladder=args.bucket_ladder, warm_ladder=args.warm_ladder))
    server = SidecarServer(engine, args.socket,
                           score_timeout_s=args.timeout_ms / 1000.0)
    print(f"sidecar: model={args.model} socket={args.socket}", flush=True)
    server.serve_forever()


if __name__ == "__main__":
    main()
