"""Feature gates keyed on platform-version maturity.

Equivalent of k8sutils/pkg/feature (feature.go:22-48): each gate records the
platform version at which it reached alpha/beta/GA; callers ask "is this
enabled on the connected cluster/runtime version". Defaults mirror the
reference's posture: beta and GA are on by default, alpha is opt-in.

Our "platform" is the runtime pair (k8s-style control plane version for the
deployment features, jax version for the TPU-path features); gates carry
which axis they key on.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

Version = tuple[int, int]


def parse_version(v: str) -> Optional[Version]:
    parts = v.lstrip("v").split(".")
    try:
        return int(parts[0]), int(parts[1]) if len(parts) > 1 else 0
    except (ValueError, IndexError):
        return None


@dataclass(frozen=True)
class Gate:
    name: str
    axis: str  # "k8s" | "jax"
    alpha: Optional[Version] = None
    beta: Optional[Version] = None
    ga: Optional[Version] = None

    def stage(self, version: Version) -> Optional[str]:
        if self.ga is not None and version >= self.ga:
            return "ga"
        if self.beta is not None and version >= self.beta:
            return "beta"
        if self.alpha is not None and version >= self.alpha:
            return "alpha"
        return None


DEFAULT_GATES: tuple[Gate, ...] = (
    # deployment-side (mirror the reference's k8s-maturity-keyed gates)
    Gate("native-sidecar-containers", "k8s",
         alpha=(1, 28), beta=(1, 29), ga=(1, 33)),
    Gate("pod-level-resources", "k8s", alpha=(1, 32), beta=(1, 34)),
    Gate("in-place-pod-resize", "k8s", alpha=(1, 27), beta=(1, 33)),
    # TPU-path features keyed on jax maturity
    Gate("shard-map-scoring", "jax", beta=(0, 4), ga=(0, 5)),
    Gate("pallas-featurizer-kernels", "jax", alpha=(0, 4), beta=(0, 6)),
    Gate("ring-attention-sp", "jax", beta=(0, 4)),
)


class Features:
    """Resolved gate set for concrete platform versions."""

    def __init__(self, k8s_version: str = "1.30", jax_version: str = "0.5",
                 gates: tuple[Gate, ...] = DEFAULT_GATES,
                 enable_alpha: bool = False):
        self._versions = {"k8s": parse_version(k8s_version) or (0, 0),
                          "jax": parse_version(jax_version) or (0, 0)}
        self._gates = {g.name: g for g in gates}
        self.enable_alpha = enable_alpha

    def stage(self, name: str) -> Optional[str]:
        gate = self._gates.get(name)
        if gate is None:
            return None
        return gate.stage(self._versions[gate.axis])

    def enabled(self, name: str) -> bool:
        stage = self.stage(name)
        if stage is None:
            return False
        return stage in ("beta", "ga") or (stage == "alpha"
                                           and self.enable_alpha)

    def snapshot(self) -> dict[str, dict]:
        return {name: {"stage": self.stage(name),
                       "enabled": self.enabled(name)}
                for name in sorted(self._gates)}
