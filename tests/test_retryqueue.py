"""Export retry/spill queue tests (ISSUE 13).

Contracts pinned:

* direct pass-through while healthy (one lock acquisition of overhead);
* a failing export SPILLS instead of raising, the retry thread replays
  FIFO with jittered exponential backoff, and recovery delivers every
  batch in the original order;
* the spill bound is enforced in spans and the overflow is a NAMED
  ``queue_full`` drop; a shutdown that cannot flush sheds leftovers as
  named ``shutdown_drain`` — sent == delivered + dropped exactly;
* queue depth publishes as the ``retry/<exporter>:pending_spans``
  admission watermark and the ``odigos_export_retry_queue_spans``
  gauge;
* ``health()`` round-trips Degraded(ExportRetrying) → Healthy;
* graph wiring: a ``retry:`` stanza wraps the exporter at build, typo'd
  stanzas die in validate_config, pipelinegen stamps the stanza from
  ``collector_gateway.export_retry``;
* jitter draws are seedable (the --chaos-seed determinism contract).
"""

from __future__ import annotations

import threading
import time

import pytest

from odigos_tpu.components.exporters.retryqueue import (
    DEFAULTS,
    RetryQueue,
    validate_retry_config,
)
from odigos_tpu.pdata import synthesize_traces
from odigos_tpu.pipeline.graph import build_graph, validate_config
from odigos_tpu.selftelemetry.flow import flow_ledger
from odigos_tpu.utils.telemetry import meter


@pytest.fixture(autouse=True)
def fresh_ledger():
    flow_ledger.reset()
    yield
    flow_ledger.reset()


class FlakyExporter:
    """Test double: fails while ``down`` is set, records delivery order."""

    def __init__(self, name="tracedb/dest"):
        self.name = name
        self.config = {}
        self.down = False
        self.batches = []
        self.started = False
        self.stopped = False
        self._lock = threading.Lock()

    def consume(self, batch):
        with self._lock:
            if self.down:
                raise RuntimeError("destination down")
            self.batches.append(batch)

    def start(self):
        self.started = True

    def shutdown(self):
        self.stopped = True

    def healthy(self):
        return True

    def health(self):
        return ("Healthy", "Running", "")

    @property
    def span_count(self):
        with self._lock:
            return sum(len(b) for b in self.batches)


def make_rq(inner=None, **spec) -> tuple[RetryQueue, FlakyExporter]:
    inner = inner or FlakyExporter()
    cfg = dict({"initial_backoff_ms": 5, "max_backoff_ms": 20,
                "seed": 0}, **spec)
    rq = RetryQueue(inner, cfg)
    rq.start()
    return rq, inner


def batches(n, spans=4):
    return [synthesize_traces(spans, seed=s) for s in range(n)]


class TestRetryDelivery:
    def test_direct_path_while_healthy(self):
        rq, inner = make_rq()
        try:
            b = synthesize_traces(3, seed=0)
            rq.consume(b)
            assert inner.span_count == len(b)
            assert rq.pending_spans() == 0
            assert rq.stats()["spilled_spans"] == 0
        finally:
            rq.shutdown()

    def test_spill_and_fifo_redelivery(self):
        rq, inner = make_rq()
        try:
            inner.down = True
            sent = batches(4, spans=2)
            for b in sent:
                rq.consume(b)
            assert rq.pending_spans() == sum(len(b) for b in sent)
            assert rq.health()[0:2] == ("Degraded", "ExportRetrying")
            time.sleep(0.1)  # let the retry thread fail at least once
            inner.down = False
            assert rq.flush(timeout=10.0)
            # FIFO: the destination sees the original byte order
            assert [id(b) for b in inner.batches] \
                == [id(b) for b in sent]
            assert rq.health()[0] == "Healthy"
            st = rq.stats()
            assert st["delivered_spans"] == sum(len(b) for b in sent)
            assert st["dropped_spans"] == 0
            assert st["retries"] > 0
        finally:
            rq.shutdown()

    def test_arrivals_behind_nonempty_queue_keep_order(self):
        rq, inner = make_rq()
        try:
            inner.down = True
            first = synthesize_traces(2, seed=0)
            rq.consume(first)
            inner.down = False
            # destination is healthy again, but the queue is non-empty:
            # a new arrival must enqueue BEHIND the head, not overtake
            second = synthesize_traces(2, seed=1)
            rq.consume(second)
            assert rq.flush(timeout=10.0)
            assert [id(b) for b in inner.batches] == [id(first),
                                                      id(second)]
        finally:
            rq.shutdown()


class TestNamedTerminalDrops:
    def test_overflow_named_queue_full(self):
        rq, inner = make_rq(max_queue_spans=10)
        try:
            inner.down = True
            sent = batches(5, spans=4)  # 20 spans into a 10-span bound
            for b in sent:
                rq.consume(b)
            st = rq.stats()
            assert st["dropped_spans"] > 0
            assert st["pending_spans"] <= 10
            drops = {
                (d["component"], r): n
                for d in flow_ledger.snapshot()["drops"]
                for r, n in d["reasons"].items()}
            assert drops.get(("retry/tracedb/dest", "queue_full")) \
                == st["dropped_spans"]
            # the export ledger closes: sent == pending + dropped
            assert st["pending_spans"] + st["dropped_spans"] \
                == sum(len(b) for b in sent)
        finally:
            rq.shutdown()

    def test_shutdown_flushes_then_names_the_rest(self):
        rq, inner = make_rq(drain_timeout_s=0.2)
        inner.down = True
        sent = batches(3, spans=2)
        for b in sent:
            rq.consume(b)
        rq.shutdown()  # destination still down: bounded flush fails
        st = rq.stats()
        assert st["pending_spans"] == 0
        assert st["dropped_spans"] == sum(len(b) for b in sent)
        drops = {
            (d["component"], r): n
            for d in flow_ledger.snapshot()["drops"]
            for r, n in d["reasons"].items()}
        assert drops.get(("retry/tracedb/dest", "shutdown_drain")) \
            == st["dropped_spans"]
        assert inner.stopped

    def test_shutdown_bounded_even_when_export_hangs(self):
        # a destination that HANGS (not raises) wedges the retry thread
        # inside inner.consume holding the export lock — shutdown must
        # still return inside the drain budget, naming the leftovers
        release = threading.Event()
        hung = threading.Event()
        inner = FlakyExporter()
        orig = inner.consume

        def hanging(batch):
            hung.set()
            release.wait(30.0)
            orig(batch)

        rq, _ = make_rq(inner, drain_timeout_s=0.3)
        inner.down = True
        rq.consume(synthesize_traces(2, seed=0))  # raises -> spills
        inner.down = False
        inner.consume = hanging  # the RETRY thread now wedges on it
        assert hung.wait(5.0), "retry thread never attempted the head"
        rq.consume(synthesize_traces(2, seed=1))  # queued behind it
        t0 = time.monotonic()
        rq.shutdown()
        assert time.monotonic() - t0 < 10.0, "shutdown wedged"
        assert rq.stats()["dropped_spans"] > 0  # named, not silent
        release.set()  # unwedge the leaked daemon thread
        rq, inner = make_rq(drain_timeout_s=5.0)
        inner.down = True
        sent = batches(2, spans=2)
        for b in sent:
            rq.consume(b)
        # stop the retry thread from winning the race deterministically:
        # recover the destination only at shutdown time
        inner.down = False
        rq.shutdown()
        assert inner.span_count == sum(len(b) for b in sent)
        assert rq.stats()["dropped_spans"] == 0


class TestObservability:
    def test_watermark_and_gauge_published(self):
        rq, inner = make_rq()
        try:
            inner.down = True
            b = synthesize_traces(3, seed=0)
            rq.consume(b)
            assert flow_ledger.watermark_current(
                "retry/tracedb/dest", "pending_spans") == len(b)
            key = ("odigos_export_retry_queue_spans"
                   "{exporter=tracedb/dest}")
            assert meter.snapshot().get(key) == float(len(b))
            inner.down = False
            assert rq.flush(10.0)
            assert flow_ledger.watermark_current(
                "retry/tracedb/dest", "pending_spans") == 0
        finally:
            rq.shutdown()

    def test_arrivals_do_not_defeat_the_backoff(self):
        # regression: the backoff sleep must NOT wake on every arriving
        # batch — sustained traffic during an outage would otherwise
        # hammer the dead destination at the arrival rate, the exact
        # re-synchronized storm the jitter exists to prevent
        inner = FlakyExporter()
        attempts = {"n": 0}
        orig = inner.consume

        def counting(batch):
            attempts["n"] += 1
            orig(batch)

        inner.consume = counting
        rq, _ = make_rq(inner, initial_backoff_ms=300,
                        max_backoff_ms=600, jitter=0.0)
        try:
            inner.down = True
            for b in batches(6, spans=2):
                rq.consume(b)
                time.sleep(0.01)
            time.sleep(0.15)
            # inside one 300 ms backoff window: at most the direct
            # attempt + the retry thread's first try — never one
            # attempt per arrival
            assert attempts["n"] <= 3, attempts["n"]
        finally:
            inner.down = False
            rq.shutdown()

    def test_jitter_is_seeded(self):
        import random

        ref = random.Random(7)
        draws_a = [ref.random() for _ in range(4)]
        rq, _ = make_rq(seed=7)
        try:
            assert [rq._rng.random() for _ in range(4)] == draws_a
        finally:
            rq.shutdown()

    def test_inner_query_api_delegates(self):
        rq, inner = make_rq()
        try:
            b = synthesize_traces(2, seed=0)
            rq.consume(b)
            assert rq.span_count == inner.span_count  # __getattr__
        finally:
            rq.shutdown()


class TestGraphWiring:
    def base_cfg(self, retry):
        return {
            "receivers": {"synthetic": {"n_batches": 0}},
            "processors": {},
            "exporters": {"tracedb/out": {"retry": retry}},
            "service": {"pipelines": {"traces/in": {
                "receivers": ["synthetic"], "processors": [],
                "exporters": ["tracedb/out"]}}},
        }

    def test_retry_stanza_wraps_exporter(self):
        g = build_graph(self.base_cfg({"max_queue_spans": 64}))
        exp = g.exporters["tracedb/out"]
        assert isinstance(exp, RetryQueue)
        assert exp.max_queue_spans == 64
        assert g.component("tracedb/out") is exp

    def test_retry_true_uses_defaults(self):
        g = build_graph(self.base_cfg(True))
        exp = g.exporters["tracedb/out"]
        assert isinstance(exp, RetryQueue)
        assert exp.max_queue_spans == DEFAULTS["max_queue_spans"]

    def test_retry_empty_mapping_also_means_defaults(self):
        # {} is the all-defaults spelling (what pipelinegen's
        # export_retry={} renders) — it must wrap, not silently skip
        g = build_graph(self.base_cfg({}))
        assert isinstance(g.exporters["tracedb/out"], RetryQueue)

    def test_retry_enabled_false_is_an_opt_out(self):
        # {"enabled": false} must leave the exporter UNWRAPPED — its
        # failures surface per batch, exactly what the opt-out asked for
        g = build_graph(self.base_cfg({"enabled": False}))
        assert not isinstance(g.exporters["tracedb/out"], RetryQueue)
        g2 = build_graph(self.base_cfg({"enabled": True}))
        assert isinstance(g2.exporters["tracedb/out"], RetryQueue)

    def test_no_stanza_no_wrapper(self):
        cfg = self.base_cfg(True)
        del cfg["exporters"]["tracedb/out"]["retry"]
        g = build_graph(cfg)
        assert not isinstance(g.exporters["tracedb/out"], RetryQueue)

    def test_validation_refuses_typos(self):
        assert validate_retry_config("e", {"max_queue_spnas": 1})
        assert validate_retry_config("e", {"jitter": 1.5})
        assert validate_retry_config("e", {"initial_backoff_ms": 0})
        assert validate_retry_config("e", {"max_queue_spans": 0.5})
        assert validate_retry_config("e", "yes")
        assert validate_retry_config("e", True) == []
        assert validate_retry_config("e", {"jitter": 0.3}) == []
        problems = validate_config(self.base_cfg({"bogus_key": 1}))
        assert any("unknown retry keys" in p for p in problems)

    def test_pipelinegen_stamps_destination_exporters(self):
        from odigos_tpu.components.api import Signal
        from odigos_tpu.destinations import Destination
        from odigos_tpu.pipelinegen import GatewayOptions
        from odigos_tpu.pipelinegen.builder import build_gateway_config

        dests = [Destination(id="db1", dest_type="tracedb",
                             signals=[Signal.TRACES])]
        spec = {"max_queue_spans": 128}
        cfg, _, _ = build_gateway_config(
            dests, options=GatewayOptions(export_retry=spec))
        dest_exporters = [e for e in cfg["exporters"]
                          if e.startswith("tracedb/")]
        assert dest_exporters
        for eid in dest_exporters:
            assert cfg["exporters"][eid]["retry"] == spec
        # internal self-telemetry exporters stay unwrapped
        assert "retry" not in cfg["exporters"].get("otlp/ui", {})
        # None renders byte-identically to the pre-ISSUE-13 shape
        cfg2, _, _ = build_gateway_config(dests,
                                          options=GatewayOptions())
        assert all("retry" not in (e or {})
                   for e in cfg2["exporters"].values())
