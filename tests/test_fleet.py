"""Fleet-plane tests (ISSUE 10): delta-publish equivalence vs full
snapshots, rollup correctness under collector churn, the >=200-collector
aggregation acceptance, alert fire-within-for-window / clear-after-
recovery (incl. a real queue_full storm through a running Collector),
hot reload editing/deleting the ``alerts:`` stanza, the recommender,
and the surfaces (api snapshot, /api/fleet, describe lines)."""

import json
import urllib.request

import pytest

from odigos_tpu.config.model import (
    AlertRuleConfiguration, Configuration)
from odigos_tpu.pipeline.service import Collector
from odigos_tpu.selftelemetry.fleet import (
    AlertEngine,
    FleetPlane,
    RECOMMENDER_RULES,
    alert_engine,
    fleet_plane,
    parse_expr,
    recommend,
    validate_alert_rules,
)
from odigos_tpu.selftelemetry.flow import flow_ledger
from odigos_tpu.selftelemetry.seriesstate import (
    COUNTER, SeriesStore, series_store)
from odigos_tpu.utils.telemetry import meter


class Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture()
def clock():
    return Clock()


@pytest.fixture()
def plane(clock):
    store = SeriesStore(interval_s=1.0, window=120, max_series=10_000,
                        clock=clock)
    return FleetPlane(store=store, clock=clock)


@pytest.fixture(autouse=True)
def fresh_globals():
    fleet_plane.reset()
    flow_ledger.reset()
    yield
    fleet_plane.reset()
    flow_ledger.reset()


# ------------------------------------------------------ expression parse


def test_parse_expr_grammar():
    p = parse_expr(
        "rate(odigos_flow_dropped_items_total{reason=queue_full}[30s])"
        " > 500")
    assert p == {"fn": "rate",
                 "metric": "odigos_flow_dropped_items_total",
                 "labels": {"reason": "queue_full"}, "window_s": 30.0,
                 "cmp": ">", "threshold": 500.0}
    assert parse_expr("latest(odigos_g) < 0.5")["window_s"] == 60.0


@pytest.mark.parametrize("bad", [
    "", "odigos_g > 5", "latest(odigos_g) >> 5",
    "stddev(odigos_g[10s]) > 1",          # unknown fn
    "rate(odigos_g) > 1",                 # rate needs explicit window
    "latest(odigos_g[0s]) > 1",           # zero window
    "latest(odigos_g{k}) > 1",            # bad matcher
    "latest(odigos_g[10s]) > threshold",  # non-numeric threshold
])
def test_parse_expr_rejects(bad):
    with pytest.raises(ValueError):
        parse_expr(bad)


def test_validate_alert_rules_aggregates_problems():
    problems = validate_alert_rules([
        {"name": "ok", "expr": "latest(odigos_g[10s]) > 1"},
        {"name": "ok", "expr": "latest(odigos_g[10s]) > 1"},   # dup
        {"name": "bad", "expr": "nope", "for_s": -1,
         "severity": "page", "bogus": 1},
        "not-a-dict",
    ])
    text = "\n".join(problems)
    assert "duplicate rule name" in text
    assert "unparsable alert expression" in text
    assert "for_s" in text and "severity" in text
    assert "unknown keys" in text and "must be a mapping" in text
    assert validate_alert_rules(
        [{"name": "a", "expr": "latest(odigos_g[5s]) >= 0"}]) == []
    assert validate_alert_rules({"a": 1}) \
        == ["service.alerts must be a list, got dict"]


# --------------------------------------------------- delta equivalence


def test_delta_publish_equivalent_to_full_snapshots(clock):
    """The equivalence oracle: the same snapshot sequence published
    delta vs full must yield identical per-series points — delta
    publishing is an optimization, never a semantic."""
    s_delta = SeriesStore(interval_s=1.0, window=120, clock=clock)
    s_full = SeriesStore(interval_s=1.0, window=120, clock=clock)
    p_delta = FleetPlane(store=s_delta, clock=clock)
    p_full = FleetPlane(store=s_full, clock=clock)
    snapshots = [
        {"odigos_g{model=z}": 1.0, "odigos_c_total": 10.0},
        {"odigos_g{model=z}": 1.0, "odigos_c_total": 10.0},  # idle
        {"odigos_g{model=z}": 2.0, "odigos_c_total": 10.0},
        {"odigos_g{model=z}": 2.0, "odigos_c_total": 25.0},
    ]
    skipped = 0
    for snap in snapshots:
        r = p_delta.publish("c1", dict(snap), group="g")
        skipped += r["skipped"]
        p_full.publish("c1", dict(snap), group="g", delta=False)
        clock.advance(2)
    assert skipped > 0  # the idle snapshot was actually elided
    for key in s_full.select("odigos_g") + s_full.select("odigos_c_total"):
        # delta publishing writes CHANGED values only, so a repeated
        # value leaves a gap in the delta store's ring — but every
        # window query that matters must agree on the value landscape
        assert s_delta.latest(key) == s_full.latest(key)
        assert s_delta.delta(key, 60) == s_full.delta(key, 60)
        assert s_delta.max_over_window(key, 60) == \
            s_full.max_over_window(key, 60)


def test_counter_kind_inferred_from_name(plane, clock):
    plane.publish("c1", {"odigos_x_total": 10.0, "odigos_g": 1.0})
    clock.advance(5)
    plane.publish("c1", {"odigos_x_total": 4.0, "odigos_g": 5.0})
    # reset-aware: the counter dropped 10 -> 4, so delta = +4, not -6
    assert plane.store.delta("odigos_x_total{collector=c1}", 60) == 4.0
    assert plane.store.delta("odigos_g{collector=c1}", 60) == 4.0


def test_steady_value_survives_delta_elision(clock):
    """Review regression: a gauge pinned at a constant, published every
    tick, must stay visible to window queries indefinitely — the
    heartbeat forces a full re-publish before the last written point
    ages out of the window, so a sustained breach cannot self-clear
    its own alert mid-incident."""
    store = SeriesStore(interval_s=1.0, window=120, clock=clock)
    plane = FleetPlane(store=store, clock=clock, heartbeat_s=10.0)
    eng = AlertEngine(store=store, clock=clock)
    eng.configure({"name": "sustained", "for_s": 0.0,
                   "expr": "avg(odigos_g[30s]) > 5"})
    for _ in range(120):  # 2 minutes of an unchanging 8.0
        plane.publish("c1", {"odigos_g": 8.0})
        clock.advance(1)
    assert store.latest("odigos_g{collector=c1}", 30) == 8.0
    assert store.avg_over_window("odigos_g{collector=c1}", 30) == 8.0
    assert eng.evaluate()[0]["firing"]
    # and the elision still did real work between heartbeats
    snap = plane.api_snapshot()
    assert snap["collectors"][0]["series_skipped"] > 50


def test_refused_series_retries_after_capacity_frees(clock):
    """Review regression: a series refused at the cardinality cap must
    not be delta-elided forever — the delta base un-marks refused keys
    so an identical next snapshot retries, and it lands once churn
    frees capacity."""
    store = SeriesStore(interval_s=1.0, window=60, max_series=2,
                        clock=clock)
    plane = FleetPlane(store=store, clock=clock)
    plane.publish("old", {"odigos_g": 1.0})  # 2 series incl. health
    r = plane.publish("new", {"odigos_g": 7.0})
    # the new collector's series were refused at the cap...
    assert store.select("odigos_g", {"collector": "new"}) == []
    assert r["published"] < 2
    plane.unregister("old")  # churn frees capacity
    clock.advance(1)
    r = plane.publish("new", {"odigos_g": 7.0})  # identical snapshot
    assert store.latest("odigos_g{collector=new}") == 7.0


# ---------------------------------------------------------- fleet scale


def test_200_collector_aggregation_with_delta_publishing(plane, clock):
    """The scale acceptance: >= 200 simulated collectors publish under
    delta elision; aggregation answers across the whole fleet."""
    N = 220
    for tick in range(3):
        for c in range(N):
            plane.publish(
                f"sim-{c:03d}",
                {"odigos_engine_queue_depth{model=z}": float(c % 7),
                 "odigos_spans_total": 100.0 * tick},
                # c % 5 lands degraded members in every pool-(c % 4)
                worst=("Degraded" if c % 5 == 0 else "Healthy",
                       "QueueSaturation" if c % 5 == 0 else "Running",
                       ""),
                group=f"pool-{c % 4}")
        clock.advance(2)
    assert len(plane.collectors()) == N
    agg = plane.aggregate("odigos_engine_queue_depth", fn="latest",
                          agg="count")
    assert agg == float(N)
    total = plane.aggregate("odigos_engine_queue_depth", fn="latest",
                            agg="sum")
    assert total == float(sum(c % 7 for c in range(N)))
    by = plane.aggregate("odigos_engine_queue_depth", fn="latest",
                         agg="max", by="collector")
    assert len(by) == N and by["sim-005"] == 5.0
    # delta elision did real work: tick 2 re-published an unchanged
    # queue_depth per collector
    snap = plane.api_snapshot()
    assert sum(c["series_skipped"] for c in snap["collectors"]) >= N
    # worst-of per group: every pool holds some degraded members
    groups = plane.group_rollup()
    assert set(groups) == {f"pool-{i}" for i in range(4)}
    for g in groups.values():
        assert g["status"] == "Degraded"
        assert g["reason"] == "QueueSaturation"
        assert g["collectors"] == N // 4


def test_churn_unregister_leaves_aggregates(plane, clock):
    for c in ("a", "b", "c"):
        plane.publish(c, {"odigos_g": 1.0}, group="g1")
    assert plane.aggregate("odigos_g", agg="count") == 3.0
    plane.unregister("b")
    assert plane.collectors() == ["a", "c"]
    # the departed collector's series left the store mid-window — the
    # aggregate answers for live members only, no window coasting
    assert plane.aggregate("odigos_g", agg="count") == 2.0
    assert plane.group_rollup()["g1"]["collectors"] == 2
    # re-registration starts a fresh delta base (full first publish)
    r = plane.publish("b", {"odigos_g": 1.0}, group="g1")
    assert r["published"] >= 1 and r["skipped"] == 0


def test_mid_window_registration_joins_aggregates(plane, clock):
    plane.publish("a", {"odigos_g": 1.0})
    clock.advance(30)
    plane.publish("late", {"odigos_g": 5.0})
    assert plane.aggregate("odigos_g", fn="latest", window_s=60,
                           agg="sum") == 6.0
    # and the older member ages out once past the window
    clock.advance(40)
    assert plane.aggregate("odigos_g", fn="latest", window_s=60,
                           agg="sum") == 5.0


# -------------------------------------------------------------- alerts


def _engine(plane, clock):
    return AlertEngine(store=plane.store, clock=clock)


def test_alert_fires_within_for_window_and_clears(plane, clock):
    """The acceptance loop: a queue_full storm breaches, the rule holds
    for for_s, fires, then clears after recovery — all on injected
    clocks."""
    eng = _engine(plane, clock)
    eng.configure({
        "name": "queue-full-storm",
        "expr": "rate(odigos_flow_dropped_items_total"
                "{reason=queue_full}[30s]) > 100",
        "for_s": 5.0, "severity": "critical"})
    key = ("odigos_flow_dropped_items_total"
           "{reason=queue_full,collector=c1}")

    def drop(total):
        plane.store.observe(key, total, kind=COUNTER)

    drop(0)
    st = eng.evaluate()[0]
    assert st["state"] == "inactive"
    # storm: +1000 drops/s
    for i in range(1, 4):
        clock.advance(1)
        drop(i * 1000.0)
    st = eng.evaluate()[0]
    assert st["state"] == "pending"  # breaching, inside the hold
    clock.advance(5)
    drop(8000.0)
    st = eng.evaluate()[0]
    assert st["state"] == "firing" and st["firing"]
    assert st["series"] == key
    fired = [e for e in eng.transitions() if e["event"] == "fired"]
    assert len(fired) == 1 and fired[0]["rule"] == "queue-full-storm"
    # recovery: the counter stops moving; once the storm leaves the
    # window the rate drops under threshold and the rule clears
    clock.advance(40)
    drop(8000.0)
    st = eng.evaluate()[0]
    assert st["state"] == "inactive" and not st["firing"]
    events = [e["event"] for e in eng.transitions()]
    assert events == ["fired", "cleared"]


def test_for_zero_fires_immediately(plane, clock):
    eng = _engine(plane, clock)
    eng.configure({"name": "now", "for_s": 0.0,
                   "expr": "latest(odigos_g[30s]) > 5"})
    plane.store.observe("odigos_g{collector=a}", 9.0)
    assert eng.evaluate()[0]["firing"]


def test_blip_shorter_than_for_never_fires(plane, clock):
    eng = _engine(plane, clock)
    eng.configure({"name": "held", "for_s": 10.0,
                   "expr": "latest(odigos_g[5s]) > 5"})
    plane.store.observe("odigos_g", 9.0)
    assert eng.evaluate()[0]["state"] == "pending"
    clock.advance(6)  # the blip ages out of the 5 s window
    assert eng.evaluate()[0]["state"] == "inactive"
    assert eng.transitions() == []


def test_worst_series_semantics_lower_bound(plane, clock):
    eng = _engine(plane, clock)
    eng.configure({"name": "low", "for_s": 0.0,
                   "expr": "latest(odigos_hit_rate[30s]) < 0.5"})
    plane.store.observe("odigos_hit_rate{collector=a}", 0.9)
    plane.store.observe("odigos_hit_rate{collector=b}", 0.2)
    st = eng.evaluate()[0]
    assert st["firing"]
    assert st["series"] == "odigos_hit_rate{collector=b}"


def test_no_matching_series_never_fires(plane, clock):
    eng = _engine(plane, clock)
    eng.configure({"name": "ghost", "for_s": 0.0,
                   "expr": "latest(odigos_never[30s]) > 0"})
    st = eng.evaluate()[0]
    assert st["state"] == "inactive" and st["value"] is None


def test_configure_identical_keeps_state_changed_recreates(plane, clock):
    eng = _engine(plane, clock)
    cfg = {"name": "r", "expr": "latest(odigos_g[30s]) > 5",
           "for_s": 0.0, "severity": "warning"}
    r1 = eng.configure(dict(cfg))
    plane.store.observe("odigos_g", 9.0)
    eng.evaluate()
    assert r1.state == "firing"
    # identical reload: same rule object, firing state survives
    assert eng.configure(dict(cfg)) is r1
    # any changed setting re-creates (threshold redefines the rule)
    r2 = eng.configure(dict(cfg, expr="latest(odigos_g[30s]) > 99"))
    assert r2 is not r1 and r2.state == "inactive"


# ------------------------------------------- collector config lifecycle


def _collector_cfg(alerts=None):
    cfg = {
        "receivers": {"synthetic": {"n_batches": 0}},
        "processors": {"batch": {}},
        "exporters": {"tracedb": {}},
        "service": {"pipelines": {"traces/in": {
            "receivers": ["synthetic"], "processors": ["batch"],
            "exporters": ["tracedb"]}}},
    }
    if alerts is not None:
        cfg["service"]["alerts"] = alerts
    return cfg


RULE = {"name": "qd", "expr": "latest(odigos_g[30s]) > 5",
        "for_s": 0.0, "severity": "critical"}


def test_collector_build_configures_and_scopes_conditions():
    c = Collector(_collector_cfg([dict(RULE)])).start()
    try:
        assert alert_engine.rule_names() == {"qd"}
        assert c.graph.alert_rule_names == {"qd"}
        series_store.observe("odigos_g{collector=x}", 9.0)
        conds = {x["component"]: x for x in c.health_conditions()}
        cond = conds["alert/qd"]
        # severity critical -> Unhealthy while firing
        assert cond["status"] == "Unhealthy"
        assert cond["reason"] == "AlertFiring"
        assert c.graph.flow_health.worst()[0] == "Unhealthy"
    finally:
        c.shutdown()


def test_rollup_without_alert_stanza_shows_no_alert_rows():
    # another collector's rules must not leak into this graph's rollup
    alert_engine.configure(dict(RULE))
    c = Collector(_collector_cfg()).start()
    try:
        assert all(not x["component"].startswith("alert/")
                   for x in c.health_conditions())
    finally:
        c.shutdown()


def test_hot_reload_edits_and_deletes_alert_stanza():
    c = Collector(_collector_cfg([dict(RULE)])).start()
    try:
        assert alert_engine.rule_names() == {"qd"}
        # edit: changed expr re-creates; new rule appears
        c.reload(_collector_cfg([
            dict(RULE, expr="latest(odigos_g[30s]) > 50"),
            {"name": "extra",
             "expr": "avg(odigos_g[30s]) > 1e9"}]))
        assert alert_engine.rule_names() == {"qd", "extra"}
        assert c.graph.alert_rule_names == {"qd", "extra"}
        [qd] = [r for r in alert_engine.status() if r["name"] == "qd"]
        assert qd["threshold"] == 50.0
        # delete the stanza entirely: every tracker retired (the
        # remove_slo discipline) and the rollup rows disappear
        c.reload(_collector_cfg())
        assert alert_engine.rule_names() == set()
        assert all(not x["component"].startswith("alert/")
                   for x in c.health_conditions())
    finally:
        c.shutdown()


def test_shutdown_retires_alert_rules():
    """Review regression: a dead collector's rules must not keep
    evaluating (and firing) against the store forever — shutdown
    retires the graph-stamped names like it unregisters the rollup."""
    c = Collector(_collector_cfg([dict(RULE)])).start()
    assert alert_engine.rule_names() == {"qd"}
    c.shutdown()
    assert alert_engine.rule_names() == set()


def test_invalid_alert_stanza_fails_build():
    with pytest.raises(ValueError, match="unparsable alert expression"):
        Collector(_collector_cfg([{"name": "x", "expr": "broken"}]))


def test_queue_full_storm_fires_through_real_ledger():
    """End-to-end regression injection: queue_full drops recorded
    through the REAL flow ledger, published by the real publish path,
    fire the storm rule; recovery (drops stop) clears it."""
    from odigos_tpu.selftelemetry.flow import FlowContext

    c = Collector(_collector_cfg([{
        "name": "storm",
        "expr": "delta(odigos_flow_dropped_items_total"
                "{reason=queue_full}[20s]) > 500",
        "for_s": 0.0, "severity": "critical"}])).start()
    try:
        meter.reset()
        # counter-delta semantics: the first point is a LEVEL; the
        # storm must rise between published points to register
        FlowContext.drop(1, "queue_full", pipeline="traces/in",
                         component_name="engine/z", signal="requests")
        fleet_plane.publish_collector(c, "gw", group="g")
        import time as _time
        _time.sleep(1.1)  # the global store's 1 s tick interval
        FlowContext.drop(2000, "queue_full", pipeline="traces/in",
                         component_name="engine/z", signal="requests")
        fleet_plane.publish_collector(c, "gw", group="g")
        conds = {x["component"]: x for x in c.health_conditions()}
        assert conds["alert/storm"]["status"] == "Unhealthy", conds
        # recovery: the counter stops moving; once the storm ages out
        # of the window the delta collapses and the rule clears
        st = fleet_plane.store
        key = ("odigos_flow_dropped_items_total{pipeline=traces/in,"
               "component=engine/z,reason=queue_full,collector=gw}")
        pts = st.points(key)
        assert pts, st.select("odigos_flow_dropped_items_total")
        # age the storm out by dropping the collector's series (the
        # wall-clock global store cannot be time-travelled in a test)
        st.drop_series({"collector": "gw"})
        conds = {x["component"]: x for x in c.health_conditions()}
        assert conds["alert/storm"]["status"] == "Healthy"
        events = [e["event"] for e in alert_engine.transitions()]
        assert events == ["fired", "cleared"]
    finally:
        c.shutdown()


# --------------------------------------------------------- recommender


def test_recommender_breach_names_knob_and_series(plane, clock):
    for _ in range(3):
        plane.publish("c1", {
            "odigos_engine_padding_waste_frac{model=z}": 0.6,
            "odigos_engine_bucket_ladder_hit_rate{model=z}": 0.99})
        clock.advance(2)
    recs = recommend(plane.store)
    assert [r["name"] for r in recs] == ["padding-waste-high"]
    rec = recs[0]
    assert rec["knob"] == "max_batch"
    assert rec["collector"] == "c1"
    assert rec["observed"] == 0.6
    assert "60%" in rec["recommendation"]


def test_recommender_replica_bound_scopes_to_preset(plane, clock):
    for _ in range(3):
        plane.publish("c1", {"odigos_engine_queue_depth{model=z}": 50.0})
        clock.advance(2)
    cfg = Configuration(resource_size_preset="size_s")
    recs = recommend(plane.store, config=cfg)
    [rec] = [r for r in recs if r["name"] == "engine-queue-sustained"]
    assert rec["knob"] == "replicas"
    assert "1-5 replicas" in rec["recommendation"]  # size_s bounds


def test_recommender_quiet_fleet_recommends_nothing(plane):
    plane.publish("c1", {"odigos_engine_queue_depth{model=z}": 0.0})
    assert recommend(plane.store) == []


def test_recommender_rules_parse():
    for rule in RECOMMENDER_RULES:
        parse_expr(rule.expr)  # must not raise


def test_backlog_rule_split_names_matching_knobs():
    """ISSUE 15 satellite: the lane rule names the lane knob (the old
    single rule said 'raise submit_lanes' while naming knob=replicas),
    the replica rule names replicas at a strictly higher threshold, and
    every TUNING_KNOBS entry is referenced by >= 1 rule (no dead
    knobs)."""
    from odigos_tpu.config.sizing import TUNING_KNOBS

    by_name = {r.name: r for r in RECOMMENDER_RULES}
    lanes = by_name["submit-lanes-saturated"]
    replicas = by_name["ingest-backlog-pressure"]
    assert lanes.knob == "submit_lanes"
    assert "submit_lanes" in lanes.action
    assert replicas.knob == "replicas"
    assert "submit_lanes" not in replicas.action
    assert parse_expr(replicas.expr)["threshold"] \
        > parse_expr(lanes.expr)["threshold"]
    referenced = {r.knob for r in RECOMMENDER_RULES}
    assert referenced == set(TUNING_KNOBS), \
        f"dead knob entries: {set(TUNING_KNOBS) - referenced}"


# --------------------------------------------- flap guard (held lifecycle)


HOLD_RULE = RECOMMENDER_RULES[0].__class__(
    name="held", expr="latest(odigos_g[30s]) > 5", knob="max_batch",
    action="a {value}", direction="down", for_s=10.0)


def test_recommendation_holds_pending_then_activates(plane, clock):
    """ISSUE 15 satellite: a breach goes pending the instant it
    appears but only ACTIVATES after persisting for_s — the actuator's
    feed never shows a one-tick blip."""
    from odigos_tpu.selftelemetry.fleet import Recommender

    rec = Recommender(store=plane.store, clock=clock,
                      rules=(HOLD_RULE,))
    plane.store.observe("odigos_g", 9.0)
    assert rec.evaluate() == []
    assert rec.rule_state("held") == "pending"
    clock.advance(5)
    plane.store.observe("odigos_g", 9.0)
    assert rec.evaluate() == []  # inside the hold
    clock.advance(6)
    plane.store.observe("odigos_g", 9.0)
    [active] = rec.evaluate()
    assert active["state"] == "active" and active["held_s"] >= 10.0
    assert rec.rule_state("held") == "active"
    # recovery clears immediately — and the next breach re-holds from
    # scratch (no credit for the previous incident)
    clock.advance(40)  # the breach ages out of the 30 s window
    assert rec.evaluate() == []
    assert rec.rule_state("held") == "inactive"
    plane.store.observe("odigos_g", 9.0)
    assert rec.evaluate() == []
    assert rec.rule_state("held") == "pending"


def test_recommendation_blip_never_activates(plane, clock):
    """A blip shorter than for_s must never reach the actuator."""
    from odigos_tpu.selftelemetry.fleet import Recommender

    rec = Recommender(store=plane.store, clock=clock,
                      rules=(HOLD_RULE,))
    plane.store.observe("odigos_g", 9.0)
    assert rec.evaluate() == []  # pending
    # the blip leaves the expr window before any evaluation finds it
    # held long enough: not breaching at evaluation time -> pending
    # resets, nothing ever activates
    clock.advance(35)
    assert rec.evaluate() == []
    assert rec.rule_state("held") == "inactive"


def test_plane_surfaces_use_held_feed(clock):
    """api_snapshot recommendations come from the held recommender: an
    instant breach shows nothing until the hold elapses."""
    store = SeriesStore(interval_s=1.0, window=240, clock=clock)
    plane = FleetPlane(store=store, clock=clock)
    plane.recommender.set_rules((HOLD_RULE,))
    store.observe("odigos_g", 9.0)
    assert plane.api_snapshot()["recommendations"] == []
    [status] = [s for s in plane.api_snapshot()["recommender"]
                if s["name"] == "held"]
    assert status["state"] == "pending"
    clock.advance(12)
    store.observe("odigos_g", 9.0)
    recs = plane.api_snapshot()["recommendations"]
    assert [r["name"] for r in recs] == ["held"]
    assert recs[0]["state"] == "active"


# ----------------------------------------------------------- surfaces


def test_api_snapshot_shape(plane, clock):
    plane.publish("c1", {"odigos_g": 1.0}, group="g1",
                  conditions=[{"component": "pipeline/traces/in",
                               "status": "Healthy",
                               "reason": "Conserved", "message": ""}],
                  worst=("Healthy", "AllHealthy", ""))
    snap = plane.api_snapshot()
    assert snap["enabled"]
    [co] = snap["collectors"]
    assert co["collector"] == "c1" and co["group"] == "g1"
    assert co["status"] == "Healthy" and co["age_s"] is not None
    assert co["conditions"][0]["component"] == "pipeline/traces/in"
    assert snap["groups"]["g1"]["collectors"] == 1
    assert snap["alerts"] == {"rules": [], "history": []}
    assert snap["recommendations"] == []
    assert snap["store"]["series"] == len(plane.store)
    json.dumps(snap)  # JSON-able end to end


def test_api_fleet_endpoint_and_fleetz():
    from odigos_tpu.api.store import Store
    from odigos_tpu.frontend import FrontendServer

    fleet_plane.publish("gw", {"odigos_g": 2.0}, group="g")
    alert_engine.configure(dict(RULE))
    fe = FrontendServer(Store(), metrics_port=None).start()
    try:
        with urllib.request.urlopen(
                f"{fe.url}/api/fleet", timeout=10) as r:
            doc = json.loads(r.read())
        assert [c["collector"] for c in doc["collectors"]] == ["gw"]
        assert [a["name"] for a in doc["alerts"]["rules"]] == ["qd"]
    finally:
        fe.shutdown()
    # the zpage serves the same document
    c = Collector({
        "receivers": {"synthetic": {"n_batches": 0}},
        "exporters": {"tracedb": {}},
        "extensions": {"zpages": {"port": 0}},
        "service": {"extensions": ["zpages"],
                    "pipelines": {"traces/in": {
                        "receivers": ["synthetic"], "processors": [],
                        "exporters": ["tracedb"]}}},
    }).start()
    try:
        port = c.graph.extensions["zpages"].port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/fleetz",
                timeout=10) as r:
            doc = json.loads(r.read())
        assert [c_["collector"] for c_ in doc["collectors"]] == ["gw"]
    finally:
        c.shutdown()


def test_describe_install_prints_fleet_and_alerts(tmp_path):
    from odigos_tpu.cli.describe import describe_install
    from odigos_tpu.cli.state import create_state

    fleet_plane.publish(
        "gw", {"odigos_g": 9.0}, group="cluster-gateway",
        worst=("Degraded", "QueueSaturation", "queue backing up"))
    alert_engine.configure(dict(RULE))
    series_store.observe("odigos_g{collector=gw}", 9.0)
    alert_engine.evaluate()
    state = create_state(str(tmp_path / "install"))
    text = describe_install(state)
    assert "fleet: 1 collector(s)" in text
    assert "group[cluster-gateway]: Degraded (QueueSaturation)" in text
    assert "gw[cluster-gateway]: Degraded QueueSaturation" in text
    assert "alerts: 1 rule(s), 1 firing" in text
    assert "[✕] qd (critical)" in text


def test_e2e_environment_publishes_fleet_and_group_condition():
    from odigos_tpu.e2e.environment import E2EEnvironment

    env = E2EEnvironment(nodes=1)
    env.start()
    try:
        env.reconcile()
        ids = fleet_plane.collectors()
        assert "gateway" in ids
        assert "gateway" in env.cluster.collector_endpoints
        groups = fleet_plane.group_rollup()
        assert env.GATEWAY_FLEET_GROUP in groups
        group = next(g for g in env.store.list("CollectorsGroup")
                     if g.role.value == "CLUSTER_GATEWAY"
                     or "gateway" in g.role.value.lower())
        types = {c.type for c in group.conditions}
        assert "FleetHealth" in types and "CollectorHealth" in types
        # churn: shutdown unregisters and drops the series
        env.shutdown()
        assert "gateway" not in fleet_plane.collectors()
        assert series_store.select(
            "odigos_collector_health_status",
            {"collector": "gateway"}) == []
    finally:
        try:
            env.shutdown()
        except Exception:
            pass


def test_kill_switch_disables_plane(monkeypatch, clock):
    store = SeriesStore(clock=clock)
    store.enabled = False
    plane = FleetPlane(store=store, clock=clock)
    assert plane.publish("c1", {"odigos_g": 1.0}) \
        == {"published": 0, "skipped": 0}
    assert plane.api_snapshot()["enabled"] is False
    eng = AlertEngine(store=store, clock=clock)
    eng.configure(dict(RULE))
    assert eng.evaluate() == []
    assert recommend(store) == []


def test_pipelinegen_renders_alert_stanza():
    from odigos_tpu.pipelinegen.builder import (
        GatewayOptions, build_gateway_config)
    from odigos_tpu.destinations import Destination
    from odigos_tpu.components.api import Signal

    dests = [Destination(id="db", dest_type="tracedb",
                         signals=[Signal.TRACES])]
    base, _, _ = build_gateway_config(dests, options=GatewayOptions())
    assert "alerts" not in base["service"]
    opts = GatewayOptions(alerts=[AlertRuleConfiguration(
        name="qd", expr="latest(odigos_g[30s]) > 5",
        for_s=2.0, severity="critical")])
    cfg, _, _ = build_gateway_config(dests, options=opts)
    assert cfg["service"]["alerts"] == [
        {"name": "qd", "expr": "latest(odigos_g[30s]) > 5",
         "for_s": 2.0, "severity": "critical"}]
    # empty list renders nothing — byte-stable configs
    cfg2, _, _ = build_gateway_config(
        dests, options=GatewayOptions(alerts=[]))
    assert cfg2 == base


def test_configuration_round_trips_alert_rules():
    cfg = Configuration(alerts=[AlertRuleConfiguration(
        name="qd", expr="latest(odigos_g[30s]) > 5")])
    back = Configuration.from_dict(cfg.to_dict())
    assert back.alerts == cfg.alerts
    assert isinstance(back.alerts[0], AlertRuleConfiguration)
