"""Columnar wire codec for SpanBatch / MetricBatch / LogBatch.

Frame layout (little-endian):
    u32 magic "OTW1"
    u32 payload length
payload:
    u32 header length, header JSON:
        {"n": points, "kind": "spans"|"metrics"|"logs" (absent = spans),
         "strings": [...], "resources": [...],
         "astore": {"keys": [...], "vals": [...], "nnz": K},  # attr pools
         "hists": {row_idx: {...}},        # metrics only, sparse
         "bodies": [...],                  # logs only
         "cols": [[name, dtype], ...]}     # order = byte layout
    raw column bytes, concatenated in header order
    attr-store arrays (when "astore" present), 8-byte aligned:
        row_ptr int32 (n+1) | key_idx int32 (K) | val_idx int32 (K)

The hot path ships the numeric columns AND the attribute entry arrays as
raw buffers (one memcpy each side); only the string table and the attr
store's deduped key/value pools go through JSON — per-DISTINCT cost,
never per-span. This replaces the old sparse ``"attrs": {row: {k: v}}``
dict-of-dicts header, which serialized every span's attributes through
the JSON encoder (O(rows) interpreter work on both sides). Frames from
pre-store encoders still carry ``"attrs"`` and decode unchanged; the
``attr_format="json"`` escape hatch emits that legacy shape for
compatibility tests. Metrics and logs ride the same attr-store section
for their point/record attrs.

Decode is **zero-copy**: columns AND attr entry arrays are read-only
``np.frombuffer`` views into the received payload (the encoder pads the
JSON header so the first column lands 8-byte aligned, and re-pads before
the attr section), copied only when an offset is misaligned for its
dtype. Two consequences the rest of the stack is built around: a decoded
batch pins its whole frame in memory for as long as any column view lives,
and in-place writes raise — every mutating path copies first (the pdata
``replace``/builder + attr-store copy-on-write discipline), which the
wire tests assert.
"""

from __future__ import annotations

import json
import struct

import numpy as np

from ..pdata.attrstore import AttrDictView, AttrStore, columnar_enabled
from ..pdata.logs import LogBatch
from ..pdata.metrics import MetricBatch
from ..pdata.spans import SpanBatch

MAGIC = b"OTW1"
_HDR = struct.Struct("<I")
_I32 = np.dtype("<i4")


def _attrs_field(batch) -> str:
    if isinstance(batch, MetricBatch):
        return "point_attrs"
    if isinstance(batch, LogBatch):
        return "record_attrs"
    return "span_attrs"


def encode_batch(batch, traceparent: str | None = None,
                 attr_format: str | None = None) -> bytes:
    """``attr_format``: None = store arrays when columnar attrs are
    enabled (default), ``"json"`` = the legacy sparse dict-of-dicts
    header (compat escape hatch / dict-path A/B)."""
    if attr_format is None:
        attr_format = "store" if columnar_enabled() else "json"
    cols = [(name, arr) for name, arr in batch.columns.items()]
    header = {
        "n": len(batch),
        "strings": list(getattr(batch, "strings", ())),
        "resources": [dict(r) for r in batch.resources],
        "cols": [[name, arr.dtype.str] for name, arr in cols],
    }
    if traceparent:
        # self-tracing context of the sending stage (W3C traceparent):
        # the receiving collector parents its receive span under it so a
        # batch's node-collector → gateway path is one internal trace.
        # Decoders that predate the key ignore it.
        header["tp"] = traceparent
    if isinstance(batch, MetricBatch):
        header["kind"] = "metrics"
        header["hists"] = {str(i): h
                           for i, h in enumerate(batch.histograms) if h}
    elif isinstance(batch, LogBatch):
        # log bodies are the bulk payload; they ride the JSON header (like
        # the string table) — raw-buffer framing is for the numeric columns
        header["kind"] = "logs"
        header["bodies"] = list(batch.bodies)

    store: AttrStore | None = None
    if attr_format == "store":
        store = batch.attrs()
        header["astore"] = {"keys": list(store.keys),
                            "vals": list(store.vals),
                            "nnz": store.nnz}
    else:
        attrs = getattr(batch, _attrs_field(batch))
        header["attrs"] = {str(i): dict(a)
                           for i, a in enumerate(attrs) if a}

    hdr = json.dumps(header, separators=(",", ":")).encode()
    # pad the header (JSON ignores trailing whitespace) so the first column
    # starts 8-byte aligned — the precondition for the decoder's zero-copy
    # views; u64/f64 columns dominate the span layout
    hdr += b" " * (-(_HDR.size + len(hdr)) % 8)
    parts = [_HDR.pack(len(hdr)), hdr]
    col_bytes = 0
    for _, arr in cols:
        b = np.ascontiguousarray(arr).tobytes()
        parts.append(b)
        col_bytes += len(b)
    if store is not None:
        # re-align so the int32 entry arrays land 8-byte aligned (narrow
        # int8 columns can leave the section end odd). The pad depends
        # ONLY on the column section's length — never on the header's —
        # so a frame whose header was rewritten (or came from an encoder
        # without header padding) still locates the attr section; the
        # decoder's misalignment copy handles the rest.
        parts.append(b"\0" * (-col_bytes % 8))
        parts.append(np.ascontiguousarray(store.row_ptr,
                                          dtype=_I32).tobytes())
        parts.append(np.ascontiguousarray(store.key_idx,
                                          dtype=_I32).tobytes())
        parts.append(np.ascontiguousarray(store.val_idx,
                                          dtype=_I32).tobytes())
    return b"".join(parts)


def decode_batch(payload: bytes):
    return decode_frame(payload)[0]


def _read_array(payload: bytes, dt: np.dtype, count: int,
                off: int) -> tuple[np.ndarray, int]:
    """Zero-copy view when aligned; the lone per-column memcpy when not."""
    nbytes = dt.itemsize * count
    if off % dt.alignment:
        arr = np.frombuffer(payload, dtype=np.uint8, count=nbytes,
                            offset=off).copy().view(dt)
    else:
        arr = np.frombuffer(payload, dtype=dt, count=count, offset=off)
    return arr, off + nbytes


def decode_frame(payload: bytes):
    """Decode a payload into ``(batch, traceparent)`` — the traceparent
    is the sender's self-tracing context (None when absent)."""
    (hdr_len,) = _HDR.unpack_from(payload, 0)
    header = json.loads(payload[4:4 + hdr_len])
    n = header["n"]
    columns = {}
    cols_start = off = 4 + hdr_len
    for name, dtype_str in header["cols"]:
        columns[name], off = _read_array(payload, np.dtype(dtype_str),
                                         n, off)

    astore = header.get("astore")
    if astore is not None:
        # encoder's inter-section pad — a function of the column
        # section's length only (see encode_batch)
        off += -(off - cols_start) % 8
        nnz = int(astore["nnz"])
        row_ptr, off = _read_array(payload, _I32, n + 1, off)
        key_idx, off = _read_array(payload, _I32, nnz, off)
        val_idx, off = _read_array(payload, _I32, nnz, off)
        store = AttrStore(keys=tuple(astore["keys"]),
                          vals=tuple(astore["vals"]),
                          row_ptr=row_ptr, key_idx=key_idx,
                          val_idx=val_idx)
        attrs = AttrDictView(store)
    else:
        # legacy frame: sparse JSON dict-of-dicts (pre-store encoders)
        attrs_sparse = {int(k): v
                        for k, v in header.get("attrs", {}).items()}
        attrs = tuple(attrs_sparse.get(i, {}) for i in range(n))

    tp = header.get("tp")
    if header.get("kind") == "metrics":
        hists_sparse = {int(k): v for k, v in header.get("hists", {}).items()}
        return MetricBatch(
            strings=tuple(header["strings"]),
            resources=tuple(header["resources"]),
            point_attrs=attrs,
            histograms=tuple(hists_sparse.get(i) for i in range(n)),
            columns=columns), tp
    if header.get("kind") == "logs":
        return LogBatch(
            resources=tuple(header["resources"]),
            bodies=tuple(header["bodies"]),
            record_attrs=attrs,
            columns=columns), tp
    return SpanBatch(
        strings=tuple(header["strings"]),
        resources=tuple(header["resources"]),
        span_attrs=attrs,
        columns=columns), tp


def frame(batch: SpanBatch, traceparent: str | None = None) -> bytes:
    payload = encode_batch(batch, traceparent)
    return MAGIC + _HDR.pack(len(payload)) + payload


def read_frame_header(buf: bytes) -> int:
    """Validate the 8-byte frame header; returns payload length."""
    if buf[:4] != MAGIC:
        raise ValueError("bad wire magic")
    (n,) = _HDR.unpack_from(buf, 4)
    return n
