"""Dashboard JS contract tests (VERDICT r3 item 3).

No JS engine ships in this image (no node/quickjs/browser), so the page's
inline script cannot be *executed* here; these tests implement the next
strongest guarantee, in both directions:

* every endpoint the JS fetches is extracted from the page source and hit
  against a live, populated server (reference analog: cypress/e2e/
  01-connection.cy.ts hitting the running webapp);
* every ``root.field`` property access the JS performs on API payloads is
  extracted from the script and checked against a hand-maintained CONTRACT
  table — adding an access without extending the table fails the sync
  guard — and every CONTRACT path is then resolved against the *actual*
  payload served by the live server. A renamed server field, or a JS
  access to a field no payload carries (the ``d.destination_type`` vs
  ``dest_type`` class of bug this test was introduced to catch), fails.
* geometry/format constants the sparkline math depends on are extracted
  from the JS and pinned, so silent edits surface in review.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.request

import pytest

from odigos_tpu.components.api import Signal
from odigos_tpu.controlplane.cluster import Container
from odigos_tpu.destinations import Destination
from odigos_tpu.e2e.environment import E2EEnvironment
from odigos_tpu.frontend import FrontendServer
from odigos_tpu.frontend.server import _dashboard_page
from odigos_tpu.pdata import synthesize_traces


def get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _script() -> str:
    page = _dashboard_page().decode()
    m = re.search(r"<script>(.*)</script>", page, re.S)
    assert m, "dashboard has no inline script"
    return m.group(1)


# --------------------------------------------------------------- the contract
#
# root variable in the JS -> (endpoint, field paths the JS reads).
# "?" suffix = the JS guards the access with a fallback (`|| {}`, ternary),
# so absence in a particular payload instance is tolerated — but the path
# must still be a real field the server CAN serve, asserted below against
# a populated instance wherever possible.
CONTRACT: dict[str, dict] = {
    "metrics": {"endpoint": "/api/metrics",
                "fields": ["totals", "services"]},
    "tot": {"endpoint": "/api/metrics",
            "at": ["totals", "odigos_traffic_spans_total"],
            "fields": ["per_sec", "total"]},
    "spans": {"endpoint": "/api/metrics",
              "at": ["services", "*", "odigos_traffic_spans_total"],
              "fields": ["per_sec", "total"]},
    "bytes": {"endpoint": "/api/metrics",
              "at": ["services", "*", "odigos_traffic_bytes_total"],
              "fields": ["per_sec"]},
    "anomalies": {"endpoint": "/api/anomalies",
                  "fields": ["scored", "scored_per_sec", "passthrough",
                             "flagged"]},
    "a": {"endpoint": "/api/anomalies",
          "fields": ["scored", "scored_per_sec", "passthrough",
                     "passthrough_per_sec", "flagged", "flagged_per_sec",
                     "local_flagged"]},
    "topo": {"endpoint": "/api/pipeline", "fields": ["pipelines"]},
    "pipe": {"endpoint": "/api/pipeline", "at": ["pipelines", "*"],
             "fields": ["receivers?", "processors?", "exporters?"]},
    "s": {"endpoint": "/api/sources", "each": True,
          "fields": ["meta", "workload", "disable_instrumentation?"]},
    "w": {"endpoint": "/api/sources", "each": True, "at": ["workload"],
          "fields": ["namespace", "name", "kind"]},
    "d": {"endpoint": "/api/destinations", "each": True,
          "fields": ["meta", "signals", "dest_type", "name?"]},
    # destination setup catalog (the (setup) wizard data source)
    "t": {"endpoint": "/api/destination-types", "each": True,
          "fields": ["type", "display_name", "signals", "fields"]},
    "f": {"endpoint": "/api/destination-types", "each": True,
          "at": ["fields", "*"], "fields": ["name", "secret"]},
    # policies section (the reference UI's actions + rules pages)
    "ac": {"endpoint": "/api/actions", "each": True,
           "fields": ["meta", "action_kind", "signals", "disabled"]},
    "ru": {"endpoint": "/api/rules", "each": True,
           "fields": ["meta", "rule_kind", "languages", "disabled"]},
    # self-tracing panel (the framework tracing itself, /api/selftrace)
    "st": {"endpoint": "/api/selftrace",
           "fields": ["traces", "spans_total", "dropped", "exemplars"]},
    "tr": {"endpoint": "/api/selftrace", "at": ["traces", "*"],
           "fields": ["root", "span_count", "duration_ms"]},
    # latency exemplars (ISSUE 3): histogram tail -> self-trace pivot
    "ex": {"endpoint": "/api/selftrace", "at": ["exemplars", "*"],
           "fields": ["metric", "value", "trace_id"]},
    # flow ledger panel (ISSUE 5): conservation balance + conditions
    "flow": {"endpoint": "/api/flow",
             "fields": ["pipelines", "conditions"]},
    "fp": {"endpoint": "/api/flow", "at": ["pipelines", "*"],
           "fields": ["items_in", "items_out", "dropped", "failed",
                      "pending", "leak"]},
    "fc": {"endpoint": "/api/flow", "at": ["conditions", "*"],
           "fields": ["component", "status", "reason"]},
    # latency attribution & SLO burn panel (ISSUE 8): per-pipeline burn
    # status + stage waterfall; per-pipeline rows are reached via locals
    # (sp/stages), validated top-level here — the fixture runs no SLO'd
    # fast-path pipeline, so the dicts are legitimately empty
    "slo": {"endpoint": "/api/slo", "fields": ["pipelines", "waterfall"]},
    # fleet plane panel (ISSUE 10): per-collector health, alert rule
    # states, sizing recommendations; per-row objects are reached via
    # locals (co/al/rec) — top-level containers validated here (always
    # served, possibly empty)
    "fleet": {"endpoint": "/api/fleet",
              "fields": ["collectors", "alerts", "recommendations"]},
    # closed-loop actuator panel (ISSUE 15): armed state, in-flight
    # canary/promotion, bounded action history; per-row objects are
    # reached via locals (h/cur) — top-level containers validated here
    # (always served: in_flight is present-but-null when idle)
    "act": {"endpoint": "/api/actuator",
            "fields": ["enabled", "dry_run", "state", "in_flight",
                       "history"]},
    # flight recorder panel (ISSUE 16): black-box counters + frozen
    # incident summaries; per-incident rows are reached via a local (it)
    # — top-level containers validated here (always served, possibly
    # empty on a clean run)
    "inc": {"endpoint": "/api/incidents",
            "fields": ["enabled", "incidents", "events_total",
                       "suppressed", "incidents_evicted"]},
    # device plane panel (ISSUE 20): sampled intra-fused attribution,
    # XLA cost/efficiency ledger rows, recent compile events, resident
    # table footprint; per-row objects are reached via locals
    # (ab/row/ev) — top-level containers validated here (always served,
    # empty until a fused engine arms attribution)
    "dev": {"endpoint": "/api/device",
            "fields": ["attribution", "cost", "compiles", "tables"]},
    # workload drill-down (the reference UI's describe view)
    "desc": {"endpoint": "/api/describe/workload", "fields": ["text"]},
    # SSE store-event JSON (validated in test_sse_event_shape)
    "e": {"endpoint": "/api/events",
          "fields": ["type", "kind", "namespace", "name"]},
}

# property accesses on these roots that are NOT payload fields (methods,
# locals the JS builds itself) — excluded from the sync guard
_NON_PAYLOAD = {
    ("s", "length"), ("d", "length"), ("a", "length"),
    ("sources", "length"), ("dests", "length"), ("names", "length"),
    ("points", "length"), ("rateHistory", "length"), ("pts", "map"),
    ("s", "meta"),  # chained s.meta.name handled via "meta" entries
}

_ROOTS = set(CONTRACT)


def _js_payload_accesses() -> set[tuple[str, str]]:
    """(root, field) pairs the script reads on contract roots."""
    out = set()
    for root, fld in re.findall(r"\b([A-Za-z_]\w*)\.([A-Za-z_]\w*)",
                                _script()):
        if root in _ROOTS and (root, fld) not in _NON_PAYLOAD:
            out.add((root, fld))
    # bracket accesses with string-literal keys: s.meta["name"] style and
    # pipe[role] dynamic ones are covered by the contract's "at"/fields
    return out


def test_contract_table_covers_every_js_access():
    """Sync guard: a new payload access in the JS without a CONTRACT entry
    fails here, keeping the table honest."""
    declared = {(root, f.rstrip("?"))
                for root, spec in CONTRACT.items()
                for f in spec["fields"]}
    accesses = _js_payload_accesses()
    extra = {(r, f) for r, f in accesses
             if (r, f) not in declared
             and f not in ("meta",)}  # chained-root container fields
    assert not extra - declared, \
        f"JS reads fields not in the CONTRACT table: {sorted(extra)}"


def test_every_fetched_endpoint_is_declared():
    """Every fetch()/EventSource URL in the script is a CONTRACT endpoint
    (and vice-versa nothing is stale)."""
    script = _script()
    # the optional second segment catches /api/describe/workload while a
    # template literal's `${` fails the class, so `/api/sources/${key}`
    # yields its static prefix /api/sources
    fetched = set(re.findall(r"/api/[a-z-]+(?:/[a-z-]+)?", script))
    declared = {spec["endpoint"] for spec in CONTRACT.values()}
    assert fetched == declared, (
        f"page fetches {sorted(fetched)} but contract declares "
        f"{sorted(declared)}")


def test_sparkline_and_format_constants_pinned():
    script = _script()
    # geometry the sparkline math depends on (sparkline())
    m = re.search(r"const W = (\d+), H = (\d+), P = (\d+)", script)
    assert m, "sparkline geometry constants moved — update this pin"
    assert (int(m.group(1)), int(m.group(2)), int(m.group(3))) == (160, 28, 2)
    # history window (renderTiles) and poll cadence
    assert "rateHistory.length > 30" in script
    assert re.search(r"setInterval\(\(\) => poll\(true\), 2000\)", script)
    # compact() thresholds: 1e6 -> M, 1e4 -> K
    assert ">= 1e6" in script and ">= 1e4" in script


# ----------------------------------------------------------- live validation

@pytest.fixture(scope="module")
def populated():
    """A running frontend with sources, destinations, and real traffic so
    payload instances carry the fields the JS renders."""
    env = E2EEnvironment(nodes=1)
    fe = FrontendServer(env.store, cluster=env.cluster).start()
    env.config.ui_endpoint = f"127.0.0.1:{fe.metrics_port}"
    env.start()
    try:
        env.cluster.add_workload("shop", "cart",
                                 [Container("main", language="python")])
        env.instrument_workload("shop", "cart")
        env.add_destination(Destination(
            id="db", dest_type="tracedb", signals=[Signal.TRACES]))
        from odigos_tpu.api.resources import (
            Action, ActionKind, InstrumentationRule, ObjectMeta, RuleKind)
        from odigos_tpu.controlplane.scheduler import ODIGOS_NAMESPACE

        env.store.apply(Action(
            meta=ObjectMeta(name="errs", namespace=ODIGOS_NAMESPACE),
            action_kind=ActionKind.ERROR_SAMPLER, signals=["traces"],
            details={"fallback_sampling_ratio": 10}))
        env.store.apply(InstrumentationRule(
            meta=ObjectMeta(name="pc0", namespace=ODIGOS_NAMESPACE),
            rule_kind=RuleKind.PAYLOAD_COLLECTION, languages=["python"]))
        env.reconcile()
        env.send_traces(synthesize_traces(80, seed=3))
        env.gateway_component("prometheus/self-metrics").scrape_once()
        assert env.gateway_component("otlp/ui").flush(timeout=10)
        deadline = time.time() + 10
        while time.time() < deadline:
            if get_json(f"{fe.url}/api/metrics")["batches_received"]:
                break
            time.sleep(0.05)
        yield env, fe
    finally:
        env.shutdown()
        fe.shutdown()


def _resolve(payload, at):
    """Walk an "at" path; "*" = every child (dict values or list items)."""
    nodes = [payload]
    for step in at:
        nxt = []
        for node in nodes:
            if step == "*":
                nxt.extend(node.values() if isinstance(node, dict)
                           else node if isinstance(node, list) else ())
            elif isinstance(node, dict) and step in node:
                nxt.append(node[step])
        nodes = nxt
    return nodes


def test_contract_paths_exist_in_live_payloads(populated):
    env, fe = populated
    # parameterized endpoints need the query the JS would send
    _QUERY = {"/api/describe/workload":
              "?namespace=shop&kind=deployment&name=cart"}
    payloads = {ep: get_json(fe.url + ep + _QUERY.get(ep, ""))
                for ep in {s["endpoint"] for s in CONTRACT.values()}
                - {"/api/events"}}
    failures = []
    for root, spec in CONTRACT.items():
        if spec["endpoint"] == "/api/events":
            continue
        payload = payloads[spec["endpoint"]]
        targets = [payload]
        if spec.get("each"):
            assert isinstance(payload, list) and payload, \
                f"{spec['endpoint']} empty — fixture must populate it"
            targets = payload
        if spec.get("at"):
            targets = [t for tgt in targets
                       for t in _resolve(tgt, spec["at"])]
            if not targets:
                failures.append(
                    f"{root}: path {spec['at']} unreachable in "
                    f"{spec['endpoint']} payload")
                continue
        for f in spec["fields"]:
            optional = f.endswith("?")
            f = f.rstrip("?")
            if not any(isinstance(t, dict) and f in t for t in targets):
                if not optional:
                    failures.append(
                        f"{root}.{f}: absent from {spec['endpoint']} "
                        f"(at={spec.get('at')}) — JS renders undefined")
    assert not failures, "\n".join(failures)


def test_sse_event_shape(populated):
    """The SSE handler destructures e.type/kind/namespace/name — assert a
    real store event carries exactly those."""
    env, fe = populated
    got: list[dict] = []
    ready = threading.Event()

    def listen():
        req = urllib.request.Request(f"{fe.url}/api/events")
        with urllib.request.urlopen(req, timeout=15) as r:
            for raw in r:
                line = raw.decode().strip()
                if line.startswith("data:"):
                    got.append(json.loads(line[5:]))
                    ready.set()
                    return

    t = threading.Thread(target=listen, daemon=True)
    t.start()
    time.sleep(0.3)
    env.cluster.add_workload("shop", "web",
                             [Container("main", language="python")])
    env.instrument_workload("shop", "web")
    assert ready.wait(10), "no SSE event"
    fields = [f.rstrip("?") for f in CONTRACT["e"]["fields"]]
    for f in fields:
        assert f in got[0], f"SSE event missing {f!r}: {got[0]}"


def test_destination_types_catalog(populated):
    """The setup wizard's backend catalog: all 63 registry entries with
    schema-driven fields (reference: frontend/webapp/app/(setup))."""
    env, fe = populated
    catalog = get_json(f"{fe.url}/api/destination-types")
    assert len(catalog) >= 60
    dd = next(t for t in catalog if t["type"] == "datadog")
    assert dd["display_name"] == "Datadog"
    assert set(dd["signals"]) == {"traces", "metrics", "logs"}
    names = {f["name"] for f in dd["fields"]}
    assert "DATADOG_SITE" in names
    assert any(f["secret"] for f in dd["fields"])


def test_destination_create_flow_e2e(populated):
    """The form's POST creates a datadog destination; its pipeline appears
    in the generated gateway config (cypress/e2e/04-destinations.cy.ts
    connect flow)."""
    env, fe = populated
    body = json.dumps({
        "name": "dd1", "type": "datadog",
        "signals": ["traces"],
        "fields": {"DATADOG_SITE": "datadoghq.eu",
                   "DATADOG_API_KEY": "k3y"}}).encode()
    req = urllib.request.Request(
        f"{fe.url}/api/destinations", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 201
    env.reconcile()
    topo = get_json(f"{fe.url}/api/pipeline")
    assert "traces/datadog-dd1" in topo["pipelines"], \
        sorted(topo["pipelines"])
    dests = get_json(f"{fe.url}/api/destinations")
    dd1 = next(d for d in dests if d["meta"]["name"] == "dd1")
    # the secret never round-trips through the store/API: it is delivered
    # to the collector env (the Secret-backed pod-env analog) and the
    # resource records only the ref
    assert "k3y" not in json.dumps(dests), "secret echoed by the API"
    assert "DATADOG_API_KEY" not in dd1["config"]
    assert dd1["secret_ref"]
    import os
    assert os.environ.get("DATADOG_API_KEY") == "k3y"
    assert dd1["config"]["DATADOG_SITE"] == "datadoghq.eu"
    # remove through the row button's DELETE and see it disappear
    req = urllib.request.Request(f"{fe.url}/api/destinations/dd1",
                                 method="DELETE")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 200
    env.reconcile()
    topo = get_json(f"{fe.url}/api/pipeline")
    assert "traces/datadog-dd1" not in topo["pipelines"]


def test_destination_create_validation_errors(populated):
    """Missing required field -> 400 with the configer's field-level
    problem, the payload the form renders into #dest-errors."""
    env, fe = populated
    body = json.dumps({"name": "dd2", "type": "datadog",
                       "signals": ["traces"], "fields": {}}).encode()
    req = urllib.request.Request(
        f"{fe.url}/api/destinations", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(req, timeout=10)
    assert exc.value.code == 400
    err = json.loads(exc.value.read())
    assert any("DATADOG_SITE" in p for p in err["problems"]), err
    # nothing was applied
    assert not any(d["meta"]["name"] == "dd2"
                   for d in get_json(f"{fe.url}/api/destinations"))
    # unsupported signal combination is refused too
    body = json.dumps({"name": "x1", "type": "xray",
                       "signals": ["logs"], "fields": {}}).encode()
    req = urllib.request.Request(
        f"{fe.url}/api/destinations", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(req, timeout=10)
    assert exc.value.code == 400


def test_actions_and_rules_api(populated):
    """Actions/rules management over the JSON API (the reference UI's
    actions + rules pages, cypress/e2e/05+06): create an action and see
    its compiled processor appear in the gateway pipeline."""
    env, fe = populated

    body = json.dumps({"name": "errs2", "kind": "ErrorSampler",
                       "signals": ["traces"],
                       "details": {"fallback_sampling_ratio": 10}}).encode()
    req = urllib.request.Request(
        f"{fe.url}/api/actions", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 201
    env.reconcile()
    actions = get_json(f"{fe.url}/api/actions")
    assert any(a["meta"]["name"] == "errs2" for a in actions)
    # the autoscaler compiled it into a sampling processor in the gateway
    topo = get_json(f"{fe.url}/api/pipeline")
    assert any("odigossampling" in n["id"] for n in topo["nodes"]), \
        [n["id"] for n in topo["nodes"]]

    # unknown kind -> 400
    bad = json.dumps({"name": "x", "kind": "Nope"}).encode()
    req = urllib.request.Request(
        f"{fe.url}/api/actions", data=bad,
        headers={"Content-Type": "application/json"}, method="POST")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=10)
    assert e.value.code == 400

    req = urllib.request.Request(f"{fe.url}/api/actions/errs2",
                                 method="DELETE")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 200
    env.reconcile()
    assert not any(a["meta"]["name"] == "errs2"
                   for a in get_json(f"{fe.url}/api/actions"))

    # rules round trip with a workload selector
    body = json.dumps({"name": "pc", "kind": "payload-collection",
                       "workloads": [{"namespace": "shop",
                                      "name": "cart"}],
                       "languages": ["python"],
                       "details": {"max_payload_len": 256}}).encode()
    req = urllib.request.Request(
        f"{fe.url}/api/rules", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 201
    rules = get_json(f"{fe.url}/api/rules")
    pc = next(r for r in rules if r["meta"]["name"] == "pc")
    assert pc["workloads"][0]["name"] == "cart"
    req = urllib.request.Request(f"{fe.url}/api/rules/pc",
                                 method="DELETE")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 200


def test_post_source_body_matches_server_expectation(populated):
    """The add-source form posts {namespace, name, kind} — assert the
    server accepts exactly that body (cypress/e2e/03-sources.cy.ts role)."""
    env, fe = populated
    env.cluster.add_workload("default", "checkout",
                             [Container("main", language="python")])
    body = json.dumps({"namespace": "default", "name": "checkout",
                       "kind": "deployment"}).encode()
    req = urllib.request.Request(
        f"{fe.url}/api/sources", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 201
    # and the delete URL scheme the delegated listener builds works
    req = urllib.request.Request(
        f"{fe.url}/api/sources/default/src-checkout", method="DELETE")
    with urllib.request.urlopen(req, timeout=10) as r:
        assert r.status == 200
