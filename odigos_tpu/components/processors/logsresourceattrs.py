"""Logs resource-attributes processor (the odigoslogsresourceattrsprocessor
equivalent).

Enriches filelog-collected log records with workload metadata, per
collector/processors/odigoslogsresourceattrsprocessor/processor.go: the pod
UID is read from the ``k8s.pod.uid`` resource attribute or parsed out of the
filelog receiver's ``log.file.path``
(``/var/log/pods/{ns}_{pod}_{uid}/{container}/x.log``), then resolved to
workload identity and written back as ``service.name`` / ``k8s.pod.name`` /
``k8s.namespace.name`` / ``k8s.<kind>.name``.

The reference resolves UIDs via a node-local kube metadata watch; ours
resolves through a pluggable ``PodMetadataResolver`` — in-cluster that's the
control plane's workload store (controlplane.store), in tests a dict. The
enrichment itself is one pass over the *resource table*, not the records
(columnar: O(distinct resources)).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Protocol

from ...pdata.logs import LogBatch
from ..api import Capabilities, ComponentKind, Factory, Processor, register

LOG_FILE_PATH_ATTR = "log.file.path"

_KIND_TO_ATTR = {
    "deployment": "k8s.deployment.name",
    "daemonset": "k8s.daemonset.name",
    "statefulset": "k8s.statefulset.name",
    "job": "k8s.job.name",
    "cronjob": "k8s.cronjob.name",
    "deploymentconfig": "k8s.deployment.name",
    "argorollout": "k8s.argoproj.rollout.name",
    "staticpod": "k8s.pod.name",
}


@dataclass(frozen=True)
class PodWorkloadMeta:
    namespace: str
    pod_name: str
    workload_kind: str  # lowercase kind, key of _KIND_TO_ATTR
    workload_name: str


class PodMetadataResolver(Protocol):
    def resolve_pod_uid(self, uid: str) -> Optional[PodWorkloadMeta]: ...


class DictResolver:
    """Test/static resolver: {uid: PodWorkloadMeta}."""

    def __init__(self, table: dict[str, PodWorkloadMeta]):
        self.table = dict(table)

    def resolve_pod_uid(self, uid: str) -> Optional[PodWorkloadMeta]:
        return self.table.get(uid)


def extract_pod_uid_from_path(path: str) -> Optional[str]:
    """/var/log/pods/{ns}_{pod}_{uid}/{container}/x.log → uid."""
    for i, segment in enumerate(path.split("/")):
        if segment == "pods":
            parts = path.split("/")
            if i + 1 < len(parts):
                pieces = parts[i + 1].rsplit("_", 2)
                if len(pieces) == 3:
                    return pieces[2]
    return None


class LogsResourceAttrsProcessor(Processor):
    capabilities = Capabilities(mutates_data=True)

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        resolver = config.get("resolver")
        if resolver is None:
            resolver = DictResolver(config.get("pod_metadata", {}))
        self.resolver: PodMetadataResolver = resolver

    def process(self, batch: LogBatch) -> Optional[LogBatch]:
        if not isinstance(batch, LogBatch) or not batch.resources:
            return batch
        # the filelog receiver records log.file.path per *record*; fall back
        # to the first record path seen for each resource
        record_paths: dict[int, str] = {}
        res_col = batch.col("resource_index")
        for i, attrs in enumerate(batch.record_attrs):
            ri = int(res_col[i])
            if ri >= 0 and ri not in record_paths:
                path = attrs.get(LOG_FILE_PATH_ATTR)
                if isinstance(path, str):
                    record_paths[ri] = path
        new_resources = []
        changed = False
        for ridx, res in enumerate(batch.resources):
            uid = res.get("k8s.pod.uid")
            if not uid:
                path = res.get(LOG_FILE_PATH_ATTR, record_paths.get(ridx))
                if isinstance(path, str):
                    uid = extract_pod_uid_from_path(path)
            meta = self.resolver.resolve_pod_uid(uid) if uid else None
            if meta is None:
                new_resources.append(res)
                continue
            enriched = dict(res)
            enriched.setdefault("service.name", meta.workload_name)
            enriched["k8s.pod.name"] = meta.pod_name
            enriched["k8s.namespace.name"] = meta.namespace
            kind_attr = _KIND_TO_ATTR.get(meta.workload_kind)
            if kind_attr:
                enriched[kind_attr] = meta.workload_name
            new_resources.append(enriched)
            changed = True
        if not changed:
            return batch
        return batch.with_resources(new_resources)


register(Factory(
    type_name="odigoslogsresourceattrs",
    kind=ComponentKind.PROCESSOR,
    create=LogsResourceAttrsProcessor,
    default_config=dict,
))
