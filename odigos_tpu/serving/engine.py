"""Batched async scoring engine — the TPU sidecar.

The north star's hardest constraint (SURVEY.md §7 "Hard parts"): the pipeline
must never block on TPU round-trips; <5 ms p99 added latency at ≥1M spans/s.
The reference's analog discipline is the eBPF receiver's hot loop + pre-decode
rejection (odigosebpfreceiver/traces.go:17, configgrpc fork).

Design:

* callers ``submit()`` featurized batches into a **bounded** queue and wait on
  a per-request event with a deadline;
* one worker thread drains the queue, **coalesces** pending requests into a
  single device call (big batches feed the MXU), splits scores back per
  request, and sets events;
* if the deadline passes, the caller forwards spans unscored (pass-through)
  and the late scores still update online state; a passthrough counter feeds
  own-telemetry (the memory-limiter-rejections pattern);
* if the queue is full, ``submit`` fails fast (admission control) instead of
  stalling the pipeline.

Backends plug in via ``ModelBackend``: zscore (streaming, online update),
transformer / autoencoder (sequence models with shape-bucketed jit), and mock
(deterministic, TPU-free — the mockdestinationexporter pattern for tests).
A gRPC/unix-socket front-end for true sidecar deployment wraps this engine in
odigos_tpu.serving.sidecar.
"""

from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional, Protocol

import numpy as np

from ..features.featurizer import (
    FeaturizerConfig, SpanFeatures, assemble_sequences, featurize)
from ..pdata.spans import SpanBatch
from ..selftelemetry.tracer import (
    NULL_SPAN, is_selftelemetry_batch, tracer)
from ..utils.telemetry import meter

PASSTHROUGH_METRIC = "odigos_anomaly_passthrough_total"
QUEUE_FULL_METRIC = "odigos_anomaly_queue_full_total"
SCORED_METRIC = "odigos_anomaly_scored_spans_total"
COLD_METRIC = "odigos_anomaly_cold_spans_total"


@dataclass(frozen=True)
class EngineConfig:
    model: str = "zscore"  # zscore | transformer | autoencoder | mock | remote
    max_queue: int = 64          # pending requests bound
    max_batch_spans: int = 65536  # coalescing cap per device call
    max_len: int = 64            # sequence models: spans per trace
    trace_bucket: int = 256      # sequence models: trace-count shape bucket
    online_update: bool = True   # zscore: fit on observed traffic
    # transformer: serve with int8 (W8A8) matmuls — ~2x MXU rate on v5e;
    # weights quantize once at load (models/quantized.py)
    quantized: bool = False
    featurizer: FeaturizerConfig = field(default_factory=FeaturizerConfig)
    model_config: Optional[Any] = None  # TransformerConfig / AutoencoderConfig
    checkpoint_path: Optional[str] = None
    socket_path: Optional[str] = None  # model "remote": sidecar unix socket
    remote_timeout_s: float = 10.0  # model "remote": per-call socket deadline
    # data-parallel scoring across chips (BASELINE config #5: dp over
    # v5e-8). 0/1 = single device; N>1 builds an N-device "data" mesh and
    # shards packed rows over it. trace_bucket must divide by N.
    data_parallel: int = 0
    seed: int = 0


class ModelBackend(Protocol):
    def score(self, batch: SpanBatch, features: SpanFeatures) -> np.ndarray:
        """Return per-span anomaly scores, shape (len(batch),)."""


class MockBackend:
    """Deterministic TPU-free backend: score = duration percentile proxy.
    Spans with attr ``mock.anomaly`` always score 1.0 (test hook)."""

    def __init__(self, cfg: EngineConfig):
        self.cfg = cfg

    def score(self, batch: SpanBatch, features: SpanFeatures) -> np.ndarray:
        log_dur = features.continuous[:, 0]
        scores = np.clip((log_dur - 5.0) / 10.0, 0.0, 1.0)
        forced = np.fromiter(("mock.anomaly" in a for a in batch.span_attrs),
                             bool, len(batch))
        return np.where(forced, 1.0, scores).astype(np.float32)


class ZScoreBackend:
    def __init__(self, cfg: EngineConfig):
        from ..models.zscore import ZScoreDetector

        self.cfg = cfg
        self.det = ZScoreDetector()

    def score(self, batch: SpanBatch, features: SpanFeatures) -> np.ndarray:
        z = self.det.score(features)
        if self.cfg.online_update:
            self.det.update(features)
        n_cold = int((z == 0.0).sum())
        if n_cold:
            meter.add(COLD_METRIC, n_cold)
        # map |z| to (0, 1): 1 - exp(-z/4) puts z=3 ≈ 0.53, z=8 ≈ 0.86
        return (1.0 - np.exp(-z / 4.0)).astype(np.float32)

    def warmup(self, batch: SpanBatch) -> None:
        self.det.update(featurize(batch, self.cfg.featurizer))


class SequenceBackend:
    """Transformer / autoencoder scoring over assembled trace sequences.

    Scores are computed per (trace, position) and scattered back to span rows
    via TraceSequences.span_index. Shape bucketing (trace_bucket, max_len)
    bounds XLA recompilation.
    """

    def __init__(self, cfg: EngineConfig):
        import jax

        self.cfg = cfg
        model_config = cfg.model_config
        variables = None
        if cfg.checkpoint_path:
            # serving bundle (training/checkpoint.py): the artifact carries
            # the model geometry, so a pipeline config only needs the path
            from ..training.checkpoint import load_bundle

            bundle = load_bundle(cfg.checkpoint_path)
            if bundle.model != cfg.model:
                raise ValueError(
                    f"checkpoint {cfg.checkpoint_path} holds a "
                    f"{bundle.model!r} model but the engine is configured "
                    f"for {cfg.model!r}")
            if model_config is not None and model_config != bundle.model_config:
                # an explicit geometry that disagrees with the restored
                # weights would mis-index silently (e.g. a too-long
                # positional table clamps instead of erroring)
                raise ValueError(
                    f"model_config disagrees with checkpoint "
                    f"{cfg.checkpoint_path}: {model_config} vs "
                    f"{bundle.model_config}")
            model_config = bundle.model_config
            variables = bundle.variables
        if cfg.model == "transformer":
            from ..models.transformer import TraceTransformer, TransformerConfig

            self.model = TraceTransformer(model_config or TransformerConfig(
                attr_slots=cfg.featurizer.attr_slots))
        else:
            from ..models.autoencoder import AutoencoderConfig, SpanAutoencoder

            self.model = SpanAutoencoder(model_config or AutoencoderConfig(
                attr_slots=cfg.featurizer.attr_slots))
        # the model's positional table bounds the sequence geometry: never
        # pack longer rows than the (possibly restored) model can embed
        self.max_len = min(cfg.max_len, self.model.cfg.max_len)
        self.device_label = str(jax.devices()[0])
        self.last_shape: Optional[list[int]] = None
        self.last_padding_waste: Optional[float] = None
        self.variables = variables if variables is not None else \
            self.model.init(jax.random.PRNGKey(cfg.seed))
        self._packed_score = None
        self._quantized = None
        if cfg.quantized and cfg.model == "transformer":
            if cfg.data_parallel and cfg.data_parallel > 1:
                # refusing beats silently serving bf16 while holding an
                # unused int8 weight copy on device
                raise ValueError(
                    "quantized serving does not compose with "
                    "data_parallel yet; pick one")
            from ..models.quantized import QuantizedTraceScorer

            self._quantized = QuantizedTraceScorer(self.model,
                                                   self.variables)
        if cfg.data_parallel and cfg.data_parallel > 1:
            if cfg.trace_bucket % cfg.data_parallel:
                raise ValueError(
                    f"trace_bucket {cfg.trace_bucket} must be a multiple "
                    f"of data_parallel {cfg.data_parallel}")
            from ..parallel import make_mesh, make_sharded_packed_score_fn

            mesh = make_mesh({"data": cfg.data_parallel})
            self._packed_score = make_sharded_packed_score_fn(
                self.model, mesh)

    def score(self, batch: SpanBatch, features: SpanFeatures) -> np.ndarray:
        import jax.numpy as jnp

        if self.cfg.model == "transformer":
            # packed rows: block-diagonal attention, ~6x the MXU density of
            # naive per-trace padding (bench.py measures this path)
            from ..features.featurizer import pack_sequences

            packed = pack_sequences(batch, features, max_len=self.max_len,
                                    pad_rows_to=self.cfg.trace_bucket)
            # scoring-span attributes: device shape + padding waste (the
            # MXU-density evidence the bench trajectory reads offline)
            self.last_shape = list(packed.categorical.shape[:2])
            self.last_padding_waste = round(1.0 - float(packed.density()), 4)
            if self._packed_score is not None:  # dp across chips
                span_scores = np.asarray(self._packed_score(
                    self.variables, packed.categorical, packed.continuous,
                    packed.segments, packed.positions), dtype=np.float32)
            elif self._quantized is not None:  # int8 serving path
                span_scores = np.asarray(self._quantized.score_packed(
                    jnp.asarray(packed.categorical),
                    jnp.asarray(packed.continuous),
                    jnp.asarray(packed.segments),
                    jnp.asarray(packed.positions)), dtype=np.float32)
            else:
                span_scores = np.asarray(self.model.score_packed(
                    self.variables, jnp.asarray(packed.categorical),
                    jnp.asarray(packed.continuous),
                    jnp.asarray(packed.segments),
                    jnp.asarray(packed.positions)), dtype=np.float32)
            out = np.zeros(len(batch), np.float32)
            m = packed.mask
            out[packed.span_index[m]] = span_scores[m]
            return out

        seqs = assemble_sequences(
            batch, features, max_len=self.max_len,
            pad_traces_to=self.cfg.trace_bucket)
        self.last_shape = list(seqs.categorical.shape[:2])
        self.last_padding_waste = round(1.0 - float(seqs.mask.mean()), 4) \
            if seqs.mask.size else 0.0
        span_scores, _ = self.model.score_spans(
            self.variables, jnp.asarray(seqs.categorical),
            jnp.asarray(seqs.continuous), jnp.asarray(seqs.mask))
        # raw reconstruction error is unbounded; squash to (0, 1) so the
        # processor's threshold contract (score in [0,1]) holds for both
        # sequence models (the transformer path is already a sigmoid)
        span_scores = 1.0 - np.exp(-np.asarray(span_scores, dtype=np.float32))
        out = np.zeros(len(batch), np.float32)
        m = seqs.mask
        out[seqs.span_index[m]] = span_scores[m]
        return out


def _remote_backend(cfg: "EngineConfig"):
    from .sidecar import RemoteBackend

    return RemoteBackend(cfg)


_BACKENDS = {
    "mock": MockBackend,
    "zscore": ZScoreBackend,
    "transformer": SequenceBackend,
    "autoencoder": SequenceBackend,
    "remote": _remote_backend,
}


@dataclass
class ScoreRequest:
    batch: SpanBatch
    features: SpanFeatures
    done: threading.Event = field(default_factory=threading.Event)
    scores: Optional[np.ndarray] = None
    submitted_ns: int = 0


class ScoringEngine:
    """One engine per collector process (shared across pipelines).

    >>> eng = ScoringEngine(EngineConfig(model="zscore")).start()
    >>> scores = eng.score_sync(batch, timeout_s=0.005)  # None on timeout
    """

    def __init__(self, config: Optional[EngineConfig] = None):
        self.cfg = config or EngineConfig()
        if self.cfg.quantized and self.cfg.model != "transformer":
            # same refuse-don't-silently-serve stance as quantized+dp:
            # only the transformer has an int8 path
            raise ValueError(
                f"quantized serving is only implemented for the "
                f"transformer model, not {self.cfg.model!r}")
        try:
            self.backend = _BACKENDS[self.cfg.model](self.cfg)
        except KeyError:
            raise ValueError(
                f"unknown scoring model {self.cfg.model!r} "
                f"(known: {sorted(_BACKENDS)})") from None
        self._queue: queue.Queue[ScoreRequest] = queue.Queue(self.cfg.max_queue)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # first-call latency split: call 0 pays jit compilation on top of
        # execution; the estimated compile share is (first - second) call
        # duration, surfaced as a gauge + span attribute
        self._device_calls = 0
        self._first_call_ms = 0.0

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "ScoringEngine":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._worker, name="scoring-engine", daemon=True)
            self._thread.start()
        return self

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ------------------------------------------------------------- scoring
    def submit(self, batch: SpanBatch,
               features: Optional[SpanFeatures] = None) -> Optional[ScoreRequest]:
        """Enqueue for scoring; returns None (and counts) if queue is full."""
        if features is None and getattr(self.backend, "needs_features", True):
            # a remote backend ships the raw batch and the sidecar
            # featurizes server-side; featurizing here too would pay the
            # host cost twice against the latency budget
            features = featurize(batch, self.cfg.featurizer)
        req = ScoreRequest(batch=batch, features=features,
                           submitted_ns=time.monotonic_ns())
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            meter.add(QUEUE_FULL_METRIC)
            return None
        return req

    def score_sync(self, batch: SpanBatch,
                   features: Optional[SpanFeatures] = None,
                   timeout_s: float = 0.005) -> Optional[np.ndarray]:
        """Submit and wait up to the latency budget; None => pass through."""
        req = self.submit(batch, features)
        if req is None:
            return None
        if req.done.wait(timeout_s):
            return req.scores
        meter.add(PASSTHROUGH_METRIC, len(batch))
        return None

    def warmup(self, batch: SpanBatch) -> None:
        """Feed presumed-normal traffic to streaming backends; also triggers
        jit compilation of the scoring path so first real batch is fast."""
        w = getattr(self.backend, "warmup", None)
        if w is not None:
            w(batch)
        feats = featurize(batch, self.cfg.featurizer)
        self.backend.score(batch, feats)

    # -------------------------------------------------------------- worker
    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            reqs = [first]
            total = len(first.batch)
            # coalesce whatever else is already waiting (bounded)
            while total < self.cfg.max_batch_spans:
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    break
                reqs.append(nxt)
                total += len(nxt.batch)
            try:
                self._score_group(reqs)
            except Exception:
                meter.add("odigos_anomaly_engine_errors_total")
                for r in reqs:
                    r.scores = None
                    r.done.set()

    def _score_group(self, reqs: list[ScoreRequest]) -> None:
        t0 = time.monotonic_ns()
        # scoring exported self-spans (a pipeline dogfooding anomaly
        # detection on internal traces) must not mint new spans about
        # them — the worker thread is outside the suppressed() scope,
        # so the batch marker is the only signal that survives the hop
        span = (NULL_SPAN
                if any(is_selftelemetry_batch(r.batch) for r in reqs)
                else tracer.span("tpu/score"))
        with span as sp:
            if len(reqs) == 1:
                r = reqs[0]
                r.scores = self.backend.score(r.batch, r.features)
                r.done.set()
                n = len(r.batch)
            else:
                from ..pdata.spans import concat_batches

                merged = concat_batches([r.batch for r in reqs])
                feats = None
                if all(r.features is not None for r in reqs):
                    feats = SpanFeatures(
                        np.concatenate([r.features.categorical
                                        for r in reqs]),
                        np.concatenate([r.features.continuous
                                        for r in reqs]))
                scores = self.backend.score(merged, feats)
                off = 0
                for r in reqs:
                    n_r = len(r.batch)
                    r.scores = scores[off:off + n_r]
                    off += n_r
                    r.done.set()
                n = off
            dt_ms = (time.monotonic_ns() - t0) / 1e6
            self._annotate_score_span(sp, reqs, n, t0, dt_ms)
        meter.add(SCORED_METRIC, n)
        meter.record("odigos_anomaly_score_latency_ms", dt_ms)

    def _annotate_score_span(self, sp, reqs: list[ScoreRequest], n: int,
                             t0: int, dt_ms: float) -> None:
        """TPU-stage span attributes: device, coalesced batch shape,
        padding waste, queue wait, and the compile-vs-execute first-call
        split (jit compilation dominates call 0; the difference to call 1
        is the estimated compile share)."""
        sp.set_attr("model", self.cfg.model)
        sp.set_attr("device",
                    getattr(self.backend, "device_label", "host"))
        sp.set_attr("batch.spans", n)
        sp.set_attr("requests", len(reqs))
        sp.set_attr("queue_wait_ms", round(
            (t0 - min(r.submitted_ns for r in reqs)) / 1e6, 3))
        shape = getattr(self.backend, "last_shape", None)
        if shape is not None:
            sp.set_attr("device.shape", "x".join(map(str, shape)))
        waste = getattr(self.backend, "last_padding_waste", None)
        if waste is not None:
            sp.set_attr("padding.waste", waste)
        if self._device_calls == 0:
            self._first_call_ms = dt_ms
            sp.set_attr("jit.first_call", True)
        elif self._device_calls == 1:
            est = max(self._first_call_ms - dt_ms, 0.0)
            sp.set_attr("jit.compile_est_ms", round(est, 3))
            meter.set_gauge("odigos_anomaly_jit_compile_est_ms",
                            round(est, 3))
        self._device_calls += 1
