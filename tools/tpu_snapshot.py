"""Opportunistic TPU bench snapshot runner.

The axon dev tunnel to the TPU is intermittently down; the end-of-round
bench run is hostage to tunnel state at that single instant (rounds 2-3
captured CPU-fallback records while the tunnel was demonstrably up
mid-round).  This runner decouples the record from the round boundary:

    python tools/tpu_snapshot.py [--interval 600] [--max-hours 11]

It loops: probe the device from a killable subprocess; when the probe
succeeds, run the full ``bench.py``, take the LAST JSON line (the bench's
consumer contract), and — only if ``platform`` is a real TPU platform —
write it to ``BENCH_tpu_snapshot.json`` with a capture timestamp, then
exit 0.  CPU-fallback runs are discarded and the loop continues.  A
`make tpu-snapshot` target invokes it once (single probe, no loop) so any
work session can cheaply attempt a capture.

Exit codes: 0 = TPU snapshot written, 3 = gave up (interval exhausted or
--once with tunnel down).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SNAPSHOT = os.path.join(REPO, "BENCH_tpu_snapshot.json")

sys.path.insert(0, REPO)
from bench import _device_reachable as device_up  # noqa: E402 — one probe


def log(*a) -> None:
    print(f"[{datetime.datetime.now():%H:%M:%S}]", *a,
          file=sys.stderr, flush=True)


def run_bench(timeout_s: float = 2400.0) -> dict | None:
    """Run bench.py; return the last JSON line, or None on failure."""
    log("tunnel up — running full bench")
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            timeout=timeout_s, capture_output=True, text=True, cwd=REPO)
    except subprocess.TimeoutExpired:
        log("bench timed out")
        return None
    tail = "\n".join(r.stderr.strip().splitlines()[-12:])
    log(f"bench rc={r.returncode}; stderr tail:\n{tail}")
    last = None
    for line in r.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                last = json.loads(line)
            except json.JSONDecodeError:
                pass
    return last


def attempt() -> bool:
    """One probe→bench→snapshot attempt. True iff a TPU record was saved."""
    if not device_up():
        return False
    rec = run_bench()
    if not rec:
        return False
    if rec.get("platform") in (None, "cpu"):
        log(f"bench fell back to {rec.get('platform')} — discarding")
        return False
    rec["captured_at"] = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    # code identity at capture: bench.py's CPU-fallback path compares
    # this against HEAD so a stale snapshot can't silently stand in for
    # current code (VERDICT r4 item 8)
    rec["git"] = subprocess.run(
        ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
        text=True, cwd=REPO).stdout.strip()
    rec["git_dirty"] = bool(subprocess.run(
        ["git", "status", "--porcelain"], capture_output=True,
        text=True, cwd=REPO).stdout.strip())
    with open(SNAPSHOT, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    log(f"TPU snapshot written to {SNAPSHOT}: "
        f"{rec.get('value'):,} {rec.get('unit')} "
        f"(vs_baseline {rec.get('vs_baseline')})")
    return True


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=600.0,
                    help="seconds between probes (loop mode)")
    ap.add_argument("--max-hours", type=float, default=11.0)
    ap.add_argument("--once", action="store_true",
                    help="single probe+attempt, no loop")
    args = ap.parse_args()

    deadline = time.time() + args.max_hours * 3600
    while True:
        if attempt():
            return 0
        if args.once:
            log("tunnel down (single attempt)")
            return 3
        if time.time() >= deadline:
            log("gave up: max-hours exhausted without a TPU capture")
            return 3
        log(f"tunnel down — next probe in {args.interval:.0f}s")
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
