"""Wire exporter + loadbalancing exporter.

``otlpwire`` exporter: the node→gateway OTLP leg with the generated retry/
queue semantics (autoscaler/controllers/nodecollector/collectorconfig/
traces.go:46-72 retry_on_failure + sending_queue): bounded queue, sender
thread, exponential backoff on connection errors and REJECTED responses.

``loadbalancing`` exporter: consistent trace routing across gateway
replicas (traces.go:26,75-85) so whole-trace operations (tail sampling,
servicegraph, trace-tree anomaly models) see complete traces on one
replica. Routing key is the trace id (vectorized ring lookup); resolver is
a pluggable callable returning the endpoint list (the k8s-resolver role).
"""

from __future__ import annotations

import hashlib
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

import numpy as np

from ..components.api import ComponentKind, Exporter, Factory, Signal, register
from ..hooks.tracecontext import current_trace_context, is_zero_trace_context
from ..pdata.spans import SpanBatch
from ..selftelemetry.tracer import tracer
from ..utils.telemetry import labeled_key, meter
from .codec import frame
from .server import ACCEPTED, MALFORMED


class WireExporter(Exporter):
    """Config:
    endpoint:        "host:port"
    queue_size:      max buffered frames (default 512; overflow drops oldest)
    retry_initial_s: first backoff (default 0.05)
    retry_max_s:     backoff cap (default 2.0)
    retry_jitter:    randomize each sleep over [backoff*(1-j), backoff*(1+j)]
                     (default 0.5, the OTel retry spec's randomization
                     factor; 0 disables). Unjittered exponential backoff
                     SYNCHRONIZES clients against a shed-based admission
                     gate: every backed-off sender fires the instant the
                     gate reopens, re-saturates it in one burst, and
                     doubles again — measured on the soak box as
                     multi-second latency oscillation at a 60 ms gate
                     limit once fast-path intake became handoff-only
                     (ISSUE 9) and REJECTED became the primary pacing
                     signal rather than a rare overload answer.
    max_elapsed_s:   give up on a frame after this long (default 30)
    """

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self._queue: deque[bytes] = deque(
            maxlen=int(config.get("queue_size", 512)))
        # guards the queue→inflight handoff so flush()/queued can never
        # observe the frame in neither place
        self._qlock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._inflight: Optional[bytes] = None
        self._dropped_metric = labeled_key(
            "odigos_exporter_dropped_frames_total", exporter=name)

    # ------------------------------------------------------------ pipeline

    def export(self, batch: SpanBatch) -> None:
        # self-tracing context is captured HERE (caller thread, while the
        # exporter stage span is active), not on the sender thread — the
        # async send must still stamp the span the batch left under
        tp = None
        if tracer.enabled:
            ctx = current_trace_context()
            if not is_zero_trace_context(ctx):
                tp = ctx
        buf = frame(batch, tp)  # encode on caller thread; send is async
        with self._qlock:
            if len(self._queue) == self._queue.maxlen:
                meter.add(self._dropped_metric)
            self._queue.append(buf)
        self._wake.set()

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        super().start()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"otlpwire-send-{self.name}")
        self._thread.start()

    def shutdown(self) -> None:
        self.flush(timeout=float(self.config.get("shutdown_flush_s", 5.0)))
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._close_sock()
        super().shutdown()

    def flush(self, timeout: float = 5.0) -> bool:
        deadline = time.monotonic() + timeout
        while self.queued and time.monotonic() < deadline:
            time.sleep(0.005)
        return not self.queued

    @property
    def queued(self) -> int:
        with self._qlock:
            return len(self._queue) + (1 if self._inflight is not None
                                       else 0)

    # ------------------------------------------------------------ sending

    def _connect(self) -> socket.socket:
        if self._sock is None:
            # service-name endpoints (generated configs address the
            # gateway as odigos-gateway.odigos-system:4317) resolve
            # through the process service registry first, then real DNS
            from .servicemap import resolve_endpoint

            host, port = resolve_endpoint(
                self.config["endpoint"]).rsplit(":", 1)
            self._sock = socket.create_connection((host, int(port)),
                                                  timeout=5.0)
        return self._sock

    def _close_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _send_one(self, buf: bytes) -> bool:
        """True = done with this frame (accepted or malformed-drop);
        False = retry later (connection trouble or server overloaded)."""
        try:
            sock = self._connect()
            sock.sendall(buf)
            status = sock.recv(1)
        except OSError:
            self._close_sock()
            return False
        if status == ACCEPTED:
            return True
        if status == b"":
            # connection died before the ack: keep the frame, reconnect
            self._close_sock()
            return False
        if status == MALFORMED:
            # permanently bad frame: drop it, don't head-of-line block
            meter.add(self._dropped_metric)
            return True
        # REJECTED: server sheds load — back off, keep the frame
        meter.add(f"odigos_exporter_backpressure_total{{exporter={self.name}}}")
        return False

    def _run(self) -> None:
        initial = float(self.config.get("retry_initial_s", 0.05))
        cap = float(self.config.get("retry_max_s", 2.0))
        max_elapsed = float(self.config.get("max_elapsed_s", 30.0))
        # clamped: j >= 1 would yield zero/negative sleeps on the low
        # side of the draw — immediate retries re-synchronize exactly
        # the gate-open stampede the jitter exists to prevent
        jitter = min(max(float(self.config.get("retry_jitter", 0.5)),
                         0.0), 0.9)
        # per-thread PRNG: the sender threads must not share one lock-
        # guarded generator (the whole point is DE-correlating them)
        rng = np.random.default_rng()
        backoff = initial
        frame_started = 0.0
        while not self._stop.is_set():
            # Pop-before-send: holding the frame out of the deque means a
            # producer overflow (deque maxlen displacing the head) can never
            # race us into sending a displaced frame or silently losing the
            # one being retried.
            if self._inflight is None:
                with self._qlock:
                    try:
                        self._inflight = self._queue.popleft()
                    except IndexError:
                        pass
                if self._inflight is None:
                    self._wake.wait(timeout=0.1)
                    self._wake.clear()
                    continue
                frame_started = time.monotonic()
            if self._send_one(self._inflight):
                self._inflight = None
                backoff = initial
            elif time.monotonic() - frame_started > max_elapsed:
                self._inflight = None
                meter.add(self._dropped_metric)
                backoff = initial
            else:
                # randomized interval (OTel retry spec): without it,
                # shed-paced senders synchronize into gate-open
                # stampedes (see the retry_jitter config note)
                self._stop.wait(backoff * (
                    1.0 + jitter * float(rng.uniform(-1.0, 1.0))))
                backoff = min(backoff * 2, cap)


# ------------------------------------------------------------ loadbalancing


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer (shared impl, utils/mix.py): spreads key
    values uniformly over the u64 ring space. Trace ids are NOT uniform
    (agents and the synthesizer hand out small/sequential ids) — placing
    raw ids on an md5-pointed ring sends every trace to the owner of the
    lowest vnode (measured: 100% hot-spotting on one replica)."""
    from ..utils.mix import splitmix64

    return splitmix64(x)


def _ring_points(endpoints: list[str], vnodes: int = 64) -> tuple[np.ndarray, list[str]]:
    """Consistent-hash ring: vnodes points per endpoint, sorted."""
    points = []
    owners = []
    for ep in endpoints:
        for v in range(vnodes):
            h = hashlib.md5(f"{ep}#{v}".encode()).digest()[:8]
            points.append(int.from_bytes(h, "little"))
            owners.append(ep)
    order = np.argsort(np.asarray(points, dtype=np.uint64), kind="stable")
    pts = np.asarray(points, dtype=np.uint64)[order]
    return pts, [owners[i] for i in order]


class LoadBalancingExporter(Exporter):
    """Config:
    endpoints: static endpoint list, or
    resolver:  callable returning the current endpoint list (re-resolved
               every ``resolve_interval_s``, default 5 — the k8s-resolver)
    child:     config dict passed to each per-endpoint WireExporter
    """

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self._children: dict[str, WireExporter] = {}
        self._dropped_metric = labeled_key(
            "odigos_exporter_dropped_frames_total", exporter=name)
        # (ring points, endpoints, vnode -> endpoint index)
        self._ring: tuple[np.ndarray, list[str], np.ndarray] = (
            np.zeros(0, np.uint64), [], np.zeros(0, np.int64))
        resolver = config.get("resolver")
        self._watched_service = ""
        if isinstance(resolver, dict):
            # generated-config spelling (traces.go:26): resolve the k8s
            # service through the process service registry (the cluster-
            # DNS seam the e2e environment populates)
            service = str(resolver.get("k8s", {}).get("service", ""))
            from .servicemap import resolve_service

            if service:
                self._watched_service = service
                resolver = lambda: resolve_service(service)  # noqa: E731
            else:
                resolver = None
        self._resolver: Optional[Callable[[], list[str]]] = resolver
        self._unwatch = None
        self._last_resolve = 0.0
        self._lock = threading.Lock()

    def start(self) -> None:
        super().start()
        if self._watched_service:
            # endpoints-watch semantics: a registration change resolves
            # immediately instead of waiting out the poll interval
            from .servicemap import watch_services

            svc = self._watched_service
            self._unwatch = watch_services(
                lambda name: self._resolve(force=True)
                if name == svc else None)
        self._resolve(force=True)

    def shutdown(self) -> None:
        if self._unwatch is not None:
            self._unwatch()
            self._unwatch = None
        with self._lock:
            children = list(self._children.values())
            self._children = {}
        for child in children:
            child.shutdown()
        super().shutdown()

    def _resolve(self, force: bool = False) -> None:
        now = time.monotonic()
        interval = float(self.config.get("resolve_interval_s", 5.0))
        if not force and now - self._last_resolve < interval:
            return
        self._last_resolve = now
        endpoints = (self._resolver() if self._resolver is not None
                     else list(self.config.get("endpoints", [])))
        with self._lock:
            current = set(self._children)
            wanted = set(endpoints)
            if current == wanted:
                return
            for ep in wanted - current:
                child = WireExporter(
                    f"{self.name}/{ep}",
                    {"endpoint": ep, **self.config.get("child", {})})
                if self._started:
                    child.start()
                self._children[ep] = child
            stale = [self._children.pop(ep) for ep in current - wanted]
            if wanted:
                points, owners = _ring_points(sorted(wanted))
                endpoints = sorted(wanted)
                ep_index = {ep: i for i, ep in enumerate(endpoints)}
                ep_of_point = np.asarray([ep_index[o] for o in owners],
                                         dtype=np.int64)
                self._ring = (points, endpoints, ep_of_point)
            else:
                self._ring = (np.zeros(0, np.uint64), [],
                              np.zeros(0, np.int64))
        for child in stale:
            child.shutdown()

    def export(self, batch: SpanBatch) -> None:
        self._resolve()
        with self._lock:  # ring + children snapshot, consistent pair
            points, endpoints, ep_of_point = self._ring
            children = dict(self._children)
        if not endpoints:
            meter.add(self._dropped_metric)
            return
        # vectorized ring lookup on the HASHED trace id: same trace ->
        # same replica, uniform spread regardless of id distribution
        keys = _mix64(batch.col("trace_id_lo"))
        idx = np.searchsorted(points, keys, side="right") % len(ep_of_point)
        span_ep = ep_of_point[idx]  # vnode -> endpoint, one frame per replica
        for i, ep in enumerate(endpoints):
            child = children.get(ep)
            if child is None:
                continue
            mask = span_ep == i
            if mask.any():
                child.export(batch.filter(mask))

    def flush(self, timeout: float = 5.0) -> bool:
        deadline = time.monotonic() + timeout
        ok = True
        with self._lock:
            children = list(self._children.values())
        for child in children:
            ok &= child.flush(max(0.0, deadline - time.monotonic()))
        return ok


register(Factory(
    type_name="otlpwire", kind=ComponentKind.EXPORTER,
    create=WireExporter, signals=(Signal.TRACES,),
    default_config=lambda: {"queue_size": 512}))

# "otlp" alias for generated destination exporters (otlp/jaeger-... etc.);
# config key "endpoint" carries host:port like the reference's otlp exporter
register(Factory(
    type_name="otlp", kind=ComponentKind.EXPORTER,
    create=WireExporter, signals=(Signal.TRACES,),
    default_config=lambda: {"queue_size": 512}))

register(Factory(
    type_name="loadbalancing", kind=ComponentKind.EXPORTER,
    create=LoadBalancingExporter, signals=(Signal.TRACES,),
    default_config=lambda: {"endpoints": [], "child": {}}))
