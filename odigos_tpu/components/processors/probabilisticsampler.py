"""``probabilisticsampler`` processor — consistent head sampling.

Upstream's probabilisticsamplerprocessor (collector/builder-config.yaml:
77): keep ``sampling_percentage`` of traces, decided by a hash of the
trace id so every span of a trace (on every collector) gets the same
verdict.  Our decision is fully vectorized: one splitmix64 finalizer
over the trace-id columns (the same mixer the load balancer uses —
loadbalancer hot-spot fix, commit 477e3a3 — because raw trace ids from
SDKs are NOT uniformly distributed) produces a uniform u64 per span,
and the batch filters on ``mixed < p * 2^64`` in one numpy op.

Config::

    probabilisticsampler:
      sampling_percentage: 15.0   # 0..100; >=100 keeps everything
      hash_seed: 0                # change to re-roll decisions fleet-wide

Logs sample on trace id too when present; records without one (trace_id
== 0) fall back to a per-record hash of (seed, row index) — the upstream
attribute-source=record behavior.  Metrics pass through untouched
(upstream does not register a metrics pipeline for it).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from ...pdata.logs import LogBatch
from ...pdata.spans import SpanBatch
from ...selftelemetry.flow import FlowContext
from ...utils.mix import splitmix64
from ..api import Capabilities, ComponentKind, Factory, Processor, register


class ProbabilisticSamplerProcessor(Processor):
    """See module docstring."""

    capabilities = Capabilities(mutates_data=True)

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        pct = float(config.get("sampling_percentage", 100.0))
        if pct < 0:
            raise ValueError("sampling_percentage must be >= 0")
        self.fraction = min(pct / 100.0, 1.0)
        self.seed = np.uint64(int(config.get("hash_seed", 0)))
        # threshold in u64 space; the comparison is then one vector op
        self.threshold = np.uint64(
            min(int(self.fraction * float(2**64)), 2**64 - 1))
        # traceless records hash a RUNNING counter, not the batch row
        # position — position is constant across batches (one-record
        # batches would be all-kept or all-dropped forever)
        self._record_counter = 0

    def _keep_mask(self, hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
        with np.errstate(over="ignore"):
            mixed = splitmix64(hi ^ splitmix64(lo ^ self.seed))
        return mixed < self.threshold

    def process(self, batch: Any) -> Any:
        if self.fraction >= 1.0:
            return batch
        if isinstance(batch, SpanBatch) and len(batch):
            keep = self._keep_mask(batch.col("trace_id_hi"),
                                   batch.col("trace_id_lo"))
            if keep.all():
                return batch
            FlowContext.drop(int((~keep).sum()), "sampled",
                             component=self)
            return batch.filter(keep)
        if isinstance(batch, LogBatch) and len(batch):
            hi = batch.col("trace_id_hi")
            lo = batch.col("trace_id_lo")
            keep = self._keep_mask(hi, lo)
            # traceless records: hash (seed, row) so the keep-rate still
            # holds (upstream attribute_source=record fallback)
            traceless = (hi == 0) & (lo == 0)
            if traceless.any():
                idx = (np.arange(len(batch), dtype=np.uint64)
                       + np.uint64(self._record_counter))
                self._record_counter += len(batch)
                with np.errstate(over="ignore"):
                    alt = splitmix64(idx ^ self.seed) < self.threshold
                keep = np.where(traceless, alt, keep)
            if keep.all():
                return batch
            FlowContext.drop(int((~keep).sum()), "sampled",
                             component=self)
            return batch.filter(keep)
        return batch


register(Factory(
    type_name="probabilisticsampler",
    kind=ComponentKind.PROCESSOR,
    create=ProbabilisticSamplerProcessor,
    default_config=lambda: {"sampling_percentage": 100.0},
))
