"""``count`` connector — telemetry in, count metrics out.

Upstream's countconnector (collector/builder-config.yaml countconnector):
counts the items flowing through a pipeline and emits them as SUM
metrics to downstream metrics pipelines. Works on any pdata batch type;
the default metric names follow the upstream convention
(``trace.span.count`` / ``log.record.count`` / ``metric.count``), one
point per (service) group for spans — the vectorized bincount over the
columnar batch, never a per-span loop.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from ...pdata.logs import LogBatch
from ...pdata.metrics import MetricBatch, MetricBatchBuilder, MetricType
from ...pdata.spans import SpanBatch
from ...utils.telemetry import labeled_key, meter
from ..api import ComponentKind, Connector, Factory, register


class CountConnector(Connector):
    """Config: span_metric / log_metric / metric_metric override the
    emitted metric names."""

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self._points_metric = labeled_key(
            "odigos_connector_points_total", connector=name)

    def consume(self, batch: Any) -> None:
        if not batch:
            return
        out = self.aggregate(batch)
        meter.add(self._points_metric, len(out))
        for consumer in self.outputs.values():
            consumer.consume(out)

    def aggregate(self, batch: Any) -> MetricBatch:
        now = time.time_ns()
        b = MetricBatchBuilder()
        if isinstance(batch, SpanBatch):
            name = str(self.config.get("span_metric", "trace.span.count"))
            svc = batch.col("service").astype(np.int64)
            counts = np.bincount(svc, minlength=int(svc.max()) + 1
                                 if len(svc) else 0)
            for sid in np.nonzero(counts)[0]:
                b.add_point(
                    name=name, value=float(counts[sid]),
                    metric_type=MetricType.SUM, time_unix_nano=now,
                    attrs={"service.name": batch.string_at(int(sid))})
        elif isinstance(batch, LogBatch):
            b.add_point(
                name=str(self.config.get("log_metric",
                                         "log.record.count")),
                value=float(len(batch)), metric_type=MetricType.SUM,
                time_unix_nano=now)
        elif isinstance(batch, MetricBatch):
            b.add_point(
                name=str(self.config.get("metric_metric", "metric.count")),
                value=float(len(batch)), metric_type=MetricType.SUM,
                time_unix_nano=now)
        return b.build()


register(Factory(
    type_name="count",
    kind=ComponentKind.CONNECTOR,
    create=CountConnector,
    default_config=dict,
))
