"""Pipeline-graph instrumentation: the per-stage span weave.

The graph builder wraps every pipeline entry with ``TracedEntry`` so each
batch entering a pipeline opens one ``pipeline/<name>`` span. Component
base classes (``components.api``) open the per-stage spans *flat* under
it — a stage span covers the stage's own work only, downstream consume
happens after the span closes — so sibling stage latencies sum to the
pipeline span's duration (the "where does the time go" view the soak
p99 investigation was missing), instead of telescoping cumulatively.

Every entry also records the batch's wall time into the
``odigos_pipeline_batch_latency_ms{pipeline=...}`` histogram, with the
pipeline span attached as an **exemplar** — the /metrics tail links
straight back to the self-trace that populated it (ISSUE 3).
"""

from __future__ import annotations

import time

from ..pdata.spans import SpanBatch
from ..utils.telemetry import labeled_key, meter
from .tracer import is_selftelemetry_batch, tracer

BATCH_LATENCY_METRIC = "odigos_pipeline_batch_latency_ms"


class TracedEntry:
    """Wraps a pipeline's entry consumer with a per-batch pipeline span.

    Transparent when tracing is disabled (one attribute load + branch —
    the latency histogram rides the traced path only, so minimal
    installs with ``ODIGOS_SELFTRACE=0`` pay neither the clock reads nor
    the meter lock); exceptions propagate unchanged either way
    (memory-limiter rejections must still reach the receiver's
    backpressure path)."""

    __slots__ = ("pipeline", "inner", "_latency_key")

    def __init__(self, pipeline: str, inner):
        self.pipeline = pipeline
        self.inner = inner
        # pipeline names come from config — sanitize once at construction
        self._latency_key = labeled_key(BATCH_LATENCY_METRIC,
                                        pipeline=pipeline)

    def consume(self, batch: SpanBatch) -> None:
        if not tracer.enabled or is_selftelemetry_batch(batch):
            self.inner.consume(batch)
            return
        t0 = time.monotonic_ns()
        with tracer.span(f"pipeline/{self.pipeline}") as sp:
            sp.set_attr("batch.spans", len(batch))
            self.inner.consume(batch)
        # record AFTER the span closes so the exemplar points at a
        # completed, ring-resident trace (a suppressed context hands out
        # the id-less NULL span: no exemplar, latency still recorded)
        tid = getattr(sp, "trace_id", None)
        meter.record(self._latency_key, (time.monotonic_ns() - t0) / 1e6,
                     exemplar=(tid, sp.span_id) if tid is not None
                     else None)


def trace_pipeline_entry(pipeline: str, entry) -> TracedEntry:
    return TracedEntry(pipeline, entry)
