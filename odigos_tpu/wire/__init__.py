"""Network wire path: OTLP-role framed transport between collectors.

The reference's node→gateway leg is OTLP gRPC with a forked configgrpc that
rejects messages *before decoding* under memory pressure (SURVEY.md §2.3
configgrpc fork, §2.7 backpressure). Here:

* ``codec``     — columnar frame format (SpanBatch ⇄ bytes, zero per-span work)
* ``server``    — ``otlpwire`` receiver with pre-decode admission control
                  feeding the rejection metric the HPA scales on
* ``client``    — ``otlpwire`` exporter (bounded queue, retry w/ backoff) and
                  ``loadbalancing`` exporter (consistent trace routing so
                  whole traces land on one gateway replica)
* ``hotreload`` — ConfigMap watcher driving Collector.reload
                  (odigosk8scmprovider role)
"""

from .codec import decode_batch, encode_batch  # noqa: F401
from .server import WireReceiver  # noqa: F401
from .client import LoadBalancingExporter, WireExporter  # noqa: F401
from .hotreload import watch_configmap  # noqa: F401
