"""``pprof`` extension — in-process profiling endpoint.

Upstream's pprofextension (collector/builder-config.yaml:12) exposes Go
pprof. The Python-runtime analog serves:

* ``/debug/threadz``  — instantaneous stacks of every thread (the
                        goroutine-dump role; first stop for a wedged
                        pipeline)
* ``/debug/profile?seconds=S&hz=H`` — statistical sampling profile:
  samples ``sys._current_frames`` at H hz for S seconds and returns
  collapsed stacks with counts (flamegraph-ready "folded" format, one
  ``frame;frame;frame count`` line per stack), JSON-wrapped.

Sampling happens in the handler thread: the data plane pays only the
GIL checkpoints it already pays, nothing runs when nobody asks.

Debug-only: binds loopback. Config: ``endpoint``/``host``/``port``,
``max_seconds`` (profile cap, default 30).
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter
from typing import Any

from ..api import ComponentKind, Factory, register
from .httpbase import HttpExtension, Page


def thread_stacks() -> dict[str, list[str]]:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        stack = [f"{f.filename}:{f.lineno}:{f.name}"
                 for f in traceback.extract_stack(frame)]
        out[names.get(ident, str(ident))] = stack
    return out


def sample_profile(seconds: float, hz: float) -> list[str]:
    """Collapsed-stack statistical profile of every thread."""
    interval = 1.0 / max(hz, 1.0)
    me = threading.get_ident()
    counts: Counter = Counter()
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            stack = ";".join(
                f.name for f in traceback.extract_stack(frame))
            counts[stack] += 1
        time.sleep(interval)
    return [f"{stack} {n}" for stack, n in counts.most_common()]


class PprofExtension(HttpExtension):
    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self.max_seconds = float(config.get("max_seconds", 30.0))

    def _threadz(self, q: dict[str, str]) -> tuple[int, dict]:
        return 200, {"threads": thread_stacks()}

    def _profile(self, q: dict[str, str]) -> tuple[int, dict]:
        seconds = min(float(q.get("seconds", 1.0)), self.max_seconds)
        hz = min(float(q.get("hz", 97.0)), 997.0)
        return 200, {"seconds": seconds, "hz": hz,
                     "folded": sample_profile(seconds, hz)}

    def pages(self) -> dict[str, Page]:
        return {"/debug/threadz": self._threadz,
                "/debug/profile": self._profile}


register(Factory(
    type_name="pprof",
    kind=ComponentKind.EXTENSION,
    create=PprofExtension,
    default_config=lambda: {"port": 0},
))
