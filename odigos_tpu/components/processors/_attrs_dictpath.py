"""Reference dict-path implementations of attrs-touching hot stages.

These are the pre-columnar per-span-dict code paths, kept (a) as the
fallback when ``columnar_enabled()`` is off and (b) as the ground truth
the parity suite and the bench A/B compare the columnar ports against.
They are NOT on the default hot path — the package-hygiene lint forbids
per-span ``span_attrs`` iteration in the scoring-route modules, and this
module is its one sanctioned home.
"""

from __future__ import annotations

from typing import Any

import numpy as np

_MISSING = object()


def filter_attr_eq_mask(batch, key: str, want: Any) -> np.ndarray:
    """Dict path of the filter processor's ``attr: {key, value}`` clause."""
    return np.fromiter(
        (a.get(key, _MISSING) == want for a in batch.span_attrs),
        bool, len(batch))


def filter_attr_has_mask(batch, key: str) -> np.ndarray:
    """Dict path of the filter processor's attr PRESENCE clause."""
    return np.fromiter((key in a for a in batch.span_attrs),
                       bool, len(batch))


def flagged_mask(batch, flag: str) -> np.ndarray:
    """Dict path of the anomaly-router / mock-backend flag probe."""
    return np.fromiter((flag in a for a in batch.span_attrs),
                       bool, len(batch))


def copy_span_attr_dicts(batch) -> list[dict[str, Any]]:
    """Dict path of the attributes processor's working copy."""
    return [dict(d) for d in batch.span_attrs]


def featurize_attr_slots(batch, slot_fn, slots: int,
                         vocab: int) -> np.ndarray:
    """Dict path of the featurizer's attr-slot hashing (per-span loop,
    cached per distinct dict content via ``slot_fn``'s lru_cache)."""
    out = np.empty((len(batch), slots), dtype=np.int32)
    for i, attrs in enumerate(batch.span_attrs):
        if attrs:
            key = tuple(sorted((k, str(v)) for k, v in attrs.items()))
            out[i] = slot_fn(key, slots, vocab)
        else:
            out[i] = 0
    return out
