"""Configuration model.

Mirrors the capability surface of ``common.OdigosConfiguration``
(common/odigos_config.go:362-402: ~40 fields covering namespaces to ignore,
gateway/node collector tuning, profiles, rollout/rollback knobs, mount and
env-injection methods, metrics sources) re-shaped for this framework: the
TPU anomaly stage gets its own first-class section (``anomaly``) instead of
being bolted on, and collector resource settings carry the memory-limiter
derivation inputs (scheduler/controllers/clustercollectorsgroup/
resource_config.go:8-39).
"""

from __future__ import annotations

import enum
from dataclasses import MISSING, asdict, dataclass, field, fields, is_dataclass
from typing import Any, Optional


class Tier(str, enum.Enum):
    COMMUNITY = "community"
    CLOUD = "cloud"
    ONPREM = "onprem"


class UiMode(str, enum.Enum):
    NORMAL = "normal"
    READONLY = "readonly"


class MountMethod(str, enum.Enum):
    """How agent files reach the workload (reference: k8s-host-path vs
    k8s-virtual-device, common/odigos_config.go MountMethod)."""

    HOST_PATH = "k8s-host-path"
    VIRTUAL_DEVICE = "k8s-virtual-device"


class EnvInjectionMethod(str, enum.Enum):
    """Reference: loader (LD_PRELOAD), pod-manifest, loader-fallback-to-pod-manifest."""

    LOADER = "loader"
    POD_MANIFEST = "pod-manifest-env-var-injection"
    LOADER_FALLBACK = "loader-fallback-to-pod-manifest"


@dataclass
class CollectorGatewayConfiguration:
    """Gateway (cluster collector) tuning. Defaults resolved by sizing
    presets; memory-limiter values derived in sizing.gateway_resources."""

    min_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    request_memory_mib: Optional[int] = None
    limit_memory_mib: Optional[int] = None
    request_cpu_m: Optional[int] = None
    limit_cpu_m: Optional[int] = None
    memory_limiter_limit_mib: Optional[int] = None
    memory_limiter_spike_limit_mib: Optional[int] = None
    gomemlimit_mib: Optional[int] = None
    service_graph_disabled: Optional[bool] = None
    cluster_metrics_enabled: Optional[bool] = None
    # TPU co-scheduling: how many gateway replicas should be co-located with
    # a TPU device for the anomaly stage (north-star extension).
    tpu_replicas: Optional[int] = None
    # Multi-chip sizing knob (ISSUE 7): how many TPU mesh slices the
    # autoscaler may co-schedule. Each TPU-backed gateway replica owns one
    # whole slice of anomaly.devices × anomaly.tensor_parallel chips (the
    # engine's dp×tp mesh); None = as many as the device pools can back.
    mesh_slices: Optional[int] = None
    # export retry/spill (ISSUE 13): a mapping ({} = defaults) stamps a
    # ``retry:`` stanza onto every destination exporter the gateway
    # config renders — bounded jittered-backoff + spill queue around a
    # destination outage, terminal drops named queue_full/
    # shutdown_drain (components/exporters/retryqueue.py). None renders
    # nothing (existing configs stay byte-identical). Keys:
    # initial_backoff_ms / max_backoff_ms / jitter / max_queue_spans /
    # drain_timeout_s.
    export_retry: Optional[dict] = None


@dataclass
class CollectorNodeConfiguration:
    """Node collector (daemonset) tuning (common/odigos_config.go
    CollectorNodeConfiguration)."""

    collector_owner_metrics_port: Optional[int] = None
    request_memory_mib: Optional[int] = None
    limit_memory_mib: Optional[int] = None
    request_cpu_m: Optional[int] = None
    limit_cpu_m: Optional[int] = None
    memory_limiter_limit_mib: Optional[int] = None
    memory_limiter_spike_limit_mib: Optional[int] = None
    gomemlimit_mib: Optional[int] = None
    k8s_node_logs_directory: Optional[str] = None


@dataclass
class RolloutConfiguration:
    """Automatic-rollout knobs (common/odigos_config.go Rollout*,
    :389-391 rollback grace/stability)."""

    automatic_rollout_disabled: Optional[bool] = None
    rollback_disabled: Optional[bool] = None
    rollback_grace_time_s: float = 300.0
    rollback_stability_window_s: float = 3600.0


@dataclass
class SloConfiguration:
    """Declarative service-level objectives for the anomaly pipeline
    (ISSUE 8): rendered by pipelinegen as the root traces pipeline's
    ``slo:`` stanza and evaluated with Google-SRE-style fast/slow-window
    burn rates (selftelemetry/latency.SloTracker). A p99 latency target
    affords a 1 % error budget; a scored-fraction target Y affords 1−Y.
    Both objectives optional — None renders nothing (byte-stable
    configs for installs without SLOs)."""

    latency_p99_ms: Optional[float] = None
    scored_fraction: Optional[float] = None
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0


@dataclass
class AlertRuleConfiguration:
    """One declarative fleet alert rule (ISSUE 10): ``expr`` is a
    window expression over the series store
    (``fn(metric{k=v,...}[Ns]) <op> number``, grammar in
    selftelemetry/fleet.parse_expr), ``for_s`` the hold duration a
    breach must persist before the rule fires (recovery clears), and
    ``severity`` maps to the HealthRollup condition raised while firing
    (critical -> Unhealthy, else Degraded). Rendered by pipelinegen as
    the gateway config's ``service.alerts`` stanza and validated by
    graph.validate_config — a typo'd rule dies at load, never silently
    sits dark."""

    name: str = ""
    expr: str = ""
    for_s: float = 0.0
    severity: str = "warning"


@dataclass
class AnomalyStageConfiguration:
    """First-class config for the TPU anomaly-detection stage (north star:
    tpuanomalyprocessor + anomalyrouter + TPU sidecar)."""

    enabled: bool = False
    model: str = "zscore"  # zscore | autoencoder | transformer
    threshold: float = 0.8  # score in [0,1] (ScoringEngine contract)
    max_batch: int = 4096
    timeout_ms: float = 5.0  # pass-through-on-timeout budget (<5ms p99)
    route_to_stream: str = "anomalies"
    devices: int = 1  # data-parallel chips ("data" mesh axis) per replica
    # tensor-parallel shards ("model" mesh axis) per replica: the engine
    # serves on a devices × tensor_parallel mesh (ISSUE 7); heads/d_ff
    # shard per parallel.PARTITION_RULES. 1 = pure data parallelism.
    tensor_parallel: int = 1
    # ingest fast path (ISSUE 6): wire frames featurize once at the
    # receiver and score through the engine's deadline-based adaptive
    # coalescer, bypassing the componentwise batch/score seams; the
    # scoring timeout doubles as the per-frame admission deadline
    fast_path: bool = False
    # completion-driven multi-lane retirement (ISSUE 9): number of
    # retirement lanes overlapping tag/forward of independent frames
    # (rendered as fast_path.lanes; only meaningful with fast_path)
    fast_path_lanes: int = 4
    # true = forward downstream in intake order (the single-forwarder
    # FIFO contract, byte-identical output order) at the cost of
    # serializing the forward leg; false = forward as completed
    fast_path_ordered: bool = False
    # predictive deadline-burn shed (ISSUE 12): frames the burn table
    # prices past the admission deadline are REJECTED before featurize
    # spends host time on them (blame=predicted); rendered as
    # fast_path.predictive
    fast_path_predictive: bool = True
    # fused device-side featurize→pack→score (ISSUE 19): the submit
    # lane hands the engine raw span columns and one jitted call does
    # hashing, the parent join, packing, and the model forward;
    # rendered as fast_path.fused ONLY when true (opt-in — existing
    # configs stay byte-identical), kill-switchable via ODIGOS_FUSED=0
    fast_path_fused: bool = False
    # declarative burn-rate SLOs for the root traces pipeline (ISSUE 8);
    # None renders nothing — existing configs stay byte-identical
    slo: Optional[SloConfiguration] = None
    # failover breaker for the scoring engine (ISSUE 13): a mapping
    # ({} = defaults; keys per serving/failover.FailoverConfig —
    # window_s, trip_errors, probe_interval_s, recovery_successes,
    # fallback_model) rendered as the tpuanomaly processor's
    # ``failover:`` knob. A persistent device fault then hot-swaps
    # scoring to the zscore CPU route (ModelFailover condition,
    # odigos_failover_* metrics) and half-open probes the primary back.
    # None renders nothing — existing configs stay byte-identical.
    failover: Optional[dict] = None


@dataclass
class SelfTelemetryConfiguration:
    """Continuous profiler + device-runtime telemetry knobs (ISSUE 3;
    rendered into the gateway config's ``service.telemetry`` stanza and
    applied by the collector via ``selftelemetry.start_from_config``).
    Disabled by default: the subsystem is a strict no-op unless opted
    in — no sampler thread, no collector thread, nothing allocated."""

    profiler_enabled: bool = False
    profiler_hz: float = 19.0       # prime default: no aliasing
    profiler_window_s: float = 60.0
    profiler_windows: int = 12      # bounded ring: 12 x 60 s
    device_runtime_enabled: bool = False
    device_runtime_interval_s: float = 10.0


@dataclass
class MetricsSourcesConfiguration:
    """Which metrics feeds are enabled (common/odigos_config.go
    MetricsSourceConfiguration: spanMetrics/hostMetrics/kubeletStats/
    odigosOwnMetrics/agentMetrics)."""

    span_metrics: bool = False
    host_metrics: bool = False
    kubelet_stats: bool = False
    own_metrics: bool = True
    agent_metrics: bool = False


@dataclass
class OidcConfiguration:
    tenant_url: str = ""
    client_id: str = ""
    client_secret: str = ""


@dataclass
class UserInstrumentationEnvs:
    """Per-language extra env for agents (common/odigos_config.go
    UserInstrumentationEnvs)."""

    languages: dict[str, dict[str, str]] = field(default_factory=dict)


@dataclass
class Configuration:
    """The single authored configuration object (ConfigMap analog)."""

    config_version: int = 1
    telemetry_enabled: bool = False
    ignored_namespaces: list[str] = field(default_factory=list)
    ignored_containers: list[str] = field(default_factory=list)
    ignore_odigos_namespace: bool = True
    image_prefix: str = ""
    cluster_name: str = ""
    # connected control-plane version (the CLI's autodetect role,
    # cli/pkg/autodetect); feature gates key on it
    cluster_version: str = "1.30"
    ui_mode: UiMode = UiMode.NORMAL
    ui_pagination_limit: int = 0
    # where collectors ship their own-telemetry metrics stream (the
    # frontend's collector-metrics consumer listens here); tests point it
    # at an ephemeral local port
    ui_endpoint: str = "ui.odigos-system:4317"
    collector_gateway: CollectorGatewayConfiguration = field(
        default_factory=CollectorGatewayConfiguration)
    collector_node: CollectorNodeConfiguration = field(
        default_factory=CollectorNodeConfiguration)
    profiles: list[str] = field(default_factory=list)
    allow_concurrent_agents: Optional[bool] = None
    mount_method: Optional[MountMethod] = None
    agent_env_vars_injection_method: Optional[EnvInjectionMethod] = None
    user_instrumentation_envs: UserInstrumentationEnvs = field(
        default_factory=UserInstrumentationEnvs)
    rollout: RolloutConfiguration = field(default_factory=RolloutConfiguration)
    oidc: Optional[OidcConfiguration] = None
    resource_size_preset: str = ""  # "", size_s, size_m, size_l
    metrics_sources: MetricsSourcesConfiguration = field(
        default_factory=MetricsSourcesConfiguration)
    anomaly: AnomalyStageConfiguration = field(
        default_factory=AnomalyStageConfiguration)
    selftelemetry: SelfTelemetryConfiguration = field(
        default_factory=SelfTelemetryConfiguration)
    # declarative fleet alert rules (ISSUE 10): rendered into the
    # gateway config's service.alerts stanza; empty list renders
    # nothing (byte-stable configs for installs without alerts)
    alerts: list[AlertRuleConfiguration] = field(default_factory=list)
    # closed-loop actuator (ISSUE 15): a mapping rendered as the
    # gateway config's service.actuator stanza (enabled, dry_run,
    # judgment_window_s, cooldown_s, max_step, knobs allowlist,
    # max_history — validated at load by controlplane/actuator.py).
    # None renders nothing (byte-stable configs; the loop stays open
    # unless the operator closes it).
    actuator: Optional[dict] = None
    # Free-form bag for profile-applied settings without a dedicated field
    # (reference profiles patch arbitrary config, e.g. disable-gin).
    extra: dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, data: dict[str, Any]) -> "Configuration":
        return _from_dict(cls, data)


# Optional nested-dataclass fields (default=None, so no default_factory to
# infer the type from at runtime under `from __future__ import annotations`)
_OPTIONAL_NESTED: dict[str, type] = {"oidc": OidcConfiguration,
                                     "slo": SloConfiguration}

# list-of-dataclass fields (default_factory=list hides the element type
# at runtime under deferred annotations, like _OPTIONAL_NESTED above)
_LIST_NESTED: dict[str, type] = {"alerts": AlertRuleConfiguration}


def _from_dict(cls, data):
    """Tolerant nested-dataclass hydration (unknown keys land in extra)."""
    if not is_dataclass(cls):
        return data
    known = {f.name: f for f in fields(cls)}
    kwargs = {}
    extra = {}
    for k, v in (data or {}).items():
        if k not in known:
            extra[k] = v
            continue
        f = known[k]
        # resolve nested dataclass types by default_factory class
        if isinstance(v, dict) and f.default_factory is not MISSING \
                and f.default_factory is not dict and is_dataclass(f.default_factory):
            kwargs[k] = _from_dict(f.default_factory, v)
        elif isinstance(v, dict) and k in _OPTIONAL_NESTED:
            kwargs[k] = _from_dict(_OPTIONAL_NESTED[k], v)
        elif isinstance(v, list) and k in _LIST_NESTED:
            kwargs[k] = [_from_dict(_LIST_NESTED[k], item)
                         if isinstance(item, dict) else item
                         for item in v]
        else:
            kwargs[k] = v
    obj = cls(**kwargs)
    if extra and hasattr(obj, "extra"):
        obj.extra.update(extra)
    return obj
