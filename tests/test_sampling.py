"""Tail-sampling rule engine + groupbytrace buffering tests (the analog of
the reference's rule_engine_test.go and internal/sampling/*_test.go)."""

import numpy as np
import pytest

from odigos_tpu.components.processors.groupbytrace import GroupByTraceProcessor
from odigos_tpu.components.processors.sampling import (
    ErrorRule, LatencyRule, RuleEngine, SamplingProcessor, ServiceNameRule,
    SpanAttributeRule, parse_rule)
from odigos_tpu.pdata import (
    SpanBatchBuilder, SpanKind, StatusCode, TraceView, concat_batches)


def make_trace(builder, trace_id, service="svc", n=3, *, error=False,
               duration_ms=10.0, attrs=None, route=None):
    """n spans, one root; trace wall time = duration_ms."""
    start = 1_000_000_000
    end = start + int(duration_ms * 1e6)
    for i in range(n):
        span_attrs = dict(attrs or {})
        if route is not None and i == 0:
            span_attrs["http.route"] = route
        builder.add_span(
            trace_id=trace_id, span_id=trace_id * 100 + i + 1,
            parent_span_id=0 if i == 0 else trace_id * 100 + 1,
            name=f"op-{i}", service=service,
            kind=SpanKind.SERVER if i == 0 else SpanKind.INTERNAL,
            status_code=StatusCode.ERROR if (error and i == n - 1)
            else StatusCode.UNSET,
            start_unix_nano=start + i, end_unix_nano=end - i,
            attrs=span_attrs)


def build(*specs):
    b = SpanBatchBuilder()
    for spec in specs:
        make_trace(b, **spec)
    return b.build()


def kept_trace_ids(batch, keep_mask=None):
    if keep_mask is not None:
        view = TraceView.of(batch)
        batch = batch.filter(view.span_mask_for(keep_mask))
    return sorted(set(batch.col("trace_id_lo").tolist()))


# ------------------------------------------------------------- TraceView
def test_trace_view_reductions():
    batch = build({"trace_id": 1, "n": 4, "duration_ms": 50},
                  {"trace_id": 2, "n": 2, "duration_ms": 5, "error": True})
    view = TraceView.of(batch)
    assert view.n_traces == 2
    assert view.count_per_trace().tolist() == [4, 2]
    err = view.any_per_trace(batch.col("status_code") == StatusCode.ERROR)
    assert err.tolist() == [False, True]
    assert view.duration_ms[0] == pytest.approx(50, abs=1e-3)
    assert view.duration_ms[1] == pytest.approx(5, abs=1e-3)


# ----------------------------------------------------------------- rules
def test_error_rule_keeps_errors_drops_rest():
    batch = build({"trace_id": 1, "error": True}, {"trace_id": 2})
    engine = RuleEngine([ErrorRule(fallback_sampling_ratio=0.0)], [], [],
                        seed=0)
    keep = engine.keep_traces(TraceView.of(batch))
    assert kept_trace_ids(batch, keep) == [1]


def test_error_rule_fallback_ratio_statistical():
    b = SpanBatchBuilder()
    for t in range(1, 401):
        make_trace(b, t, n=1)
    batch = b.build()
    engine = RuleEngine([ErrorRule(fallback_sampling_ratio=50.0)], [], [],
                        seed=0)
    keep = engine.keep_traces(TraceView.of(batch))
    assert 0.35 < keep.mean() < 0.65  # ~50%


def test_latency_rule_threshold_and_scope():
    batch = build(
        {"trace_id": 1, "service": "frontend", "route": "/buy",
         "duration_ms": 2000},  # slow → keep
        {"trace_id": 2, "service": "frontend", "route": "/buy/item",
         "duration_ms": 10},    # fast, prefix match → fallback (0) → drop
        {"trace_id": 3, "service": "frontend", "route": "/sell",
         "duration_ms": 9000},  # route mismatch → unmatched → keep
        {"trace_id": 4, "service": "backend", "route": "/buy",
         "duration_ms": 9000})  # service mismatch → unmatched → keep
    rule = LatencyRule(service_name="frontend", http_route="/buy",
                       threshold=1000, fallback_sampling_ratio=0.0)
    engine = RuleEngine([], [], [rule], seed=0)
    keep = engine.keep_traces(TraceView.of(batch))
    assert kept_trace_ids(batch, keep) == [1, 3, 4]


def test_service_name_rule():
    batch = build({"trace_id": 1, "service": "a"},
                  {"trace_id": 2, "service": "b"})
    engine = RuleEngine([], [ServiceNameRule(
        service_name="a", sampling_ratio=100.0)], [], seed=0)
    keep = engine.keep_traces(TraceView.of(batch))
    assert kept_trace_ids(batch, keep) == [1, 2]  # b unmatched → kept
    engine = RuleEngine([], [ServiceNameRule(
        service_name="a", sampling_ratio=0.0)], [], seed=0)
    keep = engine.keep_traces(TraceView.of(batch))
    assert kept_trace_ids(batch, keep) == [2]  # a matched at 0% → dropped


@pytest.mark.parametrize("ctype,op,expected,attrs,hit", [
    ("string", "equals", "x", {"k": "x"}, True),
    ("string", "equals", "x", {"k": "y"}, False),
    ("string", "contains", "bc", {"k": "abcd"}, True),
    ("string", "regex", r"^a\d+$", {"k": "a123"}, True),
    ("number", "greater_than", "10", {"k": 11}, True),
    ("number", "greater_than", "10", {"k": 9.5}, False),
    ("boolean", "equals", "true", {"k": True}, True),
    ("json", "key_equals", "1", {"k": '{"a": {"b": 1}}'}, True),
    ("json", "contains_key", "", {"k": '{"a": {"b": 1}}'}, True),
    ("json", "is_invalid_json", "", {"k": "{nope"}, True),
])
def test_span_attribute_rule(ctype, op, expected, attrs, hit):
    batch = build({"trace_id": 1, "attrs": attrs})
    rule = SpanAttributeRule(
        service_name="svc", attribute_key="k", condition_type=ctype,
        operation=op, expected_value=expected,
        json_path="$.a.b" if ctype == "json" else "",
        sampling_ratio=100.0, fallback_sampling_ratio=0.0)
    rule.validate()
    res = rule.evaluate(TraceView.of(batch))
    assert bool(res.satisfied[0]) is hit


def test_level_priority_global_decides_first():
    # error rule (global) satisfied at 100 beats endpoint latency fallback 0
    batch = build({"trace_id": 1, "service": "frontend", "route": "/buy",
                   "duration_ms": 1, "error": True})
    engine = RuleEngine(
        [ErrorRule(fallback_sampling_ratio=0.0)], [],
        [LatencyRule(service_name="frontend", http_route="/buy",
                     threshold=1000, fallback_sampling_ratio=0.0)], seed=0)
    keep = engine.keep_traces(TraceView.of(batch))
    assert keep.tolist() == [True]


def test_min_fallback_across_levels():
    # no rule satisfied; matched fallbacks 40 (global) and 10 (endpoint):
    # min = 10 applies
    batch = build({"trace_id": 1, "service": "frontend", "route": "/buy",
                   "duration_ms": 1})
    engine = RuleEngine(
        [ErrorRule(fallback_sampling_ratio=40.0)], [],
        [LatencyRule(service_name="frontend", http_route="/buy",
                     threshold=1000, fallback_sampling_ratio=10.0)], seed=0)
    T = 2000
    rng_keep = []
    for seed in range(3):
        engine._rng = np.random.default_rng(seed)
        b = SpanBatchBuilder()
        for t in range(1, T + 1):
            make_trace(b, t, service="frontend", route="/buy", duration_ms=1)
        keep = engine.keep_traces(TraceView.of(b.build()))
        rng_keep.append(keep.mean())
    assert 0.05 < np.mean(rng_keep) < 0.16  # ~10%, not ~40%


def test_parse_rule_validation():
    with pytest.raises(ValueError, match="unknown rule type"):
        parse_rule({"name": "x", "type": "nope", "rule_details": {}})
    with pytest.raises(ValueError, match="threshold"):
        parse_rule({"name": "x", "type": "http_latency",
                    "rule_details": {"service_name": "a", "http_route": "/"}})
    with pytest.raises(ValueError, match="must start with"):
        parse_rule({"name": "x", "type": "http_latency",
                    "rule_details": {"service_name": "a", "http_route": "buy",
                                     "threshold": 10}})
    rule = parse_rule({"name": "e", "type": "error",
                       "rule_details": {"fallback_sampling_ratio": 20}})
    assert isinstance(rule, ErrorRule)


def test_sampling_processor_end_to_end():
    proc = SamplingProcessor("odigossampling", {
        "rules": {"global_rules": [
            {"name": "errors-only", "type": "error",
             "rule_details": {"fallback_sampling_ratio": 0.0}}]},
        "seed": 0})
    sink = []
    proc.set_consumer(type("S", (), {"consume": lambda self, b: sink.append(b)})())
    batch = build({"trace_id": 1, "error": True}, {"trace_id": 2},
                  {"trace_id": 3, "error": True})
    proc.consume(batch)
    assert len(sink) == 1
    assert kept_trace_ids(sink[0]) == [1, 3]


# ------------------------------------------------------------ groupbytrace
class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_groupbytrace_holds_until_wait_elapses():
    clock = FakeClock()
    proc = GroupByTraceProcessor("groupbytrace", {
        "wait_duration_s": 10.0, "clock": clock, "tick_interval_s": 0})
    sink = []
    proc.set_consumer(type("S", (), {"consume": lambda self, b: sink.append(b)})())

    proc.consume(build({"trace_id": 1, "n": 2}))
    clock.t += 5
    proc.consume(build({"trace_id": 1, "n": 1}, {"trace_id": 2, "n": 2}))
    proc.tick()
    assert sink == []  # nothing expired yet

    clock.t += 6  # trace 1 first seen 11s ago, trace 2 only 6s
    proc.tick()
    assert len(sink) == 1
    assert kept_trace_ids(sink[0]) == [1]
    assert len(sink[0]) == 3  # spans from both arrival batches, regrouped

    clock.t += 5
    proc.tick()
    assert kept_trace_ids(sink[1]) == [2]


def test_groupbytrace_eviction_bounds_memory():
    clock = FakeClock()
    proc = GroupByTraceProcessor("groupbytrace", {
        "wait_duration_s": 1000.0, "num_traces": 3, "clock": clock,
        "tick_interval_s": 0})
    sink = []
    proc.set_consumer(type("S", (), {"consume": lambda self, b: sink.append(b)})())
    for t in range(1, 6):  # 5 traces, cap 3 → oldest evicted early
        clock.t += 1
        proc.consume(build({"trace_id": t, "n": 1}))
    assert sum(len(b) for b in sink) == 2
    released = sorted(i for b in sink for i in kept_trace_ids(b))
    assert released == [1, 2]


def test_groupbytrace_shutdown_flushes_all():
    clock = FakeClock()
    proc = GroupByTraceProcessor("groupbytrace", {
        "wait_duration_s": 1000.0, "clock": clock, "tick_interval_s": 0})
    sink = []
    proc.set_consumer(type("S", (), {"consume": lambda self, b: sink.append(b)})())
    proc.consume(build({"trace_id": 1}, {"trace_id": 2}))
    proc.shutdown()
    assert sum(len(b) for b in sink) == 6


def test_groupbytrace_then_sampling_pipeline():
    """The mandated composition: groupbytrace → odigossampling."""
    clock = FakeClock()
    gbt = GroupByTraceProcessor("groupbytrace", {
        "wait_duration_s": 1.0, "clock": clock, "tick_interval_s": 0})
    samp = SamplingProcessor("odigossampling", {
        "rules": {"global_rules": [
            {"name": "errors", "type": "error",
             "rule_details": {"fallback_sampling_ratio": 0.0}}]},
        "seed": 0})
    sink = []
    gbt.set_consumer(samp)
    samp.set_consumer(type("S", (), {"consume": lambda self, b: sink.append(b)})())

    # error span of trace 1 arrives in a LATER batch than its root: a head
    # sampler would have dropped the trace; tail sampling must keep it.
    b1 = SpanBatchBuilder()
    make_trace(b1, 1, n=1)
    make_trace(b1, 2, n=1)
    gbt.consume(b1.build())
    b2 = SpanBatchBuilder()
    make_trace(b2, 1, n=2, error=True)
    gbt.consume(b2.build())
    clock.t += 2
    gbt.tick()
    assert len(sink) == 1
    assert kept_trace_ids(sink[0]) == [1]


def test_groupbytrace_num_traces_one_still_buffers_newest():
    """Eviction keeps the newest num_traces traces (off-by-one regression):
    with num_traces=1, arrival of trace 2 releases only trace 1."""
    clock = FakeClock()
    proc = GroupByTraceProcessor("groupbytrace", {
        "wait_duration_s": 1000.0, "num_traces": 1, "clock": clock,
        "tick_interval_s": 0})
    sink = []
    proc.set_consumer(type("S", (), {"consume": lambda self, b: sink.append(b)})())
    proc.consume(build({"trace_id": 1, "n": 1}))
    clock.t += 1
    proc.consume(build({"trace_id": 2, "n": 1}))
    assert [kept_trace_ids(b) for b in sink] == [[1]]  # trace 2 still held


def test_span_attribute_json_exists_without_path():
    batch = build({"trace_id": 1, "attrs": {"k": '{"any": 1}'}})
    rule = SpanAttributeRule(
        service_name="svc", attribute_key="k", condition_type="json",
        operation="exists", sampling_ratio=100.0)
    rule.validate()
    assert bool(rule.evaluate(TraceView.of(batch)).satisfied[0])
