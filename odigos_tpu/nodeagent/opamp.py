"""OpAMP-style remote-config / health server.

Equivalent of opampserver/ (SURVEY.md §2.2): native-SDK agents open a
connection, describe themselves (pid + pod identity), and from then on the
server (a) pushes remote config compiled from the workload's
InstrumentationConfig, (b) turns health heartbeats into
InstrumentationInstance status writes, and (c) marks instances unhealthy on
disconnect/timeout.

Message shape (JSON-dict analog of the reference's protobufs,
opampserver/protobufs/):

agent → server: {"instance_uid", "agent_description": {...},
                 "health": {"healthy", "message"},
                 "remote_config_status": {"hash", "applied"}}
server → agent: {"remote_config": {"hash", "sections": {...}},
                 "report_full_state": bool}

Transport is pluggable: ``OpampAgent`` is the in-process client used by the
sim and tests; a socket transport only needs to deliver the same dicts.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..api.resources import (
    InstrumentationConfig, InstrumentationInstance, ObjectMeta, WorkloadKind,
    WorkloadRef)
from ..api.store import Store


@dataclass
class AgentConnection:
    """Connection-cache entry (opampserver/pkg/connection/conncache.go)."""

    instance_uid: str
    workload: WorkloadRef
    pod_name: str
    container_name: str
    pid: int
    language: str
    send: Callable[[dict[str, Any]], None]
    last_heartbeat: float = field(default_factory=time.time)
    config_hash: str = ""


def _config_hash(sections: dict[str, Any]) -> str:
    return hashlib.sha256(
        json.dumps(sections, sort_keys=True).encode()).hexdigest()[:16]


def build_remote_config(ic: InstrumentationConfig,
                        language: str) -> dict[str, Any]:
    """Compile the per-agent remote-config sections from the workload's
    InstrumentationConfig (opampserver/pkg/sdkconfig/configsections/):
    sdk section (service name, trace config), instrumentation-libraries
    section (payload collection, code attributes, http headers)."""
    sdk = next((s for s in ic.sdk_configs if s.language == language), None)
    sections: dict[str, Any] = {
        "sdk": {
            "service_name": ic.service_name or ic.workload.name,
            "data_streams": list(ic.data_stream_names),
            "trace_config": dict(sdk.trace_config) if sdk else {},
        },
        "instrumentation_libraries": {
            "payload_collection": sdk.payload_collection if sdk else None,
            "code_attributes": bool(sdk.code_attributes) if sdk else False,
            "http_headers": list(sdk.http_headers) if sdk else [],
            # custom-instrumentation rule probes (validated control-plane
            # side; configsections/instrumentationconfig.go role)
            "custom_instrumentation": (list(sdk.custom_probes)
                                       if sdk else []),
        },
    }
    return sections


class OpampServer:
    """Holds the connection cache and the store-writeback logic
    (opampserver/pkg/server/server.go:23 StartOpAmpServer,
    handlers.go:43/:125/:147)."""

    def __init__(self, store: Store, node: str = "",
                 heartbeat_timeout: float = 30.0):
        self.store = store
        self.node = node
        self.heartbeat_timeout = heartbeat_timeout
        self._conns: dict[str, AgentConnection] = {}
        self._lock = threading.Lock()

    # ---------------------------------------------------------- transport

    def handle_message(self, msg: dict[str, Any],
                       send: Callable[[dict[str, Any]], None]
                       ) -> Optional[dict[str, Any]]:
        """Process one agent→server message; returns the reply (also pushed
        through ``send`` for transports that deliver asynchronously)."""
        uid = msg.get("instance_uid", "")
        if not uid:
            return None
        with self._lock:
            conn = self._conns.get(uid)
        is_new = conn is None
        if is_new:
            desc = msg.get("agent_description")
            if not desc:
                # unknown agent without a description: ask for full state
                reply = {"report_full_state": True}
                send(reply)
                return reply
            conn = self._on_new_connection(uid, desc, send)
            if conn is None:
                return None
        conn.last_heartbeat = time.time()
        health = msg.get("health")
        if health is not None:
            self._write_instance_status(conn, bool(health.get("healthy")),
                                        str(health.get("message", "")))
        if is_new:
            # first contact always pushes config (the agent may have sent a
            # full state report — description+health+empty hash — in one
            # message; keying on 'no health yet' would leave it unconfigured)
            if health is None:
                self._write_instance_status(conn, None, "connected")
            return self._push_config(conn)
        status = msg.get("remote_config_status")
        if status is not None and status.get("hash") != conn.config_hash:
            return self._push_config(conn)
        return None

    def agent_disconnected(self, instance_uid: str) -> None:
        with self._lock:
            conn = self._conns.pop(instance_uid, None)
        if conn is not None:
            self._write_instance_status(conn, False, "agent disconnected")

    def expire_stale(self, now: Optional[float] = None) -> list[str]:
        """Heartbeat-timeout sweep; returns expired uids."""
        now = time.time() if now is None else now
        expired = []
        with self._lock:
            for uid, conn in list(self._conns.items()):
                if now - conn.last_heartbeat > self.heartbeat_timeout:
                    expired.append(uid)
        for uid in expired:
            self.agent_disconnected(uid)
        return expired

    # ----------------------------------------------------------- internals

    def _on_new_connection(self, uid: str, desc: dict[str, Any],
                           send: Callable[[dict[str, Any]], None]
                           ) -> Optional[AgentConnection]:
        """Resolve pod identity → workload (handlers.go:268); refuse agents
        we can't attribute."""
        try:
            kind = desc["workload_kind"]
            if not isinstance(kind, WorkloadKind):
                kind = WorkloadKind.parse(str(kind))  # JSON transports
            workload = WorkloadRef(desc["namespace"], kind,
                                   desc["workload_name"])
        except (KeyError, ValueError):
            return None
        conn = AgentConnection(
            instance_uid=uid, workload=workload,
            pod_name=desc.get("pod_name", ""),
            container_name=desc.get("container_name", ""),
            pid=int(desc.get("pid", 0)),
            language=desc.get("language", ""), send=send)
        with self._lock:
            self._conns[uid] = conn
        return conn

    def _find_ic(self, workload: WorkloadRef) -> Optional[InstrumentationConfig]:
        for ic in self.store.list("InstrumentationConfig",
                                  namespace=workload.namespace):
            if ic.workload == workload:
                return ic
        return None

    def _push_config(self, conn: AgentConnection) -> Optional[dict[str, Any]]:
        ic = self._find_ic(conn.workload)
        if ic is None:
            return None
        sections = build_remote_config(ic, conn.language)
        conn.config_hash = _config_hash(sections)
        reply = {"remote_config": {"hash": conn.config_hash,
                                   "sections": sections}}
        conn.send(reply)
        return reply

    def config_changed(self, workload: WorkloadRef) -> int:
        """Push updated config to every connected agent of the workload
        (server.go:220 ProcessInstrumentationUpdates); returns #pushed."""
        with self._lock:
            conns = [c for c in self._conns.values() if c.workload == workload]
        for conn in conns:
            self._push_config(conn)
        return len(conns)

    def _write_instance_status(self, conn: AgentConnection,
                               healthy: Optional[bool], message: str) -> None:
        name = f"{conn.workload.name}-{conn.pod_name}-{conn.pid}"
        inst = InstrumentationInstance(
            meta=ObjectMeta(name=name, namespace=conn.workload.namespace),
            workload=conn.workload, pod_name=conn.pod_name,
            container_name=conn.container_name, pid=conn.pid,
            healthy=healthy, message=message,
            identifying_attributes={
                "service.instance.id": conn.instance_uid,
                "telemetry.sdk.language": conn.language,
                "k8s.node.name": self.node,
            },
            last_status_time=time.time())
        self.store.apply(inst)

    @property
    def connected_uids(self) -> list[str]:
        with self._lock:
            return sorted(self._conns)


class OpampAgent:
    """In-process agent client (the role the per-language SDK agents play).

    Drives the same message protocol the server expects; the sim's pods use
    one of these per native-SDK container.
    """

    def __init__(self, server: OpampServer, instance_uid: str,
                 description: dict[str, Any]):
        self.server = server
        self.instance_uid = instance_uid
        self.description = description
        self.remote_config: Optional[dict[str, Any]] = None
        self._applied_hash = ""

    def _recv(self, msg: dict[str, Any]) -> None:
        rc = msg.get("remote_config")
        if rc is not None:
            self.remote_config = rc["sections"]
            self._applied_hash = rc["hash"]

    def connect(self) -> None:
        self.server.handle_message(
            {"instance_uid": self.instance_uid,
             "agent_description": self.description}, self._recv)

    def heartbeat(self, healthy: bool = True, message: str = "ok") -> None:
        self.server.handle_message(
            {"instance_uid": self.instance_uid,
             "health": {"healthy": healthy, "message": message},
             "remote_config_status": {"hash": self._applied_hash,
                                      "applied": True}}, self._recv)

    def disconnect(self) -> None:
        self.server.agent_disconnected(self.instance_uid)
