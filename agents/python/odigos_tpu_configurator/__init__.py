"""Process-startup configurator for the odigos-tpu Python agent.

Role analog of /root/reference/agents/python/configurator/__init__.py
(OdigosPythonConfigurator._configure -> initialize_components): called in
an instrumented process, it wires the hooks tracer's default sink to the
delivery the webhook-injected env selects and registers an atexit flush.

Env contract (injected by the instrumentor webhook / distro registry,
distros/registry.py python-community):

    ODIGOS_SERVICE_NAME    logical service (default: process name)
    ODIGOS_WIRE_ENDPOINT   host:port of the node collector's otlp wire
                           front door; spans ship as framed-TCP batches
    ODIGOS_AUTO_INIT=1     sitecustomize runs initialize() automatically

Without an endpoint the tracer buffers (bounded, drop-counted) — the app
can still call odigos_tpu.hooks.flush() after wiring its own sink.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Any, Optional

MINIMUM_PYTHON_SUPPORTED_VERSION = (3, 8)

_state: dict[str, Any] = {"initialized": False, "exporter": None}
_init_lock = threading.Lock()


def initialize(service: Optional[str] = None,
               endpoint: Optional[str] = None) -> bool:
    """Idempotent agent init; returns True when a sink was wired.

    Only a *successful* wiring latches: when sitecustomize auto-runs with
    no ODIGOS_WIRE_ENDPOINT, a later explicit ``initialize(endpoint=...)``
    from app code (the documented pip-install flow) must still work.
    The lock keeps concurrent first-use calls (lazy init from request
    handlers) from wiring two exporters.
    """
    with _init_lock:
        if _state["exporter"] is not None:
            return True

        service = service or os.environ.get("ODIGOS_SERVICE_NAME", "")
        if service:
            os.environ.setdefault("ODIGOS_SERVICE_NAME", service)
        endpoint = endpoint or os.environ.get("ODIGOS_WIRE_ENDPOINT", "")
        if not endpoint:
            return False

        from odigos_tpu.hooks import tracer as hooks
        from odigos_tpu.wire.client import WireExporter

        exporter = WireExporter("otlpwire/agent", {"endpoint": endpoint})
        exporter.start()
        _state["exporter"] = exporter
        _state["initialized"] = True  # informational: a sink is wired
        hooks.set_default_sink(exporter.export)

        def _shutdown() -> None:
            try:
                hooks.flush()
                exporter.flush(timeout=5.0)
            finally:
                exporter.shutdown()

        atexit.register(_shutdown)
        return True


class OdigosTpuConfigurator:
    """Entry-point class (the reference's _BaseConfigurator shape): the
    loader instantiates it and calls ``configure()``."""

    def configure(self, **kwargs: Any) -> None:
        initialize()

    # reference spelling (sdk_config._BaseConfigurator API)
    def _configure(self, **kwargs: Any) -> None:
        initialize()
