"""Incremental hot reload (ISSUE 14): the structural config differ's
classification table (keep / reconfigure-in-place / replace-node /
full-rebuild fallback), Graph.patch splicing on live edges, and
Collector.reload routing — a knob change under load must cost a
node-local patch, keep every warmed structure (receiver binds, shared
engines), stay conserved, and record its own cost
(odigos_collector_reload_ms{mode=} + reload_nodes_total{action=})."""

import copy
import threading
import time

import pytest

from odigos_tpu.pdata import synthesize_traces
from odigos_tpu.pipeline.configdiff import (
    FULL,
    INCREMENTAL,
    NOOP,
    RECONFIGURE,
    REPLACE,
    diff_configs,
)
from odigos_tpu.pipeline.service import Collector
from odigos_tpu.selftelemetry.flow import flow_ledger
from odigos_tpu.utils.telemetry import meter
from odigos_tpu.wire.client import WireExporter


def wait_for(cond, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def base_config(**tpu_overrides):
    cfg = {
        "receivers": {"synthetic": {"n_batches": 0, "interval_s": 60}},
        "processors": {
            "memory_limiter": {"limit_mib": 512},
            "batch": {"send_batch_size": 512, "timeout_s": 0.05},
            "tpuanomaly": dict({"model": "mock", "threshold": 0.6,
                                "timeout_ms": 10_000,
                                "shared_engine": False},
                               **tpu_overrides),
        },
        "exporters": {"tracedb": {}},
        "service": {"pipelines": {"traces/in": {
            "receivers": ["synthetic"],
            "processors": ["memory_limiter", "batch", "tpuanomaly"],
            "exporters": ["tracedb"]}}},
    }
    return cfg


def wire_config(fast_path=True, threshold=0.6, port=0, **fp_overrides):
    fp = dict({"deadline_ms": 10_000.0, "predictive": False},
              **fp_overrides)
    return {
        "receivers": {"otlpwire": {"port": port}},
        "processors": {
            "memory_limiter": {"limit_mib": 512},
            "batch": {"send_batch_size": 1, "timeout_s": 0.0},
            "tpuanomaly": {"model": "mock", "threshold": threshold,
                           "timeout_ms": 30_000,
                           "shared_engine": False},
        },
        "exporters": {"tracedb": {}},
        "service": {"pipelines": {"traces/in": dict(
            {"receivers": ["otlpwire"],
             "processors": ["memory_limiter", "batch", "tpuanomaly"],
             "exporters": ["tracedb"]},
            **({"fast_path": fp} if fast_path else {}))}},
    }


# --------------------------------------------------- differ classification


class TestDiffClassification:
    def test_identical_configs_are_noop(self):
        cfg = base_config()
        assert diff_configs(cfg, copy.deepcopy(cfg)).mode == NOOP

    def test_explicit_default_is_keep(self):
        """Normalization merges factory defaults: writing a key at its
        default value is not a change."""
        old = base_config()
        new = copy.deepcopy(old)
        new["processors"]["batch"]["send_batch_max_size"] = 0  # default
        new["processors"]["tpuanomaly"]["max_len"] = 64  # default
        d = diff_configs(old, new)
        assert d.mode == INCREMENTAL and d.actions == []

    def test_reconfigurable_knob_classifies_reconfigure(self):
        old = base_config()
        new = copy.deepcopy(old)
        new["processors"]["tpuanomaly"]["threshold"] = 0.9
        new["processors"]["batch"]["send_batch_size"] = 1024
        new["processors"]["memory_limiter"]["limit_mib"] = 256
        d = diff_configs(old, new)
        assert d.mode == INCREMENTAL
        acts = {a.node: a for a in d.actions}
        assert acts[("traces/in", "tpuanomaly")].action == RECONFIGURE
        assert acts[("traces/in", "tpuanomaly")].changed == ("threshold",)
        assert acts[("traces/in", "batch")].action == RECONFIGURE
        assert acts[("traces/in", "memory_limiter")].action == RECONFIGURE

    def test_unknown_key_classifies_replace(self):
        old = base_config()
        new = copy.deepcopy(old)
        # engine-shaping key: outside tpuanomaly's RECONFIGURABLE_KEYS
        new["processors"]["tpuanomaly"]["trace_bucket"] = 128
        new["receivers"]["synthetic"]["seed"] = 3  # no reconfigure at all
        d = diff_configs(old, new)
        assert d.mode == INCREMENTAL
        acts = {a.node: a for a in d.actions}
        assert acts[("traces/in", "tpuanomaly")].action == REPLACE
        assert acts[("synthetic",)].action == REPLACE

    @pytest.mark.parametrize("mutate,reason_frag", [
        (lambda c: c["service"]["pipelines"].update(
            {"traces/extra": {"receivers": ["synthetic"],
                              "exporters": ["tracedb"]}}),
         "pipeline set changed"),
        (lambda c: c["service"]["pipelines"]["traces/in"][
            "processors"].remove("batch"), "processors changed"),
        (lambda c: c["exporters"].update({"debug": {}}),
         "component set changed: exporters"),
        (lambda c: c["service"].update({"mystery": 1}),
         "service.mystery changed"),
    ])
    def test_topology_changes_classify_full(self, mutate, reason_frag):
        old = base_config()
        new = copy.deepcopy(old)
        mutate(new)
        d = diff_configs(old, new)
        assert d.mode == FULL
        assert any(reason_frag in r for r in d.reasons), d.reasons

    def test_fast_path_toggle_and_structural_keys_are_full(self):
        old = wire_config(fast_path=True)
        off = wire_config(fast_path=False)
        assert diff_configs(old, off).mode == FULL
        lanes = wire_config(fast_path=True, lanes=2)
        d = diff_configs(old, lanes)
        assert d.mode == FULL
        assert any("fast_path structural" in r for r in d.reasons)

    def test_fast_path_knobs_classify_reconfigure(self):
        old = wire_config(fast_path=True)
        new = wire_config(fast_path=True)
        new["service"]["pipelines"]["traces/in"]["fast_path"][
            "deadline_ms"] = 5_000.0
        d = diff_configs(old, new)
        assert d.mode == INCREMENTAL
        [act] = d.actions
        assert act.kind == "fastpath" and act.action == RECONFIGURE

    def test_scorer_replace_under_fast_path_is_full(self):
        old = wire_config(fast_path=True)
        new = copy.deepcopy(old)
        new["processors"]["tpuanomaly"]["trace_bucket"] = 128
        d = diff_configs(old, new)
        assert d.mode == FULL
        assert any("under fast_path" in r for r in d.reasons)

    def test_retry_knob_reconfigures_wrap_toggle_replaces(self):
        old = base_config()
        old["exporters"]["tracedb"] = {"retry": {"initial_backoff_ms": 20}}
        knob = copy.deepcopy(old)
        knob["exporters"]["tracedb"]["retry"]["initial_backoff_ms"] = 40
        d = diff_configs(old, knob)
        assert d.mode == INCREMENTAL
        [act] = d.actions
        # classified from the live wrapper when a graph is given; from
        # the config shape alone the wrap decision still matches, so
        # the class-level table must answer the same way
        assert act.action == RECONFIGURE and act.changed == ("retry",)
        unwrapped = copy.deepcopy(old)
        del unwrapped["exporters"]["tracedb"]["retry"]
        # retry removed entirely = component-set unchanged, key changed
        d2 = diff_configs(old, unwrapped)
        [act2] = d2.actions
        assert act2.action == REPLACE

    def test_service_stanza_flags(self):
        old = base_config()
        new = copy.deepcopy(old)
        new["service"]["alerts"] = [
            {"name": "r", "expr": "latest(odigos_g[30s]) > 5"}]
        new["service"]["gc"] = {"janitor_interval_s": 1.0}
        new["service"]["pipelines"]["traces/in"]["slo"] = {
            "latency_p99_ms": 100.0}
        d = diff_configs(old, new)
        assert d.mode == INCREMENTAL
        assert d.alerts_changed and d.gc_changed
        assert d.slo_changed == ["traces/in"]
        assert d.actions == []
        assert not d.actuator_changed

    def test_actuator_stanza_change_is_incremental(self):
        """ISSUE 15: an actuator stanza edit retunes in place (the
        alerts/gc discipline) — it must never force a graph rebuild."""
        old = base_config()
        old["service"]["actuator"] = {"enabled": True,
                                      "cooldown_s": 60.0}
        new = copy.deepcopy(old)
        new["service"]["actuator"]["cooldown_s"] = 5.0
        d = diff_configs(old, new)
        assert d.mode == INCREMENTAL and d.actuator_changed
        assert d.actions == []
        # deleting the stanza is also a non-topological change
        gone = copy.deepcopy(old)
        del gone["service"]["actuator"]
        d2 = diff_configs(old, gone)
        assert d2.mode == INCREMENTAL and d2.actuator_changed


# ------------------------------------------------ incremental reload (live)


class TestIncrementalReload:
    def test_single_knob_reload_keeps_every_node(self):
        flow_ledger.reset()
        cfg = base_config()
        c = Collector(cfg).start()
        try:
            g0 = c.graph
            recv0 = c.graph.receivers["synthetic"]
            scorer0 = c.graph.processors[("traces/in", "tpuanomaly")]
            engine0 = scorer0.engine
            reloads0 = meter.counter("odigos_collector_reloads_total")
            kept0 = meter.counter(
                "odigos_collector_reload_nodes_total{action=kept}")
            new = copy.deepcopy(cfg)
            new["processors"]["tpuanomaly"]["threshold"] = 0.95
            c.reload(new)
            assert c.graph is g0, "incremental reload keeps the graph"
            assert c.graph.receivers["synthetic"] is recv0
            assert c.graph.processors[("traces/in",
                                       "tpuanomaly")] is scorer0
            assert scorer0.engine is engine0, \
                "warm engine must survive a threshold tweak"
            assert scorer0.threshold == 0.95
            assert c.config == new
            # satellite 2: the reload priced + attributed itself
            assert meter.counter(
                "odigos_collector_reloads_total") == reloads0 + 1
            assert meter.counter(
                "odigos_collector_reload_nodes_total"
                "{action=reconfigured}") >= 1
            assert meter.counter(
                "odigos_collector_reload_nodes_total"
                "{action=kept}") >= kept0 + 4
            snap = meter.snapshot()
            assert snap.get(
                "odigos_collector_reload_ms_count{mode=incremental}",
                0) >= 1
        finally:
            c.shutdown()

    def test_replace_splices_on_existing_edges_and_conserves(self):
        """A non-reconfigurable processor change rebuilds ONE node and
        splices it onto the existing flow edges; traffic across the
        swap stays conserved and the ledger keys persist."""
        flow_ledger.reset()
        cfg = base_config()
        cfg["receivers"]["synthetic"] = {"traces_per_batch": 4,
                                         "n_batches": 0,
                                         "interval_s": 0.005}
        cfg["processors"]["probabilisticsampler"] = {
            "sampling_percentage": 100.0}
        cfg["service"]["pipelines"]["traces/in"]["processors"] = [
            "memory_limiter", "probabilisticsampler", "batch",
            "tpuanomaly"]
        c = Collector(cfg).start()
        try:
            time.sleep(0.1)
            sampler0 = c.graph.processors[("traces/in",
                                           "probabilisticsampler")]
            batch0 = c.graph.processors[("traces/in", "batch")]
            sink0 = c.graph.exporters["tracedb"]
            new = copy.deepcopy(cfg)
            new["processors"]["probabilisticsampler"] = {
                "sampling_percentage": 100.0, "hash_seed": 7}
            c.reload(new)
            assert c.graph.processors[
                ("traces/in", "probabilisticsampler")] is not sampler0, \
                "changed node must be replaced"
            assert c.graph.processors[("traces/in", "batch")] is batch0
            assert c.graph.exporters["tracedb"] is sink0
            assert meter.counter(
                "odigos_collector_reload_nodes_total"
                "{action=replaced}") >= 1
            time.sleep(0.15)
        finally:
            c.shutdown()
        bal = flow_ledger.conservation()["traces/in"]
        assert bal["leak"] == 0, bal
        assert bal["items_in"] > 0

    def test_untouched_receiver_keeps_bind_under_live_traffic(self):
        """The fixed-port constraint, incremental edition: a reload
        that doesn't touch the wire receiver must not release its bind
        — the same server socket keeps serving, senders never see a
        connection reset, and the stream stays conserved."""
        flow_ledger.reset()
        cfg = wire_config(fast_path=True)
        c = Collector(cfg).start()
        stop = threading.Event()
        try:
            recv = c.graph.receivers["otlpwire"]
            server0, port = recv._server, recv.port
            fp0 = c.graph.fastpaths["traces/in"]
            engine0 = fp0.engine
            exp = WireExporter("t", {"endpoint": f"127.0.0.1:{port}",
                                     "max_elapsed_s": 30.0})
            exp.start()
            batches = [synthesize_traces(16, seed=s) for s in range(4)]

            def sender():
                k = 0
                while not stop.is_set():
                    exp.export(batches[k % 4])
                    k += 1
                    while exp.queued > 8 and not stop.is_set():
                        time.sleep(0.001)
                    time.sleep(0.002)

            t = threading.Thread(target=sender, daemon=True)
            t.start()
            time.sleep(0.2)
            new = wire_config(fast_path=True, threshold=0.9)
            c.reload(new)
            assert c.graph.receivers["otlpwire"] is recv
            assert recv._server is server0 and recv.port == port, \
                "kept receiver must keep its exact bind"
            assert c.graph.fastpaths["traces/in"] is fp0
            assert fp0.engine is engine0
            assert fp0.threshold == 0.9, \
                "scorer reconfigure must retune the aliased fast path"
            time.sleep(0.2)
            stop.set()
            t.join(timeout=10)
            assert exp.flush(30.0)
            exp.shutdown()
            c.drain_receivers(30.0)
            bal = flow_ledger.conservation()["traces/in"]
            assert bal["leak"] == 0, bal
            assert c.graph.exporters["tracedb"].span_count > 0
        finally:
            stop.set()
            c.shutdown()

    def test_fastpath_deadline_reconfigures_live(self):
        flow_ledger.reset()
        cfg = wire_config(fast_path=True)
        c = Collector(cfg).start()
        try:
            fp = c.graph.fastpaths["traces/in"]
            new = wire_config(fast_path=True)
            new["service"]["pipelines"]["traces/in"]["fast_path"][
                "deadline_ms"] = 5_000.0
            c.reload(new)
            assert c.graph.fastpaths["traces/in"] is fp
            assert fp.deadline_ms == 5_000.0
            assert fp._deadline_ns == int(5_000.0 * 1e6)
        finally:
            c.shutdown()

    def test_admission_stanza_reconfigures_without_rebind(self):
        flow_ledger.reset()
        cfg = wire_config(fast_path=False)
        c = Collector(cfg).start()
        try:
            recv = c.graph.receivers["otlpwire"]
            server0 = recv._server
            inflight0 = recv.admission
            new = copy.deepcopy(cfg)
            new["receivers"]["otlpwire"]["admission"] = {
                "watermarks": {"traces/in/batch":
                               {"pending_spans": 4096}}}
            c.reload(new)
            assert c.graph.receivers["otlpwire"] is recv
            assert recv._server is server0
            assert recv.admission is inflight0, \
                "in-flight byte accounting must carry over"
            assert recv.admission.watermark_gate is not None
        finally:
            c.shutdown()

    def test_failed_replacement_build_leaves_old_node_serving(self):
        """Review regression: a replacement whose CONSTRUCTOR raises
        must leave the live node untouched (build-before-shutdown) —
        the receiver keeps its exact bind after the failed reload."""
        flow_ledger.reset()
        cfg = wire_config(fast_path=False)
        c = Collector(cfg).start()
        try:
            recv = c.graph.receivers["otlpwire"]
            server0, port0 = recv._server, recv.port
            bad = copy.deepcopy(cfg)
            # host change -> REPLACE classification; the bad byte
            # budget then dies in WireReceiver.__init__
            bad["receivers"]["otlpwire"]["host"] = "127.0.0.1"
            bad["receivers"]["otlpwire"]["max_inflight_bytes"] = "oops"
            with pytest.raises(Exception):
                c.reload(bad)
            assert c.graph.receivers["otlpwire"] is recv
            assert recv._server is server0 and recv.port == port0, \
                "old receiver must still hold its bind"
            assert c.config == cfg
        finally:
            c.shutdown()

    def test_failed_replacement_start_restores_old_receiver(self):
        """Review regression: a replacement that builds but cannot
        START (unbindable port) must restore + restart the old node
        before the fallback runs — the collector keeps serving with a
        live receiver instead of a half-patched dead graph."""
        import socket

        flow_ledger.reset()
        blocker = socket.socket()
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        taken = blocker.getsockname()[1]
        cfg = wire_config(fast_path=False)
        c = Collector(cfg).start()
        try:
            recv = c.graph.receivers["otlpwire"]
            bad = copy.deepcopy(cfg)
            bad["receivers"]["otlpwire"]["port"] = taken  # REPLACE
            with pytest.raises(OSError):
                c.reload(bad)
            assert c.config == cfg
            assert c.graph.receivers["otlpwire"] is recv
            assert recv._server is not None, \
                "old receiver must be serving again after the unwind"
            # the restored receiver actually answers (ephemeral port
            # re-rolled by the restart — read it fresh)
            exp = WireExporter("t", {
                "endpoint": f"127.0.0.1:{recv.port}"})
            exp.start()
            exp.export(synthesize_traces(4, seed=0))
            assert exp.flush(20.0)
            exp.shutdown()
            assert wait_for(
                lambda: c.graph.exporters["tracedb"].span_count >= 4)
        finally:
            c.shutdown()
            blocker.close()

    def test_failed_reconfigure_parse_leaves_posture_intact(self):
        """Review regression: WireReceiver.reconfigure parses every
        value before assigning any — a bad byte budget must not leave
        the NEW gate installed on the 'intact' old graph."""
        flow_ledger.reset()
        cfg = wire_config(fast_path=False)
        cfg["receivers"]["otlpwire"]["admission"] = {
            "watermarks": {"traces/in/batch": {"pending_spans": 4096}}}
        c = Collector(cfg).start()
        try:
            recv = c.graph.receivers["otlpwire"]
            gate0 = recv.admission.watermark_gate
            assert gate0 is not None
            bad = copy.deepcopy(cfg)
            bad["receivers"]["otlpwire"]["admission"] = {
                "watermarks": {"traces/in/batch":
                               {"pending_spans": 1}}}
            bad["receivers"]["otlpwire"]["max_inflight_bytes"] = "oops"
            with pytest.raises(Exception):
                c.reload(bad)
            assert recv.admission.watermark_gate is gate0, \
                "half-applied admission posture must never survive"
            assert recv.admission.max_inflight_bytes == 64 << 20
            assert c.config == cfg
        finally:
            c.shutdown()

    def test_patch_failure_falls_back_to_full_rebuild(self, monkeypatch):
        """A reconfigure that raises mid-patch must not leave a
        half-upgraded graph: the reload falls back to the full-rebuild
        path and still converges."""
        flow_ledger.reset()
        from odigos_tpu.components.processors.batch import BatchProcessor

        def boom(self, config):
            raise RuntimeError("injected reconfigure failure")

        monkeypatch.setattr(BatchProcessor, "reconfigure", boom)
        cfg = base_config()
        c = Collector(cfg).start()
        try:
            g0 = c.graph
            new = copy.deepcopy(cfg)
            new["processors"]["batch"]["send_batch_size"] = 64
            c.reload(new)  # must NOT raise
            assert c.graph is not g0, "fallback takes the full path"
            assert c.config == new
            assert c.graph.processors[("traces/in",
                                       "batch")].send_batch_size == 64
            snap = meter.snapshot()
            assert snap.get(
                "odigos_collector_reload_ms_count{mode=full}", 0) >= 1
        finally:
            c.shutdown()

    def test_batch_timeout_rearms_on_reconfigure(self):
        """Review regression: buffered spans under timeout_s=0 (pure
        size-based batching, no timer armed) must start flushing when
        a reload introduces a timeout — reconfigure re-arms the flush
        timer under the new value."""
        from odigos_tpu.components.processors.batch import BatchProcessor

        out = []

        class Sink:
            def consume(self, b):
                out.append(b)

        bp = BatchProcessor("batch", {"send_batch_size": 10_000,
                                      "timeout_s": 0.0})
        bp.set_consumer(Sink())
        bp.start()
        try:
            bp.consume(synthesize_traces(2, seed=0))
            assert not out, "below size bound, no timeout: buffered"
            bp.reconfigure({"send_batch_size": 10_000,
                            "timeout_s": 0.05})
            assert wait_for(lambda: out, 5.0), \
                "new timeout must govern the already-buffered spans"
        finally:
            bp.shutdown()

    def test_half_applied_patch_converges_on_revert(self):
        """Review regression: two reconfigurable knobs where the
        SECOND dies parsing (passes validate_config, fails int()) —
        the first retune is applied, the full fallback fails on the
        same bad value, and the live graph diverges from the recorded
        config. The dirty flag must force the operator's revert (to
        the config the collector still RECORDS) through a full rebuild
        that converges, instead of no-oping on config equality."""
        flow_ledger.reset()
        cfg = base_config()
        c = Collector(cfg).start()
        try:
            bad = copy.deepcopy(cfg)
            bad["processors"]["memory_limiter"]["limit_mib"] = 1024
            bad["processors"]["batch"]["send_batch_size"] = "8k"
            with pytest.raises(Exception):
                c.reload(bad)
            assert c.config == cfg, "recorded config must stay old"
            # live limiter was retuned before the failure (patch order
            # follows the chain) — the divergence this test pins
            ml = c.graph.processors[("traces/in", "memory_limiter")]
            assert ml.limit_bytes == 1024 * 1024 * 1024
            assert meter.counter(
                "odigos_collector_reload_patch_fallbacks_total") >= 1
            # revert to the RECORDED config: equal dicts, but the
            # dirty flag must force a converging full rebuild
            c.reload(copy.deepcopy(cfg))
            ml2 = c.graph.processors[("traces/in", "memory_limiter")]
            assert ml2.limit_bytes == 512 * 1024 * 1024, \
                "revert must converge the live graph"
            assert c.config == cfg
        finally:
            c.shutdown()

    def test_slo_only_change_is_incremental(self):
        from odigos_tpu.selftelemetry.latency import latency_ledger

        flow_ledger.reset()
        cfg = base_config()
        c = Collector(cfg).start()
        try:
            g0 = c.graph
            new = copy.deepcopy(cfg)
            new["service"]["pipelines"]["traces/in"]["slo"] = {
                "latency_p99_ms": 250.0}
            c.reload(new)
            assert c.graph is g0
            assert "traces/in" in latency_ledger.slo_status()
            # deleting the stanza retires the tracker, still in place
            c.reload(copy.deepcopy(cfg))
            assert c.graph is g0
            assert "traces/in" not in latency_ledger.slo_status()
        finally:
            c.shutdown()

    def test_invalid_config_refused_with_old_graph_intact(self):
        flow_ledger.reset()
        cfg = base_config()
        c = Collector(cfg).start()
        try:
            g0 = c.graph
            failures0 = meter.counter(
                "odigos_collector_reload_failures_total")
            bad = copy.deepcopy(cfg)
            # structurally identical (incremental candidate) but
            # invalid: a malformed slo must die at validation
            bad["service"]["pipelines"]["traces/in"]["slo"] = {
                "latency_p99_ms": -1}
            with pytest.raises(ValueError, match="slo.latency_p99_ms"):
                c.reload(bad)
            assert c.graph is g0 and c.config == cfg
            # satellite 1: counted exactly once
            assert meter.counter(
                "odigos_collector_reload_failures_total") \
                == failures0 + 1
        finally:
            c.shutdown()


# ------------------------------------------- pipelinegen node fingerprints


class TestNodeHashes:
    def _gen(self, ids=("d1",)):
        from odigos_tpu.components.api import Signal
        from odigos_tpu.destinations.registry import Destination
        from odigos_tpu.pipelinegen.builder import build_gateway_config

        dests = [Destination(id=i, dest_type="tracedb",
                             signals=[Signal.TRACES]) for i in ids]
        cfg, status, _ = build_gateway_config(dests)
        assert all(v is None for v in status.destination.values())
        return cfg

    def test_regeneration_is_hash_stable_node_for_node(self):
        """Stable node identities: re-rendering unchanged inputs must
        fingerprint identically per node, so the differ classifies a
        no-op config push as all-keep."""
        from odigos_tpu.pipelinegen.builder import config_node_hashes

        h1 = config_node_hashes(self._gen())
        h2 = config_node_hashes(self._gen())
        assert h1 == h2 and h1, "generated configs must be byte-stable"

    def test_destination_add_touches_only_its_nodes(self):
        from odigos_tpu.pipelinegen.builder import changed_node_hashes

        changed = changed_node_hashes(self._gen(("d1",)),
                                      self._gen(("d1", "d2")))
        assert changed, "a destination add must change nodes"
        # the d1 exporter and its forward connector are untouched
        assert not any("tracedb-d1" in k for k in changed), changed
        # and the diff of the rendered configs is a FULL fallback
        # (pipeline exporters list changed) — exactly today's behavior
        d = diff_configs(self._gen(("d1",)), self._gen(("d1", "d2")))
        assert d.mode == FULL
