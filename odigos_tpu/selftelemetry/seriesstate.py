"""Series state: the bounded in-process time-series ring store.

The flow ledger (PR 5) answers "what flowed", latency attribution
(PR 8) answers "where time went" — both for the *current instant* plus
a few hand-rolled windows. This module is the third leg the ROADMAP's
fleet items are blocked on: **recent history as a queryable substrate**.
The drift-detection item reads it ("detect feature drift via
``seriesstate``"), the fleet rollup publishes per-collector snapshots
into it, and the alert/recommendation engines (selftelemetry/fleet.py)
evaluate window expressions over it.

Model — deliberately much smaller than a TSDB:

* one :class:`SeriesStore` holds many **series**, each keyed by the
  meter's flat ``name{label=value,...}`` encoding (one convention for
  the whole self-telemetry stack — ``utils.telemetry.labeled_key``).
* a series is a **fixed-interval ring**: appends land in the slot for
  ``tick = int(now / interval_s)``; re-appends within one tick
  overwrite (last value wins — snapshots are level samples, not
  events). Append is O(1): two array stores, no allocation, no
  compaction, ever.
* ticks are absolute, so a slot left over from a previous lap of the
  ring simply fails the window filter at query time — there is no
  expiry pass.
* **counter-delta awareness**: a series created with
  ``kind="counter"`` stores raw cumulative values; :meth:`rate` /
  :meth:`delta` sum consecutive increases with Prometheus-style reset
  handling (a decrease restarts accumulation at the new value instead
  of producing a negative spike).
* **hard memory bound**: at most ``max_series`` series ever exist
  (each ``window`` slots of (tick int64, value float64) ≈ 16 bytes a
  slot). Past the cap, NEW series are dropped and counted in
  ``odigos_seriesstate_dropped_series_total{metric=}`` — the store
  degrades by refusing cardinality, never by growing.
* ``ODIGOS_SERIES=0`` kills the layer: ``observe`` returns before
  touching the lock, queries answer empty — the same opt-out contract
  as ``ODIGOS_FLOW`` / ``ODIGOS_LATENCY`` / ``ODIGOS_SELFTRACE``.

Window queries (all ``O(window)`` per series, lock held only for the
point gather): ``latest``, ``rate``, ``delta``, ``ewma``,
``quantile_over_window``, ``avg/max/min/sum_over_window``. Selection:
``select(metric, labels)`` matches series whose base name equals
``metric`` and whose label set is a superset of ``labels`` — the
cross-collector aggregation primitive ``fleet.py`` builds on.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Any, Callable, Iterable, Optional

import numpy as np

from ..utils.telemetry import labeled_key, meter

DROPPED_SERIES_METRIC = "odigos_seriesstate_dropped_series_total"

GAUGE = "gauge"
COUNTER = "counter"

_EMPTY = np.empty(0, dtype=np.float64)


def split_key(key: str) -> tuple[str, dict[str, str]]:
    """Flat ``name{k=v,...}`` -> (base name, labels). The inverse of
    ``labeled_key`` — values were sanitized at record time (structural
    chars replaced), so the naive split round-trips by contract."""
    if "{" not in key:
        return key, {}
    base, rest = key.split("{", 1)
    labels: dict[str, str] = {}
    for part in rest.rstrip("}").split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            labels[k] = v
    return base, labels


def with_label(key: str, **extra: str) -> str:
    """Merge labels into a flat key (the fleet publisher's
    ``{collector=}`` stamp). Existing labels keep their values unless
    overridden; label insertion order is existing-then-new, so repeated
    stamping of the same snapshot yields identical keys (delta
    publishing depends on key stability)."""
    base, labels = split_key(key)
    labels.update(extra)
    return labeled_key(base, **labels)


class _Series:
    """One ring. Owned by the store; all access under the store lock.
    Slot arrays are numpy so a window query is two vectorized masks,
    not an O(window) Python scan — the alert engine evaluates every
    matching series per tick, and a fleet of hundreds of collectors
    makes the scan the layer's own overhead-bound violation (measured:
    48k python iterations/tick before, microseconds after)."""

    __slots__ = ("key", "base", "labels", "kind", "ticks", "values",
                 "last_tick", "last_value")

    def __init__(self, key: str, kind: str, window: int):
        self.key = key
        self.base, self.labels = split_key(key)
        self.kind = kind
        # absolute tick per slot (-1 = never written) + its value
        self.ticks = np.full(window, -1, dtype=np.int64)
        self.values = np.zeros(window, dtype=np.float64)
        self.last_tick = -1
        self.last_value = 0.0

    def append(self, tick: int, value: float) -> None:
        pos = tick % len(self.ticks)
        self.ticks[pos] = tick
        self.values[pos] = value
        if tick >= self.last_tick:
            self.last_tick = tick
            self.last_value = value

    def window_arrays(self, lo_tick: int, hi_tick: int
                      ) -> tuple[np.ndarray, np.ndarray]:
        """(ticks, values) within [lo_tick, hi_tick], UNSORTED (ring
        order) — order-insensitive reductions (avg/max/min/sum/
        quantile) use these directly. Stale slots from earlier laps
        fail the absolute-tick filter."""
        mask = (self.ticks >= lo_tick) & (self.ticks <= hi_tick)
        return self.ticks[mask], self.values[mask]

    def points(self, lo_tick: int, hi_tick: int) -> list[tuple[int, float]]:
        """(tick, value) within [lo_tick, hi_tick], ascending."""
        ticks, values = self.window_arrays(lo_tick, hi_tick)
        order = np.argsort(ticks, kind="stable")
        return list(zip(ticks[order].tolist(), values[order].tolist()))


def _counter_increase(pts: list[tuple[int, float]]) -> float:
    """Sum of positive deltas with reset handling: a decrease means the
    source restarted, so the new value counts from zero (the Prometheus
    rate() reset rule) instead of a negative spike."""
    inc = 0.0
    for (_, prev), (_, cur) in zip(pts, pts[1:]):
        inc += (cur - prev) if cur >= prev else cur
    return inc


class SeriesStore:
    """Bounded fixed-interval ring store (process-global instance:
    :data:`series_store`). ``clock`` is injectable for tests; it must be
    monotonic-ish (ticks derive from it)."""

    def __init__(self, interval_s: float = 1.0, window: int = 240,
                 max_series: int = 50_000,
                 clock: Callable[[], float] = time.monotonic):
        self.enabled = os.environ.get("ODIGOS_SERIES", "1") != "0"
        self.interval_s = float(interval_s)
        self.window = int(window)
        self.max_series = int(max_series)
        self._clock = clock
        self._lock = threading.Lock()
        self._series: dict[str, _Series] = {}
        # base name -> {key: series}: select() is per-rule-per-tick and
        # must not scan the whole store to answer for one metric
        self._by_base: dict[str, dict[str, _Series]] = {}
        self._dropped: dict[str, int] = {}  # base name -> dropped count

    # ------------------------------------------------------------ append

    def _tick(self, ts: Optional[float]) -> int:
        return int((ts if ts is not None else self._clock())
                   / self.interval_s)

    def observe(self, key: str, value: float, kind: str = GAUGE,
                ts: Optional[float] = None) -> bool:
        """Append one sample; returns False when the sample was refused
        (kill switch, cardinality cap, non-finite value)."""
        if not self.enabled:
            return False
        v = float(value)
        if not math.isfinite(v):
            return False
        tick = self._tick(ts)
        with self._lock:
            return self._observe_locked(key, v, kind, tick)

    def observe_many(self, items: Iterable[tuple[str, float]],
                     kind: str = GAUGE, ts: Optional[float] = None,
                     refused: Optional[list] = None) -> int:
        """Append a correlated batch under ONE lock hold (a collector
        snapshot is hundreds of keys; per-key locking would make the
        publish path the fleet layer's own overhead bound violation).
        Returns the number of samples actually stored; ``refused``
        (optional list) collects the keys that were NOT stored
        (cardinality cap / non-finite) so publishers can un-mark them
        in their delta base and retry on the next publish."""
        if not self.enabled:
            return 0
        tick = self._tick(ts)
        n = 0
        with self._lock:
            for key, value in items:
                v = float(value)
                if math.isfinite(v) and self._observe_locked(
                        key, v, kind, tick):
                    n += 1
                elif refused is not None:
                    refused.append(key)
        return n

    def _observe_locked(self, key: str, v: float, kind: str,
                        tick: int) -> bool:
        s = self._series.get(key)
        if s is None:
            if len(self._series) >= self.max_series:
                base = key.split("{", 1)[0]
                self._dropped[base] = self._dropped.get(base, 0) + 1
                # the overflow evidence rides the METER (bounded: one
                # counter per distinct base name), never this store —
                # a store refusing cardinality must not consume it
                meter.add(labeled_key(DROPPED_SERIES_METRIC, metric=base))
                return False
            s = self._series[key] = _Series(key, kind, self.window)
            self._by_base.setdefault(s.base, {})[key] = s
        s.append(tick, v)
        return True

    # --------------------------------------------------------- selection

    def select(self, metric: str,
               labels: Optional[dict[str, str]] = None) -> list[str]:
        """Keys whose base name equals ``metric`` and whose labels are a
        superset of ``labels`` (None/{} matches every label set)."""
        with self._lock:
            out = []
            for key, s in self._by_base.get(metric, {}).items():
                if labels and any(s.labels.get(k) != v
                                  for k, v in labels.items()):
                    continue
                out.append(key)
        return out

    def drop_series(self, labels: dict[str, str]) -> int:
        """Remove every series carrying ALL the given labels (fleet
        churn: an unregistered collector's series must leave the
        aggregates instead of answering queries for a full window).
        Returns the number of series dropped; capacity is freed."""
        with self._lock:
            doomed = [s for s in self._series.values()
                      if all(s.labels.get(lk) == lv
                             for lk, lv in labels.items())]
            for s in doomed:
                del self._series[s.key]
                base = self._by_base.get(s.base)
                if base is not None:
                    base.pop(s.key, None)
                    if not base:
                        del self._by_base[s.base]
        return len(doomed)

    # ----------------------------------------------------------- queries

    def _bounds(self, window_s: Optional[float]) -> tuple[int, int]:
        now_tick = self._tick(None)
        span = self.window if window_s is None else max(
            1, int(math.ceil(window_s / self.interval_s)))
        return now_tick - min(span, self.window) + 1, now_tick

    def _points(self, key: str,
                window_s: Optional[float]) -> list[tuple[float, float]]:
        """(unix-ish seconds, value) points of one series inside the
        query window (None = the whole retained ring), time-ascending."""
        return self._points_with_kind(key, window_s)[0]

    def _points_with_kind(
            self, key: str, window_s: Optional[float]
    ) -> tuple[list[tuple[float, float]], str]:
        """Points + the series' kind from ONE lock hold — rate()/delta()
        need both, and re-reading the kind after the gather races a
        concurrent drop_series into the GAUGE fallback (a counter reset
        would then compute exactly the negative spike reset-awareness
        exists to prevent)."""
        lo, hi = self._bounds(window_s)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                return [], GAUGE
            pts = s.points(lo, hi)
            kind = s.kind
        return [(t * self.interval_s, v) for t, v in pts], kind

    def _window_values(self, key: str,
                       window_s: Optional[float]) -> np.ndarray:
        """UNSORTED window values (order-insensitive reductions — the
        hot query shape the alert engine drives per series per tick)."""
        lo, hi = self._bounds(window_s)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                return _EMPTY
            return s.window_arrays(lo, hi)[1]

    def points(self, key: str,
               window_s: Optional[float] = None) -> list[tuple[float, float]]:
        return self._points(key, window_s)

    def latest(self, key: str,
               window_s: Optional[float] = None) -> Optional[float]:
        """Most recent value inside the window — O(1): the series
        tracks its last (tick, value), and the window check is a
        bounds compare (latest is the default alert-expression fn, so
        it runs once per matching series per evaluation)."""
        lo, hi = self._bounds(window_s)
        with self._lock:
            s = self._series.get(key)
            if s is None or not lo <= s.last_tick <= hi:
                return None
            return s.last_value

    def rate(self, key: str, window_s: float) -> Optional[float]:
        """Per-second increase over the window (counter-aware: resets
        restart accumulation). None when fewer than two points exist —
        a rate over one sample would be an invented number."""
        pts, kind = self._points_with_kind(key, window_s)
        if len(pts) < 2:
            return None
        elapsed = pts[-1][0] - pts[0][0]
        if elapsed <= 0:
            return None
        if kind == COUNTER:
            return _counter_increase(pts) / elapsed
        return (pts[-1][1] - pts[0][1]) / elapsed

    def delta(self, key: str, window_s: float) -> Optional[float]:
        """Total change over the window (counter-aware like rate)."""
        pts, kind = self._points_with_kind(key, window_s)
        if len(pts) < 2:
            return None
        if kind == COUNTER:
            return _counter_increase(pts)
        return pts[-1][1] - pts[0][1]

    def ewma(self, key: str, window_s: float,
             alpha: Optional[float] = None) -> Optional[float]:
        """Exponentially-weighted mean over the window's points, oldest
        first (default alpha = 2/(n+1), the n-period EWMA convention)."""
        pts = self._points(key, window_s)
        if not pts:
            return None
        a = alpha if alpha is not None else 2.0 / (len(pts) + 1)
        acc = pts[0][1]
        for _, v in pts[1:]:
            acc = a * v + (1.0 - a) * acc
        return acc

    def quantile_over_window(self, key: str, q: float,
                             window_s: float) -> Optional[float]:
        vals = self._window_values(key, window_s)
        if not len(vals):
            return None
        vals = np.sort(vals)
        return float(vals[min(int(q * len(vals)), len(vals) - 1)])

    def avg_over_window(self, key: str, window_s: float) -> Optional[float]:
        vals = self._window_values(key, window_s)
        return float(vals.mean()) if len(vals) else None

    def max_over_window(self, key: str, window_s: float) -> Optional[float]:
        vals = self._window_values(key, window_s)
        return float(vals.max()) if len(vals) else None

    def min_over_window(self, key: str, window_s: float) -> Optional[float]:
        vals = self._window_values(key, window_s)
        return float(vals.min()) if len(vals) else None

    def sum_over_window(self, key: str, window_s: float) -> Optional[float]:
        vals = self._window_values(key, window_s)
        return float(vals.sum()) if len(vals) else None

    # the window-function vocabulary alert expressions / aggregation use
    WINDOW_FNS = ("latest", "rate", "delta", "ewma", "avg", "max", "min",
                  "sum", "p50", "p90", "p95", "p99")

    def window_value(self, key: str, fn: str,
                     window_s: float) -> Optional[float]:
        """One windowed value of one series by function name (the alert
        engine's evaluation primitive). Unknown fn raises ValueError —
        callers validate at config time."""
        if fn == "latest":
            return self.latest(key, window_s)
        if fn == "rate":
            return self.rate(key, window_s)
        if fn == "delta":
            return self.delta(key, window_s)
        if fn == "ewma":
            return self.ewma(key, window_s)
        if fn == "avg":
            return self.avg_over_window(key, window_s)
        if fn == "max":
            return self.max_over_window(key, window_s)
        if fn == "min":
            return self.min_over_window(key, window_s)
        if fn == "sum":
            return self.sum_over_window(key, window_s)
        if fn in ("p50", "p90", "p95", "p99"):
            return self.quantile_over_window(
                key, int(fn[1:]) / 100.0, window_s)
        raise ValueError(f"unknown window function {fn!r} "
                         f"(known: {self.WINDOW_FNS})")

    # ------------------------------------------------------- aggregation

    # reductions that vectorize across series in one stacked pass (the
    # alert engine evaluates every matching series per tick — a fleet
    # of hundreds of collectors × per-series numpy-call overhead was
    # the measured cost center, not the ring math itself)
    _BATCH_FNS = ("latest", "avg", "max", "min", "sum")

    def series_values(self, metric: str, fn: str, window_s: float,
                      labels: Optional[dict[str, str]] = None
                      ) -> dict[str, float]:
        """{series key: windowed value} over every matching series —
        the per-series layer; series with no answer (empty window) are
        omitted rather than invented as zero. Order-insensitive
        reductions run as ONE (n_series, window) masked matrix op."""
        if fn not in self._BATCH_FNS:
            out: dict[str, float] = {}
            for key in self.select(metric, labels):
                v = self.window_value(key, fn, window_s)
                if v is not None:
                    out[key] = v
            return out
        lo, hi = self._bounds(window_s)
        with self._lock:
            sers = [s for s in self._by_base.get(metric, {}).values()
                    if not labels or all(s.labels.get(k) == v
                                         for k, v in labels.items())]
            if not sers:
                return {}
            ticks = np.stack([s.ticks for s in sers])
            values = np.stack([s.values for s in sers])
            keys = [s.key for s in sers]
        mask = (ticks >= lo) & (ticks <= hi)
        alive = mask.any(axis=1)
        if fn == "latest":
            idx = np.where(mask, ticks, np.int64(-1)).argmax(axis=1)
            vals = values[np.arange(len(keys)), idx]
        elif fn == "avg":
            cnt = mask.sum(axis=1)
            vals = np.where(mask, values, 0.0).sum(axis=1) \
                / np.maximum(cnt, 1)
        elif fn == "sum":
            vals = np.where(mask, values, 0.0).sum(axis=1)
        elif fn == "max":
            vals = np.where(mask, values, -np.inf).max(axis=1)
        else:  # min
            vals = np.where(mask, values, np.inf).min(axis=1)
        return {k: float(v) for k, v, a in zip(keys, vals, alive) if a}

    AGGREGATIONS = ("sum", "max", "min", "avg", "p50", "p95", "p99",
                    "count")

    def aggregate(self, metric: str, fn: str = "latest",
                  window_s: float = 60.0, agg: str = "sum",
                  labels: Optional[dict[str, str]] = None,
                  by: Optional[str] = None) -> Any:
        """Cross-series aggregation: per-series windowed value via
        ``fn``, combined with ``agg``. ``by=<label>`` groups instead,
        returning {label value: aggregate} (the per-CollectorsGroup
        rollup shape); series missing the label group under ``""``."""
        vals = self.series_values(metric, fn, window_s, labels)
        if by is None:
            return self._combine(list(vals.values()), agg)
        groups: dict[str, list[float]] = {}
        for key, v in vals.items():
            _, lbls = split_key(key)
            groups.setdefault(lbls.get(by, ""), []).append(v)
        return {g: self._combine(vs, agg) for g, vs in groups.items()}

    @staticmethod
    def _combine(vals: list[float], agg: str) -> Optional[float]:
        if agg == "count":
            return float(len(vals))
        if not vals:
            return None
        if agg == "sum":
            return sum(vals)
        if agg == "max":
            return max(vals)
        if agg == "min":
            return min(vals)
        if agg == "avg":
            return sum(vals) / len(vals)
        if agg in ("p50", "p95", "p99"):
            vs = sorted(vals)
            return vs[min(int(int(agg[1:]) / 100.0 * len(vs)),
                          len(vs) - 1)]
        raise ValueError(f"unknown aggregation {agg!r} "
                         f"(known: {SeriesStore.AGGREGATIONS})")

    # --------------------------------------------------------- inventory

    def __len__(self) -> int:
        with self._lock:
            return len(self._series)

    def stats(self) -> dict[str, Any]:
        """JSON-able store inventory (the /api/fleet ``store`` block)."""
        with self._lock:
            by_metric: dict[str, int] = {}
            for s in self._series.values():
                by_metric[s.base] = by_metric.get(s.base, 0) + 1
            return {
                "enabled": self.enabled,
                "series": len(self._series),
                "max_series": self.max_series,
                "interval_s": self.interval_s,
                "window": self.window,
                "bytes_bound": self.max_series * self.window * 16,
                "metrics": len(by_metric),
                "dropped_series": dict(self._dropped),
            }

    def reset(self) -> None:
        """Test isolation (the meter.reset / flow_ledger.reset
        contract)."""
        with self._lock:
            self._series.clear()
            self._by_base.clear()
            self._dropped.clear()


series_store = SeriesStore()
