"""tpuanomaly processor — the north-star component.

The TPU-backed anomaly stage behind the stock processor Factory boundary
(modeled on odigossamplingprocessor/factory.go:13's WithTraces registration):
featurizes incoming span batches, scores them against the ScoringEngine
within a strict latency budget, and tags anomalous spans with score/flag
attributes for the anomalyrouter to route. On timeout or queue-full the batch
passes through unscored — the pipeline never blocks on the TPU (north-star
<5 ms p99 requirement).

Non-TPU installs simply never put ``tpuanomaly`` in a pipeline; nothing else
changes (byte-identical requirement).
"""

from __future__ import annotations

import threading
from typing import Any, Optional

from ...features.featurizer import FeaturizerConfig
from ...pdata.spans import SpanBatch
from ...serving.engine import EngineConfig, ScoringEngine

# tagging lives in serving/fastpath.py so the ingest fast path and this
# processor share ONE implementation (bit-identical output is the parity
# contract); the historic import locations keep working via these names
from ...serving.fastpath import (
    FLAG_ATTR, FLAGGED_METRIC, SCORE_ATTR, tag_anomalies)
from ..api import Capabilities, ComponentKind, Factory, Processor, register

__all__ = ["TpuAnomalyProcessor", "SCORE_ATTR", "FLAG_ATTR",
           "FLAGGED_METRIC", "tag_anomalies"]

# engines shared across processor instances (one TPU sidecar per collector,
# like the reference's one gateway-adjacent model server), keyed by config
_shared_engines: dict[tuple, ScoringEngine] = {}
_shared_lock = threading.Lock()


def _shutdown_shared_engines() -> None:
    """Drain shared engines at interpreter exit — a live scoring thread at
    teardown aborts the TPU runtime client (pthread cancel during PJRT
    destruction)."""
    with _shared_lock:
        engines = list(_shared_engines.values())
        _shared_engines.clear()
    for eng in engines:
        try:
            eng.shutdown()
        except Exception:
            pass


import atexit  # noqa: E402  (registration belongs next to the registry)

atexit.register(_shutdown_shared_engines)


def _engine_for(cfg: EngineConfig, shared: bool) -> ScoringEngine:
    if not shared:
        return ScoringEngine(cfg)
    try:
        hash(cfg)  # every behavioral field participates in the key
    except TypeError:  # unhashable model_config → can't dedupe safely
        return ScoringEngine(cfg)
    key = cfg
    with _shared_lock:
        eng = _shared_engines.get(key)
        if eng is None:
            eng = _shared_engines[key] = ScoringEngine(cfg)
        return eng


class TpuAnomalyProcessor(Processor):
    """Config:
    model: zscore | transformer | autoencoder | mock | remote
    socket_path: unix socket of an out-of-process scoring sidecar
        (model "remote"; serving/sidecar.py)
    threshold: score in [0,1] above which a span is tagged (default 0.8)
    timeout_ms: scoring latency budget before pass-through (default 5.0)
    mesh: {"data": N, "model": M} — multi-chip sharded serving (ISSUE 7):
        the engine owns an N×M device mesh and dispatches every packed
        call through the partition-rule dp×tp plan. ``devices: N`` (what
        pipelinegen renders from anomaly.devices) and ``data_parallel``
        are the legacy pure-DP spellings, honored when mesh is absent.
    attr_slots / max_len / trace_bucket / online_update / checkpoint_path /
    pipeline_depth / bucket_ladder / warm_ladder:
        forwarded to EngineConfig (pipeline_depth 2 = double-buffered
        scoring: host packing overlaps device execution)
    failover: circuit-broken CPU fallback (ISSUE 13) — ``true`` or a
        {window_s, trip_errors, probe_interval_s, recovery_successes,
        fallback_model} mapping; a persistent device fault hot-swaps
        scoring to the zscore CPU route, raises ModelFailover, and
        half-open probes the primary back (serving/failover.py)
    shared_engine: reuse one engine across processor instances (default True)
    """

    capabilities = Capabilities(mutates_data=True)

    # incremental hot reload (ISSUE 14): the two knobs OUTSIDE the
    # EngineConfig identity retune live — the warmed engine (bucket
    # ladder, ScoringPlan caches, failover state) is never rebuilt for
    # a threshold tweak. Any engine-shaping key (model, mesh, batch
    # geometry...) changes the shared-engine identity and replaces the
    # node (or forces a full rebuild under a fast_path alias).
    RECONFIGURABLE_KEYS = frozenset({"threshold", "timeout_ms"})

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        fz = FeaturizerConfig(attr_slots=int(config.get("attr_slots", 0)))
        model = config.get("model", "zscore")
        # a `model_config` mapping sizes the sequence model from pipeline
        # config (d_model, max_len, vocabs, dtype-by-name...); the factory —
        # not the caller — knows how to build the frozen config dataclass
        # (odigossamplingprocessor/factory.go:13 seam)
        model_config = config.get("model_config")
        if isinstance(model_config, dict):
            from ...training.checkpoint import make_model_config

            model_config = make_model_config(model, model_config)
        self.engine_cfg = EngineConfig(
            model=model,
            max_batch_spans=int(config.get("max_batch", 65536)),
            max_len=int(config.get("max_len", 64)),
            trace_bucket=int(config.get("trace_bucket", 256)),
            online_update=bool(config.get("online_update", True)),
            quantized=bool(config.get("quantized", False)),
            featurizer=fz,
            model_config=model_config,
            checkpoint_path=config.get("checkpoint_path"),
            socket_path=config.get("socket_path"),
            mesh=config.get("mesh"),
            # "devices" is what pipelinegen renders from anomaly.devices;
            # it was silently dropped before ISSUE 7 wired the mesh
            data_parallel=int(config.get("data_parallel",
                                         config.get("devices", 0))),
            seed=int(config.get("seed", 0)),
            pipeline_depth=int(config.get("pipeline_depth", 2)),
            bucket_ladder=int(config.get("bucket_ladder", 4)),
            warm_ladder=bool(config.get("warm_ladder", False)),
            failover=config.get("failover"),
            # ISSUE 20: sampled intra-fused attribution (fused route)
            device_attribution=bool(config.get("device_attribution",
                                               False)),
            device_attribution_stride=int(
                config.get("device_attribution_stride", 32)),
        )
        self.engine = _engine_for(self.engine_cfg,
                                  bool(config.get("shared_engine", True)))
        self._apply_knobs(config)

    def _apply_knobs(self, config: dict[str, Any]) -> None:
        # one parse routine for __init__ and reconfigure (no default
        # drift between a reloaded node and a freshly built one)
        self.threshold = float(config.get("threshold", 0.8))
        self.timeout_s = float(config.get("timeout_ms", 5.0)) / 1000.0

    def reconfigure(self, config: dict[str, Any]) -> None:
        self._apply_knobs(config)
        self.config = config

    def start(self) -> None:
        super().start()
        self.engine.start()

    def shutdown(self) -> None:
        # shared engines outlive individual processors; private ones stop
        if not self.config.get("shared_engine", True):
            self.engine.shutdown()
        super().shutdown()

    def process(self, batch: SpanBatch) -> Optional[SpanBatch]:
        # the engine featurizes (or skips it for remote backends, which
        # featurize sidecar-side); passing None avoids doing it twice
        scores = self.engine.score_sync(batch, None,
                                        timeout_s=self.timeout_s)
        if scores is None:  # timeout / queue full: pass through untagged
            return batch
        return tag_anomalies(batch, scores, self.threshold)


register(Factory(
    type_name="tpuanomaly",
    kind=ComponentKind.PROCESSOR,
    create=TpuAnomalyProcessor,
    default_config=lambda: {
        "model": "zscore", "threshold": 0.8, "timeout_ms": 5.0,
        "attr_slots": 0, "max_len": 64, "trace_bucket": 256,
        "online_update": True, "shared_engine": True},
))
