"""Partition rules + sharded score/train step factories.

Megatron-style layout for the trace models (odigos_tpu.models), expressed
as a ``match_partition_rules``-style table of (regex, PartitionSpec) pairs
over the mesh from parallel.mesh:

* attention q/k/v kernels (d_model, n_heads, head_dim): heads on "model"
* attention out kernel (n_heads, head_dim, d_model): heads on "model"
* encoder mlp up kernel (d_model, d_ff): d_ff on "model"; down transposed
* autoencoder decoder ffn + wide vocab heads: d_ff / vocab on "model"
* embedding tables + layernorms + small heads: replicated
* batch (packed-row / trace) axis of inputs: "data"

XLA inserts the all-reduces (psum over "model" after attention-out and
mlp-down) — we only annotate placements, per the scaling-book recipe cited
in the build brief. ``compile_plan`` graduates the rules from a demo
helper into the ScoringEngine's device layer: one plan per (model, mesh)
holding the rule-matched param placements, the explicit in/out shardings
of the packed scoring call, and the donation vector threaded through the
models' ``enable_input_donation`` plumbing.

Numerics contract: "data"-axis sharding is BITWISE identical to single
device (rows are independent; each shard runs the same per-row program).
A "model" axis reassociates the contraction reductions (partial matmul +
psum), so dp×tp parity is ULP-level (~1e-7 at fp32), never bitwise — the
parity suite and the multichip bench assert bitwise on dp and tight
allclose once tp > 1.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import jitstats
from .mesh import mesh_key

# see models/transformer.py: every jitted scoring/training entry point
# declares its recompile-bounding strategy (package hygiene test)
SHAPE_BUCKETING = {
    "make_sharded_score_fn": "delegates to model.score_spans — leading axis "
                             "padded to a data-axis multiple by "
                             "_shard_inputs on top of the engine bucketing",
    "make_sharded_packed_score_fn": "delegates to compile_plan — row "
                                    "axis bucketed by the engine's ladder "
                                    "(rungs lcm-aligned to the data axis)",
    "make_sharded_train_step": "training loop feeds fixed (batch, L) "
                               "shapes from data.py batching; one compile "
                               "per run",
    "compile_plan": "packed row axis bucketed by the engine's "
                    "BucketLadder (rungs lcm-aligned to the data axis, "
                    "warmed once per mesh shape); L/C fixed by the model "
                    "config",
    "packed_score": "the jit compile_plan builds — same row-axis "
                    "bucketing as compile_plan (one executable per "
                    "warmed rung per mesh shape)",
}

# Partition-spec declaration per sharded entry point (package-hygiene
# lint, ISSUE 7 satellite): any factory in parallel/ that jits or places
# arrays under a mesh must say where each tensor class lands — an
# undeclared sharded jit silently runs replicated and burns dp-fold HBM.
PARTITION_SPECS = {
    "compile_plan": "params via PARTITION_RULES (heads/d_ff/vocab on "
                    "'model', rest replicated); packed inputs and scores "
                    "P('data', ...) on rows",
    "make_sharded_score_fn": "params via PARTITION_RULES; (T, L, *) "
                             "inputs P('data', ...) on traces",
    "make_sharded_packed_score_fn": "alias of compile_plan (packed rows "
                                    "on 'data', params by rule table)",
    "make_sharded_train_step": "params/grads/opt state via caller's "
                               "shard_variables placement; batch inputs "
                               "P('data', ...); loss replicated",
    "shard_variables": "rule table (PARTITION_RULES) or explicit spec_fn; "
                       "non-dividing or absent axes fall back to "
                       "replication",
    "packed_score": "the compiled packed-score jit: params by committed "
                    "rule-table placement, (R, L, *) inputs and (R, L) "
                    "scores pinned P('data', ...)",
    "shard_inputs": "batch-leading arrays placed P('data', ...), leading "
                    "dim padded to a data-axis multiple (pad rows stay "
                    "masked)",
}


# ------------------------------------------------------ partition rules

# First-match-wins (re.search over the '/'-joined param path). The
# catch-all replicates embeddings, norms, biases, and small heads —
# sharding those only buys per-call collectives. Param names cover BOTH
# sequence models: flax auto-names (Attention_N, block_N/Dense_0 up /
# Dense_1 down) plus the autoencoder's decoder ffn and wide vocab heads.
PARTITION_RULES: tuple[tuple[str, P], ...] = (
    (r"Attention_\d+/(query|key|value)/kernel$", P(None, "model", None)),
    (r"Attention_\d+/out/kernel$", P("model", None, None)),
    (r"block_\d+/Dense_0/kernel$", P(None, "model")),  # mlp up: d_ff cols
    (r"block_\d+/Dense_1/kernel$", P("model", None)),  # mlp down: d_ff rows
    (r"dec_ff1/kernel$", P(None, "model")),            # autoencoder decoder
    (r"dec_ff2/kernel$", P("model", None)),
    (r"(service|name)_head/kernel$", P(None, "model")),  # wide vocab heads
    (r"", P()),  # embeddings, norms, biases, small heads: replicated
)


def match_partition_rules(params: Any,
                          rules: tuple = PARTITION_RULES) -> Any:
    """Pytree of PartitionSpecs per the rule table (the SNIPPETS.md [1]
    idiom): scalars/size-1 leaves never partition; otherwise the first
    rule whose regex matches the '/'-joined path wins. The shipped table
    ends with a catch-all, so every leaf resolves."""
    def spec_for(path, leaf) -> P:
        if getattr(leaf, "ndim", 0) == 0 or np.prod(
                getattr(leaf, "shape", ())) == 1:
            return P()
        name = "/".join(str(k.key) for k in path)
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        raise ValueError(f"partition rule not found for param: {name}")

    return jax.tree_util.tree_map_with_path(spec_for, params)


def transformer_param_spec(path: tuple, leaf: Any) -> P:
    """Shape-heuristic fallback (pre-rule-table API, kept for callers
    that shard pytrees with no stable names): q/k/v/out by position,
    any large 2D kernel by its grown dimension."""
    names = [str(p) for p in path]
    joined = "/".join(names)
    ndim = getattr(leaf, "ndim", 0)
    if "attention" in joined or any(n in ("query", "key", "value", "out")
                                    for n in names):
        if any(n in ("query", "key", "value") for n in names) and ndim == 3:
            return P(None, "model", None)  # (d_model, heads, head_dim)
        if "out" in names and ndim == 3:
            return P("model", None, None)  # (heads, head_dim, d_model)
    # mlp: first Dense grows to d_ff (shard cols), second shrinks. Size
    # gate keeps tiny matmuls (span/trace heads, embedder projections)
    # replicated — sharding them only buys per-call collectives.
    if ndim == 2 and names[-1] == "kernel":
        in_dim, out_dim = leaf.shape
        if min(in_dim, out_dim) >= 64:
            if out_dim > in_dim:
                return P(None, "model")
            if in_dim > out_dim:
                return P("model", None)
    return P()  # replicate embeddings, norms, biases, heads


def _guard_spec(spec: P, leaf: Any, mesh: Mesh) -> P:
    """Axes must exist in this mesh and divide the dim; fall back to
    replication when they don't (a pure-"data" DP mesh replicates every
    "model"-sharded param)."""
    for axis_name, dim in zip(spec, getattr(leaf, "shape", ())):
        if axis_name is None:
            continue
        if axis_name not in mesh.shape or dim % mesh.shape[axis_name] != 0:
            return P()
    return spec


def shard_variables(variables: Any, mesh: Mesh,
                    spec_fn: Optional[Callable[[tuple, Any], P]] = None,
                    rules: tuple = PARTITION_RULES) -> Any:
    """Place a variable pytree onto the mesh: by the rule table (default,
    resolved through ``match_partition_rules`` — ONE rule-resolution
    path, so placements can never drift from the specs tests and
    describe surfaces report) or an explicit ``spec_fn(path, leaf)``."""
    if spec_fn is not None:
        def place(path, leaf):
            spec = spec_fn(tuple(k.key for k in path), leaf)
            return jax.device_put(
                leaf, NamedSharding(mesh, _guard_spec(spec, leaf, mesh)))

        return jax.tree_util.tree_map_with_path(place, variables)
    specs = match_partition_rules(variables, rules)
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(
            leaf, NamedSharding(mesh, _guard_spec(spec, leaf, mesh))),
        variables, specs)


def batch_spec(mesh: Mesh) -> P:
    return P("data")


def _shard_inputs(mesh: Mesh, arrays: tuple) -> tuple:
    """Place batch-leading arrays on the data axis, padding the leading dim
    up to a multiple of the data-axis size (mask rows stay False)."""
    dp = mesh.shape["data"]
    sharded = []
    for a in arrays:
        n = a.shape[0]
        pad = (-n) % dp
        if pad:
            widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            a = np.pad(np.asarray(a), widths)
        sharded.append(jax.device_put(
            a, NamedSharding(mesh, P("data", *([None] * (a.ndim - 1))))))
    return tuple(sharded)


# ------------------------------------------------------- scoring plans


def _packed_score_jit(model, mesh: Mesh, donate: bool):
    """Compile the packed-scoring fn for one (model, mesh) pairing:
    params ride their committed placement (``place_variables`` has
    already device_put them per the rule table — an explicit in_sharding
    would just restate it); inputs and output are pinned to "data" so
    the call NEVER silently runs replicated even if a caller hands host
    arrays. The donation vector follows the model's
    ``enable_input_donation`` opt-in (TPU-gated by serving_donation)."""
    impl = getattr(model, "_score_packed_impl", None)
    if impl is None:
        return None
    from ..models.transformer import serving_donation

    row = NamedSharding(mesh, P("data", None))
    row3 = NamedSharding(mesh, P("data", None, None))
    return jitstats.track_jit(
        f"parallel.plan.score_packed[{mesh_key(mesh)}]",
        jax.jit(impl,
                in_shardings=(None, row3, row3, row, row),
                out_shardings=row,
                donate_argnums=serving_donation((1, 2, 3, 4), donate)))


class ScoringPlan:
    """One (model, mesh) pairing compiled for serving — the engine's
    device layer (ISSUE 7 tentpole, the ``compile_step_with_plan``
    pattern from SNIPPETS.md [3]).

    Owns: the rule-matched param PartitionSpecs, an identity-cached
    ``place_variables`` (params move to device once per weight pytree,
    not per call), the packed scoring fn jitted with EXPLICIT in/out
    shardings (inputs on "data", scores on "data", params per rules) and
    the donation vector from the model's ``enable_input_donation``
    plumbing, and a propagation-sharded ``score_spans`` for the
    sequence (autoencoder) route. Neither entry blocks on the device:
    the engine harvests against the next in-flight call.
    """

    def __init__(self, model: Any, mesh: Mesh,
                 rules: tuple = PARTITION_RULES,
                 donate: bool = False):
        self.model = model
        self.mesh = mesh
        self.rules = rules
        self.dp = int(mesh.shape.get("data", 1))
        self.tp = int(mesh.shape.get("model", 1))
        self.key = mesh_key(mesh)
        # cache the placed pytree of the last-seen weights. Keyed by id()
        # ALONE this is unsound — a GC'd pytree's address can be reused
        # and serve stale weights — so the cache holds a strong ref to
        # the source pytree and revalidates by identity against it.
        self._cache: dict[str, Any] = {"source": None, "placed": None}
        self._packed_jit = _packed_score_jit(model, mesh, donate)

    def param_specs(self, variables: Any) -> Any:
        """Rule-matched PartitionSpec pytree for a weight pytree
        (mesh-guarded: non-dividing or absent axes replicate) — what
        ``place_variables`` commits, exposed for tests and describe
        surfaces."""
        specs = match_partition_rules(variables, self.rules)
        flat_v = jax.tree_util.tree_leaves(variables)
        flat_s, treedef = jax.tree_util.tree_flatten(
            specs, is_leaf=lambda x: isinstance(x, P))
        guarded = [_guard_spec(s, v, self.mesh)
                   for s, v in zip(flat_s, flat_v)]
        return jax.tree_util.tree_unflatten(treedef, guarded)

    def place_variables(self, variables: Any) -> Any:
        """Device placement per the rule table, cached by identity."""
        if self._cache["source"] is not variables:
            self._cache["source"] = variables
            self._cache["placed"] = shard_variables(
                variables, self.mesh, rules=self.rules)
        return self._cache["placed"]

    def score_packed(self, variables, categorical, continuous, segments,
                     positions):
        """Sharded packed scoring; returns the (R, L) device array
        WITHOUT blocking (the engine's harvest stage fetches it)."""
        R = np.asarray(segments).shape[0]
        if R % self.dp:
            raise ValueError(
                f"packed rows {R} not divisible by data axis {self.dp}; "
                f"the engine's BucketLadder aligns rungs to the mesh — "
                f"pad rows with ladder.round_rows")
        v = self.place_variables(variables)
        categorical, continuous, segments, positions = _shard_inputs(
            self.mesh, (categorical, continuous, segments, positions))
        return self._packed_jit(v, categorical, continuous, segments,
                                positions)

    def placed_bytes(self) -> int:
        """Bytes held on device by the cached placed weight pytree (the
        plan's staging footprint — 0 until ``place_variables`` ran).
        Read by the DeviceRuntimeCollector's device-table gauges
        (ISSUE 20): the fused route's resident footprint is tables +
        whatever each live plan keeps placed."""
        placed = self._cache.get("placed")
        if placed is None:
            return 0
        total = 0
        for leaf in jax.tree_util.tree_leaves(placed):
            total += int(getattr(leaf, "nbytes", 0) or 0)
        return total

    def score_spans(self, variables, categorical, continuous, mask):
        """Sequence-route scoring (autoencoder): params per rules, inputs
        on "data"; the model's own jit propagates the placements and XLA
        inserts the collectives. Non-blocking device results."""
        v = self.place_variables(variables)
        categorical, continuous, mask = _shard_inputs(
            self.mesh, (categorical, continuous, mask))
        return self.model.score_spans(v, categorical, continuous, mask)


def compile_plan(model, mesh: Mesh, *, rules: tuple = PARTITION_RULES,
                 donate: Optional[bool] = None) -> ScoringPlan:
    """Build the (model, mesh) serving plan. ``donate=None`` follows the
    model's ``enable_input_donation`` opt-in (the engine calls it before
    compiling the plan, so the donation vector rides through here)."""
    if donate is None:
        donate = bool(getattr(model, "_donate_inputs", False))
    return ScoringPlan(model, mesh, rules=rules, donate=donate)


# ------------------------------------------------ legacy factory seams


def make_sharded_score_fn(model, mesh: Mesh):
    """Data/tensor-parallel scoring: variables pre-sharded per the rules,
    inputs split on "data". Returns fn(variables, cat, cont, mask) ->
    (span_scores, trace_scores) gathered to host-replicated arrays."""

    def score(variables, cat, cont, mask):
        n = np.asarray(mask).shape[0]
        cat, cont, mask = _shard_inputs(mesh, (cat, cont, mask))
        # model.score_spans is jitted; XLA propagates the dp/tp shardings
        # from argument placements and inserts the collectives
        span_p, trace_p = model.score_spans(variables, cat, cont, mask)
        return np.asarray(span_p)[:n], np.asarray(trace_p)[:n]

    return score


def make_sharded_train_step(model, tx, mesh: Mesh):
    """Full sharded train step (used by __graft_entry__.dryrun_multichip and
    train.loop): grads computed under dp(batch) x tp(params) sharding; optax
    update applied in the same placement; loss replicated.
    """

    @jax.jit
    def step(variables, opt_state, cat, cont, mask, span_labels, trace_labels):
        loss, grads = jax.value_and_grad(model.loss_fn)(
            variables, cat, cont, mask, span_labels, trace_labels)
        updates, opt_state = tx.update(grads, opt_state, params=variables)
        import optax

        variables = optax.apply_updates(variables, updates)
        return variables, opt_state, loss

    def run(variables, opt_state, cat, cont, mask, span_labels, trace_labels):
        cat, cont, mask, span_labels, trace_labels = _shard_inputs(
            mesh, (cat, cont, mask, span_labels, trace_labels))
        return step(variables, opt_state, cat, cont, mask, span_labels,
                    trace_labels)

    return run


def make_sharded_packed_score_fn(model, mesh: Mesh, block: bool = True):
    """Data-parallel **packed** scoring (BASELINE config #5: DP across
    v5e-8) — kept as the thin pre-plan API over ``compile_plan``.

    ``block=False`` returns the (R, L) device array without the host
    fetch: the pipelined engine harvests it against the *next* in-flight
    call so the transfer overlaps device execution. R is unpadded (the
    divisibility check guarantees it), so no trailing-slice is needed.
    """
    plan = compile_plan(model, mesh, donate=False)

    def score(variables, cat, cont, segments, positions):
        R = np.asarray(segments).shape[0]
        span_p = plan.score_packed(variables, cat, cont, segments,
                                   positions)
        if not block:
            return span_p
        return np.asarray(span_p)[:R]

    return score
