"""Collector receiver draining shared-memory span rings.

The odigosebpfreceiver role (SURVEY.md §2.3): a connector goroutine gets the
ring FDs from the handoff socket, a drain loop turns records into batches.
Producer restarts are survived by re-requesting the FDs when a ring goes
quiet and its name re-registers (reader-swap, odigosebpfreceiver.go:74-93).

Config:
  socket_path:     handoff socket to fetch rings from (optional)
  interval_s:      drain poll interval (default 0.01)
  max_records:     per-drain record cap (default 65536)
  refresh_idle_s:  re-request the handoff after this long with zero spans
                   drained (default 2.0) — picks up restarted producers'
                   replacement rings and newly instrumented processes
Rings may also be attached directly via ``attach_ring`` (tests, same-process
producers).
"""

from __future__ import annotations

import threading
from typing import Any

from ..components.api import ComponentKind, Factory, Receiver, Signal, register
from ..utils.telemetry import meter
from .ring import SpanRing
from .unixfd import receive_rings


class ShmSpanReceiver(Receiver):
    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self._rings: dict[str, SpanRing] = {}
        # names owned by the handoff inventory (vs attach_ring callers):
        # only these are eligible for stale-detach on refresh
        self._handoff_names: set[str] = set()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._lock = threading.Lock()

    def attach_ring(self, name: str, ring: SpanRing,
                    _from_handoff: bool = False) -> None:
        # close-under-lock: drain_once also drains under the lock, so the
        # old ring can never be freed while a native drain is inside it
        with self._lock:
            old = self._rings.get(name)
            self._rings[name] = ring
            if _from_handoff:
                self._handoff_names.add(name)
            else:
                self._handoff_names.discard(name)
            if old is not None:
                old.close()

    def refresh_rings(self) -> int:
        """Re-request the handoff and swap in any ring whose memfd identity
        changed (or is new). Returns rings (re)attached. The reference's
        reader-swap on odiglet restart (odigosebpfreceiver.go:74-93)."""
        # "socket" is the generated-config spelling (pipelinegen
        # nodecollector.py), "socket_path" the programmatic one
        path = str(self.config.get("socket_path")
                   or self.config.get("socket") or "")
        if path.startswith("${") and path.endswith("}"):
            # "${SPANRING_SOCKET}" — the odiglet injects the handoff path
            # into the node collector's env (unixfd server wiring)
            import os as _os
            path = _os.environ.get(path[2:-1], "")
        if not path:
            return 0
        import os
        swapped = 0
        handoff = receive_rings(path)
        for ring_name, fd in handoff.items():
            try:
                st = os.fstat(fd)
                with self._lock:
                    current = self._rings.get(ring_name)
                if current is not None and current.identity == (st.st_dev,
                                                                st.st_ino):
                    os.close(fd)  # same ring; nothing to do
                    continue
                self.attach_ring(ring_name, SpanRing.attach(fd),
                                 _from_handoff=True)
                swapped += 1
            except (OSError, ValueError):
                # not-yet-initialized or torn ring: close the fd, keep the
                # rest of the handoff working
                try:
                    os.close(fd)
                except OSError:
                    pass
        # The handoff is the full current inventory *of handoff-owned
        # rings*: ones it no longer names belong to exited producers —
        # detach them so their mmaps and drain work don't leak. Rings
        # attached directly (same-process producers) are not its to revoke.
        with self._lock:
            gone = [n for n in self._handoff_names if n not in handoff]
            stale = {n: self._rings.pop(n) for n in gone if n in self._rings}
            self._handoff_names -= set(gone)
        for ring in stale.values():
            ring.close()
        if stale:
            meter.add("odigos_receiver_detached_rings_total"
                      f"{{receiver={self.name}}}", len(stale))
        return swapped

    def start(self) -> None:
        super().start()
        try:
            self.refresh_rings()
        except Exception:
            # handoff socket not up yet (odiglet starting): the drain loop
            # retries on its idle schedule; never fail pipeline startup
            pass
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"shmspan-{self.name}")
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            rings, self._rings = dict(self._rings), {}
        for ring in rings.values():
            ring.close()
        super().shutdown()

    def drain_once(self) -> int:
        """One pass over all rings; returns spans delivered (sync test
        hook, also the loop body)."""
        delivered = 0
        with self._lock:  # same lock as attach_ring: no swap mid-drain
            batches = []
            for ring_name, ring in self._rings.items():
                batch = ring.drain(int(self.config.get("max_records",
                                                       65536)))
                if batch is not None:
                    batches.append(batch)
        for batch in batches:  # consume outside the lock
            try:
                self.next_consumer.consume(batch)
                delivered += len(batch)
            except Exception:
                meter.add("odigos_receiver_refused_batches_total"
                          f"{{receiver={self.name}}}")
        return delivered

    def _run(self) -> None:
        import time
        interval = float(self.config.get("interval_s", 0.01))
        refresh_idle = float(self.config.get("refresh_idle_s", 2.0))
        last_active = time.monotonic()
        while not self._stop.is_set():
            if self.drain_once() == 0:
                if time.monotonic() - last_active > refresh_idle:
                    try:
                        self.refresh_rings()
                    except Exception:
                        pass  # handoff unreachable/garbled; retry next window
                    last_active = time.monotonic()
                self._stop.wait(interval)
            else:
                last_active = time.monotonic()


register(Factory(
    type_name="shmspan", kind=ComponentKind.RECEIVER,
    create=ShmSpanReceiver, signals=(Signal.TRACES,),
    default_config=lambda: {"interval_s": 0.01, "max_records": 65536}))

# the name the generated node-collector config uses for this receiver
# (pipelinegen/nodecollector.py emits "spanring"; this is the same
# component under its config-facing name — odigosebpfreceiver analog)
register(Factory(
    type_name="spanring", kind=ComponentKind.RECEIVER,
    create=ShmSpanReceiver, signals=(Signal.TRACES,),
    default_config=lambda: {"interval_s": 0.01, "max_records": 65536}))
