"""Train→serve checkpoint bridge (the serving-bundle format).

The reference keeps all resumable state in CRDs and has no model artifacts
(SURVEY.md §5.4); model checkpoints are the durability requirement the TPU
scoring stage adds. This module is the seam between the trainer's
step-indexed orbax CheckpointManager (training/trainer.py) and the serving
engine (serving/engine.py SequenceBackend): an exported **serving bundle**
is a directory holding

    <dir>/variables/   orbax StandardCheckpointer tree (model variables only,
                       no optimizer state)
    <dir>/model.json   {"model": "transformer" | "autoencoder",
                        "config": {<dataclass fields, dtype by name>}}

so serving rebuilds the exact model geometry (vocab sizes, d_model, max_len)
from the artifact instead of requiring the pipeline config to re-specify it —
the config→processor seam of the reference's
odigossamplingprocessor/factory.go:13, where the factory alone knows how to
turn config into a runnable component.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Optional

MODEL_META_FILE = "model.json"
VARIABLES_DIR = "variables"


# ------------------------------------------------------------- model config

def _dtype_name(dtype: Any) -> str:
    import numpy as np

    return np.dtype(dtype).name


def _resolve_dtype(name: str) -> Any:
    import jax.numpy as jnp

    table = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
             "float16": jnp.float16, "float64": jnp.float64}
    try:
        return table[name]
    except KeyError:
        raise ValueError(f"unsupported checkpoint dtype {name!r} "
                         f"(known: {sorted(table)})") from None


def config_to_dict(model_config: Any) -> dict[str, Any]:
    """JSON-safe dict of a TransformerConfig/AutoencoderConfig."""
    d = dataclasses.asdict(model_config)
    if "dtype" in d:
        d["dtype"] = _dtype_name(d["dtype"])
    return d


def make_model_config(model: str, fields: Optional[dict[str, Any]] = None):
    """Build the frozen config dataclass for ``model`` from plain-dict
    fields (e.g. a pipeline-config ``model_config`` block or a bundle's
    model.json). Unknown keys are rejected so config typos fail loudly."""
    fields = dict(fields or {})
    if "dtype" in fields and isinstance(fields["dtype"], str):
        fields["dtype"] = _resolve_dtype(fields["dtype"])
    if model == "transformer":
        from ..models import TransformerConfig

        return TransformerConfig(**fields)
    if model == "autoencoder":
        from ..models import AutoencoderConfig

        return AutoencoderConfig(**fields)
    raise ValueError(f"model {model!r} has no config class "
                     "(known: transformer, autoencoder)")


# ----------------------------------------------------------------- save/load

def save_bundle(path: str, variables: Any, *, model: str,
                model_config: Any) -> str:
    """Write a serving bundle; returns the absolute bundle path."""
    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    ck = ocp.StandardCheckpointer()
    vdir = os.path.join(path, VARIABLES_DIR)
    # the artifact must be device-agnostic: numpy leaves carry no sharding
    # metadata, so a bundle trained on TPU restores in a CPU-only process
    # (and vice versa) without device resolution
    import numpy as np

    ck.save(vdir, jax.tree.map(np.asarray, variables), force=True)
    ck.wait_until_finished()
    meta = {"model": model, "config": config_to_dict(model_config)}
    with open(os.path.join(path, MODEL_META_FILE), "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
    return path


@dataclasses.dataclass(frozen=True)
class ServingBundle:
    model: str            # "transformer" | "autoencoder"
    model_config: Any     # TransformerConfig | AutoencoderConfig
    variables: Any        # restored variables pytree


def load_bundle(path: str) -> ServingBundle:
    """Load a serving bundle written by :func:`save_bundle`."""
    path = os.path.abspath(path)
    meta_path = os.path.join(path, MODEL_META_FILE)
    if not os.path.exists(meta_path):
        raise FileNotFoundError(
            f"{path} is not a serving bundle (missing {MODEL_META_FILE}); "
            "export one with Trainer.export() / save_bundle()")
    with open(meta_path) as f:
        meta = json.load(f)
    cfg = make_model_config(meta["model"], meta.get("config"))
    return ServingBundle(model=meta["model"], model_config=cfg,
                         variables=restore_variables(path))


def restore_variables(path: str, template: Any = None) -> Any:
    """Restore the variables pytree from a bundle directory (or directly
    from an orbax StandardCheckpointer directory)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    vdir = os.path.join(path, VARIABLES_DIR)
    if not os.path.isdir(vdir):
        vdir = path  # raw orbax dir, no bundle wrapper
    ck = ocp.StandardCheckpointer()
    if template is None:
        # derive a host-side template from checkpoint metadata so restore
        # never resolves saved device/sharding info (a TPU-trained bundle
        # must load in a CPU-only sidecar)
        try:
            import jax
            import numpy as np

            tree = ck.metadata(vdir).item_metadata.tree
            template = jax.tree.map(
                lambda m: np.zeros(m.shape, m.dtype), tree)
        except Exception:
            return ck.restore(vdir)  # pre-metadata orbax: best effort
    return ck.restore(vdir, template)
