"""Ingest fast path: wire frame → featurized, device-ready arrays with
no per-span Python and no intermediate re-materialization.

The componentwise route re-touches every span several times between the
socket and the device: the memory limiter estimates bytes, the batch
processor buffers and re-concatenates (string tables re-interned
span-by-span), and the engine re-derives features for each merged batch.
``SOAK.json`` shows the consequence — a single sender drives e2e p99 to
~1.2 s while the device itself scores in 2 ms. This module is the
shortcut the ROADMAP's "kill the soak tail" item asks for:

* the receiver hands each zero-copy ``decode_frame`` batch straight to
  :class:`IngestFastPath`, which featurizes it ONCE (hash tables
  memoized per interned string pool, attr slots memoized per store) and
  submits to the scoring engine with an **admission deadline**;
* the engine coalesces those pre-featurized requests column-only
  (``_ColumnBatch`` — no merged SpanBatch, no re-intern, no attr-store
  merge) and sizes each device call adaptively from the observed step
  cost so harvest lands inside the deadline (``engine._adaptive_cap``);
* a single forwarder thread retires requests FIFO, tags anomalies, and
  forwards downstream — the receiver thread never blocks on scoring, so
  wire intake overlaps device execution end-to-end;
* overload is bounded twice: the engine's own queue (engine-side
  ``queue_full`` accounting) and this route's pending-span window —
  saturation raises :class:`FastPathSaturated`, which the wire receiver
  answers with REJECTED (clients back off and retry), named in the flow
  ledger as ``queue_full`` so no shed span is ever silent. Watermarks
  published here and by the engine feed the receiver's pre-decode
  admission gate (wire/server.py) so a storm is shed before decode.

Deadline expiry never drops data: like the tpuanomaly processor's
timeout, an expired request forwards unscored (pass-through counter
fires) and the late scores still land in online state.

Built by ``pipeline/graph.build_graph`` when a pipeline sets
``fast_path`` — it reuses the pipeline's tpuanomaly engine + threshold,
so fast-path scores are bit-identical to the componentwise path at equal
request grouping (tests/test_ingest_fastpath.py pins this).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Optional

import numpy as np

# deliberately no components.api import: the tpuanomaly processor imports
# this module for the shared tagging helper, so depending on the
# components package here would be a cycle whichever package loads first
from ..features.featurizer import featurize
from ..hooks.tracecontext import _active
from ..pdata.spans import SpanBatch
from ..selftelemetry.flow import FlowContext
from ..selftelemetry.latency import Stage, claim_clock, latency_ledger
from ..utils.telemetry import labeled_key, meter
from .engine import PASSTHROUGH_METRIC, ScoringEngine

SCORE_ATTR = "odigos.anomaly.score"
FLAG_ATTR = "odigos.anomaly"
FLAGGED_METRIC = "odigos_anomaly_flagged_spans_total"

SPANS_METRIC = "odigos_fastpath_spans_total"
SATURATED_METRIC = "odigos_fastpath_saturated_total"
FORWARD_ERRORS_METRIC = "odigos_fastpath_forward_errors_total"

# flow-ledger watermark identity prefix: each instance reports as
# "fastpath/<pipeline>" — two fast-path pipelines must never clobber
# each other's pending_spans reading (last-writer-wins would let a
# quiet pipeline mask a saturated one at the admission gate)
WATERMARK_PREFIX = "fastpath"


def tag_anomalies(batch: SpanBatch, scores: np.ndarray,
                  threshold: float) -> SpanBatch:
    """Attribute-tag spans scoring at or above ``threshold`` — the one
    tagging implementation shared by the tpuanomaly processor and the
    fast path (bit-identical output is the parity contract)."""
    mask = scores >= threshold
    n_flagged = int(mask.sum())
    if n_flagged == 0:
        return batch
    meter.add(FLAGGED_METRIC, n_flagged)
    return batch.with_span_attrs({
        SCORE_ATTR: np.round(scores[mask], 4).tolist(),
        FLAG_ATTR: [True] * n_flagged,
    }, mask)


class FastPathSaturated(RuntimeError):
    """Raised to the receiver when the pending window is full: the wire
    answer is REJECTED, the client backs off, the ledger names the shed."""


class IngestFastPath:
    """Config (the pipeline's ``fast_path`` mapping; ``true`` = defaults):
    deadline_ms:       admission deadline per frame (default: the
                       scoring processor's timeout_ms)
    max_pending_spans: pending-window bound before REJECTED (default 128k)

    Duck-types the Component lifecycle (name/start/shutdown/health) so
    the graph can manage it, without importing components.api (see the
    module-cycle note above).
    """

    def __init__(self, pipeline: str, engine: ScoringEngine,
                 threshold: float, downstream: Any,
                 config: dict[str, Any]):
        self.name = str(config.get("name", "fastpath"))
        self.config = config
        self._started = False
        self.pipeline = pipeline
        self.engine = engine
        self.threshold = float(threshold)
        self.downstream = downstream
        self.deadline_ms = float(config.get("deadline_ms", 25.0))
        self.max_pending_spans = int(config.get("max_pending_spans",
                                                128 * 1024))
        self._feat_cfg = engine.cfg.featurizer
        self._needs_features = getattr(engine.backend, "needs_features",
                                       True)
        # stage-waterfall aggregation rides per pipeline; the admission
        # deadline is this route's burn budget (ISSUE 8)
        latency_ledger.set_deadline(pipeline, self.deadline_ms)
        # (batch, request, deadline_ns, enqueued_ns, stage clock)
        self._window: deque[tuple[SpanBatch, Any, int, int, Any]] = deque()
        self._lock = threading.Lock()
        self._have = threading.Condition(self._lock)
        self._pending_spans = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._wm_component = f"{WATERMARK_PREFIX}/{pipeline}"
        self._spans_key = labeled_key(SPANS_METRIC, pipeline=pipeline)
        self._saturated_key = labeled_key(SATURATED_METRIC,
                                          pipeline=pipeline)
        self._errors_key = labeled_key(FORWARD_ERRORS_METRIC,
                                       pipeline=pipeline)

    # ------------------------------------------------------------ intake
    def consume(self, batch: SpanBatch) -> None:
        """Receiver-thread half: featurize once (memoized pools), stamp
        the admission deadline, submit, append to the FIFO window. Never
        blocks on scoring."""
        n = len(batch)
        if n == 0:
            return  # the componentwise path drops empties in batch concat
        # latency attribution (ISSUE 8): adopt the receiver-started stage
        # clock (admission/decode already stamped) or start one for a
        # direct feed; the active self-trace (the pipeline/<name> span)
        # becomes the exemplar every histogram sample of this frame links
        clock = claim_clock()
        clock.bind_trace(_active.get())
        with self._lock:
            if self._pending_spans + n > self.max_pending_spans:
                meter.add(self._saturated_key)
                err = FastPathSaturated(
                    f"{self.name}: {self._pending_spans} spans pending "
                    f"(bound {self.max_pending_spans}); receiver should "
                    f"answer REJECTED")
                # named shed, marked so the entry edge does not also
                # count the unwind as failed (memory_limiter discipline)
                FlowContext.drop(n, "queue_full", component=self, exc=err)
                raise err
            # RESERVE inside the check's lock hold: concurrent receiver
            # threads racing the featurize window below must not all
            # pass the bound at once — the pending window IS the
            # latency budget, so an N-thread overshoot is p99 inflation
            self._pending_spans += n
            FlowContext.watermark(self._wm_component, "pending_spans",
                                  self._pending_spans)
        try:
            feats = featurize(batch, self._feat_cfg) \
                if self._needs_features else None
            clock.stamp(Stage.FEATURIZE)
            now = time.monotonic_ns()
            deadline = now + int(self.deadline_ms * 1e6)
            # req None = engine queue full / draining: the engine already
            # counted the shed request; the batch still forwards unscored
            # (lossless pass-through, exactly the tpuanomaly contract)
            req = self.engine.submit(batch, feats, deadline_ns=deadline)
            clock.stamp(Stage.ENQUEUE)
        except BaseException:
            with self._lock:
                self._pending_spans -= n  # release the reservation
                FlowContext.watermark(self._wm_component,
                                      "pending_spans",
                                      self._pending_spans)
            raise
        meter.add(self._spans_key, n)
        with self._have:
            self._window.append((batch, req, deadline, now, clock))
            # pending_ms — age of the OLDEST pending frame — is the
            # throughput-invariant admission signal: a span-denominated
            # bound means N ms of queue on a slow box but over-sheds a
            # fast one, while head age IS the latency budget directly
            FlowContext.watermark(
                self._wm_component, "pending_ms",
                (now - self._window[0][3]) / 1e6)
            self._have.notify()

    # --------------------------------------------------------- forwarding
    def _run(self) -> None:
        """Forwarder half: retire FIFO, wait out at most the remaining
        deadline, tag, forward. Downstream failures are accounted by the
        flow edges and must never kill this thread."""
        while True:
            with self._have:
                while not self._window:
                    if self._stop.is_set():
                        return
                    self._have.wait(0.05)
                batch, req, deadline, _t0, clock = self._window[0]
            try:
                scores = None
                expired = False
                if req is not None:
                    wait_s = max((deadline - time.monotonic_ns()) / 1e9,
                                 0.0)
                    if req.done.wait(wait_s):
                        scores = req.scores
                    else:
                        expired = True
                        meter.add(PASSTHROUGH_METRIC, len(batch))
                if scores is not None and req.stage_ns is not None:
                    # fold the engine call's queue/pack/device/harvest
                    # boundaries into this frame's timeline (same
                    # monotonic clock domain); WAIT then measures the
                    # head-of-line gap between scores landing and this
                    # forwarder picking the frame up
                    clock.merge_engine(req.stage_ns)
                clock.stamp(Stage.WAIT)
                out = batch if scores is None else \
                    tag_anomalies(batch, scores, self.threshold)
                clock.stamp(Stage.TAG)
                try:
                    self.downstream.consume(out)
                finally:
                    # observed even when consume raises: a downstream
                    # outage is exactly when the SLO tracker must keep
                    # seeing frames (an unfed tracker reads burn 0.0
                    # during the incident it exists to page on)
                    clock.stamp(Stage.FORWARD)
                    latency_ledger.observe(self.pipeline, clock,
                                           scored=scores is not None,
                                           n_spans=len(batch))
                    if expired:
                        # every expired deadline names a blamed stage:
                        # the device call that outran the budget when
                        # the request had been dispatched, the engine
                        # queue when it never left it (ISSUE 8 blame)
                        latency_ledger.record_expiry(
                            self.pipeline,
                            Stage.DEVICE if req.dispatched_ns
                            else Stage.QUEUE, len(batch))
            except Exception:  # noqa: BLE001 — edge-accounted; keep serving
                meter.add(self._errors_key)
            finally:
                with self._lock:
                    self._window.popleft()
                    self._pending_spans -= len(batch)
                    FlowContext.watermark(self._wm_component,
                                          "pending_spans",
                                          self._pending_spans)
                    FlowContext.watermark(
                        self._wm_component, "pending_ms",
                        (time.monotonic_ns() - self._window[0][3]) / 1e6
                        if self._window else 0.0)
                    if not self._window:
                        # wake drain() waiters the instant the window
                        # empties (a polled drain quantizes shutdown
                        # and every bench round to its sleep interval)
                        self._have.notify_all()

    # ------------------------------------------------------------ ledger
    def flow_pending(self) -> int:
        """Spans submitted but not yet forwarded — the conservation
        checker's in-flight term for this route."""
        with self._lock:
            return self._pending_spans

    # --------------------------------------------------------- lifecycle
    def healthy(self) -> bool:
        return True

    def health(self) -> tuple[str, str, str]:
        # the rollup attaches Degraded(QueueSaturation) itself from the
        # ledger's queue_full evidence; base condition mirrors Component
        return ("Healthy", "Running", "")

    def start(self) -> None:
        self._started = True
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"fastpath-{self.pipeline}")
            self._thread.start()

    def drain(self, timeout: float = 30.0) -> bool:
        """Wait until the pending window empties (everything submitted
        has been forwarded downstream). Condition-signaled by the
        forwarder's last retire — returns the instant the window
        empties, never a poll interval later."""
        deadline = time.monotonic() + timeout
        with self._have:
            while self._window:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._have.wait(min(remaining, 0.05))
            return True

    def shutdown(self) -> None:
        # lossless drain: the engine keeps scoring until its own
        # shutdown, so every windowed request resolves (or times out
        # into pass-through) before the forwarder exits
        self.drain()
        self._stop.set()
        with self._have:
            self._have.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._started = False
