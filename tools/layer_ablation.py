"""Per-stage ablation of the flagship scoring path on the real device.

Times the packed forward at n_layers = 0..4 on the SAME parameter tree
(flax apply ignores params the truncated module never references), at
the bench geometry, with the forced-execution methodology (rotated
inputs, scalar accumulation, one fetch — block_until_ready does not
synchronize through the axon tunnel). n_layers=0 is the embed+mask+heads
trunk; successive deltas are true per-encoder-block costs.

Output: one JSON line + LAYER_ABLATION.json. This is the evidence base
for kernel work — optimize what measures slow, not what looks slow.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ROWS, MAX_LEN, N_LAYERS = 3072, 64, 4  # bench.py flagship geometry


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from odigos_tpu.features import featurize, pack_sequences
    from odigos_tpu.models import TraceTransformer, TransformerConfig
    from odigos_tpu.pdata import synthesize_traces

    dev = jax.devices()[0]
    full_model = TraceTransformer(TransformerConfig(
        dtype=jnp.bfloat16, max_len=MAX_LEN, n_layers=N_LAYERS))
    variables = full_model.init(jax.random.PRNGKey(0))

    packs = []
    for s in range(4):
        b = synthesize_traces(16384, seed=7 + s)
        p = pack_sequences(b, featurize(b), max_len=MAX_LEN,
                           pad_rows_to=ROWS)
        packs.append(tuple(jnp.asarray(a) for a in (
            p.categorical, p.continuous, p.segments, p.positions)))
    n_spans = int(np.asarray(packs[0][2] > 0).sum())

    def timeit(fn, n=20):
        np.asarray(fn(*packs[0]).astype(jnp.float32).sum())  # compile+sync
        t0 = time.perf_counter()
        acc = None
        for i in range(n):
            s = fn(*packs[i % len(packs)]).astype(jnp.float32).sum()
            acc = s if acc is None else acc + s
        float(acc)
        return (time.perf_counter() - t0) / n * 1e3  # ms

    out = {"platform": dev.platform, "device": str(dev),
           "rows": ROWS, "max_len": MAX_LEN, "n_spans": n_spans,
           "stages_ms": {}, "per_block_ms": {}}
    prev = None
    for k in range(N_LAYERS + 1):
        model_k = TraceTransformer(TransformerConfig(
            dtype=jnp.bfloat16, max_len=MAX_LEN, n_layers=k))
        ms = timeit(lambda *a, m=model_k: m.score_packed(variables, *a))
        out["stages_ms"][f"n_layers={k}"] = round(ms, 3)
        if prev is not None:
            out["per_block_ms"][f"block_{k - 1}"] = round(ms - prev, 3)
        prev = ms
        print(f"n_layers={k}: {ms:.3f} ms", file=sys.stderr, flush=True)
    full_ms = out["stages_ms"][f"n_layers={N_LAYERS}"]
    out["spans_per_sec"] = round(n_spans / (full_ms / 1e3))
    with open(os.path.join(REPO, "LAYER_ABLATION.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
