"""Operator: single-resource installer.

Reference: operator/internal/controller/odigos_controller.go — apply ONE
``Odigos`` resource and its reconciler installs the whole stack (there via
Helm, here by writing the authored configuration the scheduler chain
consumes); delete it and the stack is uninstalled (:138 uninstall). Status
lands in conditions on the resource.

The operator sits ABOVE the scheduler: it owns the authored ConfigMap the
same way the reference's operator owns the Helm release, and the existing
level-triggered chain (scheduler → effective config → collectors groups →
autoscaler → gateway config) does the actual install.
"""

from __future__ import annotations

from typing import Optional

from ..api.resources import (
    Condition,
    ConditionStatus,
    ConfigMap,
    ObjectMeta,
    Odigos,
)
from ..api.store import ControllerManager, Store
from ..config.model import Configuration, Tier
from ..utils.auth import TokenError, validate_token_audience
from .scheduler import (
    AUTHORED_CONFIG_NAME,
    EFFECTIVE_CONFIG_NAME,
    GATEWAY_GROUP_NAME,
    NODE_GROUP_NAME,
    ODIGOS_NAMESPACE,
)

INSTALLED_CONDITION = "Installed"


class Operator:
    """Reconciles ``Odigos`` resources into an installed (or uninstalled)
    stack. One instance per control plane, like Scheduler/Autoscaler."""

    def __init__(self, store: Store, manager: ControllerManager) -> None:
        self.store = store
        manager.register("odigos-operator", self, {"Odigos": None})

    # ----------------------------------------------------------- reconcile

    def reconcile(self, store: Store, key: tuple[str, str]) -> None:
        odigos = store.get("Odigos", *key)
        if not isinstance(odigos, Odigos):
            # resource deleted → uninstall (odigos_controller.go:138), but
            # only when NO Odigos resource remains: deleting one of two
            # must not tear down the survivor's stack. Re-reconcile the
            # survivor so its install state is restored immediately.
            remaining = store.list("Odigos")
            if not remaining:
                self._uninstall(store)
                return
            survivor = remaining[0]
            self.reconcile(store, (survivor.meta.namespace,
                                   survivor.meta.name))
            return

        tier = Tier.COMMUNITY
        if odigos.on_prem_token:
            # the audience claim IS the entitlement — a cloud token must
            # not escalate to onprem through the operator path any more
            # than through the CLI (odigosauth.go checkTokenAttributes)
            try:
                _, aud = validate_token_audience(odigos.on_prem_token)
                if aud not in (Tier.ONPREM.value, Tier.CLOUD.value):
                    raise TokenError(
                        f"token audience {aud!r} is not a known tier")
                tier = Tier(aud)
            except TokenError as e:
                if odigos.set_condition(Condition(
                        INSTALLED_CONDITION, ConditionStatus.FALSE,
                        "InvalidToken", str(e))):
                    store.update_status(odigos)
                return

        try:
            config = self._config_from_spec(odigos)
        except ValueError as e:
            # bad enum value (ui_mode/mount_method/...) must surface as a
            # condition, not vanish into the controller error log
            if odigos.set_condition(Condition(
                    INSTALLED_CONDITION, ConditionStatus.FALSE,
                    "InvalidSpec", str(e))):
                store.update_status(odigos)
            return
        # the same gate cmd_install applies: unknown / tier-ineligible
        # profiles block the install loudly instead of being quietly
        # recorded in the effective config's problems list
        from ..config.profiles import resolve_profiles

        _, unknown = resolve_profiles(config.profiles, tier)
        if unknown:
            if odigos.set_condition(Condition(
                    INSTALLED_CONDITION, ConditionStatus.FALSE,
                    "InvalidProfiles",
                    f"unknown or tier-gated profiles: {unknown} "
                    f"(tier: {tier.value})")):
                store.update_status(odigos)
            return
        authored = store.get("ConfigMap", ODIGOS_NAMESPACE,
                             AUTHORED_CONFIG_NAME)
        desired = {"config": config.to_dict(), "tier": tier.value}
        if authored is None or authored.data != desired:
            store.apply(ConfigMap(
                meta=ObjectMeta(name=AUTHORED_CONFIG_NAME,
                                namespace=ODIGOS_NAMESPACE),
                data=desired))
        if odigos.set_condition(Condition(
                INSTALLED_CONDITION, ConditionStatus.TRUE,
                "InstalledSuccessfully",
                f"tier={tier.value} profiles={odigos.profiles or 'none'}")):
            store.update_status(odigos)

    # ----------------------------------------------------------- internals

    @staticmethod
    def _config_from_spec(odigos: Odigos) -> Configuration:
        """OdigosSpec → authored Configuration (the values.yaml rendering
        role of odigos_controller.go:162 install)."""
        from ..config.model import EnvInjectionMethod, MountMethod, UiMode

        cfg = Configuration(
            telemetry_enabled=odigos.telemetry_enabled,
            ignored_namespaces=list(odigos.ignored_namespaces),
            ignored_containers=list(odigos.ignored_containers),
            image_prefix=odigos.image_prefix,
            profiles=list(odigos.profiles),
        )
        if odigos.ui_mode:
            cfg.ui_mode = UiMode(odigos.ui_mode)
        if odigos.mount_method:
            cfg.mount_method = MountMethod(odigos.mount_method)
        if odigos.agent_env_vars_injection_method:
            cfg.agent_env_vars_injection_method = EnvInjectionMethod(
                odigos.agent_env_vars_injection_method)
        return cfg

    @staticmethod
    def _uninstall(store: Store) -> None:
        """Delete every artifact the install chain generated — the
        helmUninstall analog. Sources go first: their deletion drives the
        instrumentor's existing un-instrument path (IC removal + rollout
        restart stripping agents from running pods), so apps stop
        exporting into a gateway that no longer exists. Level-triggered
        consumers observe the deletions and quiesce."""
        from .autoscaler import GATEWAY_CONFIG_NAME, NODE_CONFIG_NAME

        for kind in ("Source", "InstrumentationRule", "DestinationResource",
                     "Processor", "Action"):
            for r in list(store.list(kind)):
                store.delete(kind, r.meta.namespace, r.meta.name)
        for name in (AUTHORED_CONFIG_NAME, EFFECTIVE_CONFIG_NAME,
                     GATEWAY_CONFIG_NAME, NODE_CONFIG_NAME):
            store.delete("ConfigMap", ODIGOS_NAMESPACE, name)
        for name in (GATEWAY_GROUP_NAME, NODE_GROUP_NAME):
            store.delete("CollectorsGroup", ODIGOS_NAMESPACE, name)


def single_odigos(store: Store) -> Optional[Odigos]:
    """Convenience for status surfaces: the (single) Odigos resource."""
    items = [r for r in store.list("Odigos") if isinstance(r, Odigos)]
    return items[0] if items else None
