"""File exporter — JSONL span dump (durable test destination)."""

from __future__ import annotations

import json
import threading
from typing import Any

from ...pdata.spans import SpanBatch
from ..api import ComponentKind, Exporter, Factory, register


class FileExporter(Exporter):
    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self._lock = threading.Lock()
        self._fh = None

    def start(self) -> None:
        super().start()
        path = self.config.get("path")
        if not path:
            raise ValueError(f"{self.name}: 'path' is required")
        self._fh = open(path, "a", encoding="utf-8")

    def export(self, batch: SpanBatch) -> None:
        if self._fh is None:
            raise RuntimeError(f"{self.name}: export before start")
        lines = [json.dumps(d, default=str) for d in batch.iter_spans()]
        with self._lock:
            self._fh.write("\n".join(lines) + "\n")
            self._fh.flush()

    def shutdown(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
        super().shutdown()


register(Factory(
    type_name="file",
    kind=ComponentKind.EXPORTER,
    create=FileExporter,
    default_config=dict,
))
