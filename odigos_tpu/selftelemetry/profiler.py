"""Continuous profiler + device-runtime telemetry (ISSUE 3 tentpole).

PR 1 gave the framework self-traces; PR 2 a pipelined engine whose
behavior is visible only while someone watches a span. This module is
the always-on layer over both — the Google-Wide-Profiling model (a
continuously sampling, low-overhead profiler whose data is queryable
after the fact) plus the Dapper model (aggregate metrics linked back to
exemplar traces, utils/telemetry exemplars) applied to our own data
plane and TPU scoring stage:

* ``ContinuousProfiler`` — a daemon thread extending
  ``pprofz.sample_profile``'s statistical sampling into an always-on
  sampler (default ~19 Hz — a prime rate, so periodic work cannot alias
  against the sampling grid) that writes folded-stack profiles into a
  bounded ring of fixed windows (default 12 x 60 s ≈ the last 12
  minutes). Windows merge on demand: ``/debug/profilez?window=N`` on the
  pprof extension serves the last-N-windows merge, and ``odigos
  diagnose`` bundles the full merged profile. Strict no-op when disabled
  in config (the default): no thread, no memory, nothing sampled.
* ``DeviceRuntimeCollector`` — periodically snapshots JAX/TPU runtime
  state into the process ``Meter``: live device arrays and device memory
  stats when the backend exposes them (graceful no-op on CPU), jit cache
  size and cumulative compile seconds per jit site
  (``models.jitstats``), and the engine gauges the scoring pipeline
  already computes but never published — queue depth, in-flight window
  occupancy, bucket-ladder hit rate, padding-waste fraction,
  device_busy_frac — sampled from every registered ``ScoringEngine``.

Both are process-global singletons (``profiler``, ``device_runtime``)
so every surface — extension pages, frontend scrape, CLI bundle — sees
the same data, and both start only when configuration says so
(``start_from_config``; collector configs carry a
``service.telemetry.profiler`` stanza).
"""

from __future__ import annotations

import functools
import os
import sys
import threading
import time
import weakref
from collections import Counter, deque
from dataclasses import dataclass
from typing import Any, Optional

from ..utils.telemetry import labeled_key, meter

SAMPLES_METRIC = "odigos_profiler_samples_total"
ROTATED_METRIC = "odigos_profiler_windows_rotated_total"
OVERRUN_METRIC = "odigos_profiler_tick_overruns_total"
SWEEP_METRIC = "odigos_profiler_sweep_ms"

# stacks beyond this per window fold into one synthetic bucket: the ring
# must stay bounded even against pathological stack diversity (deep
# recursion with varying depth mints a new folded stack per sample)
TRUNCATED_STACK = "(truncated)"


@functools.lru_cache(maxsize=4096)
def _module_label(filename: str) -> str:
    """Short module identifier from a code object's filename: the stem,
    or the parent directory for ``__init__`` (every package would
    otherwise collapse into one ``__init__`` frame)."""
    stem, _ = os.path.splitext(os.path.basename(filename))
    if stem == "__init__":
        return os.path.basename(os.path.dirname(filename)) or stem
    return stem


def advance_tick(next_tick: float, now: float,
                 interval: float) -> tuple[float, int]:
    """Advance an absolute tick grid past ``now``: the shared sampling
    discipline (continuous profiler + pprofz on-demand sampler). Returns
    ``(next_tick, missed)`` — overrun ticks are skipped on the original
    grid, never bursted, and ``missed`` counts them. A fixed
    sleep-interval-after-sweep drifts low by exactly the per-sweep cost;
    the absolute grid holds the effective rate under load."""
    next_tick += interval
    if next_tick > now:
        return next_tick, 0
    missed = int((now - next_tick) / interval) + 1
    return next_tick + missed * interval, missed


def fold_stack(frame) -> str:
    """One raw frame chain -> ``module:name;module:name;...`` root-first.

    Frames render as ``module:name``, not bare ``name`` — every
    ``process``/``export`` in the codebase would otherwise merge into a
    single flamegraph frame. Walks ``f_back`` directly: no FrameSummary
    objects, no linecache source lookups, because this runs per thread
    per sample on the always-on path."""
    parts = []
    while frame is not None:
        code = frame.f_code
        parts.append(f"{_module_label(code.co_filename)}:{code.co_name}")
        frame = frame.f_back
    parts.reverse()
    return ";".join(parts)


@dataclass(frozen=True)
class ProfilerConfig:
    """Continuous-profiler knobs (``service.telemetry.profiler`` in a
    collector config; ``selftelemetry`` section of the authored
    Configuration)."""

    enabled: bool = False       # strict no-op unless opted in
    hz: float = 19.0            # prime: no aliasing against periodic work
    window_s: float = 60.0      # fixed window length
    windows: int = 12           # ring capacity (12 x 60 s = 12 min)
    max_stacks_per_window: int = 4096  # distinct folded stacks bound

    def __post_init__(self) -> None:
        # clamp on EVERY construction path (direct construction is
        # public API): hz=0 would kill the sampler thread on a
        # ZeroDivisionError with nothing surfaced
        object.__setattr__(self, "hz",
                           max(1.0, min(float(self.hz), 997.0)))
        object.__setattr__(self, "window_s",
                           max(0.05, float(self.window_s)))
        object.__setattr__(self, "windows", max(1, int(self.windows)))
        object.__setattr__(self, "max_stacks_per_window",
                           max(64, int(self.max_stacks_per_window)))

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ProfilerConfig":
        return cls(
            enabled=bool(d.get("enabled", False)),
            hz=float(d.get("hz", 19.0)),
            window_s=float(d.get("window_s", 60.0)),
            windows=int(d.get("windows", 12)),
            max_stacks_per_window=int(
                d.get("max_stacks_per_window", 4096)),
        )


class ProfileWindow:
    """One fixed sampling window: folded-stack counts + sample meta."""

    __slots__ = ("index", "start_unix", "end_unix", "samples", "sweeps",
                 "counts")

    def __init__(self, index: int, start_unix: float):
        self.index = index
        self.start_unix = start_unix
        self.end_unix = 0.0
        self.samples = 0   # thread-stack samples folded in
        self.sweeps = 0    # sampler passes over all threads
        self.counts: Counter = Counter()

    def meta(self) -> dict[str, Any]:
        return {
            "index": self.index,
            "start_unix": round(self.start_unix, 3),
            "end_unix": round(self.end_unix, 3) if self.end_unix else None,
            "samples": self.samples,
            "sweeps": self.sweeps,
            "stacks": len(self.counts),
        }


class ContinuousProfiler:
    """Always-on statistical profiler over a bounded window ring.

    The sampler thread sweeps ``sys._current_frames`` on an absolute
    tick grid (``next = prev + 1/hz``, not ``sleep(1/hz)`` after the
    sweep — the pprofz drift fix, shared discipline) so the effective
    rate holds under load; when a sweep overruns its tick the missed
    ticks are skipped and counted, never bursted."""

    def __init__(self, config: Optional[ProfilerConfig] = None):
        self.cfg = config or ProfilerConfig()
        self._lock = threading.Lock()
        self._ring: deque[ProfileWindow] = deque(maxlen=self.cfg.windows)
        self._current: Optional[ProfileWindow] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._windows_rotated = 0

    # ----------------------------------------------------------- lifecycle

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def configure(self, config: ProfilerConfig) -> None:
        """Swap config; ring capacity follows. Refused while running (a
        live sampler holds the old geometry)."""
        if self.running:
            raise RuntimeError("configure() while the sampler is running")
        with self._lock:
            self.cfg = config
            self._ring = deque(self._ring, maxlen=config.windows)

    def start(self) -> bool:
        """Start sampling; False (and nothing allocated, nothing spawned)
        when disabled in config or already running — the strict-no-op
        contract minimal installs rely on."""
        if not self.cfg.enabled or self.running:
            return False
        # per-run stop event: a sampler that outlives a timed-out
        # stop() keeps ITS event set and exits on its next check — a
        # shared cleared event would silently resurrect the zombie
        # alongside the new thread
        stop = threading.Event()
        self._stop = stop
        self._thread = threading.Thread(
            target=self._run, args=(stop,), name="continuous-profiler",
            daemon=True)
        self._thread.start()
        return True

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None

    # ------------------------------------------------------------- sampler

    def _run(self, stop: threading.Event) -> None:
        interval = 1.0 / self.cfg.hz
        me = threading.get_ident()
        next_tick = time.monotonic()
        window_end = next_tick + self.cfg.window_s
        with self._lock:
            self._current = ProfileWindow(self._windows_rotated, time.time())
        while not stop.is_set():
            t0 = time.monotonic()
            if t0 >= window_end:
                self._rotate()
                window_end += self.cfg.window_s
                if window_end <= t0:  # long stall: realign, don't spin
                    window_end = t0 + self.cfg.window_s
            self._sweep(me)
            t1 = time.monotonic()
            meter.record(SWEEP_METRIC, (t1 - t0) * 1e3)
            next_tick, missed = advance_tick(next_tick, t1, interval)
            if missed:
                meter.add(OVERRUN_METRIC, missed)
            stop.wait(max(next_tick - time.monotonic(), 0.0))
        # flush the partial window: stop must lose nothing
        self._rotate(final=True)

    def _sweep(self, own_ident: int) -> None:
        frames = sys._current_frames()
        folded = [fold_stack(f) for ident, f in frames.items()
                  if ident != own_ident]
        with self._lock:
            w = self._current
            if w is None:
                return
            for stack in folded:
                if (len(w.counts) >= self.cfg.max_stacks_per_window
                        and stack not in w.counts):
                    stack = TRUNCATED_STACK
                w.counts[stack] += 1
            w.samples += len(folded)
            w.sweeps += 1
        meter.add(SAMPLES_METRIC, len(folded))

    def _rotate(self, final: bool = False) -> None:
        with self._lock:
            w = self._current
            if w is None or (not w.sweeps and not final):
                return
            w.end_unix = time.time()
            self._ring.append(w)
            self._windows_rotated += 1
            self._current = ProfileWindow(self._windows_rotated, time.time())
        meter.add(ROTATED_METRIC)

    # ------------------------------------------------------------ surfaces

    def windows(self) -> list[ProfileWindow]:
        """Closed windows oldest-first, plus the in-progress one."""
        with self._lock:
            out = list(self._ring)
            if self._current is not None and self._current.sweeps:
                out.append(self._current)
            return out

    def merged(self, last: Optional[int] = None) -> Counter:
        """Merge the last ``last`` windows (default: all) into one folded
        profile — the on-demand cross-window view."""
        ws = self.windows()
        if last is not None and last > 0:
            ws = ws[-last:]
        out: Counter = Counter()
        with self._lock:
            for w in ws:
                out.update(w.counts)
        return out

    def folded(self, last: Optional[int] = None) -> list[str]:
        """Merged profile as flamegraph-ready folded lines
        (``frame;frame count``), hottest first."""
        return [f"{stack} {n}" for stack, n
                in self.merged(last).most_common()]

    def snapshot(self) -> dict[str, Any]:
        """JSON-able state for /debug/profilez and the diagnose bundle."""
        ws = self.windows()
        return {
            "enabled": self.cfg.enabled,
            "running": self.running,
            "hz": self.cfg.hz,
            "window_s": self.cfg.window_s,
            "window_capacity": self.cfg.windows,
            "windows_rotated": self._windows_rotated,
            "windows": [w.meta() for w in ws],
            "samples_total": sum(w.samples for w in ws),
        }


# --------------------------------------------------------- device runtime


class _EngineRegistry:
    """Weak set of live ScoringEngines the collector samples. Weakrefs:
    an engine that is shut down and dropped must not be kept alive (or
    sampled) by telemetry. Each engine gets a stable registration
    ordinal — two live engines of the same model must not overwrite each
    other's gauges in WeakSet iteration order."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._engines: "weakref.WeakSet" = weakref.WeakSet()
        self._ids: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._next_id = 0

    def register(self, engine) -> None:
        with self._lock:
            self._engines.add(engine)
            if engine not in self._ids:
                self._ids[engine] = self._next_id
                self._next_id += 1

    def unregister(self, engine) -> None:
        with self._lock:
            self._engines.discard(engine)

    def live(self) -> list:
        """(ordinal, engine) pairs, registration order."""
        with self._lock:
            return sorted(((self._ids.get(e, -1), e)
                           for e in self._engines), key=lambda p: p[0])


engines = _EngineRegistry()


@dataclass(frozen=True)
class DeviceRuntimeConfig:
    enabled: bool = False
    interval_s: float = 10.0

    def __post_init__(self) -> None:
        # interval_s=0 would busy-spin the collector thread at 100% CPU
        object.__setattr__(self, "interval_s",
                           max(0.1, float(self.interval_s)))

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "DeviceRuntimeConfig":
        return cls(enabled=bool(d.get("enabled", False)),
                   interval_s=float(d.get("interval_s", 10.0)))


class DeviceRuntimeCollector:
    """Periodic JAX/TPU + engine runtime snapshot into the Meter.

    ``collect_once()`` is the unit of work (also called synchronously by
    tests and the diagnose bundle); ``start()`` runs it on an interval
    daemon thread. Everything device-side is best-effort: no jax in
    ``sys.modules`` means nothing device-related is touched (importing
    jax from a telemetry thread would pay seconds and may initialize a
    device runtime the process never asked for), and a CPU backend
    without ``memory_stats`` is a graceful no-op."""

    def __init__(self, config: Optional[DeviceRuntimeConfig] = None):
        self.cfg = config or DeviceRuntimeConfig()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # gauges THIS collector published last pass: anything absent in
        # the current pass is cleared from the meter — a shut-down
        # engine's queue depth must vanish, not freeze at its last value
        self._published: set = set()

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> bool:
        if not self.cfg.enabled or self.running:
            return False
        stop = threading.Event()  # per-run: see ContinuousProfiler.start
        self._stop = stop
        self._thread = threading.Thread(
            target=self._run, args=(stop,),
            name="device-runtime-collector", daemon=True)
        self._thread.start()
        return True

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None
        # the sampler is gone: its gauges must vanish with it, not
        # freeze on /metrics at their last sampled values
        for name in self._published:
            meter.clear_gauge(name)
        self._published = set()

    def _run(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                # pass THIS run's event: a zombie run that outlived a
                # timed-out stop() must consult its own (set) event, not
                # whatever self._stop points at after a restart
                self.collect_once(stop_event=stop)
            except Exception:  # noqa: BLE001 — telemetry must never kill
                meter.add("odigos_device_runtime_errors_total")
            stop.wait(self.cfg.interval_s)

    # ------------------------------------------------------------ sampling

    def collect_once(self, publish: bool = True,
                     stop_event: Optional[threading.Event] = None,
                     ) -> dict[str, float]:
        """One snapshot pass; returns the gauges it collected.
        ``publish=False`` is the read-only mode (diagnose bundle): the
        dict is returned without touching the meter, so a one-shot
        diagnostic cannot freeze stale gauges onto a scrape surface no
        periodic collector will ever refresh."""
        stop_event = stop_event if stop_event is not None else self._stop
        out: dict[str, float] = {}
        out.update(self._collect_engines())
        out.update(self._collect_jax())
        out.update(self._collect_device_tables())
        # a stop() racing a stalled pass must win: publishing after the
        # event is set would re-freeze gauges stop() just cleared, with
        # no collector left to ever refresh them
        if publish and not stop_event.is_set():
            for name, value in out.items():
                meter.set_gauge(name, value)
            for name in self._published - set(out):
                meter.clear_gauge(name)  # source gone (engine shut down)
            self._published = set(out)
            meter.add("odigos_device_runtime_collections_total")
        return out

    # gauge key -> full metric name: the names stay literal so the
    # metric-name lint (test_package_hygiene) can verify them statically
    ENGINE_GAUGES = {
        "queue_depth": "odigos_engine_queue_depth",
        "inflight": "odigos_engine_inflight",
        "window_occupancy": "odigos_engine_window_occupancy",
        "pipeline_depth": "odigos_engine_pipeline_depth",
        "device_calls": "odigos_engine_device_calls",
        "device_busy_frac": "odigos_engine_device_busy_frac",
        "padding_waste_frac": "odigos_engine_padding_waste_frac",
        "bucket_ladder_hit_rate": "odigos_engine_bucket_ladder_hit_rate",
    }

    @classmethod
    def _collect_engines(cls) -> dict[str, float]:
        out: dict[str, float] = {}
        for ordinal, eng in engines.live():
            try:
                gauges = eng.runtime_gauges()
            except Exception:  # noqa: BLE001 — a dying engine: skip it
                continue
            model = gauges.pop("model", "unknown")
            # multi-chip engines label their gauges per mesh shape
            # (ISSUE 7: padding_waste_frac / bucket_ladder_hit_rate are
            # per-mesh quantities once the engine owns a dp×tp mesh);
            # single-device engines keep the unlabeled legacy keys
            mesh = gauges.pop("mesh", None)
            labels = {"model": model, "engine": str(ordinal)}
            if mesh is not None:
                labels["mesh"] = str(mesh)
            for key, value in gauges.items():
                name = cls.ENGINE_GAUGES.get(key)
                if name is not None:
                    # engine ordinal disambiguates two live engines of
                    # the same model (blue/green overlap, A/B)
                    out[labeled_key(name, **labels)] = float(value)
        return out

    @staticmethod
    def _collect_jax() -> dict[str, float]:
        if "jax" not in sys.modules:
            return {}  # never the importer — sampling must stay passive
        import jax

        out: dict[str, float] = {}
        try:
            live = jax.live_arrays()
            out["odigos_device_live_arrays"] = float(len(live))
            out["odigos_device_live_array_bytes"] = float(
                sum(getattr(a, "nbytes", 0) or 0 for a in live))
        except Exception:  # noqa: BLE001 — backend without live_arrays
            pass
        try:
            for i, dev in enumerate(jax.devices()):
                stats = getattr(dev, "memory_stats", None)
                stats = stats() if callable(stats) else None
                if not stats:
                    continue  # CPU backends return None: graceful no-op
                for src, name in (
                        ("bytes_in_use", "odigos_device_bytes_in_use"),
                        ("bytes_limit", "odigos_device_bytes_limit"),
                        ("peak_bytes_in_use", "odigos_device_peak_bytes")):
                    if src in stats:
                        out[labeled_key(name, device=str(i))] = \
                            float(stats[src])
        except Exception:  # noqa: BLE001
            pass
        try:
            from ..models import jitstats

            for site, size in jitstats.cache_sizes().items():
                out[labeled_key("odigos_jit_cache_size", site=site)] = \
                    float(size)
            for site, secs in jitstats.compile_seconds().items():
                out[labeled_key("odigos_jit_compile_seconds_total",
                                site=site)] = round(secs, 6)
        except Exception:  # noqa: BLE001
            pass
        return out

    @staticmethod
    def _collect_device_tables() -> dict[str, float]:
        """Device-resident footprint of the fused route (ISSUE 20):
        bytes pinned by the interned hash-table LRU plus each live
        plan's placed weight pytree. Published via the same `_published`
        set as everything else, so a shut-down engine's plan gauge is
        stale-cleared, never frozen. Reads module state only — never the
        importer (no jax module in ``sys.modules`` means the fused
        module cannot be there either, and the getattr chain degrades
        to nothing)."""
        out: dict[str, float] = {}
        fused = sys.modules.get("odigos_tpu.serving.fused")
        if fused is not None:
            try:
                table_bytes = float(fused.device_table_bytes())
                if table_bytes > 0:
                    out[labeled_key("odigos_device_table_bytes",
                                    site="fused.tables")] = table_bytes
            except Exception:  # noqa: BLE001
                pass
        for ordinal, eng in engines.live():
            try:
                plan = getattr(getattr(eng, "backend", None), "_plan",
                               None)
                if plan is None:
                    continue
                placed = float(plan.placed_bytes())
                if placed > 0:
                    out[labeled_key(
                        "odigos_device_table_bytes",
                        site=f"plan.{plan.key}",
                        engine=str(ordinal))] = placed
            except Exception:  # noqa: BLE001 — a dying engine: skip it
                continue
        return out


# ----------------------------------------------------------- process-global

profiler = ContinuousProfiler()
device_runtime = DeviceRuntimeCollector()


def start_from_config(telemetry: Optional[dict[str, Any]]) -> list[str]:
    """Apply a ``service.telemetry`` stanza to the process singletons;
    returns which subsystems this call started (the caller that started
    them stops them — see ``stop_started``). Absent/disabled stanza =
    strict no-op. Never raises: a malformed stanza (``hz: "19hz"``)
    counts an error and degrades to not-started — telemetry must not
    kill a collector whose graph already started, and a reload that
    swapped the graph must not be reported failed over a profiler
    knob."""
    started = []
    try:
        stanza = (telemetry or {}).get("profiler") or {}
        if stanza.get("enabled") and not profiler.running:
            profiler.configure(ProfilerConfig.from_dict(stanza))
            if profiler.start():
                started.append("profiler")
    except Exception:  # noqa: BLE001
        meter.add("odigos_selftelemetry_config_errors_total")
    try:
        stanza = (telemetry or {}).get("device_runtime") or {}
        if stanza.get("enabled") and not device_runtime.running:
            device_runtime.cfg = DeviceRuntimeConfig.from_dict(stanza)
            if device_runtime.start():
                started.append("device_runtime")
    except Exception:  # noqa: BLE001
        meter.add("odigos_selftelemetry_config_errors_total")
    return started


def stop_started(started: list[str]) -> None:
    """Stop exactly the subsystems a prior ``start_from_config`` call
    reported starting (a collector shutting down must not stop a
    profiler another owner started)."""
    if "profiler" in started:
        profiler.stop()
    if "device_runtime" in started:
        device_runtime.stop()


def device_snapshot() -> dict[str, Any]:
    """The device-plane observability join (ISSUE 20): one JSON-able
    dict backing ``GET /api/device``, ``/debug/xlaz``, ``describe``,
    and the diagnose bundle's ``device.json``. The four top-level
    containers are ALWAYS present (empty when the subsystem never
    armed) so every consumer indexes without existence checks:

    * ``attribution`` — per live fused engine, the sampler's stats
      (stride, kill-switch state, sampled/skipped counters, the last
      published sub-stage waterfall);
    * ``cost`` — the XLA cost/efficiency ledger snapshot (expected
      FLOPs/bytes, flop-waste, achieved efficiency per site × bucket);
    * ``compiles`` — the ring of recent compile events, newest first;
    * ``tables`` — device-resident fused footprint in bytes (interned
      hash tables + each live plan's placed weights).
    """
    out: dict[str, Any] = {
        "attribution": [],
        "cost": {"rows": [], "best_flops_per_s": {},
                 "captures_skipped": 0},
        "compiles": [],
        "tables": {},
    }
    try:
        from ..models.costmodel import cost_ledger
        out["cost"] = cost_ledger.snapshot()
    except Exception:  # noqa: BLE001
        pass
    try:
        from ..models import jitstats
        out["compiles"] = jitstats.recent_compiles()
    except Exception:  # noqa: BLE001
        pass
    for ordinal, eng in engines.live():
        try:
            backend = getattr(eng, "backend", None)
            attrib = getattr(backend, "_attrib", None)
            if attrib is None:
                continue
            entry = {"engine": ordinal,
                     "site": getattr(backend, "fused_site", None)
                     or "fused"}
            entry.update(attrib.stats())
            out["attribution"].append(entry)
        except Exception:  # noqa: BLE001 — a dying engine: skip it
            continue
    fused = sys.modules.get("odigos_tpu.serving.fused")
    if fused is not None:
        try:
            out["tables"]["fused.tables"] = int(
                fused.device_table_bytes())
        except Exception:  # noqa: BLE001
            pass
    for ordinal, eng in engines.live():
        try:
            plan = getattr(getattr(eng, "backend", None), "_plan", None)
            if plan is not None:
                out["tables"][f"plan.{plan.key}"] = \
                    int(plan.placed_bytes())
        except Exception:  # noqa: BLE001
            continue
    return out
