"""Device-plane deep observability tests (ISSUE 20): the XLA
cost/efficiency ledger (capture → observe join, self-normalized
efficiency, graceful no-op on analysis-free backends), compile events
as first-class incidents (ring + filters, warm events never storm, the
storm detector freezing a bundle past the startup grace), sampled
intra-fused attribution (closed sub-stage waterfall, warmup discard,
parity guard, live kill switch resuming on the same grid, off-path
bit-parity), the latency ledger's device burn table + worst-fused
exemplar join, the shared device_snapshot() surface — and the tier-1
<2% host-wall overhead guard for the armed 1-in-N sampler (the
flight-recorder guard's paired-interleaved discipline)."""

import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from odigos_tpu.models import TransformerConfig, jitstats
from odigos_tpu.models.costmodel import CostLedger, cost_ledger
from odigos_tpu.models.jitstats import (
    STORM_THRESHOLD, record_compile_event, recent_compiles)
from odigos_tpu.pdata import synthesize_traces
from odigos_tpu.selftelemetry.flightrecorder import flight_recorder
from odigos_tpu.selftelemetry.latency import StageClock, latency_ledger
from odigos_tpu.selftelemetry.profiler import device_snapshot, engines
from odigos_tpu.serving.deviceattrib import (
    SKIP_REASONS, SUB_STAGES, DeviceAttribution, attribution_enabled)
from odigos_tpu.serving.engine import EngineConfig, ScoringEngine
from odigos_tpu.serving.fused import extract_columns
from odigos_tpu.utils.telemetry import meter


@pytest.fixture(autouse=True)
def fresh():
    jitstats.reset()
    cost_ledger.reset()
    flight_recorder.reset()
    latency_ledger.reset()
    meter.reset()
    os.environ.pop("ODIGOS_DEVICE_ATTRIB", None)
    yield
    os.environ.pop("ODIGOS_DEVICE_ATTRIB", None)
    jitstats.reset()
    cost_ledger.reset()
    flight_recorder.reset()
    latency_ledger.reset()


@pytest.fixture(scope="module")
def fused_env():
    """One warmed fused transformer backend (tiny geometry) shared by the
    attribution tests: the stride-4 sampler armed, the sub-stage jits
    built, and at least one full waterfall published. Tests that need a
    different stride build a fresh DeviceAttribution SHARING these warm
    jits/keys (dict-copied before any mutation), so no test recompiles."""
    os.environ.pop("ODIGOS_DEVICE_ATTRIB", None)
    os.environ.pop("ODIGOS_DEVICE_ATTRIB_N", None)
    cfg = EngineConfig(
        model="transformer",
        model_config=TransformerConfig(d_model=32, n_layers=1, d_ff=64,
                                       n_heads=2, max_len=16,
                                       dtype=jnp.float32),
        max_len=16, trace_bucket=32,
        device_attribution=True, device_attribution_stride=4)
    eng = ScoringEngine(cfg)  # unstarted: direct backend drive
    backend = eng.backend
    attrib = backend._attrib
    assert attrib is not None and attrib.stride == 4
    fcfg = eng.cfg.featurizer
    col_sets = []
    for v in range(3):
        cols, reason = extract_columns(synthesize_traces(192, seed=870 + v),
                                       fcfg)
        assert cols is not None, reason
        col_sets.append([cols])
    # drive sampled ticks until a full waterfall publishes (the first
    # sampled tick per (bucket, rows) key is the discarded warmup pass)
    for i in range(6 * attrib.stride):
        backend.harvest(backend.dispatch_columns(col_sets[i % 3]))
        if attrib.sampled >= 1:
            break
    assert attrib.sampled >= 1, attrib.stats()
    yield eng, backend, col_sets
    backend._attrib = attrib  # whatever a failing test left behind


def _drive(backend, col_sets, n):
    for i in range(n):
        backend.harvest(backend.dispatch_columns(col_sets[i % len(col_sets)]))


def _shared_attrib(backend, stride, warm=True):
    """Fresh sampler riding the module backend's already-built sub-stage
    jits (copied dict — corruption tests must not poison the shared
    one) and, when ``warm``, its warm key set (skips the warmup pass)."""
    a = DeviceAttribution(backend, stride=stride)
    a._jits = dict(backend._attrib._stage_jits())
    if warm:
        a._warm_keys = set(backend._attrib._warm_keys)
    return a


# --------------------------------------------------------------------------
# XLA cost/efficiency ledger


class TestCostLedger:
    def test_capture_observe_and_self_normalized_efficiency(self):
        led = CostLedger()
        f = jax.jit(lambda x: x @ x)
        x = jnp.ones((64, 64), jnp.float32)
        row = led.capture("t.mm", "r64", f, (x,), n_real=48, n_padded=64)
        assert row is not None
        assert row["flops"] > 0
        assert row["bytes_accessed"] > 0
        assert row["flop_waste_frac"] == 0.25
        # first observation defines the site's best FLOP/s: reads 1.0
        assert led.observe_device_ms("t.mm", "r64", 5.0) == 1.0
        # half the speed -> half the self-normalized efficiency
        assert led.observe_device_ms("t.mm", "r64", 10.0) == 0.5
        snap = led.snapshot()
        assert len(snap["rows"]) == 1
        r = snap["rows"][0]
        assert r["observations"] == 2
        assert r["last_device_ms"] == 10.0
        assert "t.mm" in snap["best_flops_per_s"]

    def test_memory_depth(self):
        led = CostLedger()
        f = jax.jit(lambda x: x * 2.0)
        row = led.capture("t.mem", "r8", f, (jnp.ones((8, 8)),),
                          memory=True)
        # memory=True AOT-compiles; either the stats landed as ints or
        # the whole capture degraded to the counted no-op — never a raise
        if row is not None:
            assert row["memory"] is None or all(
                isinstance(v, int) for v in row["memory"].values())
        else:
            assert led.snapshot()["captures_skipped"] == 1

    def test_graceful_noop_without_analysis(self):
        led = CostLedger()

        def plain(x):  # no .lower(): the analysis-free backend stand-in
            return x

        assert led.capture("t.plain", "r1", plain, (1.0,)) is None
        assert led.snapshot()["captures_skipped"] == 1
        # observing a never-captured (site, bucket) is a None, not a row
        assert led.observe_device_ms("t.plain", "r1", 1.0) is None
        assert led.snapshot()["rows"] == []

    def test_reset(self):
        led = CostLedger()
        f = jax.jit(lambda x: x + 1.0)
        assert led.capture("t.r", "r4", f, (jnp.ones((4,)),)) is not None
        led.reset()
        assert led.snapshot() == {"rows": [], "best_flops_per_s": {},
                                  "captures_skipped": 0}


# --------------------------------------------------------------------------
# compile events + storm detector


def _bypass_grace():
    """Arm the storm detector: plant the process-first-compile marker
    deep in the past so subsequent events are outside the startup
    grace (the soak-ramp protection the live path keeps)."""
    record_compile_event("t.seed", 0.01, shape="r0", warm=True)
    jitstats._first_event_mono = time.monotonic() - 1000.0


class TestCompileEvents:
    def test_ring_and_filters(self):
        record_compile_event("t.a", 0.5, shape="r64x16",
                             trace_id="ab" * 16)
        record_compile_event("t.b", 0.2, shape="r128x16", warm=True)
        events = recent_compiles()
        assert [e["site"] for e in events] == ["t.b", "t.a"]  # newest first
        assert all("t_mono" not in e for e in events)
        assert events[1]["shape"] == "r64x16"
        assert events[1]["trace_id"] == "ab" * 16
        assert events[0]["warm"] is True and events[1]["warm"] is False
        assert [e["site"] for e in recent_compiles(site="t.a")] == ["t.a"]
        assert [e["site"] for e in recent_compiles(shape="r128x16")] \
            == ["t.b"]
        assert recent_compiles(site="t.a", shape="r128x16") == []

    def test_warm_events_never_storm(self):
        _bypass_grace()
        for i in range(3 * STORM_THRESHOLD):
            record_compile_event("t.warm", 0.1, shape=f"r{i}", warm=True)
        assert [i for i in flight_recorder.incidents()
                if i["trigger"] == "compile_storm"] == []

    def test_storm_freezes_incident_past_grace(self):
        _bypass_grace()
        for i in range(STORM_THRESHOLD):
            record_compile_event("t.storm", 0.2, shape=f"r{64 << i}x16")
        [inc] = [i for i in flight_recorder.incidents()
                 if i["trigger"] == "compile_storm"]
        assert f"{STORM_THRESHOLD} shape(s) recompiled" in inc["detail"]
        assert "t.storm:r64x16" in inc["detail"]
        # the bundle carries the compile events themselves: the black
        # box mirror is what makes the incident stand alone offline
        assert any(e.get("kind") == "compile" for e in inc["events"])

    def test_under_threshold_is_not_a_storm(self):
        _bypass_grace()
        for i in range(STORM_THRESHOLD - 1):
            record_compile_event("t.calm", 0.2, shape=f"r{i}")
        assert [i for i in flight_recorder.incidents()
                if i["trigger"] == "compile_storm"] == []

    def test_grace_window_protects_startup_ramp(self):
        # no bypass: every event sits inside STORM_GRACE_S of the first
        for i in range(3 * STORM_THRESHOLD):
            record_compile_event("t.ramp", 0.2, shape=f"r{i}")
        assert [i for i in flight_recorder.incidents()
                if i["trigger"] == "compile_storm"] == []


# --------------------------------------------------------------------------
# sampled intra-fused attribution


class TestDeviceAttribution:
    def test_published_waterfall_closed_vocabulary(self, fused_env):
        _, backend, col_sets = fused_env
        wf = backend._attrib.last_waterfall
        assert wf is not None
        assert set(wf["stages"]) == set(SUB_STAGES)
        assert all(wf["stages"][s] >= 0.0 for s in SUB_STAGES)
        assert wf["bucket"].startswith("r") and "x16" in wf["bucket"]
        assert wf["n_spans"] in {sum(len(c) for c in cs)
                                 for cs in col_sets}
        assert wf["total_ms"] == pytest.approx(
            sum(wf["stages"].values()), abs=0.01)
        assert wf["fused_device_ms"] > 0
        assert wf["reconcile_ratio"] > 0

    def test_skip_reason_keys_closed(self, fused_env):
        _, backend, _ = fused_env
        assert set(backend._attrib.skipped) == set(SKIP_REASONS)

    def test_warmup_pass_discarded_then_publishes(self, fused_env):
        _, backend, col_sets = fused_env
        armed = backend._attrib
        a = _shared_attrib(backend, stride=1, warm=False)
        backend._attrib = a
        try:
            _drive(backend, col_sets[:1], 1)
            # cold (bucket, rows) key: stamps compile-contaminated,
            # discarded and counted — never published
            assert a.skipped["warmup"] == 1
            assert a.sampled == 0 and a.last_waterfall is None
            _drive(backend, col_sets[:1], 1)
            assert a.sampled == 1 and a.last_waterfall is not None
        finally:
            backend._attrib = armed

    def test_kill_switch_skips_and_resumes_on_grid(self, fused_env):
        _, backend, col_sets = fused_env
        a = backend._attrib
        sampled0, disabled0 = a.sampled, a.skipped["disabled"]
        # align to the grid: drive until the NEXT tick is the sampled one
        while a._ordinal % a.stride != 0:
            _drive(backend, col_sets, 1)
        os.environ["ODIGOS_DEVICE_ATTRIB"] = "0"
        assert not attribution_enabled()
        _drive(backend, col_sets, a.stride)  # exactly one sampled tick
        assert a.skipped["disabled"] == disabled0 + 1
        assert a.sampled == sampled0
        assert backend.last_attrib is None
        # re-enable: the ordinal kept advancing while killed, so the
        # very next grid point samples again — same cadence, no restart
        del os.environ["ODIGOS_DEVICE_ATTRIB"]
        assert attribution_enabled()
        _drive(backend, col_sets, a.stride)
        assert a.sampled == sampled0 + 1

    def test_off_path_bit_identical(self, fused_env):
        _, backend, col_sets = fused_env
        armed = backend._attrib
        try:
            # armed but non-sampled tick vs attribution compiled out:
            # both must take the identical one-call PR 17 hot path
            a = _shared_attrib(backend, stride=1 << 20)
            a.tick()  # consume the grid point: next ticks are unsampled
            backend._attrib = a
            on = backend.harvest(backend.dispatch_columns(col_sets[0]))
            assert backend.last_attrib is None
            backend._attrib = None
            off = backend.harvest(backend.dispatch_columns(col_sets[0]))
            np.testing.assert_array_equal(on, off)
        finally:
            backend._attrib = armed

    def test_parity_divergence_discards_waterfall(self, fused_env):
        _, backend, col_sets = fused_env
        armed = backend._attrib
        a = _shared_attrib(backend, stride=1)
        fwd = a._jits["forward"]
        a._jits["forward"] = lambda *args, **kw: fwd(*args, **kw) + 1.0
        backend._attrib = a
        try:
            _drive(backend, col_sets[:1], 1)
            assert a.skipped["parity"] == 1
            assert a.sampled == 0 and a.last_waterfall is None
        finally:
            backend._attrib = armed

    def test_substage_error_never_fails_the_frame(self, fused_env):
        _, backend, col_sets = fused_env
        armed = backend._attrib

        def boom(*args, **kw):
            raise RuntimeError("sub-stage exploded")

        a = _shared_attrib(backend, stride=1)
        a._jits["forward"] = boom
        backend._attrib = a
        try:
            scores = backend.harvest(backend.dispatch_columns(col_sets[0]))
            # the frame still scored, every real span covered
            assert len(scores) == sum(len(c) for c in col_sets[0])
            assert a.skipped["error"] == 1 and a.sampled == 0
        finally:
            backend._attrib = armed

    def test_stats_surface(self, fused_env):
        _, backend, _ = fused_env
        st = backend._attrib.stats()
        assert st["stride"] == 4 and st["enabled"] is True
        assert st["sampled"] >= 1
        assert st["frames_seen"] > st["sampled"]
        assert set(st["skipped"]) == set(SKIP_REASONS)
        assert set(st["last_waterfall"]["stages"]) == set(SUB_STAGES)

    def test_cost_row_captured_at_fused_warm_moment(self, fused_env):
        _, backend, col_sets = fused_env
        # a never-seen span count -> new bucket key -> cold dispatch
        # captures XLA's cost model for the fused site at warm time
        cols, reason = extract_columns(
            synthesize_traces(700, seed=901), backend.cfg.featurizer)
        assert cols is not None, reason
        backend.harvest(backend.dispatch_columns([cols]))
        bucket = f"r{backend.last_shape[0]}x{backend.last_shape[1]}"
        rows = [r for r in cost_ledger.snapshot()["rows"]
                if r["bucket"] == bucket]
        assert rows and rows[0]["flops"] > 0


# --------------------------------------------------------------------------
# latency ledger: device burn table + worst-fused exemplar join


def _fused_clock(fused_ms=3.0, bucket="r64x16", attrib=None,
                 ctx=(0xabc, 0xdef)):
    clock = StageClock(ctx=ctx)
    t = time.monotonic_ns()
    ms = 1_000_000
    clock.merge_engine({
        "fused": True, "pack0": t,
        "dispatch": t + int(fused_ms * ms),
        "harvest0": t + int((fused_ms + 1) * ms),
        "end": t + int((fused_ms + 2) * ms),
        "overlap_ms": 0.0,
        "device_attrib": attrib, "fused_bucket": bucket,
    })
    return clock


class TestLatencyDeviceBurn:
    def test_burn_table_folds_sampled_waterfalls(self):
        rec = latency_ledger.recorder("traces/devburn")
        attrib = {"stages": {s: 1.0 for s in SUB_STAGES},
                  "fused_device_ms": 5.5, "total_ms": 5.0,
                  "reconcile_ratio": 0.9091, "bucket": "r64x16",
                  "n_spans": 10, "shape": [64, 16], "t": time.time()}
        rec.observe(_fused_clock(attrib=attrib), scored=True)
        rec.observe(_fused_clock(), scored=True)  # unsampled: no fold
        db = rec.device_burn()
        assert db is not None
        assert db["sampled_frames"] == 1
        assert set(db["stages"]) == set(SUB_STAGES)
        assert db["stages"]["forward"] == {"mean_ms": 1.0, "count": 1}
        assert db["substage_sum_ms"] == 5.0
        assert db["fused_mean_ms"] == 5.5
        assert db["reconcile_ratio"] == pytest.approx(5.0 / 5.5, abs=1e-3)
        assert len(db["recent"]) == 1
        assert rec.burn()["device"]["sampled_frames"] == 1

    def test_no_device_section_until_sampled(self):
        rec = latency_ledger.recorder("traces/devoff")
        rec.observe(_fused_clock(), scored=True)
        assert rec.device_burn() is None
        assert "device" not in rec.burn()  # PR 17 payload untouched

    def test_worst_fused_exemplar_joins_compile_and_cost(self):
        rec = latency_ledger.recorder("traces/devjoin")
        rec.observe(_fused_clock(fused_ms=2.0, bucket="r32x16",
                                 ctx=(1, 2)), scored=True)
        rec.observe(_fused_clock(fused_ms=9.0, bucket="r64x16",
                                 ctx=(0xfeed, 0xbeef)), scored=True)
        record_compile_event("fused.join", 0.3, shape="r64x16")
        f = jax.jit(lambda x: x * 2.0)
        assert cost_ledger.capture("fused.join", "r64x16", f,
                                   (jnp.ones((8, 8)),)) is not None
        [entry] = [e for e in rec.worst_frames() if e["scope"] == "fused"]
        # the worst fused frame, by the fused stamp itself
        assert entry["fused_ms"] == pytest.approx(9.0, abs=0.5)
        assert entry["wall_ms"] == entry["fused_ms"]  # the sort key
        assert entry["bucket"] == "r64x16"
        assert entry["trace_id"] == f"{0xfeed:032x}"
        assert entry["last_compile"]["site"] == "fused.join"
        assert entry["cost"]["site"] == "fused.join"
        assert entry["cost"]["flops"] > 0
        # the ledger-level sort across every scope must hold too
        assert latency_ledger.worst_frames()

    def test_join_absent_when_bucket_never_compiled(self):
        rec = latency_ledger.recorder("traces/devnojoin")
        rec.observe(_fused_clock(bucket="r999x16"), scored=True)
        [entry] = [e for e in rec.worst_frames() if e["scope"] == "fused"]
        assert "last_compile" not in entry and "cost" not in entry


# --------------------------------------------------------------------------
# the shared device_snapshot() surface


class TestDeviceSnapshot:
    def test_containers_always_present(self):
        snap = device_snapshot()
        assert snap["attribution"] == []
        assert snap["cost"]["rows"] == []
        assert snap["compiles"] == []
        assert isinstance(snap["tables"], dict)

    def test_live_engine_join(self, fused_env):
        eng, backend, col_sets = fused_env
        _drive(backend, col_sets, 1)
        record_compile_event("fused.snap", 0.2, shape="r1x1")
        engines.register(eng)
        try:
            snap = device_snapshot()
        finally:
            engines.unregister(eng)
        [ab] = snap["attribution"]
        assert ab["site"] == (backend.fused_site or "fused")
        assert ab["stride"] == 4 and ab["sampled"] >= 1
        assert set(ab["last_waterfall"]["stages"]) == set(SUB_STAGES)
        assert any(e["site"] == "fused.snap" for e in snap["compiles"])
        assert snap["tables"].get("fused.tables", 0) > 0


# --------------------------------------------------------------------------
# tier-1 overhead guard


class TestOverheadGuard:
    def test_armed_sampler_overhead_under_2_percent(self):
        """Armed-vs-disarmed host wall of ``dispatch_columns`` on the
        warmed SOAK-geometry fused backend with the 1-in-32 sampler
        (bench.py's ``device_attribution_overhead_bench`` pairing, as
        a tier-1 bar): the identical frame dispatched in both modes
        back to back on one backend, within-pair order alternating,
        harvest blocking OUTSIDE the timer, median of the paired
        ratios. The bound is the 31-of-32 claim — a non-sampled armed
        frame pays only the ordinal tick and a None check — so each
        window aligns to the grid with the sampled tick consumed
        OUTSIDE it: the sampled frame's own waterfall cost is the
        price of the feature, reported separately by the bench, and
        its ~300× dispatch mid-window measurably disturbs the frames
        after it (allocator/clock state) in both modes. Up to three
        windows: one clean window proves the sampler CAN run under
        2%, a preempted one cannot refute it. The tiny-geometry
        backend the other tests share is deliberately NOT used here:
        sub-millisecond frames put scheduler noise at the same scale
        as the bound."""
        cfg = EngineConfig(
            model="transformer",
            model_config=TransformerConfig(d_model=64, n_layers=2,
                                           d_ff=256, n_heads=4,
                                           max_len=32, dtype=jnp.float32),
            max_len=32, trace_bucket=64,
            device_attribution=True, device_attribution_stride=32)
        eng = ScoringEngine(cfg)  # unstarted: direct backend A/B
        backend = eng.backend
        a = backend._attrib
        col_sets = []
        for v in range(4):
            cols, reason = extract_columns(
                synthesize_traces(256, seed=70 + v), eng.cfg.featurizer)
            assert cols is not None, reason
            col_sets.append([cols])
        for i in range(4 * a.stride):  # warm jits + grid: publish once
            _drive(backend, [col_sets[i % 4]], 1)
            if a.sampled >= 1:
                break
        assert a.sampled >= 1, a.stats()

        def measure():
            # burn to just past the grid point: ordinals 1..stride-1
            # cannot sample, so the window holds only steady frames
            while a._ordinal % a.stride != 1:
                _drive(backend, [col_sets[0]], 1)
            ratios = []
            for i in range(a.stride - 1):
                cols = col_sets[i % len(col_sets)]
                t = {}
                modes = ("on", "off") if i % 2 else ("off", "on")
                for mode in modes:
                    backend._attrib = a if mode == "on" else None
                    t0 = time.perf_counter()
                    h = backend.dispatch_columns(cols)
                    t[mode] = time.perf_counter() - t0
                    backend.harvest(h)
                ratios.append(t["on"] / max(t["off"], 1e-9))
            backend._attrib = a
            ratios.sort()
            return ratios[len(ratios) // 2]

        medians = []
        for _ in range(3):
            medians.append(measure())
            if medians[-1] <= 1.02:
                break
        assert min(medians) <= 1.02, \
            f"armed sampler overhead {medians} (bound 1.02)"
