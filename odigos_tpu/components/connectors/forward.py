"""Forward connector — 1:N pipeline bridge.

The reference composes destination pipelines with `forward/<dest>` connectors
(common/pipelinegen/config_builder.go:99-108). Ours passes batches through to
every configured output pipeline unchanged.
"""

from __future__ import annotations

from ...pdata.spans import SpanBatch
from ..api import ComponentKind, Connector, Factory, register


class ForwardConnector(Connector):
    def consume(self, batch: SpanBatch) -> None:
        for consumer in self.outputs.values():
            consumer.consume(batch)


register(Factory(
    type_name="forward",
    kind=ComponentKind.CONNECTOR,
    create=ForwardConnector,
    default_config=dict,
))
