"""Vendor destination exporters — the upstream-exporter-set role.

The reference distro compiles one upstream exporter per backend into the
collector (collector/builder-config.yaml: datadogexporter,
prometheusremotewriteexporter, lokiexporter, ...); the destination
configers (common/config/*.go) only *emit config* for them. Our configers
(destinations/configers.py) reproduce those config shapes — this module
supplies the factories so every emitted exporter type actually builds and
runs (without it, adding any real-backend destination produced a config
the graph builder rejected and the hot-reloader silently kept the old
graph).

One generic implementation serves every vendor:

* Types whose ingest protocol is HTTP(S) derive ``(url, headers)`` from
  their vendor-specific config shape via the extractor table below
  (datadog api.site/api.key, logzio regional listener + bearer token,
  prometheusremotewrite endpoint+headers, ...), then POST otlp-json
  documents with bounded 5xx/connection retry and terminal 4xx — the same
  delivery semantics as the blob exporter's uploader. ``endpoint_override``
  redirects delivery to any URL (tests point it at a local mock; air-gapped
  installs at their relay).
* Types with a dedicated ingest protocol (splunkhec, influxdb,
  opensearch/elasticsearch, the AWS family, azuremonitor, googlecloud)
  marshal through ``wireformats.MARSHALLERS`` — the backend's REAL wire
  format (HEC event streams, line protocol, _bulk NDJSON, SigV4-signed
  JSON-RPC, App Insights envelopes) instead of generic otlp-json.
  Bodies above ``max_body_bytes`` split the batch recursively into
  in-limit requests.
* kafka — the one genuinely non-HTTP transport left — still builds and
  starts (the collector must boot with an unreachable backend, exactly
  like the reference's lazily-connecting exporters), but export()
  counts and drops (``odigos_vendor_dropped_total``) and ``healthy()``
  reports False — visible degradation instead of a boot failure or a
  silent stall.

Also here: the ``nop`` exporter (upstream's nop component) and the
``datadog`` connector (traces→APM-stats bridge the datadog configer wires
when traces+metrics are both enabled) — the same vectorized RED
aggregation as the spanmetrics connector under APM-stats metric names.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Callable, Optional

from ...pdata.logs import LogBatch
from ...pdata.metrics import MetricBatch
from ...utils.httpsend import send_with_retry
from ...utils.telemetry import meter
from ..api import ComponentKind, Exporter, Factory, Signal, register
from ..connectors.spanmetrics import SpanMetricsConnector

DROPPED_METRIC = "odigos_vendor_dropped_total"
SENT_METRIC = "odigos_vendor_batches_sent_total"
RETRY_METRIC = "odigos_vendor_send_retries_total"

_ENV_RE = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)\}")


def expand_env(value: str) -> str:
    """Resolve ``${NAME}`` placeholders from the process environment — the
    configers emit secrets as env references (destinations/configers.py
    _secret), delivered to the collector via its pod env exactly like the
    reference's secret-ref'd env vars. Unset names stay as-is (visible in
    the failed-auth error rather than silently empty)."""
    return _ENV_RE.sub(
        lambda m: os.environ.get(m.group(1), m.group(0)), value)

# config dict -> (url or None, headers). None = not HTTP-derivable.
_Extractor = Callable[[dict[str, Any]], tuple[Optional[str], dict[str, str]]]


def _hdr_endpoint(c: dict) -> tuple[Optional[str], dict[str, str]]:
    return c.get("endpoint"), dict(c.get("headers") or {})


def _datadog(c: dict) -> tuple[Optional[str], dict[str, str]]:
    api = c.get("api") or {}
    site = api.get("site") or "datadoghq.com"
    return f"https://api.{site}", {"DD-API-KEY": str(api.get("key", ""))}


def _logzio(c: dict) -> tuple[Optional[str], dict[str, str]]:
    region = c.get("region") or "us"
    suffix = "" if region == "us" else f"-{region}"
    return (f"https://listener{suffix}.logz.io:8071",
            {"Authorization": f"Bearer {c.get('account_token', '')}"})


def _coralogix(c: dict) -> tuple[Optional[str], dict[str, str]]:
    domain = c.get("domain")
    if not domain:
        return None, {}
    return (f"https://ingress.{domain}",
            {"Authorization": f"Bearer {c.get('private_key', '')}"})


def _elasticsearch(c: dict) -> tuple[Optional[str], dict[str, str]]:
    eps = c.get("endpoints") or []
    headers = {}
    if c.get("user"):
        import base64
        cred = f"{c['user']}:{c.get('password', '')}".encode()
        headers["Authorization"] = \
            f"Basic {base64.b64encode(cred).decode()}"
    return (eps[0] if eps else None), headers


def _sdk_only(c: dict) -> tuple[Optional[str], dict[str, str]]:
    return None, {}


def _splunkhec(c: dict) -> tuple[Optional[str], dict[str, str]]:
    return c.get("endpoint"), {}


def _influxdb(c: dict) -> tuple[Optional[str], dict[str, str]]:
    return c.get("endpoint"), {}


def _opensearch(c: dict) -> tuple[Optional[str], dict[str, str]]:
    return _elasticsearch(c)


def _awss3(c: dict) -> tuple[Optional[str], dict[str, str]]:
    up = c.get("s3uploader") or {}
    bucket = up.get("s3_bucket")
    if not bucket:
        return None, {}
    region = up.get("region") or "us-east-1"
    return f"https://{bucket}.s3.{region}.amazonaws.com", {}


def _awsxray(c: dict) -> tuple[Optional[str], dict[str, str]]:
    if c.get("endpoint"):
        return str(c["endpoint"]), {}
    region = c.get("region") or "us-east-1"
    return f"https://xray.{region}.amazonaws.com", {}


def _awslogs(c: dict) -> tuple[Optional[str], dict[str, str]]:
    region = c.get("region") or "us-east-1"
    return f"https://logs.{region}.amazonaws.com", {}


def _azuremonitor(c: dict) -> tuple[Optional[str], dict[str, str]]:
    from .wireformats import parse_azure_connection_string

    parts = parse_azure_connection_string(
        str(c.get("connection_string", "")))
    ep = parts.get("IngestionEndpoint", "").rstrip("/")
    return (ep or None), {}


def _googlecloud(c: dict) -> tuple[Optional[str], dict[str, str]]:
    # OTLP-HTTP to the telemetry endpoint (the SDK-free path; the
    # marshaller appends the per-signal /v1/* path + auth)
    return "https://telemetry.googleapis.com", {}


def _sentry(c: dict) -> tuple[Optional[str], dict[str, str]]:
    from .wireformats import parse_sentry_dsn

    parsed = parse_sentry_dsn(str(c.get("dsn", "")))
    if not parsed:
        return None, {}
    scheme, _key, host, _project = parsed
    return f"{scheme}://{host}", {}


def _honeycombmarker(c: dict) -> tuple[Optional[str], dict[str, str]]:
    return c.get("api_url") or "https://api.honeycomb.io", {}


def _pubsub(c: dict) -> tuple[Optional[str], dict[str, str]]:
    return (c.get("endpoint")
            or "https://pubsub.googleapis.com"), {}


def _mezmo(c: dict) -> tuple[Optional[str], dict[str, str]]:
    ep = c.get("ingest_url") or "https://logs.mezmo.com/otel/ingest/rest"
    return ep, ({"apikey": str(c["ingest_key"])}
                if c.get("ingest_key") else {})


def _logicmonitor(c: dict) -> tuple[Optional[str], dict[str, str]]:
    ep = c.get("endpoint")
    headers = {}
    if (c.get("api_token") or {}).get("access_id"):
        tok = c["api_token"]
        headers["Authorization"] = \
            f"LMv1 {tok['access_id']}:{tok.get('access_key', '')}"
    elif c.get("headers"):
        headers.update({str(k): str(v)
                        for k, v in c["headers"].items()})
    return ep, headers


def _dataset(c: dict) -> tuple[Optional[str], dict[str, str]]:
    ep = c.get("dataset_url")
    return ep, ({"Authorization": f"Bearer {c['api_key']}"}
                if c.get("api_key") else {})


def _tencentcls(c: dict) -> tuple[Optional[str], dict[str, str]]:
    region = c.get("region")
    if not region:
        return None, {}
    return f"https://{region}.cls.tencentcs.com", {}


EXTRACTORS: dict[str, _Extractor] = {
    "otlphttp": _hdr_endpoint,
    "prometheusremotewrite": _hdr_endpoint,
    "googlemanagedprometheus": _hdr_endpoint,
    "loki": _hdr_endpoint,
    "clickhouse": _hdr_endpoint,
    "signalfx": _hdr_endpoint,
    "sapm": _hdr_endpoint,
    "sumologic": _hdr_endpoint,   # endpoint = the HTTP source URL
    "datadog": _datadog,
    "logzio": _logzio,
    "coralogix": _coralogix,
    "elasticsearch": _elasticsearch,
    "zipkin": _hdr_endpoint,
    "sentry": _sentry,
    "honeycombmarker": _honeycombmarker,
    "googlecloudpubsub": _pubsub,
    "mezmo": _mezmo,
    "logicmonitor": _logicmonitor,
    "dataset": _dataset,
    "tencentcloudlogservice": _tencentcls,
    # dedicated wire protocols (wireformats.py)
    "splunkhec": _splunkhec,
    "influxdb": _influxdb,
    "opensearch": _opensearch,
    "awsxray": _awsxray,
    "awsemf": _awslogs,
    "awscloudwatchlogs": _awslogs,
    "awss3": _awss3,
    "googlecloud": _googlecloud,
    "azuremonitor": _azuremonitor,
    # genuinely non-HTTP transports: build + run degraded (visible
    # drop) in this zero-egress build — kafka/pulsar brokers, cassandra
    # CQL, azure data explorer's OAuth'd Kusto ingest
    "kafka": _sdk_only,
    "pulsar": _sdk_only,
    "cassandra": _sdk_only,
    "azuredataexplorer": _sdk_only,
}


def _marshal(batch) -> bytes:
    if isinstance(batch, MetricBatch):
        doc = {"resourceMetrics": list(batch.iter_points())}
    elif isinstance(batch, LogBatch):
        doc = {"resourceLogs": list(batch.iter_records())}
    else:
        doc = {"resourceSpans": list(batch.iter_spans())}
    return json.dumps(doc, default=str).encode()


class VendorExporter(Exporter):
    """Shared config keys (on top of the vendor shape the configer emits):
    endpoint_override: deliver to this URL instead of the derived one
    max_retries:       5xx/connection retry budget (default 4)
    retry_backoff_s:   initial backoff, doubled per retry (default 0.05)
    timeout_s:         per-request timeout (default 10)
    """

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self._url: Optional[str] = None
        self._headers: dict[str, str] = {}

    @property
    def vendor_type(self) -> str:
        return self.name.split("/", 1)[0]

    def start(self) -> None:
        super().start()
        override = self.config.get("endpoint_override")
        extractor = EXTRACTORS.get(self.vendor_type)
        if extractor is None:
            raise ValueError(
                f"{self.name}: no vendor extractor for "
                f"{self.vendor_type!r} (known: {sorted(EXTRACTORS)})")
        self._url, self._headers = extractor(self.config)
        if override:
            # redirection keeps the derived headers: auth must survive so
            # tests exercise it against the local ingest mock
            self._url = str(override)
        # authenticator extension resolved by the graph builder into the
        # Authorization header the HTTP transport actually sends:
        # basicauth client_auth (grafana-cloud configers) or
        # bearertokenauth token (upstream bearertokenauthextension shape)
        auth = self.config.get("auth_resolved") or {}
        client = auth.get("client_auth") or {}
        if client.get("username") is not None:
            import base64
            cred = (f"{expand_env(str(client['username']))}:"
                    f"{expand_env(str(client.get('password', '')))}")
            self._headers["Authorization"] = \
                f"Basic {base64.b64encode(cred.encode()).decode()}"
        elif auth.get("token") is not None:
            scheme = str(auth.get("scheme", "Bearer"))
            self._headers["Authorization"] = \
                f"{scheme} {expand_env(str(auth['token']))}"
        elif auth.get("token_url") is not None:
            # oauth2clientauthextension: client-credentials grant at
            # start (upstream fetches/refreshes via oauth2.TokenSource;
            # one fetch covers this process's lifetime here). A failed
            # fetch leaves the exporter unauthenticated-but-running:
            # the backend's 401 is terminal and visible, a crashed boot
            # would take the whole collector down with it.
            tok = self._oauth2_fetch(auth)
            if tok:
                self._headers["Authorization"] = f"Bearer {tok}"
        elif auth.get("_type") == "googleclientauth":
            # googleclientauthextension: ambient Google credentials; the
            # zero-egress analog reads the operator-provided token env
            tok = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN", "")
            if tok:
                self._headers["Authorization"] = f"Bearer {tok}"
        if self._url is not None:
            self._url = expand_env(self._url)
        self._headers = {k: expand_env(str(v))
                         for k, v in self._headers.items()}

    def healthy(self) -> bool:
        # degraded (SDK-only transport, nothing deliverable) -> unhealthy
        return (not self._started) or self._url is not None

    # generous default: well under splunkhec's 800MB-class limits but
    # above any sane batch; backends with hard request caps get whole
    # batches split instead of a multi-MB body retried against a 413
    DEFAULT_MAX_BODY = 4 * 1024 * 1024

    def export(self, batch) -> None:
        if self._url is None:
            # non-HTTP transport in a zero-egress build (kafka): run
            # degraded, never wedge the pipeline behind an impossible
            # send
            meter.add(f"{DROPPED_METRIC}{{exporter={self.name}}}",
                      max(len(batch), 1))
            return
        self._export_bounded(batch)

    def _export_bounded(self, batch) -> None:
        """Marshal with the vendor's wire format; when a body exceeds
        max_body_bytes, split the BATCH in half and recurse — in-limit
        requests, not truncated documents."""
        from .wireformats import MARSHALLERS, WireRequest

        marshaller = MARSHALLERS.get(self.vendor_type)
        reqs = (marshaller(batch, self.config) if marshaller
                else [WireRequest(body=_marshal(batch))])
        max_body = int(self.config.get("max_body_bytes",
                                       self.DEFAULT_MAX_BODY))
        if any(len(r.body) > max_body for r in reqs) and len(batch) > 1:
            import numpy as np

            mask = np.arange(len(batch)) < len(batch) // 2
            self._export_bounded(batch.filter(mask))
            self._export_bounded(batch.filter(~mask))
            return
        for r in reqs:
            self._send(r)

    def _send(self, r) -> None:
        url = self._url + r.path
        headers = {**self._headers, **r.headers,
                   "Content-Type": r.content_type}
        if r.aws_sign is not None:
            from ...utils.awssig import sign

            region, service = r.aws_sign
            headers = sign(r.method, url, region, service, headers,
                           r.body)
        send_with_retry(
            url, r.body, method=r.method, headers=headers,
            max_retries=int(self.config.get("max_retries", 4)),
            backoff_s=float(self.config.get("retry_backoff_s", 0.05)),
            timeout_s=float(self.config.get("timeout_s", 10.0)),
            who=self.name,
            on_retry=lambda: meter.add(
                f"{RETRY_METRIC}{{exporter={self.name}}}"))
        meter.add(f"{SENT_METRIC}{{exporter={self.name}}}")


class NopExporter(Exporter):
    """Upstream's nop exporter: accepts and discards (the configers emit it
    for explicitly-disabled signals)."""

    def export(self, batch) -> None:
        pass


class DatadogAPMStatsConnector(SpanMetricsConnector):
    """datadog/connector: the traces→metrics APM-stats bridge the datadog
    configer wires when traces+metrics are both enabled
    (common/config/datadog.go). Same vectorized RED aggregation as
    spanmetrics, emitted under Datadog APM-stats names."""

    CALLS_NAME = "datadog.trace.hits"
    DURATION_NAME = "datadog.trace.duration"


_ALL_SIGNALS = (Signal.TRACES, Signal.METRICS, Signal.LOGS)

for _type in sorted(EXTRACTORS):
    register(Factory(
        type_name=_type,
        kind=ComponentKind.EXPORTER,
        create=VendorExporter,
        signals=_ALL_SIGNALS,
    ))

register(Factory(
    type_name="nop",
    kind=ComponentKind.EXPORTER,
    create=NopExporter,
    signals=_ALL_SIGNALS,
))

register(Factory(
    type_name="datadog",
    kind=ComponentKind.CONNECTOR,
    create=DatadogAPMStatsConnector,
))
