#!/bin/sh
# reference: collector/distribution/odigos-otelcol/preremove.sh
systemctl stop odigos-tpu-collector.service || true
systemctl disable odigos-tpu-collector.service || true
