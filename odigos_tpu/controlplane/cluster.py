"""In-process cluster model (workloads, pods, rollouts).

The control plane needs something to act on. In the reference that is the
k8s API server; here it is this small model — the same role KinD plays for
the reference's e2e tests (SURVEY.md §4.5) but embeddable in-process. The
instrumentor's webhook and rollout logic operate on it through the exact
seams the reference uses: a pod-mutation hook invoked on every new pod
(pods_webhook.go:76 Handle) and a restart that replaces pods with a new
template generation (rollout.go:270 rolloutRestartWorkload).

Fault injection for rollback tests: ``fail_next_rollout`` marks pods of the
next template generation CrashLoopBackOff (the crash-demo service pattern).
"""

from __future__ import annotations

import enum
import itertools
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..api.resources import WorkloadKind, WorkloadRef


class PodPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    CRASH_LOOP_BACK_OFF = "CrashLoopBackOff"
    IMAGE_PULL_BACK_OFF = "ImagePullBackOff"


@dataclass
class Container:
    name: str
    image: str = ""
    # what runtime inspection would find for this container (the sim's
    # ground truth; procdiscovery inspectors read this)
    language: str = "unknown"
    runtime_version: str = ""
    libc_type: str = "glibc"
    exe_path: str = ""
    env: dict[str, str] = field(default_factory=dict)
    other_agent: Optional[str] = None


@dataclass
class Pod:
    name: str
    namespace: str
    workload_name: str
    node: str
    template_generation: int
    containers: list[Container]
    workload_kind: WorkloadKind = WorkloadKind.DEPLOYMENT
    phase: PodPhase = PodPhase.RUNNING
    phase_since: float = field(default_factory=time.time)
    # mutations applied by the webhook at admission
    injected_env: dict[str, dict[str, str]] = field(default_factory=dict)
    injected_devices: dict[str, str] = field(default_factory=dict)
    injected_mounts: list[str] = field(default_factory=list)
    resource_attrs: dict[str, str] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)


@dataclass
class Workload:
    ref: WorkloadRef
    containers: list[Container]
    replicas: int = 1
    template_generation: int = 1
    annotations: dict[str, str] = field(default_factory=dict)


@dataclass
class CollectorEndpoint:
    """Fleet membership record of one collector (ISSUE 10): the cluster
    model's analog of the reference's OpAMP-connected collector pod.
    The fleet plane (selftelemetry/fleet.py) is the telemetry side;
    this is the control-plane side — who is supposed to exist, on which
    node, in which group — so churn (register/unregister) has one
    source of truth the e2e environment and fleet simulations share."""

    name: str
    group: str = ""
    node: Optional[str] = None
    registered_at: float = field(default_factory=time.time)


# admission webhook signature: mutate the pod in place before it "starts"
AdmissionHook = Callable[[Pod], None]


class Cluster:
    def __init__(self, nodes: int = 1) -> None:
        self.nodes = [f"node-{i}" for i in range(nodes)]
        self.workloads: dict[str, Workload] = {}
        self.pods: dict[str, Pod] = {}
        self.admission_hooks: list[AdmissionHook] = []
        self._pod_counter = itertools.count(1)
        self._node_rr = itertools.count()
        # fault injection: workload key -> phase new pods enter
        self._fail_next: dict[str, PodPhase] = {}
        # fleet membership (ISSUE 10): collector name -> endpoint record
        self.collector_endpoints: dict[str, CollectorEndpoint] = {}

    # ---------------------------------------------------------- workloads

    def add_workload(self, namespace: str, name: str,
                     containers: list[Container],
                     kind: WorkloadKind = WorkloadKind.DEPLOYMENT,
                     replicas: int = 1) -> Workload:
        ref = WorkloadRef(namespace, kind, name)
        w = Workload(ref, containers, replicas)
        self.workloads[ref.key] = w
        self._scale_pods(w)
        return w

    def remove_workload(self, ref: WorkloadRef) -> None:
        self.workloads.pop(ref.key, None)
        for pod in [p for p in self.pods.values()
                    if (p.namespace, p.workload_name) == (ref.namespace, ref.name)]:
            del self.pods[pod.name]

    def get_workload(self, ref: WorkloadRef) -> Optional[Workload]:
        return self.workloads.get(ref.key)

    def workloads_in_namespace(self, namespace: str) -> list[Workload]:
        return [w for w in self.workloads.values()
                if w.ref.namespace == namespace
                and w.ref.kind != WorkloadKind.NAMESPACE]

    # --------------------------------------------------------------- pods

    def pods_of(self, ref: WorkloadRef) -> list[Pod]:
        return [p for p in self.pods.values()
                if (p.namespace, p.workload_name) == (ref.namespace, ref.name)]

    def _spawn_pod(self, w: Workload) -> Pod:
        node = self.nodes[next(self._node_rr) % len(self.nodes)]
        pod = Pod(
            name=f"{w.ref.name}-{next(self._pod_counter):05d}",
            namespace=w.ref.namespace,
            workload_name=w.ref.name,
            node=node,
            template_generation=w.template_generation,
            containers=[Container(**vars(c)) for c in w.containers],
            workload_kind=w.ref.kind,
        )
        for hook in self.admission_hooks:
            hook(pod)  # webhook runs BEFORE the pod starts
        fail_phase = self._fail_next.get(w.ref.key)
        if fail_phase is not None:
            pod.phase = fail_phase
            pod.phase_since = time.time()
        self.pods[pod.name] = pod
        return pod

    def _scale_pods(self, w: Workload) -> None:
        current = self.pods_of(w.ref)
        for pod in current[w.replicas:]:
            del self.pods[pod.name]
        for _ in range(w.replicas - len(current)):
            self._spawn_pod(w)

    # --------------------------------------------------------- collectors

    def register_collector(self, name: str, group: str = "",
                           node: Optional[str] = None
                           ) -> CollectorEndpoint:
        """Announce a collector to the fleet (idempotent; group/node
        update in place). Simulated fleets register here and publish
        telemetry through ``selftelemetry.fleet.fleet_plane`` — the two
        registries stay in sync through these two methods."""
        ep = self.collector_endpoints.get(name)
        if ep is None:
            ep = self.collector_endpoints[name] = CollectorEndpoint(
                name, group=group, node=node)
        else:
            if group:
                ep.group = group
            if node is not None:
                ep.node = node
        return ep

    def unregister_collector(self, name: str) -> None:
        self.collector_endpoints.pop(name, None)

    def collectors_in_group(self, group: str) -> list[CollectorEndpoint]:
        return [ep for ep in self.collector_endpoints.values()
                if ep.group == group]

    # ------------------------------------------------------------ rollout

    def rollout_restart(self, ref: WorkloadRef) -> bool:
        """kubectl-rollout-restart semantics: bump template generation and
        replace all pods (new pods pass through admission hooks again)."""
        w = self.workloads.get(ref.key)
        if w is None:
            return False
        w.template_generation += 1
        w.annotations["kubectl.kubernetes.io/restartedAt"] = str(time.time())
        for pod in self.pods_of(ref):
            del self.pods[pod.name]
        for _ in range(w.replicas):
            self._spawn_pod(w)
        return True

    def rollout_complete(self, ref: WorkloadRef) -> bool:
        w = self.workloads.get(ref.key)
        if w is None:
            return False
        pods = self.pods_of(ref)
        return bool(pods) and all(
            p.template_generation == w.template_generation
            and p.phase == PodPhase.RUNNING for p in pods)

    # ----------------------------------------------------- fault injection

    def fail_next_rollout(self, ref: WorkloadRef,
                          phase: PodPhase = PodPhase.CRASH_LOOP_BACK_OFF) -> None:
        self._fail_next[ref.key] = phase

    def heal(self, ref: WorkloadRef) -> None:
        self._fail_next.pop(ref.key, None)
        for p in self.pods_of(ref):
            p.phase = PodPhase.RUNNING
            p.phase_since = time.time()

    # -------------------------------------------------------- persistence

    def to_dict(self) -> dict:
        """JSON-safe snapshot (CLI state dir / diagnose bundle); admission
        hooks are runtime wiring and re-register on boot."""
        from ..utils.serde import to_jsonable

        pod_n = next(self._pod_counter)
        self._pod_counter = itertools.count(pod_n)  # peek without skipping
        rr_n = next(self._node_rr)
        self._node_rr = itertools.count(rr_n)
        return {
            "nodes": list(self.nodes),
            "workloads": {k: to_jsonable(w)
                          for k, w in self.workloads.items()},
            "pods": {k: to_jsonable(p) for k, p in self.pods.items()},
            "fail_next": {k: v.value for k, v in self._fail_next.items()},
            "pod_counter": pod_n,
            "node_rr": rr_n,
        }

    @classmethod
    def from_dict(cls, data: dict) -> "Cluster":
        from ..utils.serde import from_jsonable

        c = cls(nodes=1)
        c.nodes = list(data["nodes"])
        c.workloads = {k: from_jsonable(Workload, w)
                       for k, w in data["workloads"].items()}
        c.pods = {k: from_jsonable(Pod, p) for k, p in data["pods"].items()}
        c._fail_next = {k: PodPhase(v)
                        for k, v in data.get("fail_next", {}).items()}
        c._pod_counter = itertools.count(data.get("pod_counter", 1))
        c._node_rr = itertools.count(data.get("node_rr", 0))
        return c
