"""Ring attention — sequence-parallel exact attention for long traces.

The reference's "long context" analog is whole-trace processing: tail sampling
and servicegraph need every span of a trace on one replica (SURVEY.md §5.7).
Our model stage must score trace trees that can exceed one chip's memory at
batch scale, so attention over the span sequence is sharded on the "seq" mesh
axis: each device holds a block of the sequence; K/V blocks rotate around the
ring via ppermute while partial attention accumulates with a streaming
(flash-style) log-sum-exp — exact softmax attention, N_seq steps, each
overlapping compute with the ICI transfer.

Reference technique: Liu et al., "Ring Attention with Blockwise Transformers
for Near-Infinite Context" (arXiv:2310.01889). Implementation is original,
shaped for shard_map + ppermute.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30

# Partition-spec declaration per sharded entry point (package-hygiene
# lint, ISSUE 7 satellite — an undeclared sharded site silently runs
# replicated): ring attention shards the SEQUENCE axis, nothing else.
PARTITION_SPECS = {
    "ring_attention": "q/k/v (B, L, H, D) and mask (B, L) sharded on "
                      "the 'seq' axis via shard_map in/out_specs; K/V "
                      "blocks rotate by ppermute, output sharded like q",
}


def _block_attention(q, k, v, kv_mask, scale):
    """One q-block x kv-block attention with streaming stats.

    q: (B, Lq, H, D), k/v: (B, Lk, H, D), kv_mask: (B, Lk) bool
    returns (unnormalized_out, row_max, row_sumexp)
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = jnp.where(kv_mask[:, None, None, :], logits, NEG_INF)
    m = logits.max(axis=-1)                      # (B, H, Lq)
    p = jnp.exp(logits - m[..., None])
    p = jnp.where(kv_mask[:, None, None, :], p, 0.0)
    s = p.sum(axis=-1)                           # (B, H, Lq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)      # (B, Lq, H, D)
    return o, m, s


def _ring_body(q, k, v, kv_mask, axis_name, scale):
    """Per-device body under shard_map: rotate K/V around the ring."""
    n = jax.lax.psum(1, axis_name)
    B, Lq, H, D = q.shape

    # accumulators start replicated; mark them device-varying over the ring
    # axis so the fori_loop carry type stays stable (jax>=0.9 vma typing)
    if hasattr(jax.lax, "pcast"):
        def _vary(x):
            return jax.lax.pcast(x, axis_name, to="varying")
    elif hasattr(jax.lax, "pvary"):  # pragma: no cover - jax 0.5-0.8
        def _vary(x):
            return jax.lax.pvary(x, axis_name)
    else:  # jax <= 0.4: shard_map has no vma typing; no marking needed
        def _vary(x):
            return x
    o = _vary(jnp.zeros((B, Lq, H, D), jnp.float32))
    m = _vary(jnp.full((B, H, Lq), NEG_INF, jnp.float32))
    s = _vary(jnp.zeros((B, H, Lq), jnp.float32))

    def step(i, carry):
        o, m, s, k, v, kv_mask = carry
        o_i, m_i, s_i = _block_attention(q, k, v, kv_mask, scale)
        # streaming softmax merge
        m_new = jnp.maximum(m, m_i)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_i - m_new)
        o = o * alpha.transpose(0, 2, 1)[..., None] \
            + o_i * beta.transpose(0, 2, 1)[..., None]
        s = s * alpha + s_i * beta
        perm = [(j, (j + 1) % n) for j in range(n)]
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        kv_mask = jax.lax.ppermute(kv_mask, axis_name, perm)
        return o, m_new, s, k, v, kv_mask

    o, m, s, *_ = jax.lax.fori_loop(
        0, n, step, (o, m, s, k.astype(jnp.float32),
                     v.astype(jnp.float32), kv_mask))
    return o / jnp.maximum(s, 1e-30).transpose(0, 2, 1)[..., None]


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mask: jax.Array, mesh: Mesh,
                   axis_name: str = "seq") -> jax.Array:
    """Exact masked attention with the sequence axis sharded over ``mesh``.

    q/k/v: (B, L, H, D) with L divisible by mesh.shape[axis_name];
    mask: (B, L) bool padding mask. Returns (B, L, H, D) float32.
    """
    scale = 1.0 / (q.shape[-1] ** 0.5)
    body = partial(_ring_body, axis_name=axis_name, scale=scale)
    spec_qkv = P(None, axis_name, None, None)
    spec_mask = P(None, axis_name)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(spec_qkv, spec_qkv, spec_qkv, spec_mask),
        out_specs=spec_qkv,
    )
    return fn(q, k, v, mask)


def reference_attention(q, k, v, mask):
    """Single-device exact attention (test oracle)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    p = jnp.where(mask[:, None, None, :], p, 0.0)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
