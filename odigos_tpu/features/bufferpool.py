"""Pinned reusable buffers for the steady-state featurize/pack tensors.

The fast path's host cost below the knee is featurize + pack; AT the
knee the remaining tail comes from what those kernels do per frame:
``np.zeros``/``np.empty``/``np.full`` on every call (~25 sites across
``features/featurizer.py``), i.e. a malloc storm that (a) burns
allocator time exactly when every core is busy and (b) feeds the GC/
allocator churn behind the multi-hundred-ms saturated tails PR 9
recorded. This module is the host-side extension of PR 7's
``donate_argnums`` discipline: buffers are OWNED BY A LEASE, checked
out, fully initialized, handed to the engine, and returned to the pool
only when every holder is done with them — steady state allocates
nothing per frame.

Design:

* :class:`BufferPool` keeps freed backing buffers on a power-of-two
  byte-bucket ladder (the same bounded-shape-set idea as the engine's
  ``BucketLadder``): a request for any (shape, dtype) takes the
  smallest free bucket that holds it and returns an exact-shape view
  over its head. A bounded ``max_bytes`` of freed capacity is retained;
  beyond it, returns are dropped to the allocator (a size storm cannot
  pin unbounded memory).
* :class:`Lease` scopes a checkout group (one frame's featurize, one
  engine call's pack) and is REFCOUNTED: the fast path holds one
  reference for the frame and one for the engine request, so buffers
  return only after both the retirement lane released the frame AND the
  engine's done-callback confirmed the device call consumed them —
  exactly the donate-after-last-use contract, host-side. Releasing is
  idempotent-by-construction (each holder releases its own reference
  exactly once).
* ``alloc(shape, dtype, fill)`` is the one allocation helper the
  featurize/pack kernels call: inside a ``lease_scope`` it checks out
  from the active lease's pool; outside any scope (training, tools,
  cold paths) it falls back to plain numpy — callers never thread pool
  objects through kernel signatures.

Safety contract (pinned by ``tests/test_bufferpool.py``):

* every ``take`` is **fully initialized** (``fill=`` or a complete
  overwrite by the caller — the ``np.empty`` discipline), so recycled
  content can never leak between frames;
* two live leases never share backing memory (no cross-frame
  aliasing); holding a checked-out array past its lease's final
  release is a contract violation — ``poison=True`` (tests) overwrites
  returned buffers so such a bug is deterministic, not heisenbergian;
* pooled-vs-unpooled outputs are **bitwise identical** (the kernels
  only ever get exact-shape, initialized views).

``ODIGOS_POOL=0`` (or :func:`set_pools_enabled`) disables the layer:
leases become plain allocations and ``alloc`` always falls back —
the bench's ``steady_state_allocs`` off/on A/B toggle.
"""

from __future__ import annotations

import contextvars
import math
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Iterator, Optional

import numpy as np

from ..utils.telemetry import labeled_key, meter

POOL_BYTES_GAUGE = "odigos_bufferpool_bytes_held"
POOL_FREE_GAUGE = "odigos_bufferpool_free_buffers"
POOL_OUTSTANDING_GAUGE = "odigos_bufferpool_outstanding_leases"
POOL_HIT_RATE_GAUGE = "odigos_bufferpool_hit_rate"
POOL_MISSES_METRIC = "odigos_bufferpool_misses_total"
POOL_HITS_METRIC = "odigos_bufferpool_hits_total"
POOL_DROPPED_METRIC = "odigos_bufferpool_dropped_buffers_total"

# smallest backing bucket: below this every request shares one rung, so
# tiny scratch vectors (run starts, per-trace offsets) don't fragment
# the ladder into hundreds of micro-buckets
MIN_BUCKET_BYTES = 4096
# freed capacity retained per pool; beyond it returns go back to the
# allocator. Sized for the fast path's worst frame (a few padded
# (R, L, C) tensors) times a handful of rungs.
DEFAULT_MAX_BYTES = 128 << 20

# gauge publish throttle: steady state must not pay a meter lock per
# checkout, so the hot take() path publishes at most once a second
_PUBLISH_INTERVAL_S = 1.0

_enabled = os.environ.get("ODIGOS_POOL", "1") != "0"

# process-wide count of alloc() calls that fell back to plain numpy —
# the bench's "allocations per frame with pools off" numerator (and,
# with pools on, the proof that no steady-state site bypassed a lease)
_fallback_allocs = 0


def pools_enabled() -> bool:
    return _enabled


def set_pools_enabled(on: bool) -> None:
    """Flip the layer globally (the bench A/B + kill-switch hook).
    Leases already outstanding keep their buffers and still return them
    — disabling mid-flight only stops NEW checkouts from pooling."""
    global _enabled
    _enabled = bool(on)


def fallback_allocs() -> int:
    return _fallback_allocs


# the lease the current frame's kernels check out from; None = plain
# numpy (cold paths, training, tools). Context-local like the stage
# clock: each submit lane / engine worker scopes its own frame.
_active_lease: contextvars.ContextVar[Optional["Lease"]] = \
    contextvars.ContextVar("odigos_buffer_lease", default=None)


@contextmanager
def lease_scope(lease: Optional["Lease"]) -> Iterator[Optional["Lease"]]:
    """Make ``lease`` the allocation target for ``alloc`` calls in this
    context (None = explicit plain-numpy scope, used by the parity
    oracle)."""
    token = _active_lease.set(lease)
    try:
        yield lease
    finally:
        _active_lease.reset(token)


def _plain(shape, dtype, fill) -> np.ndarray:
    if fill is None:
        return np.empty(shape, dtype)
    if isinstance(fill, (int, float)) and fill == 0:
        return np.zeros(shape, dtype)
    return np.full(shape, fill, dtype)


def alloc(shape, dtype, fill=None) -> np.ndarray:
    """The featurize/pack kernels' one allocation site: a pooled,
    exact-shape array when a lease is active, plain numpy otherwise.
    ``fill=None`` is the ``np.empty`` contract — the CALLER fully
    overwrites every element (pinned by the parity tests: recycled
    content must never be observable)."""
    lease = _active_lease.get()
    if lease is None:
        global _fallback_allocs
        _fallback_allocs += 1
        return _plain(shape, dtype, fill)
    return lease.take(shape, dtype, fill)


class Lease:
    """One checkout scope's buffers, refcounted across holders.

    ``retain()`` before handing the buffers to another owner (the
    engine request); each owner calls ``release()`` exactly once; at
    zero the backing buffers go back to the pool. A lease is single-
    checkout-threaded (one submit lane / one engine worker) but
    released from arbitrary threads — the count is lock-protected.
    """

    __slots__ = ("pool", "_bufs", "_refs", "_lock")

    def __init__(self, pool: "BufferPool"):
        self.pool = pool
        self._bufs: list[np.ndarray] = []
        self._refs = 1
        self._lock = threading.Lock()

    def take(self, shape, dtype, fill=None) -> np.ndarray:
        return self.pool._take(self, shape, dtype, fill)

    def retain(self) -> "Lease":
        with self._lock:
            self._refs += 1
        return self

    def release(self) -> None:
        with self._lock:
            self._refs -= 1
            if self._refs != 0:
                return
            bufs, self._bufs = self._bufs, []
        self.pool._give_back(bufs)


class BufferPool:
    """Power-of-two-bucketed reusable backing store (see module doc).

    One pool per hot-path lane (fast-path submit lanes, the engine
    worker): checkouts are effectively uncontended; the lock only
    serializes the cross-thread give-back at frame retirement.
    """

    def __init__(self, name: str, max_bytes: int = DEFAULT_MAX_BYTES,
                 poison: bool = False):
        self.name = name
        self.max_bytes = int(max_bytes)
        self.poison = bool(poison)
        self._lock = threading.Lock()
        self._free: dict[int, list[np.ndarray]] = {}
        self._bytes_held = 0
        self._hits = 0
        self._misses = 0
        self._dropped = 0
        self._leases = 0
        self._outstanding = 0
        # deltas since the last throttled publish
        self._pub_hits = 0
        self._pub_misses = 0
        self._pub_dropped = 0
        self._next_publish = 0.0
        self._keys = {
            "bytes": labeled_key(POOL_BYTES_GAUGE, pool=name),
            "free": labeled_key(POOL_FREE_GAUGE, pool=name),
            "out": labeled_key(POOL_OUTSTANDING_GAUGE, pool=name),
            "rate": labeled_key(POOL_HIT_RATE_GAUGE, pool=name),
            "hits": labeled_key(POOL_HITS_METRIC, pool=name),
            "misses": labeled_key(POOL_MISSES_METRIC, pool=name),
            "dropped": labeled_key(POOL_DROPPED_METRIC, pool=name),
        }

    # ------------------------------------------------------------ leases
    def lease(self) -> Lease:
        with self._lock:
            self._leases += 1
            self._outstanding += 1
        return Lease(self)

    @staticmethod
    def _bucket(nbytes: int) -> int:
        b = MIN_BUCKET_BYTES
        while b < nbytes:
            b <<= 1
        return b

    def _take(self, lease: Lease, shape, dtype, fill) -> np.ndarray:
        dt = np.dtype(dtype)
        # math.prod over the 1-3 small ints: np.prod's array round trip
        # costs ~10x on the exact path this module exists to make cheap
        nbytes = math.prod(shape) * dt.itemsize
        bucket = self._bucket(nbytes)
        now = time.monotonic()
        publish = False
        with self._lock:
            stack = self._free.get(bucket)
            buf = stack.pop() if stack else None
            if buf is None:
                # a LARGER idle buffer beats a fresh allocation: shape
                # jitter (varying coalesce widths, in-flight depth
                # wobble) then rides existing capacity instead of
                # minting a new rung. Two rungs up keeps worst-case
                # slack at 4x, same as the bucket ladder's geometry.
                for bigger in (bucket << 1, bucket << 2):
                    stack = self._free.get(bigger)
                    if stack:
                        buf = stack.pop()
                        break
            if buf is not None:
                self._bytes_held -= buf.nbytes
                self._hits += 1
                self._pub_hits += 1
            if now >= self._next_publish:
                self._next_publish = now + _PUBLISH_INTERVAL_S
                publish = True
        if buf is None:
            # the pool's ONE fresh-allocation site (lint-allowlisted):
            # a miss here is exactly what steady_state_allocs counts
            buf = self._fresh(bucket)
        arr = buf[:nbytes].view(dt).reshape(shape)
        if fill is not None:
            arr.fill(fill)
        lease._bufs.append(buf)
        if publish:
            self._publish()
        return arr

    def _fresh(self, bucket: int) -> np.ndarray:
        with self._lock:
            self._misses += 1
            self._pub_misses += 1
        return np.empty(bucket, np.uint8)

    def _give_back(self, bufs: list[np.ndarray]) -> None:
        with self._lock:
            self._outstanding -= 1
            for buf in bufs:
                n = buf.nbytes
                if self._bytes_held + n > self.max_bytes:
                    # over the retention cap: back to the allocator —
                    # a one-off giant frame must not pin its high-water
                    # footprint forever
                    self._dropped += 1
                    self._pub_dropped += 1
                    continue
                if self.poison:
                    buf.fill(0xAB)  # use-after-release turns deterministic
                self._free.setdefault(n, []).append(buf)
                self._bytes_held += n

    # ------------------------------------------------------------- stats
    def stats(self) -> dict[str, Any]:
        with self._lock:
            total = self._hits + self._misses
            return {
                "pool": self.name,
                "hits": self._hits,
                "misses": self._misses,
                "hit_rate": round(self._hits / total, 4) if total else 0.0,
                "dropped": self._dropped,
                "leases": self._leases,
                "outstanding_leases": self._outstanding,
                "bytes_held": self._bytes_held,
                "free_buffers": sum(len(s) for s in self._free.values()),
            }

    def _publish(self) -> None:
        """Throttled gauge/counter publish (called off the lock)."""
        with self._lock:
            total = self._hits + self._misses
            rate = self._hits / total if total else 0.0
            bytes_held = self._bytes_held
            free = sum(len(s) for s in self._free.values())
            out = self._outstanding
            d_hits, self._pub_hits = self._pub_hits, 0
            d_miss, self._pub_misses = self._pub_misses, 0
            d_drop, self._pub_dropped = self._pub_dropped, 0
        meter.set_gauge(self._keys["bytes"], bytes_held)
        meter.set_gauge(self._keys["free"], free)
        meter.set_gauge(self._keys["out"], out)
        meter.set_gauge(self._keys["rate"], round(rate, 4))
        if d_hits:
            meter.add(self._keys["hits"], d_hits)
        if d_miss:
            meter.add(self._keys["misses"], d_miss)
        if d_drop:
            meter.add(self._keys["dropped"], d_drop)
