from .engine import BucketLadder, ScoringEngine, EngineConfig, ScoreRequest
from .sidecar import RemoteBackend, SidecarClient, SidecarServer

__all__ = ["BucketLadder", "ScoringEngine", "EngineConfig", "ScoreRequest",
           "RemoteBackend", "SidecarClient", "SidecarServer"]
