"""Autoscaler: collector config rendering + Action compilation + HPA.

Reference: autoscaler/ (SURVEY.md §2.1) — renders the gateway ConfigMap
from pipelinegen on every Destination/Processor/Action/Source change
(clustercollector/configmap.go:150, §3.4 call stack), renders node
collector configs per signal (nodecollector/collectorconfig/), compiles
Action resources into sampling/attribute processors
(controllers/actions/*.go), and scales the gateway with a hybrid HPA
combining cpu, memory, and the pre-decode rejection custom metric
(clustercollector/hpa.go:36-68, metricshandler/custom_metrics_handler.go).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ..api.resources import (
    Action,
    ActionKind,
    CollectorsGroup,
    CollectorsGroupRole,
    ConfigMap,
    Condition,
    ConditionStatus,
    DestinationResource,
    ObjectMeta,
    Processor,
    Source,
)
from ..api.store import ControllerManager, Event, Store
from ..components.api import Signal
from ..config.model import Configuration
from ..destinations.registry import Destination
from ..pipelinegen import (
    DataStream,
    DataStreamDestination,
    GatewayOptions,
    NodeCollectorOptions,
    SourceRef,
    build_gateway_config,
    build_node_collector_config,
)
from ..selftelemetry.tracer import tracer
from .scheduler import EFFECTIVE_CONFIG_NAME, ODIGOS_NAMESPACE

GATEWAY_CONFIG_NAME = "odigos-gateway-config"
NODE_CONFIG_NAME = "odigos-data-collection-config"
REJECTION_METRIC = "odigos_gateway_memory_limiter_rejections_total"


# ------------------------------------------------------ action compilation


def compile_action(action: Action) -> Optional[dict[str, Any]]:
    """Action CR -> processor entry for pipelinegen (the per-kind compilers
    of autoscaler/controllers/actions/*.go; sampling kinds target the
    odigossampling rule engine, attribute kinds the attributes/resource
    processors, piimasking the conditional-attributes masker)."""
    if action.disabled:
        return None
    d = action.details
    signals = action.signals or ["traces"]
    k = action.action_kind
    if k == ActionKind.ADD_CLUSTER_INFO:
        attrs = [{"key": a["key"], "value": a.get("value"),
                  "action": "insert", "scope": "resource"}
                 for a in d.get("cluster_attributes", [])]
        return {"id": f"attributes/{action.name}", "type": "attributes",
                "signals": signals, "config": {"actions": attrs}}
    if k == ActionKind.DELETE_ATTRIBUTE:
        attrs = [{"key": key, "action": "delete", "scope": scope}
                 for key in d.get("attribute_names", [])
                 for scope in ("span", "resource")]
        return {"id": f"attributes/{action.name}", "type": "attributes",
                "signals": signals, "config": {"actions": attrs}}
    if k == ActionKind.RENAME_ATTRIBUTE:
        attrs = [{"key": old, "new_key": new, "action": "rename",
                  "scope": "span"}
                 for old, new in d.get("renames", {}).items()]
        return {"id": f"attributes/{action.name}", "type": "attributes",
                "signals": signals, "config": {"actions": attrs}}
    if k == ActionKind.PII_MASKING:
        return {"id": f"odigosconditionalattributes/{action.name}",
                "type": "odigosconditionalattributes", "signals": signals,
                "config": {"mask": d.get("pii_categories", ["CREDIT_CARD"])}}
    if k == ActionKind.K8S_ATTRIBUTES:
        attrs = [{"key": key, "action": "upsert", "scope": "resource",
                  "value": d.get("values", {}).get(key)}
                 for key in d.get("attributes", [])]
        return {"id": f"resource/{action.name}", "type": "resource",
                "signals": signals, "config": {"attributes": attrs}}
    # sampling kinds compile to odigossampling rule-engine configs
    # (autoscaler/controllers/actions/sampling/*.go)
    rule_map = {
        ActionKind.ERROR_SAMPLER: ("global", "error", {
            "fallback_sampling_ratio": d.get("fallback_sampling_ratio", 0)}),
        ActionKind.LATENCY_SAMPLER: ("endpoint", "latency", {
            "rules": d.get("endpoints_filters", [])}),
        ActionKind.PROBABILISTIC_SAMPLER: ("global", "probabilistic", {
            "sampling_percentage": d.get("sampling_percentage", 100)}),
        ActionKind.SERVICE_NAME_SAMPLER: ("service", "service-name", {
            "services": d.get("services_name_filters", [])}),
        ActionKind.SPAN_ATTRIBUTE_SAMPLER: ("service", "span-attribute", {
            "rules": d.get("attribute_filters", [])}),
        ActionKind.SAMPLERS: ("global", "composite", dict(d)),
    }
    if k in rule_map:
        level, rule_type, details = rule_map[k]
        return {"id": f"odigossampling/{action.name}",
                "type": "odigossampling", "signals": ["traces"],
                "config": {"rules": [{
                    "level": level, "type": rule_type,
                    "name": action.name, **details}]}}
    return None


# ----------------------------------------------------------------- HPA


@dataclass
class HpaDecider:
    """Pure scaling policy of clustercollector/hpa.go:36-68: hybrid
    cpu+memory+rejection metrics; aggressive up (+2 pods / 15s window),
    conservative down (max(1 pod, 25%) / 60s, 15 min stabilization)."""

    min_replicas: int = 1
    max_replicas: int = 10
    cpu_target_pct: float = 80.0
    memory_target_pct: float = 80.0
    rejections_per_pod_target: float = 1.0
    scale_up_pods: int = 2
    scale_up_window_s: float = 15.0
    scale_down_pct: float = 25.0
    scale_down_window_s: float = 60.0
    stabilization_s: float = 900.0
    _last_scale_up: float = field(default=0.0, repr=False)
    _last_scale_down: float = field(default=0.0, repr=False)
    _recommendations: list[tuple[float, int]] = field(default_factory=list,
                                                      repr=False)

    def desired_replicas(self, current: int, cpu_pct: float,
                         memory_pct: float, rejections_per_pod: float,
                         now: Optional[float] = None) -> int:
        now = time.time() if now is None else now
        # raw desire: max over the three metrics (k8s HPA semantics)
        ratios = [cpu_pct / self.cpu_target_pct,
                  memory_pct / self.memory_target_pct,
                  rejections_per_pod / self.rejections_per_pod_target]
        import math
        raw = max(1, math.ceil(current * max(ratios))) if current else 1
        raw = min(max(raw, self.min_replicas), self.max_replicas)

        # prune the stabilization window on every sample, not only in the
        # scale-down branch — steady load would otherwise grow the list
        # unboundedly (~one tuple per observe interval, forever)
        cutoff = now - self.stabilization_s
        self._recommendations = [(t, r) for t, r in self._recommendations
                                 if t >= cutoff]

        if raw > current:
            if now - self._last_scale_up < self.scale_up_window_s:
                return current
            desired = min(raw, current + self.scale_up_pods)
            self._last_scale_up = now
            self._recommendations.append((now, desired))
            return desired
        if raw < current:
            # stabilization: use the max recommendation in the window
            self._recommendations.append((now, raw))
            stabilized = max(r for _, r in self._recommendations)
            if stabilized >= current:
                return current
            if now - self._last_scale_down < self.scale_down_window_s:
                return current
            step = max(1, int(current * self.scale_down_pct / 100.0))
            desired = max(stabilized, current - step, self.min_replicas)
            self._last_scale_down = now
            return desired
        self._recommendations.append((now, raw))
        return current


# -------------------------------------------------------------- autoscaler


class Autoscaler:
    """Watches Destination/Processor/Action/Source/CollectorsGroup and
    keeps the generated collector ConfigMaps + gateway scale in sync."""

    def __init__(self, store: Store, manager: ControllerManager,
                 effective_config: Configuration) -> None:
        self.store = store
        self.config = effective_config
        self.hpa = HpaDecider()
        self.gateway_replicas = 1
        # TPU co-scheduling (north star): node device registries attached by
        # the environment; each held SLICE (plugin, [device ids]) backs one
        # gateway replica's dp×tp scoring mesh (ISSUE 7: mesh-slice
        # co-scheduling — the reference co-schedules collector replicas,
        # we co-schedule replicas with whole accelerator slices)
        self._device_registries: list[Any] = []
        self._tpu_held: list[tuple[Any, list[str]]] = []
        gateway_key = lambda e: [(ODIGOS_NAMESPACE, GATEWAY_CONFIG_NAME)]
        manager.register("cluster-collector", self, {
            "DestinationResource": gateway_key,
            "Processor": gateway_key,
            "Action": gateway_key,
            "Source": gateway_key,
            "CollectorsGroup": gateway_key,
            "ConfigMap": lambda e: (
                [(ODIGOS_NAMESPACE, GATEWAY_CONFIG_NAME)]
                if e.key == (ODIGOS_NAMESPACE, EFFECTIVE_CONFIG_NAME) else []),
        })

    def set_effective_config(self, cfg: Configuration) -> None:
        self.config = cfg

    # ---------------------------------------------------------- reconcile

    def reconcile(self, store: Store, key: tuple[str, str]) -> None:
        destinations, dest_resources = self._destinations(store)
        processors = self._processors(store)
        data_streams = self._data_streams(store, destinations)
        gateway_group = self._gateway_group(store)
        if gateway_group is None:
            # no CollectorsGroup = not installed (or uninstalled by the
            # operator): quiesce instead of re-creating the config the
            # uninstall just deleted
            store.delete("ConfigMap", ODIGOS_NAMESPACE, GATEWAY_CONFIG_NAME)
            return

        eff_cm = store.get("ConfigMap", ODIGOS_NAMESPACE,
                           EFFECTIVE_CONFIG_NAME)
        if isinstance(eff_cm, ConfigMap):
            self.config = Configuration.from_dict(eff_cm.data["config"])

        options = GatewayOptions(
            service_graph_disabled=bool(
                gateway_group and gateway_group.service_graph_disabled),
            cluster_metrics_enabled=bool(
                gateway_group and gateway_group.cluster_metrics_enabled),
            small_batches=self.config.extra.get("small_batches"),
            anomaly=self.config.anomaly,
            ui_endpoint=self.config.ui_endpoint,
            telemetry_config=self.config.selftelemetry,
            alerts=self.config.alerts,
            export_retry=self.config.collector_gateway.export_retry,
            actuator=self.config.actuator,
        )
        with tracer.span("autoscaler/render-gateway-config") as sp:
            sp.set_attr("cr.kind", "ConfigMap")
            sp.set_attr("cr.name", GATEWAY_CONFIG_NAME)
            sp.set_attr("destinations", len(destinations))
            sp.set_attr("processors", len(processors))
            config, status, enabled_signals = build_gateway_config(
                destinations, processors, data_streams, options)
            # status.destination maps every dest id; None means success
            sp.set_attr("outcome",
                        "errors" if any(
                            v is not None
                            for v in status.destination.values())
                        else "rendered")

        store.apply(ConfigMap(
            meta=ObjectMeta(name=GATEWAY_CONFIG_NAME,
                            namespace=ODIGOS_NAMESPACE),
            data={"collector-conf": config,
                  "enabled_signals": [s.value for s in enabled_signals]}))

        # surface per-destination reconcile outcome on the resources
        # (change-gated: an identical condition must not re-trigger watches)
        for dest_res in dest_resources:
            err = status.destination.get(dest_res.meta.name)
            if dest_res.set_condition(Condition(
                    "DestinationConfigured",
                    ConditionStatus.FALSE if err else ConditionStatus.TRUE,
                    "ConfigerError" if err else "TransformedToOtelcolConfig",
                    err or "")):
                store.update_status(dest_res)

        # node collector config follows the gateway's enabled signals
        node_cfg = build_node_collector_config(NodeCollectorOptions(
            enabled_signals=tuple(enabled_signals) or (Signal.TRACES,),
            span_metrics_enabled=self.config.metrics_sources.span_metrics,
            host_metrics_enabled=self.config.metrics_sources.host_metrics,
            kubelet_stats_enabled=self.config.metrics_sources.kubelet_stats,
            log_collection_enabled=Signal.LOGS in enabled_signals,
        ))
        store.apply(ConfigMap(
            meta=ObjectMeta(name=NODE_CONFIG_NAME,
                            namespace=ODIGOS_NAMESPACE),
            data={"collector-conf": node_cfg}))

        # update the CollectorsGroup status (collectors hot-reload config
        # via the watch; the reference's odigosk8scmprovider seam)
        if gateway_group is not None:
            new_signals = [s.value for s in enabled_signals]
            if (not gateway_group.ready
                    or gateway_group.received_signals != new_signals):
                gateway_group.ready = True
                gateway_group.received_signals = new_signals
                store.update_status(gateway_group)
            res = gateway_group.resources
            if res:
                self.hpa.min_replicas = res.get("min_replicas", 1)
                self.hpa.max_replicas = res.get("max_replicas", 10)

    # -------------------------------------------------------------- scale

    def observe_metrics(self, cpu_pct: float, memory_pct: float,
                        rejections_per_pod: float,
                        now: Optional[float] = None) -> int:
        """Feed the HPA one metrics sample; returns (and records) the new
        replica count (custom_metrics_handler.go:251 scrapeGatewayMetric +
        hpa.go behavior). When the anomaly stage is on, scale-out is
        co-scheduled with TPU devices (north star: the virtual-device
        affinity pattern of distros/yamls/golang-community.yaml:15-18
        applied to gateway replicas)."""
        with tracer.span("autoscaler/hpa-observe") as sp:
            sp.set_attr("cpu_pct", round(cpu_pct, 2))
            sp.set_attr("memory_pct", round(memory_pct, 2))
            sp.set_attr("rejections_per_pod", round(rejections_per_pod, 2))
            desired = self.hpa.desired_replicas(
                self.gateway_replicas, cpu_pct, memory_pct,
                rejections_per_pod, now)
            group = self._gateway_group(self.store)
            if group is not None:
                desired = self._co_schedule_tpu(desired, group)
            sp.set_attr("outcome",
                        "scale" if desired != self.gateway_replicas
                        else "steady")
            sp.set_attr("replicas", desired)
        self.gateway_replicas = desired
        return self.gateway_replicas

    # ------------------------------------------------- TPU co-scheduling

    def attach_device_registries(self, registries: list[Any]) -> None:
        """Give the autoscaler sight of the nodes' device-plugin pools
        (deviceplugin/pkg/instrumentation/plugin.go:24 role)."""
        self._device_registries = list(registries)

    def _tpu_plugins(self) -> list[Any]:
        from ..nodeagent.deviceplugin import TPU_DEVICE

        return [r.plugins[TPU_DEVICE] for r in self._device_registries
                if TPU_DEVICE in getattr(r, "plugins", {})]

    def tpu_devices_held(self) -> int:
        return sum(len(devs) for _, devs in self._tpu_held)

    def mesh_slices_held(self) -> int:
        return len(self._tpu_held)

    def _mesh_slice_size(self) -> int:
        """Devices per gateway replica: the anomaly engine's dp×tp mesh
        (anomaly.devices × anomaly.tensor_parallel, ISSUE 7). 1 when the
        stage runs single-chip — the pre-mesh behavior exactly."""
        a = self.config.anomaly
        tp = getattr(a, "tensor_parallel", 1) or 1
        return max(1, int(a.devices or 1)) * max(1, int(tp))

    def _co_schedule_tpu(self, desired: int, group) -> int:
        """Align gateway scale with TPU mesh slices: every replica carries
        the full pipeline (shared-nothing, SURVEY §2.7), so with the
        anomaly stage enabled each replica needs one WHOLE slice of
        dp×tp devices for its scoring mesh — a slice never straddles
        pools (ICI does not cross hosts). Scale-out is capped at what the
        pools can back and at the ``mesh_slices`` sizing knob; a
        shortfall surfaces as a TpuScheduling condition on the
        CollectorsGroup (the HPA-visible 'tpu-starved' signal)."""
        plugins = self._tpu_plugins()
        if group.tpu_replicas <= 0:
            if self._tpu_held:  # anomaly turned off: give devices back
                for plugin, devs in self._tpu_held:
                    plugin.release(list(devs))
                self._tpu_held = []
            return desired

        slice_size = self._mesh_slice_size()
        max_slices = self.config.collector_gateway.mesh_slices
        want = desired if max_slices is None else min(desired,
                                                      int(max_slices))

        # a config reload can resize the slice (anomaly.devices /
        # tensor_parallel changed): release any held slice of the WRONG
        # size first, or replicas keep serving dp×tp meshes backed by
        # stale allocations while the condition reports DevicesAllocated
        stale = [(p, d) for p, d in self._tpu_held if len(d) != slice_size]
        if stale:
            self._tpu_held = [(p, d) for p, d in self._tpu_held
                              if len(d) == slice_size]
            for plugin, devs in stale:
                plugin.release(list(devs))

        # grow/shrink holdings toward `want`, one whole slice per replica
        while len(self._tpu_held) > want:
            plugin, devs = self._tpu_held.pop()
            plugin.release(list(devs))
        for plugin in plugins:
            while (len(self._tpu_held) < want
                   and plugin.ids.free_count >= slice_size):
                ids, _resp = plugin.allocate(slice_size)
                self._tpu_held.append((plugin, list(ids)))
            if len(self._tpu_held) >= want:
                break

        held = len(self._tpu_held)
        total = sum(p.ids.capacity for p in plugins)
        # starved whenever the HPA's desired scale cannot be backed —
        # pools short of whole slices, or the mesh_slices budget capping
        # scale-out below desire
        starved = held < desired
        capped = desired if held >= desired else max(
            self.hpa.min_replicas, held)

        slice_note = "" if slice_size == 1 else (
            f", mesh slice = {slice_size} devices"
            f" ({self.config.anomaly.devices}dp x "
            f"{getattr(self.config.anomaly, 'tensor_parallel', 1)}tp)")
        if group.set_condition(Condition(
                "TpuScheduling",
                ConditionStatus.FALSE if starved else ConditionStatus.TRUE,
                "TpuStarved" if starved else "DevicesAllocated",
                f"{held}/{desired} gateway replicas TPU-backed "
                f"({total} devices in cluster{slice_note})")):
            self.store.update_status(group)
        return capped

    # ------------------------------------------------------------ helpers

    def _destinations(self, store: Store
                      ) -> tuple[list[Destination], list[DestinationResource]]:
        dests, resources = [], []
        for d in store.list("DestinationResource"):
            assert isinstance(d, DestinationResource)
            if d.disabled:
                continue
            resources.append(d)
            dests.append(Destination(
                id=d.meta.name, dest_type=d.dest_type,
                signals=[Signal(s) for s in d.signals],
                config=dict(d.config),
                data_stream_names=list(d.data_stream_names)))
        return dests, resources

    def _processors(self, store: Store) -> list[dict[str, Any]]:
        out = []
        for p in sorted(store.list("Processor"),
                        key=lambda p: p.order_hint):
            assert isinstance(p, Processor)
            if p.disabled:
                continue
            entry = {"id": f"{p.processor_type}/{p.meta.name}",
                     "type": p.processor_type,
                     "config": p.processor_config}
            if p.signals:  # omit the key entirely: empty means all signals
                entry["signals"] = p.signals
            out.append(entry)
        for a in store.list("Action"):
            assert isinstance(a, Action)
            compiled = compile_action(a)
            if compiled is not None:
                out.append(compiled)
        return out

    def _data_streams(self, store: Store,
                      destinations: list[Destination]) -> list[DataStream]:
        """Streams from destination membership + source labels
        (common/pipelinegen/datastreams.go:21)."""
        names: dict[str, dict] = {}
        for d in destinations:
            for s in (d.data_stream_names or ["default"]):
                names.setdefault(s, {"dests": [], "sources": []})[
                    "dests"].append(d.id)
        for src in store.list("Source"):
            assert isinstance(src, Source)
            if src.is_namespace_source:
                continue
            for s in (src.data_stream_names or ["default"]):
                if s in names:
                    names[s]["sources"].append(SourceRef(
                        src.workload.namespace,
                        src.workload.kind.value.lower(),
                        src.workload.name))
        return [DataStream(name,
                           tuple(DataStreamDestination(d) for d in v["dests"]),
                           tuple(v["sources"]))
                for name, v in sorted(names.items())]

    def _gateway_group(self, store: Store) -> Optional[CollectorsGroup]:
        for g in store.list("CollectorsGroup"):
            assert isinstance(g, CollectorsGroup)
            if g.role == CollectorsGroupRole.CLUSTER_GATEWAY:
                return g
        return None
