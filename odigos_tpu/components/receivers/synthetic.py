"""Synthetic trace receiver.

The in-process stand-in for the reference's traffic-generator Job + OTLP
receiver front door (tests/common/apply/generate-traffic-job.yaml feeding the
otlp receiver in every generated pipeline). Pushes deterministic synthetic
trace batches at a configured rate — used by tests, the e2e slice, and bench.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from ...pdata.gen import synthesize_traces
from ...utils.telemetry import meter
from ..api import ComponentKind, Factory, Receiver, Signal, register


class SyntheticReceiver(Receiver):
    """Config:
    traces_per_batch: traces per emitted batch
    n_batches: stop after this many (0 = run until shutdown)
    interval_s: sleep between batches (0 = as fast as possible)
    seed: base RNG seed (batch i uses seed+i)
    """

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def start(self) -> None:
        super().start()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"recv-{self.name}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        cfg = self.config
        n_batches = int(cfg.get("n_batches", 0))
        interval = float(cfg.get("interval_s", 0.0))
        per_batch = int(cfg.get("traces_per_batch", 10))
        seed = int(cfg.get("seed", 0))
        i = 0
        while not self._stop.is_set():
            if n_batches and i >= n_batches:
                break
            batch = synthesize_traces(per_batch, seed=seed + i)
            try:
                self.next_consumer.consume(batch)
            except Exception:
                # downstream refused (memory limiter, flaky destination):
                # backpressure = drop this batch, back off, keep emitting.
                meter.add(f"odigos_receiver_refused_batches_total{{receiver={self.name}}}")
                self._stop.wait(max(interval, 0.01))
            i += 1
            if interval:
                self._stop.wait(interval)

    def drain(self, timeout: float = 30.0) -> None:
        """Block until the configured n_batches have been emitted."""
        t = self._thread
        if t is not None:
            t.join(timeout)

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        super().shutdown()


register(Factory(
    type_name="synthetic",
    kind=ComponentKind.RECEIVER,
    create=SyntheticReceiver,
    default_config=lambda: {
        "traces_per_batch": 10, "n_batches": 0, "interval_s": 0.0, "seed": 0},
    signals=(Signal.TRACES,),
))
