"""Completion-driven multi-lane retirement (ISSUE 9 tentpole).

The contracts pinned here:

* scores/attrs stay BIT-IDENTICAL to the componentwise path for both
  ``ordered: true`` and unordered lanes (the engine semantics are
  untouched — only retirement changed);
* ``ordered: true`` forwards downstream in exact intake order (the
  single-forwarder FIFO byte stream) even when lanes finish out of
  order; unordered lanes deliver the same frames, any order;
* conservation and ledger balance hold under concurrent retirement
  with injected downstream failures, a deadline-expiry storm, and a
  hot reload mid-stream;
* the expiry timer runs OFF the retire loop: a frame whose deadline
  passes is marked passed-through (and blamed) even while every lane
  is busy;
* the stage clock still tiles each frame's wall under N-lane
  retirement (Σstages == wall, the ISSUE 8 acceptance bound), with
  WAIT redefined as score-landing → lane-pickup;
* the engine's done-callback (completion queue) fires exactly once per
  request, after scores/stage_ns are final — including on failure and
  shutdown-drain paths.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from odigos_tpu.pdata import synthesize_traces
from odigos_tpu.pipeline.graph import validate_config
from odigos_tpu.pipeline.service import Collector
from odigos_tpu.selftelemetry.flow import flow_ledger
from odigos_tpu.selftelemetry.latency import STAGES, latency_ledger
from odigos_tpu.serving.engine import EngineConfig, ScoringEngine
from odigos_tpu.serving.fastpath import IngestFastPath
from odigos_tpu.serving.lanes import OrderedGate, RetirementLanes
from odigos_tpu.utils.telemetry import meter
from odigos_tpu.wire.client import WireExporter

from tests.test_ingest_fastpath import run_frames, soak_config, wait_for
from tests.test_latency import assert_frame_accounts


@pytest.fixture(autouse=True)
def _isolate_latency_ledger():
    yield
    latency_ledger.reset()


def lane_config(lanes=4, ordered=False, deadline_ms=30_000, **kw):
    cfg = soak_config(fast_path=True, **kw)
    cfg["service"]["pipelines"]["traces/in"]["fast_path"] = {
        "deadline_ms": deadline_ms, "lanes": lanes, "ordered": ordered}
    return cfg


# --------------------------------------------------------------- parity

class TestLaneParity:
    """Retirement changed; scoring did not: outputs stay bit-identical
    to the componentwise chain at matched grouping, for both ordering
    modes."""

    def make_batches(self):
        out = []
        for s in range(4):
            b = synthesize_traces(24, seed=s)
            if s == 2:
                mask = np.zeros(len(b), bool)
                mask[:5] = True
                b = b.with_span_attrs({"mock.anomaly": [True] * 5}, mask)
            out.append(b)
        return out

    @pytest.mark.parametrize("ordered", [True, False])
    def test_scores_and_attrs_bit_identical(self, ordered):
        batches = self.make_batches()
        got_fast = run_frames(lane_config(lanes=4, ordered=ordered),
                              batches)
        got_slow = run_frames(soak_config(fast_path=False), batches)
        spans_fast = [d for b in got_fast for d in b.span_attrs]
        spans_slow = [d for b in got_slow for d in b.span_attrs]
        assert len(spans_fast) == len(spans_slow) \
            == sum(len(b) for b in batches)
        for a, b in zip(spans_fast, spans_slow):
            assert dict(a) == dict(b)


# ------------------------------------------------------------- ordering

class _RecordingSink:
    """Downstream that records frame identity (span count) in arrival
    order; optionally stalls on the first frame to force lanes to race
    past it."""

    def __init__(self, stall_len=None, stall_s=0.0):
        self.order = []
        self.stall_len = stall_len
        self.stall_s = stall_s
        self._lock = threading.Lock()

    def consume(self, b):
        if self.stall_len is not None and len(b) == self.stall_len:
            time.sleep(self.stall_s)
        with self._lock:
            self.order.append(len(b))


def _distinct_batches():
    """Frames with pairwise-distinct span counts (arrival-order ids)."""
    sizes = []
    out = []
    for k in range(1, 7):
        b = synthesize_traces(k, seed=k)
        if len(b) in sizes:
            continue
        sizes.append(len(b))
        out.append(b)
    assert len(out) >= 4
    return out


def _drive(fp, batches):
    for b in batches:
        fp.consume(b)
    assert fp.drain(30.0)


class TestOrderingContract:
    def _run(self, ordered):
        latency_ledger.reset()
        engine = ScoringEngine(EngineConfig(model="mock",
                                            max_queue=64)).start()
        batches = _distinct_batches()
        # the FIRST frame's forward stalls; later frames' lanes race it
        sink = _RecordingSink(stall_len=len(batches[0]), stall_s=0.5)
        fp = IngestFastPath(
            f"traces/order-{ordered}", engine, threshold=0.99,
            downstream=sink,
            config={"deadline_ms": 30_000, "lanes": 4,
                    "ordered": ordered})
        fp.start()
        try:
            _drive(fp, batches)
        finally:
            fp.shutdown()
            engine.shutdown()
        return [len(b) for b in batches], sink.order

    def test_ordered_output_is_intake_fifo(self):
        """ordered: true — the single-forwarder FIFO contract survives
        a stalled head: later lanes tag concurrently but forward waits
        its turn."""
        sent, got = self._run(ordered=True)
        assert got == sent

    def test_unordered_lanes_overtake_a_stalled_head(self):
        """Unordered lanes exist to kill exactly this head-of-line:
        every frame arrives, and the stalled head arrives LAST."""
        sent, got = self._run(ordered=False)
        assert sorted(got) == sorted(sent)
        assert got[-1] == sent[0], \
            f"stalled head was not overtaken: {got} vs {sent}"

    def test_consume_before_start_renumbers_ordered_seqs(self):
        """Regression: consume() has no started-guard, so frames
        accepted before start() carried pre-epoch seqs that collided
        with post-start frames' after start() reset the counter — the
        ordered gate (keyed by seq) parked the duplicate at a slot it
        had already advanced past, forever. start() now renumbers the
        pending frames into the fresh epoch instead."""
        latency_ledger.reset()
        engine = ScoringEngine(EngineConfig(model="mock",
                                            max_queue=64)).start()
        batches = _distinct_batches()[:4]
        sink = _RecordingSink()
        fp = IngestFastPath(
            "traces/prestart", engine, threshold=0.99, downstream=sink,
            config={"deadline_ms": 30_000, "lanes": 2, "ordered": True})
        try:
            for b in batches[:2]:
                fp.consume(b)  # accepted before any epoch exists
            fp.start()
            for b in batches[2:]:
                fp.consume(b)
            assert fp.drain(30.0), \
                "a seq collision parked a frame forever"
        finally:
            fp.shutdown()
            engine.shutdown()
        assert sink.order == [len(b) for b in batches]

    def test_ordered_parks_count_once_in_retired_counter(self):
        """A park at the ordered gate is not a retirement: each frame
        lands in the odigos_fastpath_lane_retired_frames_total family
        exactly once (on its forwarding invocation), so the per-lane
        distribution stays a usable diagnostic."""
        from odigos_tpu.serving.lanes import LANE_RETIRED_METRIC
        latency_ledger.reset()
        engine = ScoringEngine(EngineConfig(model="mock",
                                            max_queue=64)).start()
        batches = _distinct_batches()
        # the head stalls in the sink, so later frames offer out of
        # turn and PARK — the double-count shape under the old code
        sink = _RecordingSink(stall_len=len(batches[0]), stall_s=0.5)
        fp = IngestFastPath(
            "traces/retcount", engine, threshold=0.99, downstream=sink,
            config={"deadline_ms": 30_000, "lanes": 4, "ordered": True})
        fp.start()
        try:
            _drive(fp, batches)
        finally:
            fp.shutdown()
            engine.shutdown()
        retired = sum(
            meter.counter(
                f"{LANE_RETIRED_METRIC}"
                f"{{pipeline=traces/retcount,lane={i}}}") or 0
            for i in range(4))
        assert retired == len(batches), \
            f"each frame must count exactly once, got {retired}"

    def test_ordered_head_completing_last_cannot_deadlock_the_pool(self):
        """Regression: frames become ready OUT of intake order while
        every lane is occupied. A blocking turnstile wedged here — the
        lone lane held frame 1 waiting its turn while frame 0, ready in
        the queue, had no lane left to run on (drain timed out at 30 s
        under suite load). The parking gate frees the lane instead: the
        tail parks, the head forwards the moment it completes, and the
        parked frames drain in sequence."""
        import odigos_tpu.serving.fastpath as fp_mod

        latency_ledger.reset()
        engine = ScoringEngine(EngineConfig(model="mock",
                                            max_queue=64)).start()
        batches = _distinct_batches()[:4]
        head_len = len(batches[0])
        release_head = threading.Event()
        orig_featurize = fp_mod.featurize

        def gated(batch, cfg):
            # the HEAD sticks in its submit lane until released, so
            # every later frame completes (and must park) first
            if len(batch) == head_len:
                release_head.wait(10.0)
            return orig_featurize(batch, cfg)

        sink = _RecordingSink()
        fp = IngestFastPath(
            "traces/order-parked", engine, threshold=0.99,
            downstream=sink,
            config={"deadline_ms": 30_000, "lanes": 1,
                    "submit_lanes": 2, "ordered": True})
        fp_mod.featurize = gated
        fp.start()
        try:
            for b in batches:
                fp.consume(b)
            # all three tail frames tagged and parked; the single lane
            # is idle again (a turnstile would be blocking it here)
            assert wait_for(lambda: len(fp._gate._parked) == 3), \
                "tail frames never parked"
            assert sink.order == []  # nothing forwarded ahead of turn
            release_head.set()
            assert fp.drain(30.0)
        finally:
            release_head.set()
            fp_mod.featurize = orig_featurize
            fp.shutdown()
            engine.shutdown()
        assert sink.order == [len(b) for b in batches]


# ------------------------------------ conservation under concurrency

class TestLaneConservation:
    def test_burst_conserves_with_lanes(self):
        flow_ledger.reset()
        collector = Collector(lane_config(lanes=4)).start()
        try:
            port = collector.graph.receivers["otlpwire"].port
            exp = WireExporter("t", {"endpoint": f"127.0.0.1:{port}",
                                     "queue_size": 256,
                                     "max_elapsed_s": 30.0})
            exp.start()
            total = 0
            for s in range(16):
                b = synthesize_traces(32, seed=s)
                exp.export(b)
                total += len(b)
            assert exp.flush(30.0)
            exp.shutdown()
            collector.drain_receivers(30.0)
            sink = collector.graph.exporters["tracedb"]
            assert sink.span_count == total
            bal = flow_ledger.conservation()["traces/in"]
            assert bal["items_in"] == total
            assert bal["leak"] == 0, bal
        finally:
            collector.shutdown()

    def test_downstream_failures_stay_balanced(self):
        """Every third frame's export raises mid-retirement: the edges
        count the failures, the lanes keep serving, the reservation
        releases exactly once — the balance names every span."""
        flow_ledger.reset()
        collector = Collector(lane_config(lanes=4)).start()
        try:
            sink = collector.graph.exporters["tracedb"]
            orig = sink.consume
            calls = [0]
            lock = threading.Lock()

            def flaky(b):
                with lock:
                    calls[0] += 1
                    boom = calls[0] % 3 == 0
                if boom:
                    raise RuntimeError("injected exporter outage")
                return orig(b)

            sink.consume = flaky
            port = collector.graph.receivers["otlpwire"].port
            exp = WireExporter("t", {"endpoint": f"127.0.0.1:{port}",
                                     "queue_size": 256,
                                     "max_elapsed_s": 30.0})
            exp.start()
            total = 0
            for s in range(12):
                b = synthesize_traces(24, seed=s)
                exp.export(b)
                total += len(b)
            assert exp.flush(30.0)
            exp.shutdown()
            collector.drain_receivers(30.0)
            fp = collector.graph.fastpaths["traces/in"]
            assert wait_for(lambda: fp.flow_pending() == 0)
            bal = flow_ledger.conservation()["traces/in"]
            assert sum(bal["failed"].values()) > 0, \
                "no injected failure was counted"
            assert bal["leak"] == 0, bal
            assert bal["items_in"] == total
            # the attribution layer saw every frame (downstream outage
            # must not starve the SLO tracker)
            rec = latency_ledger.snapshot()["pipelines"]["traces/in"]
            assert rec["frames"] == 12
        finally:
            collector.shutdown()

    def test_reload_mid_stream_with_lanes_conserved(self):
        flow_ledger.reset()
        cfg = lane_config(lanes=4)
        collector = Collector(cfg).start()
        stop = threading.Event()
        try:
            port = collector.graph.receivers["otlpwire"].port
            exp = WireExporter("t", {"endpoint": f"127.0.0.1:{port}",
                                     "max_elapsed_s": 30.0})
            exp.start()
            batches = [synthesize_traces(16, seed=s) for s in range(4)]

            def sender():
                k = 0
                while not stop.is_set():
                    exp.export(batches[k % 4])
                    k += 1
                    while exp.queued > 8 and not stop.is_set():
                        time.sleep(0.001)
                    time.sleep(0.002)

            t = threading.Thread(target=sender, daemon=True)
            t.start()
            time.sleep(0.25)
            new_cfg = lane_config(lanes=2, ordered=True, threshold=0.9)
            new_cfg["receivers"]["otlpwire"] = {"port": port}
            collector.reload(new_cfg)
            fp = collector.graph.fastpaths["traces/in"]
            assert fp.lanes == 2 and fp.ordered
            time.sleep(0.25)
            stop.set()
            t.join(timeout=10)
            assert exp.flush(30.0)
            exp.shutdown()
            collector.drain_receivers(30.0)
            bal = flow_ledger.conservation()["traces/in"]
            assert bal["leak"] == 0, bal
            assert collector.graph.exporters["tracedb"].span_count > 0
        finally:
            stop.set()
            collector.shutdown()


class TestTagFailure:
    def test_tag_failure_frames_not_counted_scored(self):
        """Regression: a frame whose tag_anomalies raised was observed
        into the ledger scored=True — keeping the scored_fraction SLO
        green during exactly the failure it should burn on. ``scored``
        is now set only after tagging succeeds."""
        import odigos_tpu.serving.fastpath as fp_mod

        latency_ledger.reset()
        engine = ScoringEngine(EngineConfig(model="mock",
                                            max_queue=64)).start()
        sink = _RecordingSink()
        orig = fp_mod.tag_anomalies

        def boom(batch, scores, threshold):
            raise RuntimeError("injected tag failure")

        fp = IngestFastPath(
            "traces/tagfail", engine, threshold=0.99, downstream=sink,
            config={"deadline_ms": 30_000, "lanes": 2})
        fp_mod.tag_anomalies = boom
        fp.start()
        try:
            for s in range(3):
                fp.consume(synthesize_traces(4, seed=s))
            assert fp.drain(30.0)
        finally:
            fp_mod.tag_anomalies = orig
            fp.shutdown()
            engine.shutdown()
        rec = latency_ledger.snapshot()["pipelines"]["traces/tagfail"]
        assert rec["frames"] == 3
        assert rec["scored_frames"] == 0, \
            "tag-failed frames must not read as scored"
        assert sink.order == []  # a tag-failed frame cannot forward
        assert fp.flow_pending() == 0  # but its reservation released


# ------------------------------------------------------- expiry timer

class _StuckBackend:
    """Backend whose score blocks until released: requests never
    resolve on their own, so only the expiry timer can free frames."""

    def __init__(self):
        self.release = threading.Event()

    def score(self, batch, features):
        self.release.wait(10.0)
        return np.zeros(len(batch), np.float32)


class TestDeadlineAnchor:
    def test_deadline_anchored_at_intake_not_post_featurize(self):
        """Regression: the admission deadline was stamped AFTER
        featurize in the submit lane, so time queued for (or inside)
        featurize burned no budget — a featurize-bound overload could
        hold frames for seconds and still 'meet' a 25 ms deadline with
        zero expiries. The deadline now runs from frame acceptance."""
        import odigos_tpu.serving.fastpath as fp_mod

        engine = ScoringEngine(EngineConfig(model="mock",
                                            max_queue=8)).start()
        captured = {}
        orig_submit = engine.submit

        def recording_submit(batch, features=None, deadline_ns=None,
                             on_done=None, **kw):
            captured["deadline_ns"] = deadline_ns
            return orig_submit(batch, features, deadline_ns=deadline_ns,
                               on_done=on_done, **kw)

        engine.submit = recording_submit
        orig_featurize = fp_mod.featurize

        def slow_featurize(batch, cfg):
            time.sleep(0.1)
            return orig_featurize(batch, cfg)

        fp_mod.featurize = slow_featurize

        class Sink:
            def consume(self, b):
                pass

        fp = IngestFastPath(
            "traces/anchor", engine, threshold=0.9, downstream=Sink(),
            config={"deadline_ms": 500.0, "lanes": 1})
        fp.start()
        try:
            t0 = time.monotonic_ns()
            fp.consume(synthesize_traces(4, seed=0))
            assert fp.drain(10.0)
            budget_ms = (captured["deadline_ns"] - t0) / 1e6
            # intake-anchored: ~500 ms from consume; the old post-
            # featurize anchor would read >= 600 ms (500 + the 100 ms
            # featurize sleep)
            assert budget_ms < 560.0, \
                f"deadline anchored post-featurize: {budget_ms:.1f} ms"
        finally:
            fp_mod.featurize = orig_featurize
            fp.shutdown()
            engine.shutdown()


class TestExpiryTimer:
    def test_expiry_storm_blames_every_frame(self):
        """Deadline storm: the device is stuck, every frame expires at
        its deadline via the timer, retires unscored through the lanes,
        and every expired span carries a blamed stage."""
        latency_ledger.reset()
        meter.reset()
        engine = ScoringEngine(EngineConfig(model="mock", max_queue=64))
        backend = _StuckBackend()
        engine.backend = backend
        engine._depth = 1
        engine.start()
        seen = []
        lock = threading.Lock()

        class Sink:
            def consume(self, b):
                with lock:
                    seen.append(len(b))

        fp = IngestFastPath(
            "traces/storm", engine, threshold=0.9, downstream=Sink(),
            config={"deadline_ms": 25.0, "lanes": 4})
        fp.start()
        try:
            batches = [synthesize_traces(6, seed=s) for s in range(6)]
            total = sum(len(b) for b in batches)
            for b in batches:
                fp.consume(b)
            assert fp.drain(20.0)
            assert sum(seen) == total, "a frame was lost in the storm"
            rec = latency_ledger.snapshot()["pipelines"]["traces/storm"]
            assert rec["frames"] == 6 and rec["scored_frames"] == 0
            blames = rec["burn"]["expired_spans_by_blame"]
            assert sum(blames.values()) == total, blames
            assert set(blames) <= {"queue", "device"}, blames
            assert fp.flow_pending() == 0
        finally:
            backend.release.set()
            fp.shutdown()
            engine.shutdown()

    def test_expiry_fires_while_lanes_are_busy(self):
        """The timer is OFF the retire loop: with the only lane stalled
        in a slow downstream, a later frame's deadline still marks it
        passed-through (counter fires before any lane frees)."""
        latency_ledger.reset()
        meter.reset()
        engine = ScoringEngine(EngineConfig(model="mock",
                                            max_queue=64)).start()
        gate = threading.Event()
        in_sink = threading.Event()

        class StallingSink:
            def consume(self, b):
                in_sink.set()
                gate.wait(10.0)

        fp = IngestFastPath(
            "traces/busy-lanes", engine, threshold=0.9,
            downstream=StallingSink(),
            config={"deadline_ms": 150.0, "lanes": 1})
        fp.start()
        try:
            # frame 1 scores fast and occupies THE lane (stalled sink)
            fp.consume(synthesize_traces(4, seed=1))
            assert in_sink.wait(10.0), "lane never reached the sink"
            # frame 2's request never resolves (stuck device): with no
            # free lane, only the earliest-deadline timer can mark it —
            # the old retire-loop expiry would sit behind the stall
            stuck = _StuckBackend()
            engine.backend = stuck
            b2 = synthesize_traces(4, seed=3)
            fp.consume(b2)

            def n_pass():
                return meter.counter(
                    "odigos_anomaly_passthrough_total") or 0

            assert wait_for(lambda: n_pass() >= len(b2), timeout=10.0), \
                "expiry never fired while the lane was busy"
            assert not gate.is_set()  # the lane really was still stalled
            stuck.release.set()
            gate.set()
            assert fp.drain(20.0)
        finally:
            gate.set()
            fp.shutdown()
            engine.shutdown()


class TestEpochStraggler:
    def test_straggler_lane_across_restart_cannot_park_forever(self):
        """Regression: a lane stuck in tag across a shutdown→start
        cycle read the NEW epoch's (unset) stop flag on resume and
        offered into the ORPHANED old gate — whose head never advances
        again — parking the frame and leaking its reservation forever.
        The lane now aliases its epoch's stop flag alongside the gate,
        sees it set, and gate-bypasses on resume."""
        import odigos_tpu.serving.fastpath as fp_mod

        engine = ScoringEngine(EngineConfig(model="mock",
                                            max_queue=64)).start()
        batches = _distinct_batches()[:2]
        head_len, stuck_len = len(batches[0]), len(batches[1])
        in_sink = threading.Event()
        sink_gate = threading.Event()
        in_tag = threading.Event()
        tag_gate = threading.Event()
        orig_tag = fp_mod.tag_anomalies

        def gated_tag(batch, scores, threshold):
            if len(batch) == stuck_len:
                in_tag.set()
                tag_gate.wait(30.0)
            return orig_tag(batch, scores, threshold)

        class HeadStallSink:
            def consume(self, b):
                if len(b) == head_len:
                    in_sink.set()
                    sink_gate.wait(30.0)

        fp = IngestFastPath(
            "traces/epoch", engine, threshold=0.99,
            downstream=HeadStallSink(),
            config={"deadline_ms": 30_000, "lanes": 2, "ordered": True,
                    "drain_timeout_s": 0.2})
        fp_mod.tag_anomalies = gated_tag
        fp.start()
        try:
            fp.consume(batches[0])  # seq 0: holds the gate, stalls in sink
            assert in_sink.wait(10.0), "head never reached the sink"
            fp.consume(batches[1])  # seq 1: its lane wedges in tag
            assert in_tag.wait(10.0), "lane never reached tag"
            fp.shutdown()  # drain times out; both lanes still stuck
            fp.start()     # fresh epoch (new gate, new stop flag)
            tag_gate.set()  # the tag-stuck lane resumes FIRST: the old
            # gate's head (seq 0, still in the sink) has not advanced,
            # so an offer into it would park forever — the resumed lane
            # must bypass instead and release seq 1's reservation
            assert wait_for(lambda: fp.flow_pending() == head_len,
                            timeout=10.0), \
                "straggler parked in the orphaned gate (leak)"
            sink_gate.set()  # free the old head; it advances its own
            assert wait_for(lambda: fp.flow_pending() == 0)  # old gate
        finally:
            tag_gate.set()
            sink_gate.set()
            fp_mod.tag_anomalies = orig_tag
            fp.shutdown()
            engine.shutdown()


# ------------------------------------------------- bounded shutdown

class TestBoundedShutdown:
    def test_wedged_downstream_cannot_block_shutdown(self):
        """A downstream that never returns must not wedge shutdown():
        past drain_timeout_s the unretired frames are CLAIMED and shed
        as named shutdown_drain drops (reservation released, balance
        exact), while the frame a stuck lane still holds stays its
        property — no double release when the lane finally finishes."""
        flow_ledger.reset()
        meter.reset()
        engine = ScoringEngine(EngineConfig(model="mock",
                                            max_queue=64)).start()
        gate = threading.Event()
        in_sink = threading.Event()

        class WedgedSink:
            def consume(self, b):
                in_sink.set()
                gate.wait(30.0)

        fp = IngestFastPath(
            "traces/wedged", engine, threshold=0.9,
            downstream=WedgedSink(),
            config={"deadline_ms": 30_000, "lanes": 1,
                    "drain_timeout_s": 0.3})
        fp.start()
        try:
            a = synthesize_traces(4, seed=1)
            b = synthesize_traces(6, seed=2)
            fp.consume(a)
            assert in_sink.wait(10.0), "lane never reached the sink"
            fp.consume(b)  # scores land; no lane free to retire it
            assert wait_for(lambda: fp._retire_lanes.depth() == 1)
            t0 = time.monotonic()
            fp.shutdown()
            # bounded: drain timeout + thread joins, NOT the sink's 30 s
            assert time.monotonic() - t0 < 15.0
            # frame b was shed and named; frame a is still the stuck
            # lane's property, its reservation held
            assert fp.flow_pending() == len(a)
            snap = flow_ledger.snapshot()
            shed = sum(
                d["reasons"].get("shutdown_drain", 0)
                for d in snap["drops"]
                if d["pipeline"] == "traces/wedged")
            assert shed == len(b), snap["drops"]
        finally:
            gate.set()
            # the released lane finishes frame a and releases exactly
            # once — the pending window must fully empty
            assert wait_for(lambda: fp.flow_pending() == 0)
            engine.shutdown()


class TestPayloadRelease:
    def test_done_frames_behind_stalled_head_drop_payloads(self):
        """Regression: _live prunes only its contiguous done prefix, so
        a done frame can sit pinned behind a stalled (not-yet-done)
        head indefinitely — with its reservation already released, the
        max_pending_spans window no longer bounded what _live kept
        alive. _release_frame now drops batch/out/req refs, so the
        pinned shell is slim."""
        engine = ScoringEngine(EngineConfig(model="mock",
                                            max_queue=64)).start()
        gate = threading.Event()
        in_sink = threading.Event()
        batches = _distinct_batches()[:4]
        head_len = len(batches[0])

        class StallSink:
            def consume(self, b):
                if len(b) == head_len:
                    in_sink.set()
                    gate.wait(15.0)

        fp = IngestFastPath(
            "traces/pinned", engine, threshold=0.99,
            downstream=StallSink(),
            config={"deadline_ms": 30_000, "lanes": 2})
        fp.start()
        try:
            fp.consume(batches[0])
            assert in_sink.wait(10.0), "head never reached the sink"
            for b in batches[1:]:
                fp.consume(b)

            def done_behind_head():
                with fp._lock:
                    return sum(1 for f in fp._live if f.done)

            assert wait_for(lambda: done_behind_head() == 3)
            with fp._lock:
                pinned = [f for f in fp._live if f.done]
                assert len(pinned) == 3  # head still stalls the prune
                assert all(f.batch is None and f.out is None
                           and f.req is None for f in pinned), \
                    "done frames behind the head must not pin payloads"
            gate.set()
            assert fp.drain(20.0)
        finally:
            gate.set()
            fp.shutdown()
            engine.shutdown()


# ------------------------------------------------ tiling under lanes

class TestLaneTiling:
    def test_stage_tiling_holds_under_multilane_burst(self):
        """Σstages == wall per frame (the ISSUE 8 acceptance bound)
        survives concurrent retirement — the clock handoff is sequenced
        through the fast-path lock, never shared between lanes."""
        flow_ledger.reset()
        latency_ledger.reset()
        collector = Collector(lane_config(lanes=4,
                                          deadline_ms=10_000)).start()
        try:
            port = collector.graph.receivers["otlpwire"].port
            exp = WireExporter("t", {"endpoint": f"127.0.0.1:{port}",
                                     "queue_size": 64})
            exp.start()
            batches = [synthesize_traces(16, seed=s) for s in range(4)]
            want = 0
            for k in range(24):
                exp.export(batches[k % 4])
                want += len(batches[k % 4])
            assert exp.flush(30.0)
            exp.shutdown()
            collector.drain_receivers(30.0)
            sink = collector.graph.exporters["tracedb"]
            assert sink.span_count == want
            rec = latency_ledger.snapshot()["pipelines"]["traces/in"]
            assert rec["frames"] == 24 and rec["scored_frames"] == 24
            for frame in rec["recent"]:
                assert_frame_accounts(frame)
            wf = rec["waterfall"]
            assert set(wf) == set(STAGES)
        finally:
            collector.shutdown()


# --------------------------------------------------- completion queue

class TestCompletionCallback:
    def test_callback_fires_once_with_final_scores(self):
        engine = ScoringEngine(EngineConfig(model="mock",
                                            max_queue=8)).start()
        fired = []
        done = threading.Event()

        def cb(req):
            fired.append((req.scores is not None,
                          req.done.is_set()))
            done.set()

        try:
            b = synthesize_traces(4, seed=0)
            req = engine.submit(b, None, on_done=cb)
            assert req is not None
            assert done.wait(10.0)
            assert fired == [(True, True)]
        finally:
            engine.shutdown()
        assert len(fired) == 1  # shutdown drain must not re-fire

    def test_callback_fires_on_shutdown_drain(self):
        engine = ScoringEngine(EngineConfig(model="mock", max_queue=8))
        # never started: the queue drains at shutdown
        fired = []
        b = synthesize_traces(4, seed=0)
        req = engine.submit(b, None, on_done=lambda r: fired.append(
            r.scores is None))
        assert req is not None
        engine.shutdown()
        assert fired == [True], \
            "drained request must still signal its completion"


# ------------------------------------------------------------- config

class TestLaneConfigContract:
    def _cfg(self, fp):
        cfg = soak_config(fast_path=True)
        cfg["service"]["pipelines"]["traces/in"]["fast_path"] = fp
        return cfg

    def test_bad_lane_configs_rejected(self):
        assert any("fast_path.lanes" in p for p in validate_config(
            self._cfg({"deadline_ms": 10, "lanes": 0})))
        assert any("fast_path.lanes" in p for p in validate_config(
            self._cfg({"deadline_ms": 10, "lanes": True})))
        assert any("fast_path.ordered" in p for p in validate_config(
            self._cfg({"deadline_ms": 10, "ordered": "yes"})))
        assert any("unknown fast_path keys" in p for p in
                   validate_config(self._cfg({"lane_count": 4})))
        assert any("fast_path.deadline_ms" in p for p in validate_config(
            self._cfg({"deadline_ms": -1})))
        assert any("fast_path.submit_lanes" in p for p in validate_config(
            self._cfg({"deadline_ms": 10, "submit_lanes": 0})))
        # fractional max_pending_spans int()-truncates in the fast path
        # (0.9 -> a zero-span window rejecting EVERY frame): integer-only
        assert any("fast_path.max_pending_spans" in p for p in
                   validate_config(self._cfg(
                       {"deadline_ms": 10, "max_pending_spans": 0.9})))
        assert validate_config(self._cfg(
            {"deadline_ms": 10, "lanes": 4, "submit_lanes": 2,
             "ordered": True})) == []

    def test_submit_pool_sized_apart_from_retirement(self):
        # the pools bound different legs (featurize+submit vs the
        # downstream forward); submit_lanes defaults to lanes but may
        # be set independently for host-contended boxes
        engine = ScoringEngine(EngineConfig(model="mock")).start()
        try:
            fp = IngestFastPath(
                "traces/pools", engine, 0.5, None,
                {"deadline_ms": 10, "lanes": 3})
            assert (fp.lanes, fp.submit_lanes) == (3, 3)
            fp = IngestFastPath(
                "traces/pools", engine, 0.5, None,
                {"deadline_ms": 10, "lanes": 3, "submit_lanes": 1})
            assert (fp.lanes, fp.submit_lanes) == (3, 1)
            fp.start()
            try:
                assert len(fp._submit_threads) == 1
                assert len(fp._retire_lanes._threads) == 3
            finally:
                fp.shutdown()
        finally:
            engine.shutdown()


# ----------------------------------------------------- lane plumbing

class TestLanePool:
    def test_gate_parks_out_of_turn_and_surfaces_in_order(self):
        """The ordered gate never blocks a caller: out-of-turn offers
        park, and each advance() surfaces exactly the next parked
        frame — seqs emerge 0,1,2,3 no matter the offer order."""
        gate = OrderedGate()
        # 3, 1, 2 arrive before the head: all park, no caller waits
        assert not gate.offer(3, "f3")
        assert not gate.offer(1, "f1")
        assert not gate.offer(2, "f2")
        assert gate.offer(0, "f0")  # the head holds the gate
        assert gate.advance() == "f1"
        assert gate.advance() == "f2"
        assert gate.advance() == "f3"
        assert gate.advance() is None  # seq 4 not offered yet
        assert gate.offer(4, "f4")

    def test_gate_flush_returns_parked_in_sequence_order(self):
        gate = OrderedGate()
        gate.offer(2, "f2")
        gate.offer(5, "f5")
        gate.offer(1, "f1")
        assert gate.flush() == ["f1", "f2", "f5"]
        assert gate.flush() == []

    def test_lane_pool_survives_retire_errors(self):
        retired = []

        def retire(frame, lane):
            if frame == "boom":
                raise RuntimeError("frame error")
            retired.append(frame)

        lanes = RetirementLanes("traces/pool-test", 2, retire).start()
        try:
            lanes.push("boom")
            lanes.push("a")
            lanes.push("b")
            assert wait_for(lambda: sorted(retired) == ["a", "b"])
            assert meter.counter(
                "odigos_fastpath_lane_errors_total"
                "{pipeline=traces/pool-test}") >= 1
        finally:
            lanes.shutdown()
