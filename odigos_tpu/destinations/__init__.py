"""Destination registry + collector-config generators.

Reference: destinations/ (63 declarative backend YAMLs: signal support, UI
field schema, secret flags — destinations/data/*.yaml, loaded at
destinations/load.go:19) and common/config/*.go (~75 per-backend configers
implementing ModifyConfig, dispatched from
common/pipelinegen/config_builder.go:92).

Our design folds both into one table-driven module: ``DestinationSpec``
carries the declarative schema *and* the exporter-generation recipe, so a
new backend is one table entry instead of a YAML file + a Go file. Secrets
stay out of generated configs via ``${ENV_VAR}`` placeholders, same
convention as the reference.
"""

from .registry import (
    DestinationSpec,
    Destination,
    SPECS,
    get_spec,
    validate_destination,
)
from .configers import modify_config, ConfigerError

__all__ = [
    "DestinationSpec",
    "Destination",
    "SPECS",
    "get_spec",
    "validate_destination",
    "modify_config",
    "ConfigerError",
]
