"""``remotetap`` processor — live peek at pipeline data over HTTP.

Upstream's remotetapprocessor (collector/builder-config.yaml:85) is a
pass-through that serves rate-limited copies of the data flowing by on a
websocket.  Our analog serves NDJSON over plain HTTP (no websocket
dependency in this image): the processor keeps a small bounded ring of
recent sampled rows and ``GET /`` drains a snapshot of it — the
operator's ``curl`` replaces the websocket client.  Sampling is
rate-limited to ``limit`` rows/second so a tap on a hot pipeline costs
amortized O(limit), never O(traffic).

Config::

    remotetap:
      port: 0          # 0 = ephemeral (resolved port on .port after start)
      limit: 1.0       # sampled rows per second
      buffer: 256      # ring capacity

The data plane is never blocked: process() appends to the ring under a
lock and returns the batch unchanged (mutates_data=False).
"""

from __future__ import annotations

import http.server
import json
import threading
import time
from collections import deque
from typing import Any, Optional

from ...pdata.logs import LogBatch
from ...pdata.metrics import MetricBatch
from ...pdata.spans import SpanBatch
from ..api import Capabilities, ComponentKind, Factory, Processor, register


class RemoteTapProcessor(Processor):
    """See module docstring."""

    capabilities = Capabilities(mutates_data=False)

    def __init__(self, name: str, config: dict[str, Any]):
        super().__init__(name, config)
        self.limit = float(config.get("limit", 1.0))
        self.ring: deque = deque(maxlen=int(config.get("buffer", 256)))
        self._lock = threading.Lock()
        self._next_sample = 0.0
        self._want_port = int(config.get("port", 0))
        self.port: Optional[int] = None
        self._http: Optional[http.server.ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        ring, lock = self.ring, self._lock

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802
                with lock:
                    rows = list(ring)
                    ring.clear()  # a poll DRAINS: no duplicate rows
                body = ("\n".join(json.dumps(r, default=str)
                                  for r in rows) + "\n").encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a) -> None:  # quiet
                pass

        self._http = http.server.ThreadingHTTPServer(
            ("127.0.0.1", self._want_port), Handler)
        self.port = self._http.server_address[1]
        self._thread = threading.Thread(
            target=self._http.serve_forever, name=f"remotetap-{self.name}",
            daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
            self._http = None

    # --------------------------------------------------------- data plane
    def process(self, batch: Any) -> Any:
        now = time.monotonic()
        with self._lock:
            if now < self._next_sample:
                return batch
            self._next_sample = now + (1.0 / self.limit
                                       if self.limit > 0 else 3600.0)
            row = self._sample_row(batch)
            if row is not None:
                self.ring.append(row)
        return batch

    @staticmethod
    def _sample_row(batch: Any) -> Optional[dict]:
        if isinstance(batch, SpanBatch) and len(batch):
            return {"signal": "traces", "n": len(batch),
                    "first": next(iter(batch.iter_spans()), None)}
        if isinstance(batch, MetricBatch) and len(batch):
            return {"signal": "metrics", "n": len(batch),
                    "first": next(iter(batch.iter_points()), None)}
        if isinstance(batch, LogBatch) and len(batch):
            return {"signal": "logs", "n": len(batch),
                    "first": next(iter(batch.iter_records()), None)}
        return None


register(Factory(
    type_name="remotetap",
    kind=ComponentKind.PROCESSOR,
    create=RemoteTapProcessor,
    default_config=lambda: {"port": 0, "limit": 1.0},
))
