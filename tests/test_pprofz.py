"""pprof extension coverage (ISSUE 3 satellite): concurrent
``/debug/profile`` + ``/debug/threadz`` requests don't interleave
sampler state, ``seconds``/``hz`` clamp against hostile query values
(negative, NaN, garbage), folded output parses as ``frame;frame count``
lines with ``module:name`` frames, and the absolute-tick scheduler holds
its effective rate instead of drifting low by the per-sweep cost."""

from __future__ import annotations

import json
import threading
import time
import urllib.request

import pytest

from odigos_tpu.components.extensions.pprofz import (
    PprofExtension, sample_profile, thread_stacks)


@pytest.fixture
def ext():
    e = PprofExtension("pprof", {"port": 0, "max_seconds": 2.0})
    e.start()
    yield e
    e.shutdown()


def get_json(ext, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{ext.port}{path}", timeout=10) as r:
        return json.loads(r.read())


class TestSampleProfile:
    def test_folded_lines_parse(self):
        # a busy helper thread guarantees at least one sampled stack
        stop = threading.Event()

        def spin():
            while not stop.is_set():
                sum(range(100))

        t = threading.Thread(target=spin, daemon=True)
        t.start()
        try:
            folded = sample_profile(seconds=0.2, hz=200.0)
        finally:
            stop.set()
            t.join()
        assert folded
        for line in folded:
            stack, count = line.rsplit(" ", 1)
            assert count.isdigit() and int(count) >= 1
            assert stack  # "frame;frame" part non-empty

    def test_frames_carry_module_names(self):
        """``module:name`` frames: same-named functions in different
        modules must not merge into one flamegraph frame."""
        stop = threading.Event()

        def spin():  # this frame must fold as "test_pprofz:spin"
            while not stop.is_set():
                sum(range(100))

        t = threading.Thread(target=spin, daemon=True)
        t.start()
        try:
            folded = sample_profile(seconds=0.2, hz=200.0)
        finally:
            stop.set()
            t.join()
        joined = "\n".join(folded)
        assert "test_pprofz:spin" in joined
        # every frame in every stack is module-qualified
        for line in folded:
            for frame in line.rsplit(" ", 1)[0].split(";"):
                assert ":" in frame, f"unqualified frame {frame!r}"

    def test_effective_rate_holds_near_target(self):
        """Absolute-tick scheduling: sweeps/elapsed stays near hz even
        though each sweep costs time (the old sleep(interval) drifted
        low by exactly the sweep cost)."""
        hz, seconds = 100.0, 0.5
        stop = threading.Event()

        def spin():  # a thread to sample, so sweep count is observable
            while not stop.is_set():
                sum(range(100))

        t = threading.Thread(target=spin, daemon=True)
        t.start()
        t0 = time.monotonic()
        try:
            folded = sample_profile(seconds=seconds, hz=hz)
        finally:
            stop.set()
            t.join()
        elapsed = time.monotonic() - t0
        # total samples across stacks / threads-per-sweep ≈ sweep count;
        # count sweeps via the busiest single stack as a lower bound
        sweeps = max(int(line.rsplit(" ", 1)[1]) for line in folded) \
            if folded else 0
        assert elapsed < seconds + 0.3
        # allow generous scheduler noise; the drifting implementation
        # loses far more than 40% under a sweep cost of ~1ms at 100hz
        assert sweeps >= hz * seconds * 0.6, \
            f"only {sweeps} sweeps in {elapsed:.2f}s at {hz}hz"


class TestProfileEndpoint:
    @pytest.mark.parametrize("query,exp_seconds,exp_hz", [
        ("seconds=0.05&hz=200", 0.05, 200.0),
        ("seconds=9999&hz=99999", 0.2, 997.0),      # clamped to caps
        ("seconds=-3&hz=-7", 0.01, 1.0),            # clamped to floors
        ("seconds=nan&hz=nan", 0.2, 97.0),          # NaN -> capped default
        ("seconds=bogus&hz=bogus", 0.2, 97.0),      # garbage -> default
    ])
    def test_clamping(self, query, exp_seconds, exp_hz):
        # handler exercised directly (no HTTP hop): the clamp contract is
        # pure; max_seconds kept tiny so default-fallback cases stay fast
        ext = PprofExtension("pprof", {"port": 0, "max_seconds": 0.2})
        q = dict(kv.split("=") for kv in query.split("&"))
        code, body = ext._profile(q)
        assert code == 200
        assert body["seconds"] == pytest.approx(exp_seconds)
        assert body["hz"] == pytest.approx(exp_hz)
        for line in body["folded"]:
            stack, count = line.rsplit(" ", 1)
            assert count.isdigit()

    def test_concurrent_profile_and_threadz(self, ext):
        """Concurrent requests: profiles serialize on the sampler lock
        (no interleaved sampler state), threadz stays lock-free, and
        every response is complete and well-formed."""
        results: dict[str, object] = {}
        errors: list[BaseException] = []

        def hit(name, path):
            try:
                results[name] = get_json(ext, path)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        threads = [
            threading.Thread(target=hit, args=(
                "p1", "/debug/profile?seconds=0.3&hz=97")),
            threading.Thread(target=hit, args=(
                "p2", "/debug/profile?seconds=0.3&hz=97")),
            threading.Thread(target=hit, args=("t1", "/debug/threadz")),
            threading.Thread(target=hit, args=("t2", "/debug/threadz")),
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        assert set(results) == {"p1", "p2", "t1", "t2"}
        # the two profiles serialized: total wall >= 2 x 0.3s
        assert time.monotonic() - t0 >= 0.55
        for name in ("p1", "p2"):
            body = results[name]
            assert body["seconds"] == pytest.approx(0.3)
            for line in body["folded"]:
                stack, count = line.rsplit(" ", 1)
                assert count.isdigit() and stack
        for name in ("t1", "t2"):
            threads_out = results[name]["threads"]
            assert threads_out  # at least the main + handler threads
            for stack in threads_out.values():
                assert isinstance(stack, list)

    def test_threadz_sees_named_threads(self, ext):
        hold = threading.Event()
        release = threading.Event()

        def parked():
            hold.set()
            release.wait(5)

        t = threading.Thread(target=parked, name="parked-probe",
                             daemon=True)
        t.start()
        hold.wait(5)
        try:
            out = get_json(ext, "/debug/threadz")
            assert "parked-probe" in out["threads"]
        finally:
            release.set()
            t.join()


def test_thread_stacks_maps_names():
    out = thread_stacks()
    assert any("MainThread" in name or name for name in out)
