"""Effective-config computation (the scheduler's core pure function).

Reference: scheduler/controllers/odigosconfiguration/
odigosconfiguration_controller.go:44-112 — take the authored configuration,
resolve profiles (dependencies :73-110, tier gating) and apply each profile's
config mutation, merge the sizing preset (:112), and emit the effective
configuration all other components read.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field

from ..utils.feature import Features
from .model import Configuration, Tier
from .profiles import Profile, resolve_profiles
from .sizing import SIZING_PRESETS, ResolvedResources, gateway_resources, node_resources


def _jax_version() -> str:
    """jax's installed version without importing it (config computation
    runs in CLI paths where a jax import costs seconds)."""
    try:
        from importlib.metadata import version

        return version("jax")
    except Exception:  # noqa: BLE001 — absent jax = all jax gates off
        return "0.0"


@dataclass
class EffectiveConfig:
    config: Configuration
    applied_profiles: list[str] = field(default_factory=list)
    problems: list[str] = field(default_factory=list)
    gateway: ResolvedResources | None = None
    node: ResolvedResources | None = None
    # resolved feature-gate snapshot (k8sutils/pkg/feature role): what the
    # connected platform versions enable, surfaced via describe/diagnose
    features: dict = field(default_factory=dict)


def calculate_effective_config(authored: Configuration,
                               tier: Tier = Tier.COMMUNITY) -> EffectiveConfig:
    cfg = copy.deepcopy(authored)
    profiles, problems = resolve_profiles(cfg.profiles, tier)
    for p in profiles:
        if p.modify_config is not None:
            p.modify_config(cfg)

    preset = None
    if cfg.resource_size_preset:
        preset = SIZING_PRESETS.get(cfg.resource_size_preset)
        if preset is None:
            problems.append(f"unknown resource size preset {cfg.resource_size_preset!r}")

    # feature gates keyed on the connected platform versions
    # (k8sutils/pkg/feature/feature.go:22-48): maturity decides defaults,
    # and immature paths are clamped rather than silently deployed
    features = Features(k8s_version=cfg.cluster_version,
                        jax_version=_jax_version())
    if cfg.anomaly.devices > 1 and not features.enabled("shard-map-scoring"):
        problems.append(
            f"anomaly.devices={cfg.anomaly.devices} requires the "
            f"shard-map-scoring gate (jax too old) — clamped to 1")
        cfg.anomaly.devices = 1
    if cfg.anomaly.tensor_parallel > 1 \
            and not features.enabled("shard-map-scoring"):
        problems.append(
            f"anomaly.tensor_parallel={cfg.anomaly.tensor_parallel} "
            f"requires the shard-map-scoring gate (jax too old) — "
            f"clamped to 1")
        cfg.anomaly.tensor_parallel = 1

    return EffectiveConfig(
        config=cfg,
        applied_profiles=[p.name for p in profiles],
        problems=problems,
        gateway=gateway_resources(cfg.collector_gateway, preset),
        node=node_resources(cfg.collector_node, preset),
        features=features.snapshot(),
    )
