"""Diagnose — support-bundle collection (odigos diagnose;
cli/cmd/diagnose.go + k8sutils/pkg/diagnose/ in the reference): dump the
full installation state, effective config, self-telemetry metrics snapshot,
the internal-tracing span ring, and environment info into one tar.gz an
operator can attach to a bug report.

``--redact`` strips destination-secret values (delivered env credentials
and the CLI secrets file) from every archived file before it is written:
span attributes, metric label values, and resource dumps all pass through
the same scrub, so a bundle built from a cluster with live credentials is
safe to attach to a public issue.
"""

from __future__ import annotations

import io
import json
import os
import platform
import tarfile
import threading
import time
from typing import Iterable, Optional

from ..components.extensions.pprofz import sample_profile
from ..controlplane.scheduler import (
    EFFECTIVE_CONFIG_NAME, ODIGOS_NAMESPACE)
from ..selftelemetry.profiler import DeviceRuntimeCollector, profiler
from ..selftelemetry.tracer import tracer
from ..utils.serde import to_jsonable
from ..utils.telemetry import meter
from .describe import describe_install
from .state import CliState

REDACTED = "[REDACTED]"


def _add_file(tar: tarfile.TarFile, name: str, content: str) -> None:
    data = content.encode()
    info = tarfile.TarInfo(name)
    info.size = len(data)
    info.mtime = int(time.time())
    tar.addfile(info, io.BytesIO(data))


def _secret_values(state: CliState) -> list[str]:
    """Every destination-secret VALUE reachable from this install: the
    CLI secrets file plus the env vars destination configers reference as
    ``${NAME}`` (the frontend delivers submitted credentials there).
    Values shorter than 4 chars are skipped — scrubbing them would
    mangle unrelated text more than it would protect anything."""
    from ..destinations.registry import referenced_secret_env_names

    values = set(state.secrets.values())
    for env_name in referenced_secret_env_names(
            state.store.list("DestinationResource")):
        v = os.environ.get(env_name)
        if v:
            values.add(v)
    # longest first: when one secret is a prefix of another (sk-abcd /
    # sk-abcd-prod-…), replacing the short one first would leave the
    # long one's distinguishing suffix in cleartext
    return sorted((v for v in values if len(v) >= 4),
                  key=lambda v: (-len(v), v))


def _redact_text(content: str, secrets: Iterable[str]) -> str:
    """Replace each secret value (and its JSON-escaped form — archived
    files are JSON, where e.g. a quote in a token appears as ``\\"``)
    with the redaction marker."""
    for secret in secrets:
        content = content.replace(secret, REDACTED)
        escaped = json.dumps(secret)[1:-1]
        if escaped != secret:
            content = content.replace(escaped, REDACTED)
    return content


def collect_bundle(state: CliState, out_path: Optional[str] = None,
                   redact: bool = False) -> str:
    """Write the support bundle; returns its path."""
    out_path = out_path or os.path.join(
        state.path, f"odigos-diagnose-{int(time.time())}.tar.gz")
    secrets = _secret_values(state) if redact else []

    with tarfile.open(out_path, "w:gz") as tar:
        def add(name: str, content: str) -> None:
            if secrets:
                content = _redact_text(content, secrets)
            _add_file(tar, name, content)

        # resources, kind by kind (the kubectl-get-everything analog)
        for kind, objs in sorted(state.store._objects.items()):
            dump = json.dumps([to_jsonable(r) for r in objs.values()],
                              indent=1, sort_keys=True)
            add(f"resources/{kind}.json", dump)
        add("cluster.json", json.dumps(state.cluster.to_dict(), indent=1))
        add("config/authored.json",
            json.dumps(state.config.to_dict(), indent=1))
        eff = state.store.get("ConfigMap", ODIGOS_NAMESPACE,
                              EFFECTIVE_CONFIG_NAME)
        if eff is not None:
            add("config/effective.json",
                json.dumps(to_jsonable(eff.data), indent=1))
        # self-telemetry snapshot (the pprof/metrics piece of the bundle)
        add("metrics.json",
            json.dumps(meter.snapshot(), indent=1, sort_keys=True))
        # internal-tracing span ring: where time went inside the pipeline,
        # the reconcile loops, and the TPU scoring engine right before the
        # bundle was cut — the evidence layer for latency bug reports
        add("selftrace.json",
            json.dumps(tracer.snapshot(), indent=1, sort_keys=True))
        # histogram exemplars: the metric→trace links (tail witnesses)
        # pairing the metrics snapshot with the span ring above
        add("exemplars.json",
            json.dumps(meter.exemplars(), indent=1, sort_keys=True))
        # flow ledger (ISSUE 5): per-edge conservation counters, named
        # drops with last-drop trace witnesses, queue high-watermarks,
        # the per-pipeline balance, and the live condition rollup —
        # "where did my spans go", frozen at bundle time
        from ..selftelemetry.flow import active_conditions, flow_ledger

        flow_doc = flow_ledger.snapshot()
        flow_doc["conservation"] = flow_ledger.conservation()
        flow_doc["conditions"] = active_conditions()
        add("flow.json", json.dumps(flow_doc, indent=1, sort_keys=True))
        # latency attribution (ISSUE 8): the per-pipeline stage
        # waterfall, deadline-burn table with expiry blames, recent
        # frame timelines, and SLO burn-rate status — "where did the
        # time go", frozen at bundle time
        from ..selftelemetry.latency import latency_ledger

        add("latency.json", json.dumps(latency_ledger.snapshot(),
                                       indent=1, sort_keys=True))
        # fleet plane (ISSUE 10): per-collector health rollups, worst-
        # of per group, alert rule states + fired/cleared history, and
        # the sizing recommendations scoped to this install's preset —
        # "how is the fleet doing", frozen at bundle time
        from ..selftelemetry.fleet import fleet_plane

        add("fleet.json", json.dumps(
            fleet_plane.api_snapshot(config=state.config),
            indent=1, sort_keys=True))
        # closed-loop actuator (ISSUE 15): what the fleet tried to tune
        # about itself — proposals, canaries, promotions, rollbacks and
        # refusals with reasons — frozen at bundle time
        from ..controlplane.actuator import fleet_actuator

        add("actuator.json", json.dumps(
            fleet_actuator.api_snapshot(), indent=1, sort_keys=True))
        # flight recorder (ISSUE 16): the frozen incident bundles — the
        # black box an operator opens first after a page. Full bundles
        # (event timeline, series excerpt, worst-frame exemplars, config
        # hash, conditions), not summaries: a diagnose archive must
        # stand alone offline.
        from ..selftelemetry.flightrecorder import flight_recorder

        add("incidents.json", json.dumps({
            "snapshot": flight_recorder.api_snapshot(),
            "incidents": flight_recorder.incidents(),
        }, indent=1, sort_keys=True))
        # device-runtime snapshot, taken fresh at bundle time: engine
        # gauges + (when jax is loaded) live arrays, device memory, and
        # per-jit-site cache/compile accounting. Read-only: a one-shot
        # diagnostic must not publish gauges nothing will ever refresh.
        add("device_runtime.json",
            json.dumps(DeviceRuntimeCollector().collect_once(
                publish=False), indent=1, sort_keys=True))
        # device plane (ISSUE 20): the XLA cost/efficiency ledger,
        # sampled intra-fused attribution state per engine, recent
        # compile events, and the device-resident table footprint —
        # "what should the device be doing and what is it actually
        # doing", frozen at bundle time
        from ..selftelemetry.profiler import device_snapshot

        add("device.json", json.dumps(device_snapshot(),
                                      indent=1, sort_keys=True))
        # continuous profiler (ISSUE 3): ring metadata + the merged
        # folded profile — where CPU time went over the retained windows.
        # With the profiler off (the default) a brief on-demand sample
        # stands in, so a bundle always carries a stack profile.
        add("profiler.json",
            json.dumps(profiler.snapshot(), indent=1, sort_keys=True))
        folded = profiler.folded()
        if not folded:
            # on-demand fallback runs on a helper thread: the sampler
            # excludes its own thread, so sampling from the (possibly
            # only) CLI main thread directly would see nothing — from a
            # helper, the main thread's join() stack is always visible
            box: dict[str, list[str]] = {}
            t = threading.Thread(
                target=lambda: box.setdefault(
                    "folded", sample_profile(seconds=0.25, hz=97.0)),
                daemon=True)
            t.start()
            t.join(timeout=5.0)
            folded = box.get("folded", [])
        add("profile.folded", "\n".join(folded) + "\n")
        add("describe.txt", describe_install(state))
        add("environment.json", json.dumps({
            "python": platform.python_version(),
            "platform": platform.platform(),
            "state_dir": state.path,
            "redacted": bool(secrets),
            "collected_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        }, indent=1))
    return out_path
