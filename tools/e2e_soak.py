"""Sustained end-to-end wire-path throughput soak.

The device-side record (bench.py / BENCH_tpu_snapshot.json) measures the
TPU scoring hot loop; this is the CPU-side complement the round-3 verdict
asked for (item 7): a pinned-duration soak through the REAL wire path —

    WireExporter (framed TCP) -> otlpwire receiver w/ admission control
    -> memory_limiter -> batch -> tpuanomaly (zscore model, CPU-friendly)
    -> anomalyrouter -> tracedb exporters

reporting end-to-end spans/s and asserting span conservation (everything
accepted by the receiver reaches a terminal exporter; REJECTED frames are
counted, not lost). Writes ``SOAK.json`` and prints one JSON line.

    python tools/e2e_soak.py [--seconds 20] [--senders 2]

Reference discipline: the hot-loop zero-alloc rule of
collector/receivers/odigosebpfreceiver/traces.go:17 and the
tests/e2e/trace-collection conservation asserts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seconds", type=float, default=20.0)
    ap.add_argument("--senders", type=int, default=2)
    ap.add_argument("--traces-per-batch", type=int, default=256)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")  # the soak measures the wire

    from odigos_tpu.pdata import synthesize_traces
    from odigos_tpu.pipeline.service import Collector
    from odigos_tpu.wire.client import WireExporter

    cfg = {
        "receivers": {"otlpwire": {}},
        "processors": {
            "memory_limiter": {"limit_mib": 512},
            "batch": {"send_batch_size": 8192, "timeout_s": 0.1},
            "tpuanomaly": {"model": "zscore", "threshold": 0.6,
                           "timeout_ms": 30000, "shared_engine": False},
        },
        "connectors": {"anomalyrouter": {
            "anomaly_pipelines": ["traces/anomaly"],
            "default_pipelines": ["traces/normal"],
            "mode": "trace"}},
        "exporters": {"tracedb/anomaly": {}, "tracedb/normal": {}},
        "service": {"pipelines": {
            "traces/in": {
                "receivers": ["otlpwire"],
                "processors": ["memory_limiter", "batch", "tpuanomaly"],
                "exporters": ["anomalyrouter"]},
            "traces/anomaly": {"receivers": ["anomalyrouter"],
                               "exporters": ["tracedb/anomaly"]},
            "traces/normal": {"receivers": ["anomalyrouter"],
                              "exporters": ["tracedb/normal"]},
        }},
    }

    collector = Collector(cfg).start()
    port = collector.graph.receivers["otlpwire"].port

    # pre-synthesize a few distinct batches per sender (generation must not
    # rate-limit the wire); a quarter carry injected faults so the anomaly
    # route is exercised under load, not just the passthrough path
    from odigos_tpu.pdata import inject_faults

    batches = []
    for s in range(8):
        b = synthesize_traces(args.traces_per_batch, seed=s)
        if s % 4 == 0:
            b, _, _ = inject_faults(b, fault_fraction=0.2, seed=100 + s)
        batches.append(b)
    batch_spans = [len(b) for b in batches]

    sent_spans = [0] * args.senders
    dropped_spans = [0] * args.senders
    stop = threading.Event()

    def sender(i: int) -> None:
        exp = WireExporter(f"otlpwire/soak-{i}", {
            "endpoint": f"127.0.0.1:{port}", "queue_size": 64,
            "max_elapsed_s": 60.0})
        exp.start()
        k = i
        while not stop.is_set():
            exp.export(batches[k % len(batches)])
            sent_spans[i] += batch_spans[k % len(batches)]
            k += args.senders
            # bounded in-flight: wait for the queue to drain enough that
            # "sent" means accepted-by-socket, not buffered locally
            while exp.queued > 32 and not stop.is_set():
                time.sleep(0.001)
        ok = exp.flush(timeout=60.0)
        if not ok:
            # the residual queue holds the most recently enqueued batches
            # (FIFO drains from the front); this sender enqueued indices
            # i, i+senders, i+2*senders, ... so walk back from the last
            # one (k - senders) to count the exact spans still queued —
            # batches differ in span count per seed, so multiplying by
            # batch_spans[0] would mis-state conservation precisely in
            # the failure case this check exists to catch
            q = exp.queued
            dropped_spans[i] = sum(
                batch_spans[(k - args.senders * (j + 1)) % len(batches)]
                for j in range(q))
        exp.shutdown()

    threads = [threading.Thread(target=sender, args=(i,), daemon=True)
               for i in range(args.senders)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(args.seconds)
    stop.set()
    for t in threads:
        t.join(timeout=90)
    collector.drain_receivers(timeout=60.0)
    elapsed = time.perf_counter() - t0

    anomaly = collector.graph.exporters["tracedb/anomaly"]
    normal = collector.graph.exporters["tracedb/normal"]
    received = anomaly.span_count + normal.span_count
    sent = sum(sent_spans) - sum(dropped_spans)
    collector.shutdown()

    result = {
        "metric": "e2e_wire_spans_per_sec",
        "value": round(received / elapsed, 1),
        "unit": "spans/s",
        "elapsed_s": round(elapsed, 2),
        "senders": args.senders,
        "spans_sent": int(sent),
        "spans_received": int(received),
        "conservation": received == sent,
        "anomaly_spans": int(anomaly.span_count),
    }
    with open(os.path.join(REPO, "SOAK.json"), "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))
    if received != sent:
        print(f"SPAN LOSS: sent {sent} received {received}",
              file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
