from .service import Collector
from .graph import build_graph, validate_config

__all__ = ["Collector", "build_graph", "validate_config"]
