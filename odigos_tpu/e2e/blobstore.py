"""Minimal blob-storage / vendor-ingest HTTP API for exporter tests.

The reference exporters talk to Azure Blob / GCS over HTTPS through the
cloud SDKs (collector/exporters/azureblobstorageexporter/exporter.go,
googlecloudstorageexporter/gcs_writer.go); this build has zero egress, so
tests stand up this server instead: a PUT-per-object API with bearer-token
auth and injectable 5xx faults, storing objects through the same
LocalDirUploader double the file:// exporter path uses. It plays the role
of the cloud service in tests — upload success, retry-on-5xx, and
auth-rejection semantics are exercised over a real socket. PUT is the
blob contract (path = object key); POST is the vendor-ingest contract
(components/exporters/vendor.py) where each request appends an object,
with ``require_header`` standing in for vendor auth schemes.

Usage:
    store = BlobStoreServer(root_dir, token="secret")
    store.start()                      # -> listening on 127.0.0.1:<port>
    store.fail_next(2)                 # next 2 PUTs answer 503
    ... exporter PUTs to store.url ...
    store.stop()
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..components.exporters.blob import LocalDirUploader


class BlobStoreServer:
    def __init__(self, root: str, token: str = "", host: str = "127.0.0.1"):
        self._uploader = LocalDirUploader(root)
        self.token = token
        self._host = host
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._fail_budget = 0
        self.put_count = 0
        self.auth_failures = 0
        self.bodies: list[bytes] = []  # accepted payloads, arrival order
        # full request records for protocol-shape assertions:
        # {method, path, headers, body}
        self.requests: list[dict] = []
        # vendor exporters send vendor-shaped auth (DD-API-KEY: ... etc.);
        # set to (header_name, value) to require that instead of bearer
        self.require_header: tuple[str, str] | None = None

    def _next_seq(self) -> int:
        """Atomically count the request and reserve its sequence number."""
        with self._lock:
            seq = self.put_count
            self.put_count += 1
            return seq

    def _auth_ok(self, headers) -> bool:
        if self.require_header is not None:
            name, value = self.require_header
            return headers.get(name, "") == value
        if self.token:
            return headers.get("Authorization", "") == f"Bearer {self.token}"
        return True

    # --- fault injection -------------------------------------------------
    def fail_next(self, n: int) -> None:
        """The next ``n`` PUTs answer 503 (transient server fault)."""
        with self._lock:
            self._fail_budget = int(n)

    def _take_fault(self) -> bool:
        with self._lock:
            if self._fail_budget > 0:
                self._fail_budget -= 1
                return True
            return False

    # --- lifecycle -------------------------------------------------------
    @property
    def url(self) -> str:
        assert self._httpd is not None, "start() first"
        return f"http://{self._host}:{self._httpd.server_address[1]}"

    def start(self) -> "BlobStoreServer":
        store = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet test output
                pass

            def _ingest(self, key: str):
                if not store._auth_ok(self.headers):
                    with store._lock:
                        store.auth_failures += 1
                    self.send_error(401, "bad or missing credentials")
                    return
                if store._take_fault():
                    self.send_error(503, "injected transient fault")
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                try:
                    store._uploader.upload(key, body)
                except ValueError as e:  # path-escape attempt
                    self.send_error(400, str(e))
                    return
                with store._lock:
                    store.bodies.append(body)
                    store.requests.append({
                        "method": self.command, "path": self.path,
                        "headers": {k: v for k, v in self.headers.items()},
                        "body": body})
                self.send_response(201)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def do_PUT(self):
                # blob semantics: the path IS the object key
                store._next_seq()
                self._ingest(self.path.lstrip("/"))

            def do_POST(self):
                # vendor-ingest semantics: POSTs to one URL append objects
                # (seq reserved atomically — concurrent handler threads
                # must not derive colliding object keys)
                seq = store._next_seq()
                key = (self.path.strip("/") or "ingest").replace("/", "_")
                self._ingest(f"{key}/{seq}.json")

        self._httpd = ThreadingHTTPServer((self._host, 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="blobstore-http")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
